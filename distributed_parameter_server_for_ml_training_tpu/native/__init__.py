"""Native (C++) runtime components, bound via ctypes.

The reference's native code all lived in pip deps (grpc C-core, libtorch —
SURVEY.md §2.9). Here the host-side runtime hot paths are in-repo C++
(native/ps_core.cpp): a contiguous-arena parameter store with seqlock
fetches and fused fp16-decode + staleness-weighted SGD pushes, plus a
multithreaded fp16 codec. Python binds with ctypes (no pybind11 in this
environment); everything degrades gracefully to the pure-Python/numpy
implementations when the library isn't built.
"""

from .bindings import load_library, native_available
from .store import NativeParameterStore

__all__ = ["load_library", "native_available", "NativeParameterStore"]
