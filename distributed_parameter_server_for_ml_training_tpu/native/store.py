"""Native-backed parameter store (async hot path in C++).

API-compatible with :class:`~..ps.store.ParameterStore` for the worker-facing
surface (register_worker / fetch / push / job_finished / metrics), so
:class:`~..ps.worker.PSWorker`, the gRPC service, and the trainers accept it
interchangeably. The arena layout (one flat float buffer + a name->slice
index) is what lets C++ do the whole push in one multithreaded pass.

Async mode only — the sync TPU path has no server at all (parallel/sync_dp),
and the Python store covers sync-store experiments.
"""

from __future__ import annotations

import threading
import time
from typing import Mapping

import numpy as np

from ..ps.semantics import DEFAULT_STALENESS_BOUND
from ..ps.store import MAX_WORKERS, MembershipMixin, StoreConfig, _Stats
from .bindings import _f32p, _u16p, load_library


class NativeParameterStore(MembershipMixin):
    """ParameterStore drop-in with the C++ core under the hot path."""

    def __init__(self, initial_params: Mapping[str, np.ndarray],
                 config: StoreConfig | None = None):
        self.config = config or StoreConfig(mode="async")
        if self.config.mode != "async":
            raise ValueError(
                "NativeParameterStore supports async mode only; the sync "
                "mode is the SPMD path (parallel/sync_dp.py) or the Python "
                "store")
        if self.config.fetch_codec != "none":
            raise ValueError(
                "NativeParameterStore fetches fp32 from the arena; "
                "fetch_codec compression is Python-store only")
        lib = load_library()
        if lib is None:
            raise RuntimeError("native library unavailable; build native/ "
                               "or use ParameterStore")
        self._lib = lib

        # Flat arena + index.
        self._index: dict[str, tuple[int, tuple[int, ...]]] = {}
        offset = 0
        for name, arr in initial_params.items():
            arr = np.asarray(arr, np.float32)
            self._index[name] = (offset, arr.shape)
            offset += arr.size
        self._size = offset
        arena = np.empty(self._size, np.float32)
        for name, arr in initial_params.items():
            off, shape = self._index[name]
            arena[off:off + int(np.prod(shape, dtype=np.int64))] = np.asarray(
                arr, np.float32).reshape(-1)
        self._handle = lib.dps_store_create(
            self._size, _f32p(arena), float(self.config.learning_rate))

        self._registration_lock = threading.Lock()
        self._next_worker_id = 0
        self.active_workers: set[int] = set()
        self.last_seen: dict[int, float] = {}
        self.stats = _Stats()
        self._finished_event = threading.Event()

    # -- properties mirroring ParameterStore ---------------------------------

    @property
    def push_codec(self) -> str:
        return self.config.push_codec

    @property
    def fetch_codec(self) -> str:
        return "none"  # the arena always fetches fp32

    @property
    def global_step(self) -> int:
        return int(self._lib.dps_store_step(self._handle))

    @property
    def parameters(self) -> dict[str, np.ndarray]:
        """Name->array view of a consistent snapshot (copy)."""
        flat, _ = self._fetch_flat()
        return self._unpack(flat)

    # -- lifecycle (register/finish/expire inherited) ------------------------

    def _fetch_flat(self) -> tuple[np.ndarray, int]:
        out = np.empty(self._size, np.float32)
        step = int(self._lib.dps_store_fetch(self._handle, _f32p(out)))
        return out, step

    def _unpack(self, flat: np.ndarray) -> dict[str, np.ndarray]:
        out = {}
        for name, (off, shape) in self._index.items():
            n = int(np.prod(shape, dtype=np.int64))
            out[name] = flat[off:off + n].reshape(shape)
        return out

    def fetch(self, worker_id: int | None = None
              ) -> tuple[dict[str, np.ndarray], int]:
        flat, step = self._fetch_flat()
        if worker_id is not None:
            self.last_seen[worker_id] = time.time()
        return self._unpack(flat), step

    def _pack(self, gradients: Mapping[str, np.ndarray],
              dtype) -> np.ndarray:
        flat = np.empty(self._size, dtype)
        for name, (off, shape) in self._index.items():
            g = np.ascontiguousarray(gradients[name], dtype)
            n = int(np.prod(shape, dtype=np.int64))
            flat[off:off + n] = g.reshape(-1)
        return flat

    def push(self, worker_id: int, gradients: Mapping[str, np.ndarray],
             fetched_step: int) -> bool:
        self.last_seen[worker_id] = time.time()
        t0 = time.time()
        bound = int(self.config.staleness_bound)
        before = self.global_step
        if self.config.push_codec == "fp16":
            flat = self._pack(gradients, np.float16)
            new_step = int(self._lib.dps_store_push_fp16(
                self._handle, _u16p(flat.view(np.uint16)),
                int(fetched_step), bound))
        else:
            flat = self._pack(gradients, np.float32)
            new_step = int(self._lib.dps_store_push_fp32(
                self._handle, _f32p(flat), int(fetched_step), bound))
        if new_step < 0:
            self.stats.gradients_rejected += 1
            return False
        self.stats.gradients_processed += 1
        self.stats.total_parameter_updates += 1
        self.stats.staleness_values.append(before - int(fetched_step))
        self.stats.update_times.append(time.time() - t0)
        return True

    def metrics(self) -> dict:
        elapsed = time.time() - self.stats.start_time
        sv = self.stats.staleness_values
        return {
            "mode": "async",
            "backend": "native",
            "total_workers": self.config.total_workers,
            "total_training_time_seconds": round(elapsed, 2),
            "global_steps_completed": self.global_step,
            "total_parameter_updates": self.stats.total_parameter_updates,
            "gradients_processed": self.stats.gradients_processed,
            "average_update_time_seconds": (
                round(float(np.mean(self.stats.update_times)), 6)
                if self.stats.update_times else 0.0),
            "updates_per_second": (
                round(self.stats.total_parameter_updates / elapsed, 3)
                if elapsed > 0 else 0.0),
            "learning_rate": self.config.learning_rate,
            "staleness_bound": self.config.staleness_bound,
            "gradients_rejected": self.stats.gradients_rejected,
            "average_staleness": (round(float(np.mean(sv)), 3) if sv else 0.0),
            "max_staleness": int(max(sv)) if sv else 0,
        }

    def __del__(self):
        try:
            self._lib.dps_store_destroy(self._handle)
        except Exception:
            pass
