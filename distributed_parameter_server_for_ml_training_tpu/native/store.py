"""Native-backed parameter store (hot paths in C++).

API-compatible with :class:`~..ps.store.ParameterStore` for the worker-facing
surface (register_worker / fetch / push / job_finished / metrics), so
:class:`~..ps.worker.PSWorker`, the gRPC service, and the trainers accept it
interchangeably. The arena layout (one flat float buffer + a name->slice
index) is what lets C++ do the whole push in one multithreaded pass.

Both modes run native bulk passes: async pushes are a fused
decode + staleness-weighted SGD (server.py:171-186 semantics in
ps_core.cpp) with fp32/fp16/int8 codecs — the int8 kernel dequantizes
per-tensor symmetric scales segment-wise in the same single pass; sync
rounds stash each worker's gradients into a C++ slot buffer (same three
codecs) and complete with one fused mean+apply pass (server.py:264-288 +
145-169 + 126-143). Round ORCHESTRATION (locks, counts, elastic targets,
quirk-3 double-push semantics) stays in Python, mirroring
:class:`~..ps.store.AggregationBase`.

Restriction vs the Python store: pushes must carry the FULL parameter set
(the arena is contiguous); the reference's partial-push averaging is a
Python-store behavior.
"""

from __future__ import annotations

import threading
import time
from typing import Mapping

import numpy as np


from ..ops.compression import _SCALE_SUFFIX
from ..ps.store import MembershipMixin, StoreConfig, TelemetryMixin, _Stats
from ..telemetry import now as _tnow, trace_span
from .bindings import _f32p, _i8p, _i64p, _u16p, load_library


class NativeParameterStore(TelemetryMixin, MembershipMixin):
    """ParameterStore drop-in with the C++ core under the hot path."""

    store_backend = "native"

    def __init__(self, initial_params: Mapping[str, np.ndarray],
                 config: StoreConfig | None = None):
        self.config = config or StoreConfig(mode="async")
        # Resolve the sentinel locally; never mutate a (possibly shared)
        # StoreConfig.
        self._push_codec = (self.config.push_codec
                            if self.config.push_codec is not None
                            else "fp16")  # reference default
        if self._push_codec not in ("none", "fp16", "int8"):
            raise ValueError(
                f"push_codec must be none|fp16|int8, got "
                f"{self._push_codec!r}")
        if self.config.fetch_codec not in ("none", "fp16", "bf16"):
            raise ValueError(f"fetch_codec must be none|fp16|bf16, got "
                             f"{self.config.fetch_codec!r}")
        lib = load_library()
        if lib is None:
            raise RuntimeError("native library unavailable; build native/ "
                               "or use ParameterStore")
        self._lib = lib

        # Flat arena + index.
        self._index: dict[str, tuple[int, tuple[int, ...]]] = {}
        offset = 0
        for name, arr in initial_params.items():
            arr = np.asarray(arr, np.float32)
            self._index[name] = (offset, arr.shape)
            offset += arr.size
        self._size = offset
        # Per-tensor segment boundaries in index (= arena) order, for the
        # int8 kernels' per-tensor scales (ps_core.cpp segment walk).
        self._names = list(self._index)
        self._offsets = np.fromiter(
            (self._index[n][0] for n in self._names), np.int64,
            count=len(self._names))
        self._offsets = np.append(self._offsets, np.int64(self._size))
        arena = np.empty(self._size, np.float32)
        for name, arr in initial_params.items():
            off, shape = self._index[name]
            arena[off:off + int(np.prod(shape, dtype=np.int64))] = np.asarray(
                arr, np.float32).reshape(-1)
        self._handle = lib.dps_store_create(
            self._size, _f32p(arena), float(self.config.learning_rate))

        self._registration_lock = threading.Lock()
        self._next_worker_id = 0
        self.active_workers: set[int] = set()
        self.last_seen: dict[int, float] = {}
        self.stats = _Stats()
        self._finished_event = threading.Event()

        # Sync-round state (orchestrated here, bulk work in C++): worker id
        # -> C++ slot holding its stashed gradients this round. Slots of
        # departed/expired workers are RELEASED (C++ buffer freed) and their
        # indices recycled — membership churn must not grow memory without
        # bound (each slot is a full arena, ~45 MB at ResNet-18 scale).
        self._sync_lock = threading.Lock()
        self._slot_of: dict[int, int] = {}
        self._free_slots: list[int] = []
        self._next_slot = 0
        self._pending: dict[int, int] = {}      # worker_id -> slot
        self._gradients_received = 0
        self._init_telemetry()

    # -- properties mirroring ParameterStore ---------------------------------

    @property
    def push_codec(self) -> str:
        return self._push_codec

    @property
    def fetch_codec(self) -> str:
        return self.config.fetch_codec

    @property
    def global_step(self) -> int:
        return int(self._lib.dps_store_step(self._handle))

    @property
    def parameters(self) -> dict[str, np.ndarray]:
        """Name->array view of a consistent snapshot (copy)."""
        flat, _ = self._fetch_flat()
        return self._unpack(flat)

    # -- lifecycle (register/finish/expire inherited) ------------------------

    def _fetch_flat(self) -> tuple[np.ndarray, int]:
        out = np.empty(self._size, np.float32)
        step = int(self._lib.dps_store_fetch(self._handle, _f32p(out)))
        return out, step

    def _unpack(self, flat: np.ndarray) -> dict[str, np.ndarray]:
        out = {}
        for name, (off, shape) in self._index.items():
            n = int(np.prod(shape, dtype=np.int64))
            out[name] = flat[off:off + n].reshape(shape)
        return out

    def fetch(self, worker_id: int | None = None
              ) -> tuple[dict[str, np.ndarray], int]:
        t0 = _tnow()
        with trace_span("store.fetch", backend=self.store_backend):
            return self._fetch_traced(worker_id, t0)

    def _fetch_traced(self, worker_id: int | None, t0: float
                      ) -> tuple[dict[str, np.ndarray], int]:
        flat, step = self._fetch_flat()
        if worker_id is not None:
            self.last_seen[worker_id] = time.time()
        codec = self.config.fetch_codec
        if codec == "fp16":
            # C++ multithreaded cast over the whole arena, then slice views.
            from .bindings import fp32_to_fp16
            flat = fp32_to_fp16(flat)
        elif codec == "bf16":
            from .bindings import fp32_to_bf16
            flat = fp32_to_bf16(flat)
        out = self._unpack(flat), step
        self._tm_fetch_s.observe(_tnow() - t0)
        self._tm_fetches.inc()
        return out

    # -- checkpoint surface (same contract as AggregationBase.snapshot) ------

    def snapshot(self) -> tuple[dict[str, np.ndarray], int]:
        """Consistent (params, step) via the seqlock fetch — pushes are never
        blocked while a snapshot copies the arena."""
        flat, step = self._fetch_flat()
        return self._unpack(flat), step

    def load_snapshot(self, params: Mapping[str, np.ndarray],
                      step: int) -> None:
        """Write a snapshot back into the C++ arena under its write lock
        (dps_store_load brackets the copy with the seqlock, so concurrent
        fetches retry rather than observe a half-restored arena)."""
        flat = self._pack(params, np.float32)
        self._lib.dps_store_load(self._handle, _f32p(flat), int(step))

    def _pack(self, gradients: Mapping[str, np.ndarray],
              dtype) -> np.ndarray:
        flat = np.empty(self._size, dtype)
        for name, (off, shape) in self._index.items():
            g = np.ascontiguousarray(gradients[name], dtype)
            n = int(np.prod(shape, dtype=np.int64))
            flat[off:off + n] = g.reshape(-1)
        return flat

    def _pack_int8(self, gradients: Mapping[str, np.ndarray]
                   ) -> tuple[np.ndarray, np.ndarray] | None:
        """(int8 arena-ordered values, per-tensor fp32 scales) from an
        int8-wire payload ({name: int8, name::int8scale: fp32[1]},
        ops/compression.py). Returns None for an uncompressed payload
        (in-process pushes may skip the wire codec; like the Python
        store's decompressor, fp32 passes through — via the fp32 kernel).
        """
        if not any(isinstance(v, np.ndarray) and v.dtype == np.int8
                   for v in gradients.values()):
            return None
        flat = np.empty(self._size, np.int8)
        scales = np.empty(len(self._names), np.float32)
        for t, name in enumerate(self._names):
            g = np.ascontiguousarray(gradients[name])
            if g.dtype != np.int8:
                raise ValueError(f"mixed int8 payload: {name} is {g.dtype}")
            scale = gradients.get(name + _SCALE_SUFFIX)
            if scale is None:
                raise ValueError(f"int8 wire entry {name!r} missing its "
                                 f"{_SCALE_SUFFIX} companion")
            off, seg_end = int(self._offsets[t]), int(self._offsets[t + 1])
            if g.size != seg_end - off:
                # Must reject BEFORE the kernel: a short tensor would leave
                # np.empty garbage in its segment and a long one would
                # bleed into the next (the Python store's shape check,
                # ps/store.py, is this guard's twin).
                raise ValueError(
                    f"push size mismatch for {name}: got {g.size} elements,"
                    f" server segment holds {seg_end - off} (model/dataset "
                    f"mismatch?)")
            flat[off:seg_end] = g.reshape(-1)
            scales[t] = np.float32(np.asarray(scale).reshape(-1)[0])
        return flat, scales

    def _pack_push(self, gradients: Mapping[str, np.ndarray]) -> tuple:
        """Compact a push payload into arena order: ('int8', values, scales)
        or ('fp16'|'fp32', flat). Raises ValueError/KeyError on malformed
        payloads (wrong sizes, missing tensors/scales) — callers reject."""
        if self._push_codec == "int8":
            packed = self._pack_int8(gradients)
            if packed is not None:
                return ("int8",) + packed
        if self._push_codec == "fp16":
            return ("fp16", self._pack(gradients, np.float16))
        return ("fp32", self._pack(gradients, np.float32))

    def push(self, worker_id: int, gradients: Mapping[str, np.ndarray],
             fetched_step: int) -> bool:
        t_push = _tnow()
        with trace_span("store.push", backend=self.store_backend) as sp:
            try:
                accepted = self._push_timed(worker_id, gradients,
                                            fetched_step)
                sp.attrs["accepted"] = accepted
                return accepted
            finally:
                self._tm_push_s.observe(_tnow() - t_push)

    def _push_timed(self, worker_id: int,
                    gradients: Mapping[str, np.ndarray],
                    fetched_step: int) -> bool:
        self.last_seen[worker_id] = time.time()
        try:
            # Pack OUTSIDE any lock (pure host compaction) — and reject
            # malformed payloads up front, like the Python store's shape
            # check: the C++ kernels must never see a mis-sized buffer.
            packed = self._pack_push(gradients)
        except (ValueError, KeyError) as e:
            self.stats.gradients_rejected += 1
            self._tm_push_rej.inc()
            print(f"rejecting push from worker {worker_id}: {e}")
            return False
        if self.config.mode == "sync":
            self._push_sync(worker_id, packed)
            return True
        t0 = time.time()
        bound = int(self.config.staleness_bound)
        before = self.global_step
        self._tm_staleness.observe(before - int(fetched_step))
        with trace_span("store.apply", backend=self.store_backend,
                        mode="async",
                        staleness=before - int(fetched_step)):
            if packed[0] == "int8":
                _, flat, scales = packed
                new_step = int(self._lib.dps_store_push_int8(
                    self._handle, _i8p(flat), _f32p(scales),
                    _i64p(self._offsets), len(self._names),
                    int(fetched_step), bound))
            elif packed[0] == "fp16":
                new_step = int(self._lib.dps_store_push_fp16(
                    self._handle, _u16p(packed[1].view(np.uint16)),
                    int(fetched_step), bound))
            else:
                new_step = int(self._lib.dps_store_push_fp32(
                    self._handle, _f32p(packed[1]), int(fetched_step),
                    bound))
        if new_step < 0:
            self.stats.gradients_rejected += 1
            self._tm_push_rej.inc()
            return False
        self.stats.gradients_processed += 1
        self.stats.total_parameter_updates += 1
        self.stats.staleness_values.append(before - int(fetched_step))
        dt = time.time() - t0
        self.stats.update_times.append(dt)
        self._tm_apply_s.observe(dt)
        self._tm_push_ok.inc()
        self._tm_step.set(new_step)
        return True

    # -- sync rounds (orchestration mirrors AggregationBase; _round_target
    #    and the elastic hooks' call sites are inherited) --------------------

    def _push_sync(self, worker_id: int, packed: tuple) -> None:
        """server.py:264-288 semantics: stash (C++ decode into the worker's
        slot), count, and complete the round with one fused mean+apply.
        ``packed`` comes from :meth:`_pack_push` (payload already validated
        and arena-ordered, no shared state touched yet).

        The WHOLE stash happens under ``_sync_lock`` — exactly like the
        Python store, whose pushes hold the lock for the full stash —
        otherwise apply_mean could read a slot mid-overwrite (quirk-3
        double pushes make that reachable, not just theoretical).
        """
        with self._sync_lock:
            slot = self._slot_of.get(worker_id)
            if slot is None:
                if self._free_slots:
                    slot = self._free_slots.pop()
                else:
                    slot = self._next_slot
                    self._next_slot += 1
                self._slot_of[worker_id] = slot
            if packed[0] == "int8":
                _, flat, scales = packed
                self._lib.dps_store_stash_int8(
                    self._handle, slot, _i8p(flat), _f32p(scales),
                    _i64p(self._offsets), len(self._names))
            elif packed[0] == "fp16":
                self._lib.dps_store_stash_fp16(
                    self._handle, slot, _u16p(packed[1].view(np.uint16)))
            else:
                self._lib.dps_store_stash_fp32(self._handle, slot,
                                               _f32p(packed[1]))
            if self.config.strict_rounds:
                self._pending[worker_id] = slot
                self._gradients_received = len(self._pending)
            else:
                # Faithful quirk 3: a double push overwrites the slot (the
                # stash above already did) but still counts.
                self._pending[worker_id] = slot
                self._gradients_received += 1
            self._maybe_complete_round_locked()
            self.stats.gradients_processed += 1
        self._tm_push_ok.inc()

    def _maybe_complete_round_locked(self) -> None:
        if self._gradients_received >= self._round_target() and self._pending:
            t0 = time.time()
            try:
                slots = np.fromiter(self._pending.values(), np.int64)
                with trace_span("store.apply", backend=self.store_backend,
                                mode="sync", n_grads=len(slots)):
                    self._lib.dps_store_apply_mean(
                        self._handle, _i64p(slots), len(slots))
                self.stats.total_parameter_updates += 1
                dt = time.time() - t0
                self.stats.update_times.append(dt)
                self._tm_apply_s.observe(dt)
                self._tm_rounds.inc()
                self._tm_step.set(self.global_step)
            finally:
                # Workers that departed/expired while this round was still
                # pending had their slot release deferred (their stash was a
                # live contribution) — sweep them now that it is consumed.
                departed = [w for w in self._pending
                            if w not in self.active_workers]
                self._pending.clear()
                self._gradients_received = 0
                for w in departed:
                    self._release_slot_locked(w)

    def _release_slot_locked(self, worker_id: int) -> None:
        """Free the worker's C++ slot buffer and recycle its index (safe:
        apply_mean and stashes all serialize on _sync_lock, which the
        caller holds)."""
        slot = self._slot_of.pop(worker_id, None)
        if slot is not None:
            self._lib.dps_store_free_slot(self._handle, slot)
            self._free_slots.append(slot)

    def _on_workers_expired(self, stale) -> None:
        """Purge dead workers' stashed slots from the round (elastic) and
        release their C++ buffers (always)."""
        with self._sync_lock:
            elastic = getattr(self.config, "elastic", False)
            for w in stale:
                if elastic:
                    self._pending.pop(w, None)
                if w not in self._pending:  # never free a pending slot
                    self._release_slot_locked(w)
            if elastic and (self._pending or self._gradients_received):
                self._gradients_received = len(self._pending)
                self._maybe_complete_round_locked()

    def _on_worker_departed(self, worker_id: int) -> None:
        with self._sync_lock:
            if getattr(self.config, "elastic", False) \
                    and self._gradients_received:
                self._maybe_complete_round_locked()
            # The departure's own final push (if any) was consumed by the
            # round check above or stays pending for the faithful path —
            # only release the slot once it is no longer pending.
            if worker_id not in self._pending:
                self._release_slot_locked(worker_id)

    def metrics(self) -> dict:
        elapsed = time.time() - self.stats.start_time
        out = {
            "mode": self.config.mode,
            # Same key as AggregationBase.metrics so the ETL can filter
            # records from all three backends uniformly.
            "store_backend": self.store_backend,
            "total_workers": self.config.total_workers,
            "total_training_time_seconds": round(elapsed, 2),
            "global_steps_completed": self.global_step,
            "total_parameter_updates": self.stats.total_parameter_updates,
            "gradients_processed": self.stats.gradients_processed,
            "average_update_time_seconds": (
                round(float(np.mean(self.stats.update_times)), 6)
                if self.stats.update_times else 0.0),
            "updates_per_second": (
                round(self.stats.total_parameter_updates / elapsed, 3)
                if elapsed > 0 else 0.0),
            "learning_rate": self.config.learning_rate,
        }
        if self.config.mode == "async":
            sv = self.stats.staleness_values
            out.update({
                "staleness_bound": self.config.staleness_bound,
                "gradients_rejected": self.stats.gradients_rejected,
                "average_staleness": (round(float(np.mean(sv)), 3)
                                      if sv else 0.0),
                "max_staleness": int(max(sv)) if sv else 0,
            })
        return out

    def __del__(self):
        try:
            self._lib.dps_store_destroy(self._handle)
        except Exception:  # noqa: BLE001 — __del__ during interpreter teardown
            pass
