"""Parse jax.profiler dumps into device-time attribution tables.

The read half of the perf observatory (write half:
:mod:`..telemetry.profiler`). A ``--profile-dir`` capture leaves one
Chrome-format ``*.trace.json.gz`` per host under
``plugins/profile/<run>/``; this module turns that into the thing the
"break the plateau" ROADMAP item needs: per-op-class device time, and a
reconciliation against the flight-recorder ``critical_path_report`` so
one artifact attributes each step's wall end-to-end (host phase ->
device op class), with the unattributed residual REPORTED, not hidden.

Honesty rules:

- On CPU (the CI/demo platform) jax emits no ``/device:`` lanes. The
  host lane still carries per-op thunk events (``convolution.687``,
  ``dot.12``), which classify into real op classes (``basis:
  "host_ops"``); a capture with no op events at all degrades to the
  executor-wrapper time (``basis: "host_execute_proxy"``, excluding the
  double-counting "wait for completion" variant). Either way
  ``device_lanes_present`` stays False and the residual stays visible —
  host attribution is never presented as measured device time.
- Op classification is by name pattern; on device lanes anything
  unmatched (fused kernels with opaque names) lands in ``other`` — the
  fractions always sum to 1 over attributed time. On host lanes
  unmatched names are python frames/bookkeeping, NOT ops, so they stay
  unattributed rather than polluting ``other``.
"""

from __future__ import annotations

import gzip
import json
import os
import re

__all__ = [
    "OP_CLASSES",
    "attribute_profile",
    "classify_op",
    "device_time_tables",
    "diff_profiles",
    "load_chrome_trace",
    "render_profile_diff",
    "render_profile_table",
]

#: op class -> one-line meaning (docs/OBSERVABILITY.md documents exactly
#: these rows; tools/dpslint's catalog-drift check pins the two to each
#: other both directions).
OP_CLASSES = {
    "matmul": "dense MXU work: dot/matmul/gemm/einsum kernels",
    "conv": "convolution kernels",
    "collective": "cross-device comms: all-reduce/all-gather/"
                  "reduce-scatter/all-to-all/permute/psum",
    "quantize-pack": "codec arithmetic: quantize/dequantize/pack/unpack",
    "transfer": "host<->device + on-device copies, infeed/outfeed",
    "host_execute": "host-side executable dispatch (the CPU-backend "
                    "proxy when no device lanes exist)",
    "other": "unclassified device ops (opaque fusion names)",
}

#: Ordered (class, pattern) — first match wins, so collectives beat the
#: ``dot`` inside a fused all-reduce name.
_CLASS_PATTERNS = (
    ("collective", re.compile(
        r"all[-_]?reduce|all[-_]?gather|reduce[-_]?scatter|"
        r"all[-_]?to[-_]?all|collective|psum|permute", re.I)),
    ("quantize-pack", re.compile(r"quant|dequant|pack|unpack", re.I)),
    ("transfer", re.compile(
        r"copy|memcpy|infeed|outfeed|transfer|h2d|d2h", re.I)),
    ("conv", re.compile(r"conv", re.I)),
    ("matmul", re.compile(r"dot|matmul|gemm|einsum", re.I)),
)

#: Host events that ARE the executable running (the last-resort CPU
#: proxy for device time). ParseArguments/donation bookkeeping etc. stay
#: unattributed; the "(wait for completion)" variant is excluded in code
#: because it wraps the inner Execute events and would double-count.
_HOST_EXECUTE_RE = re.compile(
    r"Executable::Execute|ExecuteOnLocalDevice|ThunkExecutor::Execute",
    re.I)


def classify_op(name: str) -> str:
    for cls, pat in _CLASS_PATTERNS:
        if pat.search(name):
            return cls
    return "other"


def load_chrome_trace(path: str) -> dict:
    """One dumped Chrome trace (gzipped or plain JSON)."""
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rt") as f:
        return json.load(f)


def _lanes(events: list) -> dict[int, str]:
    """pid -> process name, from the "M" (metadata) events."""
    names: dict[int, str] = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pid = ev.get("pid")
            nm = (ev.get("args") or {}).get("name")
            if isinstance(pid, int) and isinstance(nm, str):
                names[pid] = nm
    return names


def device_time_tables(trace: dict) -> dict:
    """Per-op-class device-time table for one Chrome trace dict.

    Durations are summed over complete ("X") events. Attribution basis,
    in preference order (reported as ``"basis"``):

    - ``device_lanes`` — events on ``/device:`` lanes; every event
      counts (unmatched names -> ``other``).
    - ``host_ops`` — no device lanes, but the host lane carries per-op
      thunk events (CPU backend); only pattern-matched op names count.
    - ``host_execute_proxy`` — no op events either; executor-wrapper
      time stands in (excluding the outer "wait for completion" events,
      which wrap the inner Execute and would double-count).
    - ``none`` — nothing attributable.
    """
    events = trace.get("traceEvents") or []
    lanes = _lanes(events)
    device_pids = {p for p, n in lanes.items() if "/device:" in n}
    device_ops: dict[str, dict] = {}
    host_ops: dict[str, dict] = {}
    host_exec: dict[str, dict] = {}
    t_min, t_max = None, None

    def add(table: dict, cls: str, dur_s: float) -> None:
        row = table.setdefault(cls, {"time_s": 0.0, "events": 0})
        row["time_s"] += dur_s
        row["events"] += 1

    for ev in events:
        if ev.get("ph") != "X":
            continue
        dur = ev.get("dur")
        if not isinstance(dur, (int, float)) or dur < 0:
            continue
        ts = ev.get("ts")
        if isinstance(ts, (int, float)):
            t_min = ts if t_min is None else min(t_min, ts)
            t_max = ts + dur if t_max is None else max(t_max, ts + dur)
        name = str(ev.get("name", ""))
        if device_pids:
            if ev.get("pid") in device_pids:
                add(device_ops, classify_op(name), dur / 1e6)  # dur: us
        else:
            cls = classify_op(name)
            if cls != "other":
                add(host_ops, cls, dur / 1e6)
            elif _HOST_EXECUTE_RE.search(name) \
                    and "wait for completion" not in name:
                add(host_exec, "host_execute", dur / 1e6)

    if device_pids:
        basis, per_class = "device_lanes", device_ops
    elif host_ops:
        basis, per_class = "host_ops", host_ops
    elif host_exec:
        basis, per_class = "host_execute_proxy", host_exec
    else:
        basis, per_class = "none", {}
    total = sum(r["time_s"] for r in per_class.values())
    for row in per_class.values():
        row["time_s"] = round(row["time_s"], 6)
        row["fraction"] = round(row["time_s"] / total, 4) if total else 0.0
    return {
        "basis": basis,
        "device_lanes_present": bool(device_pids),
        "lanes": sorted(lanes.values()),
        "op_classes": per_class,
        "total_attributed_s": round(total, 6),
        "trace_wall_s": round((t_max - t_min) / 1e6, 6)
        if t_min is not None else 0.0,
    }


def _merge_tables(tables: list[dict]) -> dict:
    """Sum per-class rows across hosts/files into one table. Mixed bases
    (one host dumped device lanes, another only host events) keep the
    strongest basis and sum only the files that share it — averaging a
    proxy into measured device time would corrupt both."""
    order = ("device_lanes", "host_ops", "host_execute_proxy", "none")
    basis = min((t.get("basis", "none") for t in tables),
                key=order.index, default="none")
    counted = [t for t in tables if t.get("basis", "none") == basis]
    merged: dict = {
        "basis": basis,
        "device_lanes_present": any(t["device_lanes_present"]
                                    for t in tables),
        "lanes": sorted({ln for t in tables for ln in t["lanes"]}),
        "op_classes": {},
        "total_attributed_s": 0.0,
        "trace_wall_s": max((t["trace_wall_s"] for t in tables),
                            default=0.0),
    }
    for t in counted:
        for cls, row in t["op_classes"].items():
            m = merged["op_classes"].setdefault(
                cls, {"time_s": 0.0, "events": 0})
            m["time_s"] += row["time_s"]
            m["events"] += row["events"]
    total = sum(r["time_s"] for r in merged["op_classes"].values())
    merged["total_attributed_s"] = round(total, 6)
    for row in merged["op_classes"].values():
        row["time_s"] = round(row["time_s"], 6)
        row["fraction"] = round(row["time_s"] / total, 4) if total else 0.0
    return merged


def attribute_profile(logdir: str, critical: dict | None = None,
                      cost: dict | None = None,
                      mfu_value: float | None = None,
                      device_kind: str | None = None) -> dict:
    """The merged perf-observatory artifact for one capture.

    ``critical`` is an ``analysis.traces.critical_path_report`` result
    (host-phase surface), ``cost`` a ``telemetry.profiler.compiled_cost``
    result; both optional — whatever is absent is reported absent.
    Reconciliation: span-level step wall vs profiler-attributed time,
    residual = wall - attributed, clamped at 0 and REPORTED.
    """
    from ..telemetry.profiler import find_profile_dumps
    paths = find_profile_dumps(logdir)
    tables = []
    errors = []
    for p in paths:
        try:
            tables.append(device_time_tables(load_chrome_trace(p)))
        except (OSError, ValueError, json.JSONDecodeError) as e:
            errors.append({"file": os.path.basename(p), "error": str(e)})
    profile = _merge_tables(tables) if tables else {
        "basis": "none", "device_lanes_present": False, "lanes": [],
        "op_classes": {}, "total_attributed_s": 0.0, "trace_wall_s": 0.0,
    }
    out: dict = {
        "profile": profile,
        "trace_files": [os.path.basename(p) for p in paths],
        "parse_errors": errors,
    }
    if device_kind is not None:
        out["device_kind"] = device_kind
    if cost is not None:
        out["cost"] = dict(cost)
        out["cost"]["mfu"] = mfu_value
    if critical is not None:
        out["critical_path"] = critical
        wall = float(sum((critical.get("phase_totals_s") or {}).values()))
        step_wall = critical.get("step_wall_total_s")
        if step_wall is None:  # older report shape: top-N lower bound
            step_wall = sum(s.get("wall_s", 0.0)
                            for s in critical.get("stragglers") or [])
        step_wall = float(step_wall)
        attributed = profile["total_attributed_s"]
        out["reconciliation"] = {
            "step_wall_s": round(step_wall, 6),
            "phase_covered_s": round(wall, 6),
            "attributed_s": round(attributed, 6),
            "attribution_basis": profile.get("basis", "none"),
            "residual_s": round(max(0.0, step_wall - attributed), 6),
            "residual_fraction": round(
                max(0.0, step_wall - attributed) / step_wall, 4)
            if step_wall > 0 else None,
        }
    return out


def _inner_profile(artifact: dict) -> dict:
    """The per-op-class table inside a recorded artifact — accepts a
    full ``attribute_profile`` result, a ``PROFILE_*.json`` ledger
    record (same nesting), or the bare inner profile dict."""
    if not isinstance(artifact, dict):
        return {}
    prof = artifact.get("profile")
    return prof if isinstance(prof, dict) else artifact


def diff_profiles(a: dict, b: dict,
                  unchanged_tolerance: float = 0.01) -> dict:
    """Per-op-class delta between two attribution artifacts (``a`` the
    baseline, ``b`` the candidate) — the before/after table every
    kernel PR cites.

    HONEST-BASIS RULE: artifacts attributed on different bases
    (``device_lanes`` vs ``host_ops`` vs ``host_execute_proxy``) are
    not comparable — a host-proxy number against real device lanes
    would manufacture a regression out of methodology — so a basis
    mismatch raises ``ValueError`` instead of producing a table.
    Classes present on one side only are reported as ``new`` /
    ``vanished``; a class whose time moved less than
    ``unchanged_tolerance`` (relative) is ``unchanged``. Reconciliation
    residuals diff too, when both sides carry them.
    """
    pa, pb = _inner_profile(a), _inner_profile(b)
    basis_a = pa.get("basis", "none")
    basis_b = pb.get("basis", "none")
    if basis_a != basis_b:
        raise ValueError(
            f"attribution basis mismatch: baseline={basis_a!r} vs "
            f"candidate={basis_b!r} — these artifacts measure different "
            f"things and cannot be diffed honestly")
    ops_a = pa.get("op_classes") or {}
    ops_b = pb.get("op_classes") or {}
    rows = {}
    for cls in sorted(set(ops_a) | set(ops_b)):
        ta = float((ops_a.get(cls) or {}).get("time_s") or 0.0)
        tb = float((ops_b.get(cls) or {}).get("time_s") or 0.0)
        if cls not in ops_a:
            status = "new"
        elif cls not in ops_b:
            status = "vanished"
        elif ta > 0 and abs(tb - ta) / ta <= unchanged_tolerance:
            status = "unchanged"
        else:
            status = "changed"
        rows[cls] = {
            "baseline_s": round(ta, 6),
            "candidate_s": round(tb, 6),
            "delta_s": round(tb - ta, 6),
            "ratio": round(tb / ta, 4) if ta > 0 else None,
            "baseline_fraction": (ops_a.get(cls) or {}).get("fraction"),
            "candidate_fraction": (ops_b.get(cls) or {}).get("fraction"),
            "status": status,
        }
    total_a = float(pa.get("total_attributed_s") or 0.0)
    total_b = float(pb.get("total_attributed_s") or 0.0)
    out = {
        "basis": basis_a,
        "op_classes": rows,
        "total_baseline_s": round(total_a, 6),
        "total_candidate_s": round(total_b, 6),
        "total_delta_s": round(total_b - total_a, 6),
        "new_classes": sorted(c for c, r in rows.items()
                              if r["status"] == "new"),
        "vanished_classes": sorted(c for c, r in rows.items()
                                   if r["status"] == "vanished"),
    }
    rec_a = (a or {}).get("reconciliation") if isinstance(a, dict) else None
    rec_b = (b or {}).get("reconciliation") if isinstance(b, dict) else None
    if isinstance(rec_a, dict) and isinstance(rec_b, dict):
        ra = float(rec_a.get("residual_s") or 0.0)
        rb = float(rec_b.get("residual_s") or 0.0)
        out["residual"] = {
            "baseline_s": round(ra, 6),
            "candidate_s": round(rb, 6),
            "delta_s": round(rb - ra, 6),
        }
    return out


def render_profile_diff(diff: dict) -> str:
    """Human-readable delta table for ``cli perf diff`` — slowest-moving
    class first, so the regression's culprit is the top row."""
    lines = [f"attribution basis: {diff.get('basis', 'none')} "
             f"(both artifacts)"]
    rows = sorted((diff.get("op_classes") or {}).items(),
                  key=lambda kv: -abs(kv[1]["delta_s"]))
    if rows:
        lines.append(f"{'op class':<15} {'baseline_s':>12} "
                     f"{'candidate_s':>12} {'delta_s':>11} {'ratio':>7} "
                     f"{'status':>10}")
        for cls, r in rows:
            ratio = "-" if r["ratio"] is None else f"{r['ratio']:.2f}x"
            lines.append(f"{cls:<15} {r['baseline_s']:>12.6f} "
                         f"{r['candidate_s']:>12.6f} "
                         f"{r['delta_s']:>+11.6f} {ratio:>7} "
                         f"{r['status']:>10}")
    else:
        lines.append("(no attributed op classes on either side)")
    lines.append(f"total attributed: {diff.get('total_baseline_s', 0):.6f}s "
                 f"-> {diff.get('total_candidate_s', 0):.6f}s "
                 f"({diff.get('total_delta_s', 0):+.6f}s)")
    if diff.get("new_classes"):
        lines.append("new classes: " + ", ".join(diff["new_classes"]))
    if diff.get("vanished_classes"):
        lines.append("vanished classes: "
                     + ", ".join(diff["vanished_classes"]))
    res = diff.get("residual")
    if res:
        lines.append(f"reconciliation residual: {res['baseline_s']:.6f}s "
                     f"-> {res['candidate_s']:.6f}s "
                     f"({res['delta_s']:+.6f}s)")
    return "\n".join(lines)


def render_profile_table(report: dict) -> str:
    """Human-readable table for ``cli perf profile``."""
    lines = []
    prof = report.get("profile") or {}
    basis_text = {
        "device_lanes": "device lanes",
        "host_ops": "host op events (no device lanes in this capture)",
        "host_execute_proxy": "host-execute proxy (no device lanes or "
                              "op events in this capture)",
        "none": "none (nothing attributable)",
    }
    basis = prof.get("basis", "none")
    lines.append(f"attribution basis: {basis_text.get(basis, basis)}")
    rows = sorted((prof.get("op_classes") or {}).items(),
                  key=lambda kv: -kv[1]["time_s"])
    if rows:
        lines.append(f"{'op class':<15} {'time_s':>12} {'share':>7} "
                     f"{'events':>8}")
        for cls, r in rows:
            lines.append(f"{cls:<15} {r['time_s']:>12.6f} "
                         f"{r['fraction']*100:>6.1f}% {r['events']:>8}")
    else:
        lines.append("(no attributable events in the capture)")
    cost = report.get("cost")
    if cost:
        flops = cost.get("flops")
        by = cost.get("bytes_accessed")
        mfu_v = cost.get("mfu")
        lines.append(f"per-step cost: flops="
                     f"{'n/a' if flops is None else f'{flops:.3e}'} "
                     f"bytes={'n/a' if by is None else f'{by:.3e}'}")
        lines.append("mfu: " + ("n/a (unknown device peak)"
                                if mfu_v is None else f"{mfu_v*100:.2f}%"))
    rec = report.get("reconciliation")
    if rec:
        lines.append(
            f"reconciliation: step wall {rec['step_wall_s']:.4f}s, "
            f"attributed {rec['attributed_s']:.4f}s "
            f"({rec['attribution_basis']}), residual "
            f"{rec['residual_s']:.4f}s")
    return "\n".join(lines)
