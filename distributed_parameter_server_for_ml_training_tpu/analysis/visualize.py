"""Experiment visualization (reference: scripts/visualize_results.py).

Reads experiment JSONs (ours or the reference's recorded
``experiment_results/*.json`` — same schema) and produces the same figure
families: sync-vs-async comparison panels per worker count
(visualize_results.py:77-170), scaling analysis with log2 axes and an
ideal-speedup line (172-276), and a console summary table (278-296).
"""

from __future__ import annotations

import json
import os
from glob import glob

import numpy as np


class ExperimentVisualizer:
    def __init__(self, results_dir: str):
        self.results_dir = results_dir
        self.experiments: dict[str, dict] = {}
        for path in sorted(glob(os.path.join(results_dir, "*.json"))):
            with open(path) as f:
                rec = json.load(f)
            if "worker_metrics_aggregated" not in rec:
                continue  # manifests / convergence curves, not matrix cells
            name = rec.get("experiment_name") or os.path.splitext(
                os.path.basename(path))[0]
            self.experiments[name] = rec

    # -- helpers -------------------------------------------------------------

    @staticmethod
    def _mode_workers(rec: dict) -> tuple[str, int]:
        server = rec.get("server_metrics", {})
        mode = server.get("mode", "unknown")
        workers = server.get("total_workers") or rec.get(
            "worker_metrics_aggregated", {}).get("num_workers", 0)
        return mode, int(workers)

    @staticmethod
    def _total_time(rec: dict) -> float:
        agg = rec.get("worker_metrics_aggregated", {})
        return float(agg.get("total_training_time_seconds")
                     or rec.get("server_metrics", {}).get(
                         "total_training_time_seconds", 0.0))

    @staticmethod
    def _final_acc(rec: dict) -> float:
        return float(rec.get("worker_metrics_aggregated", {}).get(
            "average_final_accuracy", 0.0))

    # -- figures -------------------------------------------------------------

    def plot_sync_vs_async(self, out_path: str) -> None:
        """4-panel sync-vs-async comparison (visualize_results.py:77-170)."""
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        by_workers: dict[int, dict[str, dict]] = {}
        for rec in self.experiments.values():
            mode, workers = self._mode_workers(rec)
            by_workers.setdefault(workers, {})[mode] = rec

        fig, axes = plt.subplots(2, 2, figsize=(13, 9))
        counts = sorted(by_workers)
        width = 0.35
        xs = np.arange(len(counts))

        for i, (metric, title) in enumerate([
                (self._total_time, "Total training time (s)"),
                (self._final_acc, "Final accuracy")]):
            ax = axes[0, i]
            for j, mode in enumerate(["sync", "async"]):
                vals = [metric(by_workers[c][mode])
                        if mode in by_workers[c] else 0.0 for c in counts]
                ax.bar(xs + (j - 0.5) * width, vals, width, label=mode)
            ax.set_xticks(xs)
            ax.set_xticklabels([f"{c} workers" for c in counts])
            ax.set_title(title)
            ax.legend()

        ax = axes[1, 0]
        for name, rec in self.experiments.items():
            per_epoch = rec.get("worker_metrics_aggregated", {}).get(
                "per_epoch", [])
            if per_epoch:
                ax.plot([p["epoch"] for p in per_epoch],
                        [p["avg_accuracy"] for p in per_epoch],
                        "o-", label=name)
        ax.set_title("Accuracy per epoch")
        ax.set_xlabel("epoch")
        ax.legend(fontsize=7)

        ax = axes[1, 1]
        for name, rec in self.experiments.items():
            per_epoch = rec.get("worker_metrics_aggregated", {}).get(
                "per_epoch", [])
            if per_epoch:
                ax.plot([p["epoch"] for p in per_epoch],
                        [p["avg_time"] for p in per_epoch],
                        "s-", label=name)
        ax.set_title("Epoch time (s)")
        ax.set_xlabel("epoch")
        ax.legend(fontsize=7)

        fig.tight_layout()
        fig.savefig(out_path, dpi=120)
        plt.close(fig)

    def plot_scaling_analysis(self, out_path: str) -> None:
        """Scaling panels with log2 axes + ideal-speedup line
        (visualize_results.py:172-276)."""
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        series: dict[str, list[tuple[int, float]]] = {}
        for rec in self.experiments.values():
            mode, workers = self._mode_workers(rec)
            if workers:
                series.setdefault(mode, []).append(
                    (workers, self._total_time(rec)))
        for mode in series:
            series[mode].sort()

        fig, axes = plt.subplots(2, 2, figsize=(13, 9))

        ax = axes[0, 0]
        for mode, pts in series.items():
            ax.plot([w for w, _ in pts], [t for _, t in pts], "o-",
                    label=mode)
        ax.set_xscale("log", base=2)
        ax.set_title("Total time vs workers")
        ax.set_xlabel("workers")
        ax.legend()

        ax = axes[0, 1]
        for mode, pts in series.items():
            if not pts:
                continue
            w0, t0 = pts[0]
            ws = [w for w, _ in pts]
            speedup = [t0 / t if t else 0.0 for _, t in pts]
            ax.plot(ws, speedup, "o-", label=f"{mode} measured")
            ax.plot(ws, [w / w0 for w in ws], "--", label=f"{mode} ideal")
        ax.set_xscale("log", base=2)
        ax.set_yscale("log", base=2)
        ax.set_title("Speedup vs ideal")
        ax.legend()

        ax = axes[1, 0]
        for mode, pts in series.items():
            if not pts:
                continue
            w0, t0 = pts[0]
            eff = [100.0 * (t0 / t) / (w / w0) if t else 0.0
                   for w, t in pts]
            ax.plot([w for w, _ in pts], eff, "o-", label=mode)
        ax.set_xscale("log", base=2)
        ax.set_title("Scaling efficiency (%)")
        ax.axhline(100, ls="--", c="gray")
        ax.legend()

        ax = axes[1, 1]
        for rec in self.experiments.values():
            mode, workers = self._mode_workers(rec)
            ax.scatter(self._total_time(rec), self._final_acc(rec),
                       label=f"{mode}-{workers}")
        ax.set_xlabel("total time (s)")
        ax.set_ylabel("final accuracy")
        ax.set_title("Time/accuracy tradeoff")
        ax.legend(fontsize=7)

        fig.tight_layout()
        fig.savefig(out_path, dpi=120)
        plt.close(fig)

    # -- live-telemetry time-series (snapshot streams) -----------------------

    @staticmethod
    def plot_telemetry(ts_record: dict, out_path: str) -> None:
        """4-panel view of a run's snapshot stream
        (``analysis.build_telemetry_timeseries`` output): per-worker
        training throughput, wire bytes/s, the async staleness histogram,
        and store global-step progress. The live complement to the
        exit-line figures above — regenerable from any run's logs with
        ``--telemetry`` enabled (docs/OBSERVABILITY.md)."""
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        from .parse_logs import (_parse_metric_key, staleness_series,
                                 worker_throughput_series)

        fig, axes = plt.subplots(2, 2, figsize=(13, 9))

        ax = axes[0, 0]
        for label, s in sorted(worker_throughput_series(ts_record).items()):
            ax.plot(s["t"], s["steps_per_second"], "o-", ms=3, label=label)
        ax.set_title("Training throughput (steps/s)")
        ax.set_xlabel("run time (s)")
        ax.legend(fontsize=7)

        ax = axes[0, 1]
        for proc_key, proc in sorted(ts_record.get("procs", {}).items()):
            for key, rate in sorted(proc.get("rates", {}).items()):
                name, labels = _parse_metric_key(key)
                if name not in ("dps_rpc_client_bytes_total",
                                "dps_worker_push_bytes_total"):
                    continue
                tag = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
                label = f"{name.split('_bytes')[0]}[{tag}]"
                if len(ts_record["procs"]) > 1:
                    label += f" ({proc_key})"  # disambiguate across procs
                ax.plot(proc["t"][1:], [r / 1e6 for r in rate], "-",
                        label=label)
        ax.set_title("Bytes on wire (MB/s)")
        ax.set_xlabel("run time (s)")
        ax.legend(fontsize=6)

        ax = axes[1, 0]
        st = staleness_series(ts_record)
        if st["le"]:
            edges = [str(int(e)) for e in st["le"]] + ["inf"]
            ax.bar(range(len(st["counts"])), st["counts"])
            ax.set_xticks(range(len(edges)))
            ax.set_xticklabels(edges, fontsize=7)
            ax.set_xlabel("staleness (versions behind, bucket <= edge)")
        ax.set_title("Async staleness distribution")

        ax = axes[1, 1]
        for proc_key, proc in sorted(ts_record.get("procs", {}).items()):
            for key, vals in sorted(proc.get("gauges", {}).items()):
                name, labels = _parse_metric_key(key)
                if name != "dps_store_global_step":
                    continue
                ax.plot(proc["t"], vals, "s-", ms=3,
                        label=f"{labels.get('backend', '?')} ({proc_key})")
        ax.set_title("Store global step")
        ax.set_xlabel("run time (s)")
        ax.legend(fontsize=7)

        fig.tight_layout()
        fig.savefig(out_path, dpi=120)
        plt.close(fig)

    # -- cluster health (kind=cluster monitor records) -----------------------

    @staticmethod
    def plot_cluster_health(logs: str, out_path: str) -> dict:
        """4-panel cluster-health figure from a run's captured stdout
        (``serve --telemetry`` emits the ``"kind": "cluster"`` records):
        per-worker step progress and loss curves with ALERT overlays
        (vertical lines at each fired alert, colored by severity), the
        alert timeline itself (rule vs time), and per-worker examples/s.
        Returns ``{"timeline": [...], "workers": [...]}`` so callers (the
        recorded demo) can assert on what was plotted."""
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        from .parse_logs import alert_timeline, cluster_worker_series

        timeline = alert_timeline(logs)
        series = cluster_worker_series(logs)
        sev_color = {"critical": "tab:red", "warning": "tab:orange",
                     "info": "tab:blue"}
        fired = [e for e in timeline if e["state"] == "fired"]

        fig, axes = plt.subplots(2, 2, figsize=(13, 9))

        def overlay(ax):
            for e in fired:
                ax.axvline(e["t"], color=sev_color.get(e["severity"],
                                                       "gray"),
                           ls="--", lw=1, alpha=0.7)

        ax = axes[0, 0]
        for name, w in sorted(series["workers"].items()):
            ax.plot(series["t"], w["step"], "o-", ms=3, label=name)
        overlay(ax)
        ax.set_title("Worker step progress (cluster view)")
        ax.set_xlabel("run time (s)")
        ax.legend(fontsize=7)

        ax = axes[0, 1]
        for name, w in sorted(series["workers"].items()):
            ax.plot(series["t"], w["loss"], "o-", ms=3, label=name)
        overlay(ax)
        ax.set_title("Worker loss (alert overlays)")
        ax.set_xlabel("run time (s)")
        ax.legend(fontsize=7)

        ax = axes[1, 0]
        rules = sorted({e["rule"] for e in timeline})
        ridx = {r: i for i, r in enumerate(rules)}
        marks = {"fired": "o", "refired": "s", "resolved": "x"}
        for e in timeline:
            ax.scatter(e["t"], ridx[e["rule"]],
                       marker=marks.get(e["state"], "."),
                       color=sev_color.get(e["severity"], "gray"), s=60)
        ax.set_yticks(range(len(rules)))
        ax.set_yticklabels(rules, fontsize=8)
        ax.set_title("Alert timeline (o fired, s refired, x resolved)")
        ax.set_xlabel("run time (s)")

        ax = axes[1, 1]
        for name, w in sorted(series["workers"].items()):
            ax.plot(series["t"], w["examples_per_s"], "o-", ms=3,
                    label=name)
        ax.set_title("Worker throughput (examples/s, reported)")
        ax.set_xlabel("run time (s)")
        ax.legend(fontsize=7)

        fig.tight_layout()
        fig.savefig(out_path, dpi=120)
        plt.close(fig)
        return {"timeline": timeline,
                "workers": sorted(series["workers"])}

    def summary_table(self) -> str:
        """Console summary (visualize_results.py:278-296)."""
        lines = [f"{'experiment':<28}{'mode':<8}{'workers':>8}"
                 f"{'time(s)':>12}{'final acc':>12}",
                 "-" * 68]
        for name, rec in sorted(self.experiments.items()):
            mode, workers = self._mode_workers(rec)
            lines.append(f"{name:<28}{mode:<8}{workers:>8}"
                         f"{self._total_time(rec):>12.1f}"
                         f"{self._final_acc(rec):>12.4f}")
        return "\n".join(lines)
