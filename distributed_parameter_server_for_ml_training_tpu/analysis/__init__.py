"""Experiment/analysis layer (reference L5: scripts/)."""

from .parse_logs import (
    aggregate_worker_metrics,
    alert_timeline,
    build_telemetry_timeseries,
    cluster_worker_series,
    parse_cluster_series,
    parse_experiment,
    parse_snapshot_series,
    staleness_series,
    worker_throughput_series,
)
from .device_profile import (
    OP_CLASSES,
    attribute_profile,
    classify_op,
    device_time_tables,
    diff_profiles,
    load_chrome_trace,
    render_profile_diff,
    render_profile_table,
)
from .fleet_series import extract_exemplars, resolve_exemplars
from .incidents import (
    PHASE_ORDER,
    build_timeline,
    classify_event,
    describe_event,
    list_incidents,
    load_incident,
    render_timeline,
)
from .runner import run_cell, run_matrix
from .traces import (
    PHASES,
    assemble_traces,
    critical_path_report,
    find_trace_dumps,
    load_trace_dumps,
    save_chrome_trace,
    to_chrome_trace,
)
from .visualize import ExperimentVisualizer

__all__ = ["OP_CLASSES", "PHASES", "PHASE_ORDER",
           "aggregate_worker_metrics", "alert_timeline",
           "assemble_traces", "attribute_profile",
           "build_telemetry_timeseries", "build_timeline",
           "classify_event", "classify_op",
           "cluster_worker_series",
           "critical_path_report", "describe_event",
           "device_time_tables", "diff_profiles",
           "extract_exemplars",
           "list_incidents", "load_incident", "render_timeline",
           "find_trace_dumps", "load_chrome_trace", "load_trace_dumps",
           "resolve_exemplars",
           "parse_cluster_series",
           "parse_experiment", "parse_snapshot_series",
           "render_profile_diff", "render_profile_table",
           "save_chrome_trace", "staleness_series", "to_chrome_trace",
           "worker_throughput_series",
           "ExperimentVisualizer", "run_cell", "run_matrix"]
