"""Experiment/analysis layer (reference L5: scripts/)."""

from .parse_logs import (
    aggregate_worker_metrics,
    build_telemetry_timeseries,
    parse_experiment,
    parse_snapshot_series,
    staleness_series,
    worker_throughput_series,
)
from .runner import run_cell, run_matrix
from .visualize import ExperimentVisualizer

__all__ = ["aggregate_worker_metrics", "build_telemetry_timeseries",
           "parse_experiment", "parse_snapshot_series", "staleness_series",
           "worker_throughput_series",
           "ExperimentVisualizer", "run_cell", "run_matrix"]
