"""Experiment/analysis layer (reference L5: scripts/)."""

from .parse_logs import aggregate_worker_metrics, parse_experiment
from .runner import run_cell, run_matrix
from .visualize import ExperimentVisualizer

__all__ = ["aggregate_worker_metrics", "parse_experiment",
           "ExperimentVisualizer", "run_cell", "run_matrix"]
