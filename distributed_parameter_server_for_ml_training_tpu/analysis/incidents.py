"""Causal postmortem timelines from the durable telemetry journal.

``cli incident report`` answers the morning-after question — *what
happened, in what order, and did the system heal itself?* — from disk
alone: every live process may be gone. This module is the pure
(dicts-in, dicts-out, dependency-free) engine behind it:

- :func:`load_incident` reads a frozen ``incidents/<id>/`` bundle and
  merges its ``journal_window.jsonl`` with the live journal directory
  named in the manifest — the window ends at the capture edge, but the
  remediation and resolution that FOLLOW the edge live in the journal's
  later segments, and a postmortem needs the whole arc.
- :func:`build_timeline` joins the merged records across processes by
  time (and worker/rule/shard identity) into an ordered
  fault → alert → remediation → resolution narrative, with per-phase
  first-arrival stamps and an ``ordered`` verdict (did causality run
  the right way?).
- :func:`render_timeline` formats it for humans; the dict shape is the
  JSON form.

Timeline phases (:data:`PHASE_ORDER`): a ``fault`` record marks the
seeded/observed root cause; ``alert``/``slo_burn`` fired edges are the
detection; ``remediation``/``respawn``/``directive`` the response;
``alert`` resolved edges the resolution. Everything else journaled
(checkpoints, migrations, re-parents, incident captures) rides along as
``context`` — present in the narrative, not in the causal verdict.
"""

from __future__ import annotations

import json
import os

from ..telemetry.journal import JournalReader

__all__ = [
    "PHASE_ORDER",
    "build_timeline",
    "classify_event",
    "describe_event",
    "list_incidents",
    "load_incident",
    "render_timeline",
]

#: Causal phases in the order a healthy self-healing arc visits them.
PHASE_ORDER = ("fault", "alert", "remediation", "resolution")

#: Journal types that never enter the timeline (dense metric samples).
_SERIES_TYPES = ("snapshot", "fleet_tick")


def classify_event(rec: dict) -> str | None:
    """Phase for one journal record; ``"context"`` for narrative-only
    types, ``None`` for dense series records."""
    t = rec.get("type")
    if t in _SERIES_TYPES:
        return None
    if t == "fault":
        return "fault"
    if t == "alert":
        return ("resolution" if rec.get("state") == "resolved"
                else "alert")
    if t == "slo_burn":
        return "alert"
    if t in ("remediation", "respawn", "directive"):
        return "remediation"
    return "context"


def describe_event(rec: dict) -> str:
    """One human line for a timeline record."""
    t = rec.get("type")
    if t == "fault":
        return f"fault plan armed: {rec.get('spec')!r}"
    if t == "alert":
        return (f"{rec.get('state')} {rec.get('rule')} "
                f"[{rec.get('severity')}]"
                + (f" worker={rec.get('worker')}"
                   if rec.get("worker") is not None else "")
                + (f" value={rec.get('value')}"
                   if rec.get("value") is not None else ""))
    if t == "slo_burn":
        return (f"SLO burn {rec.get('rule')} {rec.get('objective')} "
                f"burn={rec.get('burn')} "
                f"(threshold {rec.get('burn_threshold')})")
    if t in ("remediation", "respawn"):
        return f"{rec.get('action')} -> {rec.get('outcome')}"
    if t == "directive":
        return (f"directive {rec.get('action')} -> worker "
                f"{rec.get('worker')} (seq {rec.get('seq')})")
    if t == "migration":
        return (f"migration {rec.get('id')} phase={rec.get('phase')} "
                f"role={rec.get('mig_role')}")
    if t == "reparent":
        return (f"replica shard {rec.get('shard')} reparented "
                f"{rec.get('old')} -> {rec.get('new')}")
    if t == "checkpoint":
        return f"checkpoint step {rec.get('step')} -> {rec.get('path')}"
    if t == "incident":
        return f"incident bundle {rec.get('id')} frozen"
    return json.dumps({k: v for k, v in rec.items()
                       if k not in ("v", "seq")}, default=str)


def build_timeline(records: list) -> dict:
    """The ordered cross-process narrative over merged journal records.

    Returns ``{"events", "phases", "span", "counts", "ordered",
    "workers"}``: events sorted by ``(ts, pid, seq)`` each carrying
    ``phase``/``rel_s``/``summary``; ``phases`` maps each causal phase
    present to its first/last arrival and count; ``ordered`` is True
    when the first arrivals of the present causal phases respect
    :data:`PHASE_ORDER`; ``workers`` groups event indices by worker
    identity for per-actor reading."""
    rows = []
    for rec in records:
        phase = classify_event(rec)
        if phase is None:
            continue
        rows.append((rec, phase))
    rows.sort(key=lambda rp: (rp[0].get("ts", 0.0),
                              rp[0].get("pid", 0),
                              rp[0].get("seq", 0)))
    t0 = rows[0][0].get("ts", 0.0) if rows else 0.0
    events = []
    phases: dict = {}
    counts: dict = {}
    workers: dict = {}
    for i, (rec, phase) in enumerate(rows):
        ts = rec.get("ts", 0.0)
        ev = {
            "ts": ts,
            "rel_s": round(ts - t0, 3),
            "phase": phase,
            "type": rec.get("type"),
            "role": rec.get("role"),
            "pid": rec.get("pid"),
            "summary": describe_event(rec),
        }
        for key in ("worker", "rule", "shard", "action", "state"):
            if rec.get(key) is not None:
                ev[key] = rec[key]
        events.append(ev)
        counts[ev["type"]] = counts.get(ev["type"], 0) + 1
        if phase in PHASE_ORDER:
            row = phases.setdefault(phase, {"first_ts": ts,
                                            "last_ts": ts, "count": 0})
            row["first_ts"] = min(row["first_ts"], ts)
            row["last_ts"] = max(row["last_ts"], ts)
            row["count"] += 1
        if ev.get("worker") is not None:
            workers.setdefault(str(ev["worker"]), []).append(i)
    firsts = [phases[p]["first_ts"] for p in PHASE_ORDER if p in phases]
    ordered = all(a <= b for a, b in zip(firsts, firsts[1:]))
    span = {"start_ts": t0,
            "end_ts": rows[-1][0].get("ts", 0.0) if rows else 0.0}
    return {"events": events, "phases": phases, "span": span,
            "counts": counts, "ordered": ordered, "workers": workers}


def render_timeline(timeline: dict, manifest: dict | None = None) -> str:
    """Human rendering: header, phase ledger, then the event log."""
    lines = []
    if manifest:
        lines.append(f"incident {manifest.get('id')} — trigger "
                     f"{(manifest.get('trigger') or {}).get('rule')} "
                     f"[{(manifest.get('trigger') or {}).get('severity')}]")
    span = timeline["span"]
    dur = span["end_ts"] - span["start_ts"]
    lines.append(f"{len(timeline['events'])} events over {dur:.1f}s — "
                 f"causal order "
                 f"{'OK' if timeline['ordered'] else 'VIOLATED'}")
    for phase in PHASE_ORDER:
        row = timeline["phases"].get(phase)
        if row is None:
            lines.append(f"  {phase:<12} -")
            continue
        lines.append(f"  {phase:<12} first +"
                     f"{row['first_ts'] - span['start_ts']:.2f}s "
                     f"x{row['count']}")
    lines.append("")
    for ev in timeline["events"]:
        who = f"{ev.get('role')}/{ev.get('pid')}"
        lines.append(f"  +{ev['rel_s']:8.2f}s  [{ev['phase']:<11}] "
                     f"{who:<16} {ev['summary']}")
    return "\n".join(lines)


def load_incident(bundle_dir: str, journal_dir: str | None = None
                  ) -> dict:
    """One frozen bundle + the journal's post-edge continuation.

    ``journal_dir`` overrides the manifest's recorded directory (the
    bundle may have moved hosts). Records are deduped by
    ``(role, pid, seq)`` — the frozen window and the live journal
    overlap by construction."""
    with open(os.path.join(bundle_dir, "manifest.json"),
              encoding="utf-8") as f:
        manifest = json.load(f)
    records = []
    stats: dict = {}
    window = os.path.join(bundle_dir, "journal_window.jsonl")
    if os.path.exists(window):
        reader = JournalReader(window)
        records.extend(reader.records())
        stats["window"] = dict(reader.stats)
    jdir = journal_dir or manifest.get("journal_dir")
    if jdir and os.path.isdir(jdir):
        reader = JournalReader(jdir)
        records.extend(reader.records())
        stats["journal"] = dict(reader.stats)
    seen = set()
    deduped = []
    for rec in sorted(records, key=lambda r: (r.get("ts", 0.0),
                                              r.get("pid", 0),
                                              r.get("seq", 0))):
        key = (rec.get("role"), rec.get("pid"), rec.get("seq"))
        if key in seen:
            continue
        seen.add(key)
        deduped.append(rec)
    return {"manifest": manifest, "records": deduped, "stats": stats}


def list_incidents(incidents_dir: str) -> list:
    """Manifest rows for every bundle under ``incidents_dir``, oldest
    first; unreadable bundles are reported, not fatal."""
    out = []
    try:
        names = sorted(os.listdir(incidents_dir))
    except OSError:
        return out
    for name in names:
        bundle = os.path.join(incidents_dir, name)
        manifest_path = os.path.join(bundle, "manifest.json")
        if not os.path.isfile(manifest_path):
            continue
        try:
            with open(manifest_path, encoding="utf-8") as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            out.append({"id": name, "path": bundle,
                        "error": repr(e)})
            continue
        manifest["path"] = bundle
        out.append(manifest)
    return out
