"""Experiment matrix runner: the reference's §6 tables, in-process.

The reference produced its sync/async x {4,8,16} worker results by deploying
Fargate clusters per cell (EXPERIMENT_GUIDE.md:95-111) and scraping
CloudWatch. Here one process runs the full matrix: each cell is a
ParameterStore (sync or async aggregation) + N worker threads sharing the
accelerator, and the output is one experiment JSON per cell in the recorded
``experiment_results/*.json`` schema, plus the comparison/scaling figures.

(The SPMD sync path is the *performance* story and is benchmarked by
bench.py; this runner exists to reproduce the reference's experiment
semantics — logical workers, staleness, aggregated metrics — at any worker
count on any device count.)
"""

from __future__ import annotations

import json
import os

import jax
import numpy as np

from ..data.cifar import Dataset
from ..models import ResNet18
from ..ps.store import ParameterStore, StoreConfig
from ..ps.worker import WorkerConfig, run_workers
from ..utils.pytree import flatten_params
from .parse_logs import aggregate_worker_metrics


def run_cell(dataset: Dataset, mode: str, n_workers: int, *,
             epochs: int = 3, batch_size: int = 128, lr: float = 0.1,
             staleness_bound: int = 5, num_classes: int = 100,
             model=None, seed: int = 0, backend: str = "python",
             augment: bool = True) -> dict:
    """One experiment cell -> experiment record (reference JSON schema)."""
    import jax.numpy as jnp

    model = model or ResNet18(num_classes=num_classes, dtype=jnp.bfloat16)
    variables = model.init(jax.random.PRNGKey(seed),
                           np.zeros((1, 32, 32, 3), np.float32), train=False)
    flat = flatten_params(variables["params"])
    cfg = StoreConfig(mode=mode, total_workers=n_workers, learning_rate=lr,
                      staleness_bound=staleness_bound)
    # 'device' keeps tensors in HBM — the only backend that runs
    # reference-scale cells at full speed on a remote-attached TPU (the
    # ~3 MB/s tunnel would otherwise move ~90 MB per worker step).
    from ..ps import make_store
    store = make_store(backend, flat, cfg)

    results = run_workers(
        store, model, dataset, n_workers,
        WorkerConfig(batch_size=batch_size, num_epochs=epochs,
                     augment=augment, seed=seed))
    wc = WorkerConfig(batch_size=batch_size, num_epochs=epochs)
    worker_dicts = [r.metrics(n_workers, lr, wc) for r in results]
    return {
        "experiment_name": f"{mode}_{n_workers}workers",
        # Provenance: the reference's records came from real CIFAR-100 on
        # Fargate; ours must say what data (and device) produced them.
        "dataset": {
            "synthetic": bool(dataset.synthetic),
            "num_classes": int(dataset.num_classes),
            "n_train": int(len(dataset.x_train)),
            "n_test": int(len(dataset.x_test)),
        },
        "device": str(jax.devices()[0]),
        "server_metrics": store.metrics(),
        "worker_metrics_aggregated": aggregate_worker_metrics(worker_dicts),
        "raw_worker_metrics": worker_dicts,
    }


def run_matrix(dataset: Dataset, out_dir: str, *,
               modes=("sync", "async"), worker_counts=(4, 8),
               epochs: int = 3, batch_size: int = 128, lr: float = 0.1,
               num_classes: int = 100, backend: str = "python",
               plots: bool = True, **cell_kw) -> list[dict]:
    os.makedirs(out_dir, exist_ok=True)
    records = []
    for mode in modes:
        for n in worker_counts:
            print(f"=== cell: {mode} x {n} workers ===", flush=True)
            rec = run_cell(dataset, mode, n, epochs=epochs,
                           batch_size=batch_size, lr=lr,
                           num_classes=num_classes, backend=backend,
                           **cell_kw)
            records.append(rec)
            path = os.path.join(out_dir, rec["experiment_name"] + ".json")
            with open(path, "w") as f:
                json.dump(rec, f, indent=2)
            agg = rec["worker_metrics_aggregated"]
            print(f"    total {agg['total_training_time_seconds']:.1f}s, "
                  f"final acc {agg['average_final_accuracy']:.4f}")
    if plots:
        from .visualize import ExperimentVisualizer
        viz = ExperimentVisualizer(out_dir)
        viz.plot_sync_vs_async(os.path.join(out_dir, "sync_vs_async.png"))
        viz.plot_scaling_analysis(os.path.join(out_dir, "scaling.png"))
        print(viz.summary_table())
    return records
