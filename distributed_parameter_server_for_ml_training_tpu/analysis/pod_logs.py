"""Pod log ingestion: METRICS_JSON lines off a TPU pod -> experiment JSON.

The reference closes its L5 loop remotely: parse_cloudwatch_logs.py:34-60
discovers log groups from ``terraform output -json`` and shells out to
``aws logs filter-log-events`` to pull METRICS_JSON lines. The TPU-native
mirror:

- discovery: ``terraform output -json`` on deploy/terraform (pod_name /
  pod_zone outputs), or explicit --name/--zone,
- collection: ``gcloud compute tpus tpu-vm ssh --worker=all`` cat of the
  ``~/dps_train.log`` each host teed during ``tpu-pod.sh train``,
- aggregation: the same parse_experiment ETL used for local logs
  (analysis/parse_logs.py), writing a reference-schema experiment JSON.

One command turns a ``tpu-pod.sh train`` run into an experiment record:

    dps-tpu experiments ingest-pod --tf-dir deploy/terraform \
        --experiment-name pod_sync --out results/pod_sync.json

All shell-outs go through an injectable ``runner`` so the pipeline is
testable without gcloud/terraform on the box (tests/test_analysis.py).
"""

from __future__ import annotations

import json
import os
import subprocess
from typing import Callable

from .parse_logs import parse_experiment

Runner = Callable[[list[str]], str]


def _default_runner(cmd: list[str]) -> str:
    """Run ``cmd`` and return stdout; raises CalledProcessError on failure
    with stderr attached (surfaced to the CLI user)."""
    proc = subprocess.run(cmd, check=True, capture_output=True, text=True)
    return proc.stdout


def discover_pod(tf_dir: str, runner: Runner = _default_runner) -> dict:
    """Pod identity from the IaC state (parse_cloudwatch_logs.py:34-60's
    discovery, against deploy/terraform's pod_name/pod_zone outputs)."""
    out = runner(["terraform", f"-chdir={tf_dir}", "output", "-json"])
    values = json.loads(out)
    try:
        return {"name": values["pod_name"]["value"],
                "zone": values["pod_zone"]["value"]}
    except KeyError as e:
        raise KeyError(
            f"terraform output missing {e} — is deploy/terraform applied "
            f"(outputs pod_name/pod_zone)?") from e


def collect_pod_logs(name: str, zone: str,
                     log_path: str = "~/dps_train.log",
                     runner: Runner = _default_runner) -> str:
    """ssh-cat every host's teed training log (``--worker=all`` streams
    all hosts' output back concatenated — exactly what the METRICS_JSON
    regex parser wants)."""
    return runner([
        "gcloud", "compute", "tpus", "tpu-vm", "ssh", name,
        "--zone", zone, "--worker=all",
        "--command", f"cat {log_path}",
    ])


def ingest_pod(experiment_name: str,
               name: str | None = None, zone: str | None = None,
               tf_dir: str | None = None,
               log_path: str = "~/dps_train.log",
               out_path: str | None = None,
               runner: Runner = _default_runner) -> dict:
    """Discover (unless name+zone given) -> collect -> aggregate -> write.

    Returns the experiment record (reference schema, like
    experiments/results/*.json)."""
    if name is None or zone is None:
        if tf_dir is None:
            raise ValueError("need --name/--zone or --tf-dir to discover")
        pod = discover_pod(tf_dir, runner)
        # Explicit values override discovery INDIVIDUALLY (e.g. --pod-name
        # with the zone discovered from the IaC state).
        name = name if name is not None else pod["name"]
        zone = zone if zone is not None else pod["zone"]
    logs = collect_pod_logs(name, zone, log_path, runner)
    record = parse_experiment(logs, experiment_name)
    record["source"] = {"pod_name": name, "pod_zone": zone,
                        "log_path": log_path}
    if out_path:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
    return record
