"""Fleet-series analysis: joining ``/fleet`` rollups to flight recorders.

The fleet observatory (telemetry/fleet.py) attaches head-sampled trace
exemplars to the serve-path latency histograms it merges, so a fleet
p99 spike is not just a number — it carries the trace ids of recent
requests that actually landed in the slow buckets. This module closes
the loop: extract those exemplars from a ``/fleet`` snapshot and
resolve them against flight-recorder dumps (``trace-*.json``,
analysis/traces.py conventions) into assembled trace trees, so "the
fleet p99 jumped at 14:02" becomes "…and here is the worker step /
RPC handler tree of a request that was slow".

Offline and dependency-free (pure dicts in, dicts out): runs in the
same environments as the rest of ``analysis/``.
"""

from __future__ import annotations

from .traces import assemble_traces, find_trace_dumps, load_trace_dumps

__all__ = ["extract_exemplars", "resolve_exemplars"]


def extract_exemplars(fleet_view: dict, min_value_s: float = 0.0,
                      series_prefix: str | None = None) -> list[dict]:
    """Flatten every histogram exemplar in a ``/fleet`` snapshot.

    Returns rows ``{"series", "bucket", "le", "trace_id", "value",
    "ts"}`` sorted slowest-first — the head of the list is what a p99
    investigation wants. ``min_value_s`` keeps only exemplars at or
    above a latency floor (e.g. the SLO threshold); ``series_prefix``
    restricts to one histogram family (``"dps_rpc_server_latency"``).
    """
    rows: list[dict] = []
    hists = (fleet_view.get("rollups") or {}).get("histograms") or {}
    for series, snap in hists.items():
        if series_prefix is not None \
                and not series.startswith(series_prefix):
            continue
        edges = snap.get("le") or []
        for idx_s, ex in (snap.get("exemplars") or {}).items():
            try:
                idx = int(idx_s)
            except (TypeError, ValueError):
                continue
            value = float(ex.get("value", 0.0))
            if value < min_value_s:
                continue
            rows.append({
                "series": series,
                "bucket": idx,
                "le": (edges[idx] if 0 <= idx < len(edges) else None),
                "trace_id": ex.get("trace_id"),
                "value": value,
                "ts": ex.get("ts"),
            })
    rows.sort(key=lambda r: -r["value"])
    return rows


def resolve_exemplars(fleet_view: dict, dump_dir: str | None = None,
                      dump_paths: list | None = None,
                      min_value_s: float = 0.0,
                      series_prefix: str | None = None) -> dict:
    """Join a snapshot's exemplars against flight-recorder dumps.

    Loads every ``trace-*.json`` under ``dump_dir`` (and/or the explicit
    ``dump_paths``), assembles the spans into per-trace trees, and marks
    each exemplar resolved when its trace id has at least one recorded
    span. Returns::

        {"exemplars": [row + {"resolved", "span_count"}],
         "resolved": n, "unresolved": n,
         "traces": {trace_id: assembled-trace}}   # resolved ones only

    Unresolved exemplars are expected in steady state — the recorder is
    a bounded ring, so only exemplars recent enough to still be in some
    process's buffer (or in a dump taken near the spike) resolve. The
    slowest-resolved exemplar's tree is the one to read first.
    """
    paths = list(dump_paths or [])
    if dump_dir is not None:
        paths.extend(find_trace_dumps(dump_dir))
    spans = load_trace_dumps(dict.fromkeys(paths)) if paths else []
    assembled = assemble_traces(spans) if spans else {"traces": []}
    by_trace = {t["trace_id"]: t for t in assembled["traces"]}
    rows = extract_exemplars(fleet_view, min_value_s=min_value_s,
                             series_prefix=series_prefix)
    resolved_traces: dict[str, dict] = {}
    n_resolved = 0
    for row in rows:
        t = by_trace.get(row["trace_id"])
        row["resolved"] = t is not None
        row["span_count"] = 0 if t is None else t["span_count"]
        if t is not None:
            n_resolved += 1
            resolved_traces[row["trace_id"]] = t
    return {"exemplars": rows, "resolved": n_resolved,
            "unresolved": len(rows) - n_resolved,
            "traces": resolved_traces}
