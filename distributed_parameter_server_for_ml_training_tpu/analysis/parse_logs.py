"""Log -> experiment-JSON ETL (reference: scripts/parse_cloudwatch_logs.py).

The reference shells out to ``aws logs filter-log-events`` and regex-extracts
``METRICS_JSON:`` lines (parse_cloudwatch_logs.py:61-121). Here logs are
local files or strings (there is no CloudWatch in the loop), but the
aggregation semantics are reproduced exactly
(parse_cloudwatch_logs.py:125-177):

- server metrics pass through,
- worker totals: MAX total time across workers (the slowest worker defines
  the run), MEAN epoch time, MEAN final accuracy,
- per-epoch: max/avg/min across workers,
- raw per-worker records preserved under ``raw_worker_metrics``.

Output schema matches ``experiment_results/*.json`` (e.g.
sync_4workers.json) so the visualizer — ours or the reference's — can read
either's files.
"""

from __future__ import annotations

import json
import os
from typing import Iterable

import numpy as np

from ..utils.metrics import parse_metrics_lines


def _is_worker(m: dict) -> bool:
    return "worker_id" in m


def _is_snapshot(m: dict) -> bool:
    """Live-telemetry snapshot lines (telemetry/snapshot.py) share the
    METRICS_JSON wire convention but are a different record kind: they
    carry ``"kind": "snapshot"`` and must not enter the final-stats
    aggregation (the reference schema has exactly one exit record per
    process)."""
    return m.get("kind") == "snapshot"


def _is_cluster(m: dict) -> bool:
    """Cluster-monitor records (telemetry/cluster.py ``"kind": "cluster"``)
    — same wire convention, same exclusion from the final aggregation."""
    return m.get("kind") == "cluster"


def aggregate_worker_metrics(workers: list[dict]) -> dict:
    """parse_cloudwatch_logs.py:125-177 semantics."""
    if not workers:
        return {}
    total_times = [w.get("total_training_time_seconds", 0.0) for w in workers]
    epoch_means = [w.get("average_epoch_time_seconds", 0.0) for w in workers]
    final_accs = [w.get("final_test_accuracy", 0.0) for w in workers]

    n_epochs = max((len(w.get("epoch_times_seconds", [])) for w in workers),
                   default=0)
    per_epoch = []
    for e in range(n_epochs):
        times = [w["epoch_times_seconds"][e] for w in workers
                 if len(w.get("epoch_times_seconds", [])) > e]
        accs = [w["all_test_accuracies"][e] for w in workers
                if len(w.get("all_test_accuracies", [])) > e]
        row = {
            "epoch": e + 1,
            "max_time": float(np.max(times)) if times else 0.0,
            "avg_time": float(np.mean(times)) if times else 0.0,
            "min_time": float(np.min(times)) if times else 0.0,
            "max_accuracy": float(np.max(accs)) if accs else 0.0,
            "avg_accuracy": float(np.mean(accs)) if accs else 0.0,
            "min_accuracy": float(np.min(accs)) if accs else 0.0,
        }
        # Measured per-slot training metrics (SPMD sync rows): unlike the
        # time/test-accuracy fields above — which sync workers share by
        # construction — these genuinely differ per worker.
        for field, label in (("train_loss_per_epoch", "train_loss"),
                             ("train_accuracy_per_epoch",
                              "train_accuracy")):
            vals = [w[field][e] for w in workers
                    if len(w.get(field, [])) > e]
            if vals:
                row.update({f"max_{label}": float(np.max(vals)),
                            f"avg_{label}": float(np.mean(vals)),
                            f"min_{label}": float(np.min(vals))})
        per_epoch.append(row)

    out = {
        "num_workers": len(workers),
        # the slowest worker defines the run's wall clock
        "total_training_time_seconds": float(np.max(total_times)),
        "average_epoch_time_seconds": float(np.mean(epoch_means)),
        "average_final_accuracy": float(np.mean(final_accs)),
        "per_epoch": per_epoch,
    }
    # Surface the measured-vs-derived distinction (round-4 VERDICT item
    # 10): SPMD sync rows mark which fields were measured per worker and
    # that the rest are one shared model/program measurement.
    measured = sorted({f for w in workers
                       for f in w.get("measured_per_worker_fields", [])})
    if measured:
        out["measured_per_worker_fields"] = measured
    if any(w.get("shared_model_metrics") for w in workers):
        out["shared_model_metrics"] = True
    return out


def parse_experiment(logs: str | Iterable[str],
                     experiment_name: str = "experiment") -> dict:
    """Full log text (possibly many processes' stdout) -> experiment record."""
    metrics = [m for m in parse_metrics_lines(logs)
               if not _is_snapshot(m) and not _is_cluster(m)]
    server = next((m for m in metrics
                   if not _is_worker(m) and "mode" in m), None)
    workers = [m for m in metrics if _is_worker(m)]
    return {
        "experiment_name": experiment_name,
        "server_metrics": server or {},
        "worker_metrics_aggregated": aggregate_worker_metrics(workers),
        "raw_worker_metrics": workers,
    }


# ---------------------------------------------------------------------------
# Live-telemetry snapshot streams (telemetry/snapshot.py) -> time-series.
#
# Snapshots are CUMULATIVE registry dumps on a fixed interval; rates are
# derived here from consecutive-snapshot deltas. A run's interleaved stdout
# (many processes tee into one log) demultiplexes on (role, pid).
# ---------------------------------------------------------------------------

def _parse_metric_key(key: str) -> tuple[str, dict]:
    """``'name{k=v,k2=v2}'`` -> ('name', {'k': 'v', 'k2': 'v2'})."""
    if "{" not in key:
        return key, {}
    name, _, rest = key.partition("{")
    labels = dict(part.split("=", 1) for part in rest.rstrip("}").split(",")
                  if "=" in part)
    return name, labels


def parse_snapshot_series(logs: str | Iterable[str]) -> dict[str, list[dict]]:
    """All snapshot payloads, grouped by emitting process (``role:pid``),
    each group sorted by ``seq``."""
    out: dict[str, list[dict]] = {}
    for m in parse_metrics_lines(logs):
        if not _is_snapshot(m):
            continue
        key = f"{m.get('role', 'process')}:{m.get('pid', 0)}"
        out.setdefault(key, []).append(m)
    for snaps in out.values():
        snaps.sort(key=lambda s: s.get("seq", 0))
    return out


def _counter_series(snaps: list[dict]) -> tuple[dict, dict]:
    """Per-counter cumulative values and interval rates across snapshots.

    Rates align with ``t[1:]`` (a rate needs two samples); the first
    snapshot's cumulative value is still visible in ``values``.
    """
    names = sorted({k for s in snaps for k in s.get("counters", {})})
    values = {n: [float(s.get("counters", {}).get(n, 0.0)) for s in snaps]
              for n in names}
    ts = [float(s.get("ts", 0.0)) for s in snaps]
    rates = {}
    for n in names:
        r = []
        for i in range(1, len(snaps)):
            dt = ts[i] - ts[i - 1]
            dv = values[n][i] - values[n][i - 1]
            r.append(round(dv / dt, 6) if dt > 0 else 0.0)
        rates[n] = r
    return values, rates


def build_telemetry_timeseries(logs: str | Iterable[str]) -> dict:
    """Snapshot stream -> per-process time-series record.

    Output shape (JSON-ready; consumed by
    :meth:`.visualize.ExperimentVisualizer.plot_telemetry` and the recorded
    demo artifacts under ``experiments/results/telemetry/``)::

        {"procs": {"worker:1234": {
            "role": "worker", "pid": 1234,
            "t": [...relative seconds...],
            "counters": {key: [cumulative...]},
            "rates":    {key: [per-second, aligned to t[1:]]},
            "gauges":   {key: [...]},
            "histograms_final": {key: {le, counts, sum, count}},
            "pipeline": {  # only when comms-pipeline metrics were recorded
                "not_modified_ratio": [...aligned to t...],
                "queue_depth": {"worker-N": [...]},
                "overlap_saved_seconds_total": float,
                "overlap_windows": int}}}}
    """
    series = parse_snapshot_series(logs)
    procs = {}
    for proc_key, snaps in series.items():
        if not snaps:
            continue
        t0 = float(snaps[0].get("ts", 0.0)) \
            - float(snaps[0].get("uptime_seconds", 0.0))
        values, rates = _counter_series(snaps)
        gauge_names = sorted({k for s in snaps for k in s.get("gauges", {})})
        proc = {
            "role": snaps[0].get("role", "process"),
            "pid": snaps[0].get("pid", 0),
            "t": [round(float(s.get("ts", 0.0)) - t0, 3) for s in snaps],
            "counters": values,
            "rates": rates,
            "gauges": {n: [s.get("gauges", {}).get(n) for s in snaps]
                       for n in gauge_names},
            "histograms_final": dict(snaps[-1].get("histograms", {})),
        }
        pipeline = _pipeline_series(proc)
        if pipeline:
            proc["pipeline"] = pipeline
        procs[proc_key] = proc
    return {"procs": procs}


def _pipeline_series(proc: dict) -> dict:
    """Comms-pipeline evidence from one process's series (docs/
    WIRE_PROTOCOL.md metrics): the delta-fetch not-modified ratio over
    time, per-worker pipeline queue-depth series, and the total overlap
    saving. Empty dict when the process recorded none of them."""
    out: dict = {}
    # Not-modified ratio: store-side NOT_MODIFIED replies over all fetches,
    # cumulative per snapshot, summed across backends.
    fetches = [0.0] * len(proc["t"])
    not_mod = [0.0] * len(proc["t"])
    saw_nm = False
    for key, series in proc.get("counters", {}).items():
        name, _ = _parse_metric_key(key)
        if name == "dps_store_fetches_total":
            fetches = [a + b for a, b in zip(fetches, series)]
        elif name == "dps_store_fetch_not_modified_total":
            saw_nm = True
            not_mod = [a + b for a, b in zip(not_mod, series)]
    if saw_nm:
        out["not_modified_ratio"] = [
            round(nm / f, 4) if f > 0 else 0.0
            for nm, f in zip(not_mod, fetches)]
    # Queue depth: one gauge series per overlapped worker.
    depth = {}
    for key, series in proc.get("gauges", {}).items():
        name, labels = _parse_metric_key(key)
        if name == "dps_worker_pipeline_depth":
            depth[f"worker-{labels.get('worker', '?')}"] = series
    if depth:
        out["queue_depth"] = depth
    # Overlap savings: final-histogram totals (seconds of comms hidden
    # behind compute) summed across workers.
    saved_s = 0.0
    saved_n = 0
    for key, hist in proc.get("histograms_final", {}).items():
        name, _ = _parse_metric_key(key)
        if name == "dps_worker_overlap_saved_seconds":
            saved_s += float(hist.get("sum", 0.0))
            saved_n += int(hist.get("count", 0))
    if saved_n:
        out["overlap_saved_seconds_total"] = round(saved_s, 6)
        out["overlap_windows"] = saved_n
    return out


def worker_throughput_series(ts_record: dict) -> dict[str, dict]:
    """Per-worker training throughput from a built time-series record.

    Pulls every ``dps_worker_steps_total{worker=N}`` (PS workers) and
    ``dps_trainer_steps_total{mode=...}`` (SPMD trainer) counter; keys are
    ``worker-N`` / ``trainer-<mode>``, values carry the rate series aligned
    to ``t[1:]``.
    """
    out: dict[str, dict] = {}
    for proc_key, proc in ts_record.get("procs", {}).items():
        for key, rate in proc.get("rates", {}).items():
            name, labels = _parse_metric_key(key)
            if name == "dps_worker_steps_total":
                label = f"worker-{labels.get('worker', '?')}"
            elif name == "dps_trainer_steps_total":
                label = f"trainer-{labels.get('mode', '?')}"
            else:
                continue
            out[f"{label} ({proc_key})" if len(
                ts_record["procs"]) > 1 else label] = {
                "t": proc["t"][1:],
                "steps_per_second": rate,
                "cumulative_steps": proc["counters"][key],
            }
    return out


def staleness_series(ts_record: dict) -> dict:
    """Aggregate async-staleness evidence from a time-series record:
    the final histogram (summed across backends/processes) plus the
    per-snapshot observation-count series (arrival intensity over time).
    """
    le = None
    counts = None
    total_series: dict[str, dict] = {}
    for proc_key, proc in ts_record.get("procs", {}).items():
        for key, hist in proc.get("histograms_final", {}).items():
            name, _ = _parse_metric_key(key)
            if name != "dps_store_staleness_versions":
                continue
            if le is None:
                le = list(hist["le"])
                counts = [0] * len(hist["counts"])
            for i, c in enumerate(hist["counts"]):
                counts[i] += c
    for proc_key, proc in ts_record.get("procs", {}).items():
        for key in proc.get("rates", {}):
            name, labels = _parse_metric_key(key)
            if name == "dps_store_pushes_total":
                total_series[f"{labels.get('outcome', '?')} ({proc_key})"] = {
                    "t": proc["t"][1:],
                    "pushes_per_second": proc["rates"][key],
                }
    return {"le": le or [], "counts": counts or [],
            "push_rates": total_series}


# ---------------------------------------------------------------------------
# Cluster-monitor records (telemetry/cluster.py "kind": "cluster") ->
# health history. The monitor emits one record per evaluation interval:
# the live worker table + active alerts, plus the EDGE events (fired/
# refired/resolved) since the previous record. These parsers turn a run's
# captured stdout into an alert timeline and per-worker health series the
# visualizer overlays on the training curves.
# ---------------------------------------------------------------------------

def parse_cluster_series(logs: str | Iterable[str]
                         ) -> dict[str, list[dict]]:
    """All ``"kind": "cluster"`` records, grouped by emitting process
    (``role:pid``), each group sorted by ``seq``."""
    out: dict[str, list[dict]] = {}
    for m in parse_metrics_lines(logs):
        if not _is_cluster(m):
            continue
        key = f"{m.get('role', 'server')}:{m.get('pid', 0)}"
        out.setdefault(key, []).append(m)
    for recs in out.values():
        recs.sort(key=lambda r: r.get("seq", 0))
    return out


def alert_timeline(logs: str | Iterable[str]) -> list[dict]:
    """Flattened alert edge events across every cluster record, ordered by
    time. Each event: ``{"t" (seconds since the first record), "ts",
    "state" (fired|refired|resolved), "rule", "severity", "worker",
    "message", ...}`` — the overlay input for
    :meth:`.visualize.ExperimentVisualizer.plot_cluster_health`."""
    series = parse_cluster_series(logs)
    starts = [float(rec["ts"]) - float(rec.get("uptime_seconds", 0.0))
              for recs in series.values() for rec in recs
              if rec.get("ts")]
    t0 = min(starts) if starts else None
    events: list[dict] = []
    for proc_key, recs in series.items():
        for rec in recs:
            for ev in rec.get("events", []):
                if not isinstance(ev, dict):
                    continue
                ts = float(ev.get("last_ts") or ev.get("since")
                           or rec.get("ts") or 0.0)
                events.append({
                    "t": round(ts - t0, 3) if t0 is not None else 0.0,
                    "ts": ts,
                    "proc": proc_key,
                    "state": ev.get("state"),
                    "rule": ev.get("rule"),
                    "severity": ev.get("severity"),
                    "worker": ev.get("worker"),
                    "message": ev.get("message"),
                    "value": ev.get("value"),
                    "threshold": ev.get("threshold"),
                })
    events.sort(key=lambda e: e["ts"])
    return events


def cluster_worker_series(logs: str | Iterable[str]) -> dict:
    """Per-worker health time-series from the cluster records: ``t``
    (relative seconds) plus step/loss/grad-norm/examples-per-second
    sequences keyed ``worker-N`` — the cluster-eye view of each worker,
    as opposed to the worker's own snapshot stream."""
    series = parse_cluster_series(logs)
    recs = [r for recs in series.values() for r in recs]
    recs.sort(key=lambda r: float(r.get("ts", 0.0)))
    if not recs:
        return {"t": [], "workers": {}}
    t0 = float(recs[0].get("ts", 0.0)) \
        - float(recs[0].get("uptime_seconds", 0.0))
    t = [round(float(r.get("ts", 0.0)) - t0, 3) for r in recs]
    workers: dict[str, dict] = {}
    for i, rec in enumerate(recs):
        for row in rec.get("workers", []):
            wid = row.get("worker")
            if wid is None:
                continue
            w = workers.setdefault(
                f"worker-{wid}",
                {k: [None] * len(recs)
                 for k in ("step", "loss", "grad_norm",
                           "examples_per_s", "alive")})
            for k in ("step", "loss", "grad_norm", "examples_per_s",
                      "alive"):
                w[k][i] = row.get(k)
    return {"t": t, "workers": workers}


def parse_log_files(paths: list[str], experiment_name: str,
                    out_path: str | None = None) -> dict:
    texts = []
    for p in paths:
        with open(p) as f:
            texts.append(f.read())
    record = parse_experiment("\n".join(texts), experiment_name)
    if out_path:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(record, f, indent=2)
    return record
