"""Log -> experiment-JSON ETL (reference: scripts/parse_cloudwatch_logs.py).

The reference shells out to ``aws logs filter-log-events`` and regex-extracts
``METRICS_JSON:`` lines (parse_cloudwatch_logs.py:61-121). Here logs are
local files or strings (there is no CloudWatch in the loop), but the
aggregation semantics are reproduced exactly
(parse_cloudwatch_logs.py:125-177):

- server metrics pass through,
- worker totals: MAX total time across workers (the slowest worker defines
  the run), MEAN epoch time, MEAN final accuracy,
- per-epoch: max/avg/min across workers,
- raw per-worker records preserved under ``raw_worker_metrics``.

Output schema matches ``experiment_results/*.json`` (e.g.
sync_4workers.json) so the visualizer — ours or the reference's — can read
either's files.
"""

from __future__ import annotations

import json
import os
from typing import Iterable

import numpy as np

from ..utils.metrics import parse_metrics_lines


def _is_worker(m: dict) -> bool:
    return "worker_id" in m


def aggregate_worker_metrics(workers: list[dict]) -> dict:
    """parse_cloudwatch_logs.py:125-177 semantics."""
    if not workers:
        return {}
    total_times = [w.get("total_training_time_seconds", 0.0) for w in workers]
    epoch_means = [w.get("average_epoch_time_seconds", 0.0) for w in workers]
    final_accs = [w.get("final_test_accuracy", 0.0) for w in workers]

    n_epochs = max((len(w.get("epoch_times_seconds", [])) for w in workers),
                   default=0)
    per_epoch = []
    for e in range(n_epochs):
        times = [w["epoch_times_seconds"][e] for w in workers
                 if len(w.get("epoch_times_seconds", [])) > e]
        accs = [w["all_test_accuracies"][e] for w in workers
                if len(w.get("all_test_accuracies", [])) > e]
        row = {
            "epoch": e + 1,
            "max_time": float(np.max(times)) if times else 0.0,
            "avg_time": float(np.mean(times)) if times else 0.0,
            "min_time": float(np.min(times)) if times else 0.0,
            "max_accuracy": float(np.max(accs)) if accs else 0.0,
            "avg_accuracy": float(np.mean(accs)) if accs else 0.0,
            "min_accuracy": float(np.min(accs)) if accs else 0.0,
        }
        # Measured per-slot training metrics (SPMD sync rows): unlike the
        # time/test-accuracy fields above — which sync workers share by
        # construction — these genuinely differ per worker.
        for field, label in (("train_loss_per_epoch", "train_loss"),
                             ("train_accuracy_per_epoch",
                              "train_accuracy")):
            vals = [w[field][e] for w in workers
                    if len(w.get(field, [])) > e]
            if vals:
                row.update({f"max_{label}": float(np.max(vals)),
                            f"avg_{label}": float(np.mean(vals)),
                            f"min_{label}": float(np.min(vals))})
        per_epoch.append(row)

    out = {
        "num_workers": len(workers),
        # the slowest worker defines the run's wall clock
        "total_training_time_seconds": float(np.max(total_times)),
        "average_epoch_time_seconds": float(np.mean(epoch_means)),
        "average_final_accuracy": float(np.mean(final_accs)),
        "per_epoch": per_epoch,
    }
    # Surface the measured-vs-derived distinction (round-4 VERDICT item
    # 10): SPMD sync rows mark which fields were measured per worker and
    # that the rest are one shared model/program measurement.
    measured = sorted({f for w in workers
                       for f in w.get("measured_per_worker_fields", [])})
    if measured:
        out["measured_per_worker_fields"] = measured
    if any(w.get("shared_model_metrics") for w in workers):
        out["shared_model_metrics"] = True
    return out


def parse_experiment(logs: str | Iterable[str],
                     experiment_name: str = "experiment") -> dict:
    """Full log text (possibly many processes' stdout) -> experiment record."""
    metrics = parse_metrics_lines(logs)
    server = next((m for m in metrics
                   if not _is_worker(m) and "mode" in m), None)
    workers = [m for m in metrics if _is_worker(m)]
    return {
        "experiment_name": experiment_name,
        "server_metrics": server or {},
        "worker_metrics_aggregated": aggregate_worker_metrics(workers),
        "raw_worker_metrics": workers,
    }


def parse_log_files(paths: list[str], experiment_name: str,
                    out_path: str | None = None) -> dict:
    texts = []
    for p in paths:
        with open(p) as f:
            texts.append(f.read())
    record = parse_experiment("\n".join(texts), experiment_name)
    if out_path:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(record, f, indent=2)
    return record
