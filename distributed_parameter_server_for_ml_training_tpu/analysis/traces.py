"""Trace assembly, Perfetto export, and critical-path straggler attribution.

Consumes the span records the flight recorder produces
(``telemetry/trace.py``): crash/atexit dump files, ``/debug/trace``
bodies, or raw span lists. Three capabilities:

- :func:`assemble_traces` — join spans from MANY processes by
  ``trace_id`` and parent links into per-step trace trees (the server's
  ``rpc.server``/``store.*`` spans nest under the originating worker's
  step via the wire-propagated context);
- :func:`to_chrome_trace` — Chrome trace-event JSON (the ``traceEvents``
  array format), loadable directly in Perfetto / ``chrome://tracing``;
- :func:`critical_path_report` — classify each ``worker.step``'s wall
  time into **compute / fetch-wait / push-wait / server-apply / codec**
  and rank steps by wall time with their dominant phase: the per-step
  straggler attribution aggregate metrics cannot give (a slow snapshot
  tells you *that* a worker lagged; this tells you *which phase of which
  step* did it).

Attribution semantics: the wait phases are the training thread's blocked
time measured inline; nested codec spans are subtracted from the wait
they occurred under, and ``store.apply`` time reached through the push's
propagated context is reported as its own ``server_apply`` phase
(subtracted from push-wait, where it physically overlapped). The phases
are therefore disjoint and their sum over wall time is the report's
``coverage`` — the acceptance gate asks ≥95% on a straggler step.
"""

from __future__ import annotations

import json
import os
from typing import Iterable

#: Span names the attribution pass classifies (telemetry SPAN_CATALOG).
_PHASE_OF = {
    "worker.compute": "compute",
    "worker.fetch_wait": "fetch_wait",
    "worker.push_wait": "push_wait",
    "worker.codec": "codec",
    "store.apply": "server_apply",
}
_WAIT_NAMES = ("worker.fetch_wait", "worker.push_wait")
PHASES = ("compute", "fetch_wait", "push_wait", "server_apply", "codec")


def load_trace_dumps(paths: Iterable[str]) -> list[dict]:
    """Merge span records from flight-recorder dump files (or any JSON
    file holding either a ``{"spans": [...]}`` payload or a bare span
    list). Deduplicates by ``span_id`` — a SIGTERM dump followed by an
    atexit dump of the same process overlaps almost entirely."""
    spans: list[dict] = []
    seen: set[str] = set()
    for path in paths:
        with open(path) as f:
            payload = json.load(f)
        records = payload.get("spans", []) if isinstance(payload, dict) \
            else payload
        for s in records:
            sid = s.get("span_id")
            if isinstance(sid, str) and sid in seen:
                continue
            if isinstance(sid, str):
                seen.add(sid)
            spans.append(s)
    return spans


def find_trace_dumps(dump_dir: str) -> list[str]:
    """All flight-recorder dump files under ``dump_dir`` (the
    ``trace-<role>-<pid>-<reason>.json`` naming of
    ``FlightRecorder.dump_to_dir``), sorted for stable assembly order."""
    return sorted(
        os.path.join(dump_dir, f) for f in os.listdir(dump_dir)
        if f.startswith("trace-") and f.endswith(".json"))


# -- assembly ----------------------------------------------------------------

def assemble_traces(spans: list[dict]) -> dict:
    """Join spans (any mix of processes) into per-trace trees.

    Returns ``{"traces": [{"trace_id", "span_count", "roots": [tree...]}],
    "orphan_spans": n}`` where each tree node is the span dict plus a
    ``"children"`` list (sorted by start time). A span whose parent never
    made it into a dump (ring-buffer eviction, a process that produced no
    dump) becomes a root of its trace rather than disappearing — partial
    post-mortems still assemble.
    """
    by_id: dict[str, dict] = {}
    span_counts: dict[str, int] = {}
    for s in spans:
        sid = s.get("span_id")
        if isinstance(sid, str):
            by_id[sid] = {**s, "children": []}
    traces: dict[str, list] = {}
    orphans = 0
    for node in by_id.values():
        tid = node.get("trace_id", "?")
        span_counts[tid] = span_counts.get(tid, 0) + 1
        pid_ = node.get("parent_id")
        parent = by_id.get(pid_) if isinstance(pid_, str) else None
        if parent is not None and parent.get("trace_id") == tid:
            parent["children"].append(node)
        else:
            if pid_ is not None and parent is None:
                orphans += 1
            traces.setdefault(tid, []).append(node)
    for node in by_id.values():
        node["children"].sort(key=lambda n: n.get("ts", 0.0))
    out = []
    for tid, roots in traces.items():
        roots.sort(key=lambda n: n.get("ts", 0.0))
        out.append({
            "trace_id": tid,
            "span_count": span_counts.get(tid, 0),
            "roots": roots,
        })
    out.sort(key=lambda t: t["roots"][0].get("ts", 0.0) if t["roots"]
             else 0.0)
    return {"traces": out, "orphan_spans": orphans}


def _walk(node: dict):
    yield node
    for c in node.get("children", ()):
        yield from _walk(c)


def _walk_critical(node: dict):
    """Descendants on the training thread's critical path: subtrees under
    a ``pipeline.comms`` span are the OVERLAPPED comms work — it ran on
    the comms thread hidden behind compute, so counting its store/apply/
    codec time as step phases would double-book wall clock (the step only
    paid the submit/await waits, which are measured directly)."""
    for c in node.get("children", ()):
        if c.get("name") == "pipeline.comms":
            continue
        yield c
        yield from _walk_critical(c)


# -- Chrome trace-event / Perfetto export ------------------------------------

def to_chrome_trace(spans: list[dict]) -> dict:
    """Span records -> Chrome trace-event JSON object format.

    Loadable by Perfetto (ui.perfetto.dev) and ``chrome://tracing``:
    complete events (``"ph": "X"``) with microsecond ``ts``/``dur``, one
    timeline row per (process, thread), process rows named
    ``<role>:<pid>``, and the trace/span ids in ``args`` so a row can be
    joined back to the JSON dumps. Validated structurally by
    ``tests/test_trace.py`` (tier-1)."""
    events: list[dict] = []
    seen_procs: set = set()
    for s in spans:
        pid_ = int(s.get("pid", 0))
        tid = int(s.get("tid", 0)) % (1 << 31)  # Perfetto wants small-ish ints
        if pid_ not in seen_procs:
            seen_procs.add(pid_)
            events.append({"ph": "M", "name": "process_name", "pid": pid_,
                           "tid": 0,
                           "args": {"name": f"{s.get('role', 'process')}:"
                                            f"{pid_}"}})
        args = dict(s.get("attrs", {}))
        args["trace_id"] = s.get("trace_id")
        args["span_id"] = s.get("span_id")
        if s.get("parent_id"):
            args["parent_id"] = s["parent_id"]
        events.append({
            "ph": "X",
            "name": str(s.get("name", "?")),
            "cat": str(s.get("name", "?")).split(".", 1)[0],
            "ts": round(float(s.get("ts", 0.0)) * 1e6, 3),
            "dur": max(0.0, round(float(s.get("dur", 0.0)) * 1e6, 3)),
            "pid": pid_,
            "tid": tid,
            "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def save_chrome_trace(spans: list[dict], path: str) -> str:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(to_chrome_trace(spans), f)
    return path


# -- critical-path attribution -----------------------------------------------

def _attribute_step(root: dict) -> dict:
    """Phase breakdown of one ``worker.step`` tree (docstring above for
    the disjointness rules)."""
    wall = float(root.get("dur", 0.0))
    phases = {p: 0.0 for p in PHASES}
    # Pass 1: per-span phase durations along the critical path; nested
    # codec/apply noted per wait in pass 2.
    for node in _walk_critical(root):
        phase = _PHASE_OF.get(node.get("name"))
        if phase:
            phases[phase] += float(node.get("dur", 0.0))
    # Pass 2: waits are reported EXCLUSIVE of the codec/apply work nested
    # under them (physically inside the wait, reported as their own
    # phases).
    for wait_name in _WAIT_NAMES:
        phase = _PHASE_OF[wait_name]
        for node in _walk_critical(root):
            if node.get("name") != wait_name:
                continue
            nested = sum(
                float(d.get("dur", 0.0)) for d in _walk_critical(node)
                if _PHASE_OF.get(d.get("name")) in ("codec",
                                                    "server_apply"))
            phases[phase] = max(0.0, phases[phase] - nested)
    covered = sum(phases.values())
    attrs = dict(root.get("attrs", {}))
    staleness = [
        n.get("attrs", {}).get("staleness") for n in _walk(root)
        if n.get("name") == "store.apply"
        and n.get("attrs", {}).get("staleness") is not None]
    entry = {
        "trace_id": root.get("trace_id"),
        "worker": attrs.get("worker"),
        "step": attrs.get("step"),
        "epoch": attrs.get("epoch"),
        "epoch_open": bool(attrs.get("epoch_open", False)),
        "role": root.get("role"),
        "pid": root.get("pid"),
        "ts": root.get("ts"),
        "wall_s": round(wall, 6),
        "phases_s": {p: round(v, 6) for p, v in phases.items()},
        "coverage": round(covered / wall, 4) if wall > 0 else 0.0,
        "dominant_phase": max(phases, key=phases.get) if covered > 0
        else "other",
    }
    if staleness:
        entry["staleness"] = max(staleness)
    return entry


def critical_path_report(spans: list[dict], top: int = 10) -> dict:
    """Rank ``worker.step`` traces by wall time with per-phase attribution.

    Returns::

        {"steps": n,
         "step_wall_total_s": summed wall over ALL steps (not just the
                              top-N — the perf-observatory
                              reconciliation base),
         "phase_totals_s": {compute, fetch_wait, push_wait,
                            server_apply, codec},
         "stragglers": [top-N step entries, slowest first, each with
                        wall_s / phases_s / coverage / dominant_phase
                        (+ staleness when an async apply recorded it)],
         "by_dominant_phase": {phase: count}}
    """
    assembled = assemble_traces(spans)
    entries = []
    for trace in assembled["traces"]:
        for root in trace["roots"]:
            if root.get("name") == "worker.step":
                entries.append(_attribute_step(root))
    entries.sort(key=lambda e: e["wall_s"], reverse=True)
    totals = {p: 0.0 for p in PHASES}
    by_dom: dict[str, int] = {}
    for e in entries:
        for p in PHASES:
            totals[p] += e["phases_s"][p]
        by_dom[e["dominant_phase"]] = by_dom.get(e["dominant_phase"], 0) + 1
    return {
        "steps": len(entries),
        "step_wall_total_s": round(sum(e["wall_s"] for e in entries), 6),
        "phase_totals_s": {p: round(v, 6) for p, v in totals.items()},
        "stragglers": entries[:top],
        "by_dominant_phase": by_dom,
    }
