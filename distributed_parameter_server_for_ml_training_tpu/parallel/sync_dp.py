"""Synchronous data parallelism as SPMD over a named ``data`` mesh axis.

This file *is* the reference's sync mode, re-designed for TPU. The whole gRPC
round trip — worker pushes pickled fp16 gradients (worker.py:270-311), server
stashes them per worker under a lock, waits for all N, averages per-parameter
(server.py:145-169, 264-288), applies SGD (server.py:126-143), workers fetch
~45 MB of re-pickled params (server.py:213-237) — collapses into ONE compiled
program per step:

- each mesh slot ("worker") computes gradients on its contiguous shard of the
  batch,
- ``lax.pmean`` over the ``data`` axis is the per-parameter average, executed
  as an XLA all-reduce over ICI (no server process, no serialization, no
  star-topology bandwidth bottleneck),
- the SGD update runs replicated on every worker, so "fetch" is free — the
  updated params are already resident on every device.

Gradient compression: the reference casts fp32->fp16 before the wire
(worker.py:264-268, ~50% bytes). The TPU analogue is reducing in bfloat16 —
``compression='bf16'`` casts gradients before the all-reduce, halving ICI
traffic, and restores fp32 for the update.

Unlike the reference's "sync" (which returns PushReply immediately and lets
workers run ahead on stale params — SURVEY.md appendix quirk 2), this is a
true barrier: the XLA collective synchronizes all workers every step. That is
both more faithful to the *name* and strictly better behaved; the reference's
no-barrier behavior is unreproducible in SPMD and documented as such.
"""

from __future__ import annotations
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..data.cifar import augment_batch, standardize, to_float
from ..ops.compression import compress_for_allreduce, decompress_from_allreduce
from ..train.steps import cross_entropy_loss
from ..train.train_state import TrainState
from .mesh import DATA_AXIS, shard_map


def _int8_ring_allreduce_mean(grads, axis: str, axis_size: int, seed):
    """Quantized all-reduce as a reduce-scatter ring + all-gather ring with
    int8 payloads on every hop (EQuARX-style; PAPERS.md prior art).

    The round-3 formulation (quantize once, ``all_gather`` values+scales,
    local mean) moved N x S int8 bytes per device — O(N) in the mesh size,
    already tying bf16-pmean traffic at N=4 and ~2x it at N=8. Quantizing
    *inside* the ring keeps per-device bytes ~N-independent:

    - reduce-scatter phase: N-1 hops; each hop quantizes the running
      partial sum of ONE 1/N-sized chunk (stochastic rounding, per-hop
      seed — requantization noise stays unbiased), ``ppermute``s it to the
      next neighbor, and accumulates the received block into the local
      contribution for the next chunk. After N-1 hops device d holds the
      full sum of chunk (d+1) mod N.
    - all-gather phase: the reduced mean chunk is quantized ONCE and its
      int8+scales payload rotated N-1 hops; every device (owner included)
      applies the SAME dequantized values, so replicas stay bit-identical.

    Per-device ICI bytes: 2 (N-1)/N x S x 1B (+ scales, 4B / 32768 elems)
    vs bf16-pmean's 4 (N-1)/N x S — int8 is ~half bf16 at every N, and
    strictly below it from N=2 up (the round-3 scheme crossed above bf16
    at N>=4). Byte model recorded in experiments/results/PERF.md and
    asserted against compiled HLO by tests/test_quantize.py.
    """
    from jax.flatten_util import ravel_pytree

    from ..ops.pallas.quantize import dequantize_int8, quantize_int8

    flat, unravel = ravel_pytree(grads)
    n = axis_size
    my = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    chunk = -(-flat.size // n)
    own = jnp.pad(flat, (0, n * chunk - flat.size)).reshape(n, chunk)

    def quant(x, s):
        # Distinct PRNG stream per hop (and per device/step via ``seed``,
        # already folded with worker index + step by the caller).
        hop_seed = jax.random.randint(jax.random.fold_in(seed, s), (),
                                      0, 2 ** 31 - 1, dtype=jnp.int32)
        return quantize_int8(x, seed=hop_seed, stochastic=True)

    # -- reduce-scatter ring: partial sums travel int8 ---------------------
    part = jnp.take(own, my % n, axis=0)
    for s in range(n - 1):
        v, sc = quant(part, s)
        v = jax.lax.ppermute(v, axis, perm)
        sc = jax.lax.ppermute(sc, axis, perm)
        recv = dequantize_int8(v, sc, (chunk,))
        part = jnp.take(own, (my - s - 1) % n, axis=0) + recv

    # -- all-gather ring: the mean chunk quantized once, rotated N-1 hops --
    v, sc = quant(part / n, n - 1)
    out = jnp.zeros((n, chunk), jnp.float32)
    idx = (my + 1) % n
    out = out.at[idx].set(dequantize_int8(v, sc, (chunk,)))
    for _ in range(n - 1):
        v = jax.lax.ppermute(v, axis, perm)
        sc = jax.lax.ppermute(sc, axis, perm)
        idx = (idx - 1) % n
        out = out.at[idx].set(dequantize_int8(v, sc, (chunk,)))
    return unravel(out.reshape(-1)[:flat.size])


def shard_batch(mesh: Mesh, batch, axis: str = DATA_AXIS):
    """Place host arrays onto the mesh, batch dim split along ``axis``.

    This is the reference's data sharding (worker.py:166-179) done by the
    runtime: contiguous equal slices of the leading dim per worker slot.
    """
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), batch)


def make_sync_dp_step(mesh: Mesh, *, axis: str = DATA_AXIS,
                      compression: str = "bf16",
                      augment: bool = True) -> Callable:
    """Build the sync data-parallel ``step(state, images_u8, labels, rng)``.

    ``state`` must be built from a model constructed with
    ``axis_name=axis`` so BatchNorm statistics sync across workers (the
    sane resolution of the reference's frozen-BN defect, SURVEY.md §7(b)).
    Returns ``(state, metrics)`` with metrics pmean'd across workers.
    """

    def worker_step(state: TrainState, images_u8, labels, rng):
        # Per-worker RNG: fold in the worker index (distinct augmentation
        # per shard) and the global step.
        widx = jax.lax.axis_index(axis)
        rng = jax.random.fold_in(jax.random.fold_in(rng, widx), state.step)

        # torchvision order (worker.py:145-154): crop/flip raw pixels
        # (zero pad = black), then per-channel standardize. Gathers run
        # on uint8 — bit-identical floats at 1/4 the bandwidth
        # (train/steps.py).
        images = images_u8
        if augment:
            images = augment_batch(rng, images)
        images = standardize(to_float(images))

        def loss_fn(params):
            from ..train.steps import _variables
            outputs, mutated = state.apply_fn(
                _variables(params, state.batch_stats),
                images, train=True, mutable=["batch_stats"],
            )
            loss = cross_entropy_loss(outputs, labels)
            return loss, (outputs, mutated.get("batch_stats", {}))

        (loss, (logits, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)

        # == server.py:145-169 aggregate_gradients_sync, as one all-reduce,
        # with compression on the wire (the reference cast fp16,
        # worker.py:264-268):
        #   bf16/fp16 -> reduced-precision pmean (half the ICI bytes)
        #   int8      -> quantized reduce-scatter + all-gather ring
        #                (~1/2 bf16's bytes, N-independent; EQuARX-style)
        if compression == "int8":
            # Dedicated PRNG stream: augment_batch consumes split(rng)
            # (= fold_in(rng, 0/1)), so the ring's hop seeds must branch
            # off a tag those small indices can never produce.
            grads = _int8_ring_allreduce_mean(
                grads, axis, mesh.shape[axis],
                jax.random.fold_in(rng, 0x7FFFFFFF))
        else:
            grads = compress_for_allreduce(grads, compression)
            grads = jax.lax.pmean(grads, axis)
            grads = decompress_from_allreduce(grads, compression)

        # == server.py:126-143 apply_gradients, replicated on every worker.
        state = state.apply_gradients(grads=grads)
        state = state.replace(batch_stats=new_stats)

        acc = jnp.mean(jnp.argmax(logits, -1) == labels)
        metrics = {
            "loss": jax.lax.pmean(loss, axis),
            "accuracy": jax.lax.pmean(acc, axis),
            # Per-slot measurements ([N] when gathered): each logical
            # worker's OWN shard loss/accuracy — the honest basis for
            # per-worker METRICS_JSON rows (round-4 VERDICT item 10; the
            # reference's workers each report their own numbers,
            # worker.py:350-366).
            "worker_loss": loss[None],
            "worker_accuracy": acc[None],
        }
        return state, metrics

    metric_specs = {"loss": P(), "accuracy": P(),
                    "worker_loss": P(axis), "worker_accuracy": P(axis)}
    sharded = shard_map(
        worker_step,
        mesh=mesh,
        in_specs=(P(), P(axis), P(axis), P()),
        out_specs=(P(), metric_specs),
        check_vma=False,
    )
    # Donating the state lets XLA update params/opt_state in place instead of
    # holding both generations in HBM (same as train/baseline.py's step).
    return jax.jit(sharded, donate_argnums=0)
