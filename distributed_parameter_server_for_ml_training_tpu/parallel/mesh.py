"""Device mesh construction.

The reference's "cluster topology" is N worker containers in a star around one
gRPC server (terraform/main.tf:327-435). Here a *worker* is a logical index
along the ``data`` axis of a `jax.sharding.Mesh`; registration/membership
(server.py:190-211) is replaced by the mesh — worker_id == axis index, always
contiguous, never duplicated (the reference's restart-induced duplicate-id
pollution, README.md:368-371, cannot occur by construction).
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True,
              axis_names=None):
    """Version-portable ``shard_map``.

    jax >= 0.5 exposes ``jax.shard_map`` with the replication check named
    ``check_vma`` and manual axes selected by ``axis_names``; 0.4.x only has
    ``jax.experimental.shard_map.shard_map`` with the check named
    ``check_rep`` and the COMPLEMENT of the manual set passed as ``auto``.
    Every SPMD builder in this package routes through here so the rest of
    the code targets one spelling.
    """
    if hasattr(jax, "shard_map"):
        kwargs = {} if axis_names is None else {"axis_names": axis_names}
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma,
                             **kwargs)
    from jax.experimental.shard_map import shard_map as _shard_map
    kwargs = {}
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        if auto:
            kwargs["auto"] = auto
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma, **kwargs)


def make_mesh(num_workers: int | None = None,
              axis_names: tuple[str, ...] = (DATA_AXIS,),
              devices=None) -> Mesh:
    """Build a mesh whose leading axis is the logical worker (data) axis.

    With a single axis name, shape is ``(num_workers,)``. With two
    (``('data','model')``), the trailing ``model`` axis takes all remaining
    devices: ``(num_workers, len(devices)//num_workers)``.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if num_workers is None:
        num_workers = n
    if len(axis_names) == 1:
        if num_workers > n:
            raise ValueError(
                f"{num_workers} workers > {n} devices; shrink the worker "
                f"count or use a CPU mesh with "
                f"--xla_force_host_platform_device_count")
        shape = (num_workers,)
        devs = np.array(devices[:num_workers]).reshape(shape)
    else:
        if n % num_workers:
            raise ValueError(f"{n} devices not divisible by {num_workers}")
        shape = (num_workers, n // num_workers)
        devs = np.array(devices).reshape(shape)
    return Mesh(devs, axis_names)


def worker_axis_size(mesh: Mesh, axis: str = DATA_AXIS) -> int:
    return mesh.shape[axis]
