"""Ring attention: sequence/context parallelism over a mesh axis.

Net-new capability (the reference has no sequence parallelism of any kind —
SURVEY.md §5.7). Long sequences are sharded along a ``seq`` mesh axis; each
device holds a [B, T/N, H, D] slice of q/k/v. K/V blocks rotate around the
ring via ``lax.ppermute`` (one ICI hop per step, overlapping compute with the
neighbor transfer) while each device accumulates attention for its resident
queries with the online-softmax (flash-attention) merge, so the full [T, T]
score matrix never materializes anywhere.

Equivalent math to dense softmax attention (tests assert allclose); memory
per device is O(T/N) instead of O(T^2).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _merge(m, l, o, logits, v_blk):
    """Online-softmax merge of one K/V block into the running (m, l, o)."""
    m_blk = jnp.max(logits, axis=-1)                      # [B,H,Tq]
    m_new = jnp.maximum(m, m_blk)
    p = jnp.exp(logits - m_new[..., None])                # [B,H,Tq,Tk]
    alpha = jnp.exp(m - m_new)                            # [B,H,Tq]
    l_new = l * alpha + jnp.sum(p, axis=-1)
    o_new = o * alpha[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v_blk)
    return m_new, l_new, o_new


def ring_attention_local(q, k, v, *, axis_name: str, axis_size: int,
                         causal: bool = False):
    """Per-shard body; call inside ``shard_map`` over ``axis_name``.

    q/k/v: [B, T_local, H, D] (this shard's slice). Returns [B, T_local, H, D].
    """
    b, t_local, h, d = q.shape
    scale = 1.0 / np.sqrt(d)
    my = jax.lax.axis_index(axis_name)

    qf = q.astype(jnp.float32)
    m = jnp.full((b, h, t_local), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, t_local), jnp.float32)
    o = jnp.zeros((b, h, t_local, d), jnp.float32)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    kk, vv = k.astype(jnp.float32), v.astype(jnp.float32)

    for step in range(axis_size):
        # After `step` rotations we hold the block that started on shard
        # (my - step) mod N.
        src = (my - step) % axis_size
        logits = jnp.einsum("bqhd,bkhd->bhqk", qf, kk) * scale
        if causal:
            q_pos = my * t_local + jnp.arange(t_local)        # global rows
            k_pos = src * t_local + jnp.arange(t_local)       # global cols
            mask = q_pos[:, None] >= k_pos[None, :]           # [Tq,Tk]
            logits = jnp.where(mask[None, None], logits, _NEG_INF)
        m, l, o = _merge(m, l, o, logits, vv)
        if step != axis_size - 1:
            kk = jax.lax.ppermute(kk, axis_name, perm)
            vv = jax.lax.ppermute(vv, axis_name, perm)

    out = o / jnp.maximum(l, 1e-30)[..., None]                # [B,H,Tq,D]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)   # [B,Tq,H,D]


def make_ring_attention(mesh: Mesh, axis: str = "data",
                        causal: bool = False) -> Callable:
    """Jitted ``fn(q, k, v) -> out`` over sequence-sharded [B, T, H, D]."""
    axis_size = mesh.shape[axis]
    body = partial(ring_attention_local, axis_name=axis,
                   axis_size=axis_size, causal=causal)
    spec = P(None, axis)  # shard the T dimension
    fn = jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    return jax.jit(fn)


def dense_attention(q, k, v, causal: bool = False):
    """Reference dense softmax attention (for tests / single-device)."""
    d = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk",
                        q.astype(jnp.float32),
                        k.astype(jnp.float32)) / np.sqrt(d)
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)
