"""Ring attention: sequence/context parallelism over a mesh axis.

Net-new capability (the reference has no sequence parallelism of any kind —
SURVEY.md §5.7). Long sequences are sharded along a ``seq`` mesh axis; each
device holds a [B, T/N, H, D] slice of q/k/v. K/V blocks rotate around the
ring via ``lax.ppermute`` (one ICI hop per step, overlapping compute with the
neighbor transfer) while each device accumulates attention for its resident
queries with the online-softmax (flash-attention) merge, so the full [T, T]
score matrix never materializes anywhere.

Equivalent math to dense softmax attention (tests assert allclose); memory
per device is O(T/N) instead of O(T^2).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import shard_map

_NEG_INF = -1e30


def _merge(m, l, o, logits, v_blk):
    """Online-softmax merge of one K/V block into the running (m, l, o)."""
    m_blk = jnp.max(logits, axis=-1)                      # [B,H,Tq]
    m_new = jnp.maximum(m, m_blk)
    p = jnp.exp(logits - m_new[..., None])                # [B,H,Tq,Tk]
    alpha = jnp.exp(m - m_new)                            # [B,H,Tq]
    l_new = l * alpha + jnp.sum(p, axis=-1)
    o_new = o * alpha[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v_blk)
    return m_new, l_new, o_new


def ring_attention_local(q, k, v, *, axis_name: str, axis_size: int,
                         causal: bool = False):
    """Per-shard body; call inside ``shard_map`` over ``axis_name``.

    q/k/v: [B, T_local, H, D] (this shard's slice). Returns [B, T_local, H, D].
    """
    b, t_local, h, d = q.shape
    scale = 1.0 / np.sqrt(d)
    my = jax.lax.axis_index(axis_name)

    qf = q.astype(jnp.float32)
    m = jnp.full((b, h, t_local), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, t_local), jnp.float32)
    o = jnp.zeros((b, h, t_local, d), jnp.float32)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    kk, vv = k.astype(jnp.float32), v.astype(jnp.float32)

    for step in range(axis_size):
        # After `step` rotations we hold the block that started on shard
        # (my - step) mod N.
        src = (my - step) % axis_size
        logits = jnp.einsum("bqhd,bkhd->bhqk", qf, kk) * scale
        if causal:
            q_pos = my * t_local + jnp.arange(t_local)        # global rows
            k_pos = src * t_local + jnp.arange(t_local)       # global cols
            mask = q_pos[:, None] >= k_pos[None, :]           # [Tq,Tk]
            logits = jnp.where(mask[None, None], logits, _NEG_INF)
        m, l, o = _merge(m, l, o, logits, vv)
        if step != axis_size - 1:
            kk = jax.lax.ppermute(kk, axis_name, perm)
            vv = jax.lax.ppermute(vv, axis_name, perm)

    out = o / jnp.maximum(l, 1e-30)[..., None]                # [B,H,Tq,D]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)   # [B,Tq,H,D]


def make_ring_attention(mesh: Mesh, axis: str = "data",
                        causal: bool = False) -> Callable:
    """Jitted ``fn(q, k, v) -> out`` over sequence-sharded [B, T, H, D]."""
    axis_size = mesh.shape[axis]
    body = partial(ring_attention_local, axis_name=axis,
                   axis_size=axis_size, causal=causal)
    spec = P(None, axis)  # shard the T dimension
    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# Ring x flash: the Pallas flash kernels as the per-hop block core
# ---------------------------------------------------------------------------

def _to3(x):
    """[B, T, H, D] -> [B*H, T, D] (the flash kernels' layout)."""
    b, t, h, d = x.shape
    return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, t, d)


def _to4(x3, b, h):
    """[B*H, T, D] -> [B, T, H, D] (inverse of _to3)."""
    _, t, d = x3.shape
    return jnp.transpose(x3.reshape(b, h, t, d), (0, 2, 1, 3))


def _hop_fwd(q4, k4, v4, use_pallas: bool, causal=False,
             q_offset=0, k_offset=0):
    """One hop's flash forward on [B, Tq, H, D] q against a [B, Tk, H, D]
    K/V block -> (normalized fp32 partial out [B,Tq,H,D], lse [B*H,Tq,1]).
    Partials stay fp32: the ring accumulators merge N of them, and rounding
    each hop to the input dtype would stack N quantization errors."""
    from ..ops.pallas.flash_attention import _flash_fwd_impl, pick_block

    b, tq, h, d = q4.shape
    tk = k4.shape[1]
    o3, lse3 = _flash_fwd_impl(_to3(q4), _to3(k4), _to3(v4), tk,
                               pick_block(tq), pick_block(tk), use_pallas,
                               out_dtype=jnp.float32, causal=causal,
                               q_offset=q_offset, k_offset=k_offset)
    return _to4(o3, b, h), lse3


def _hop_bwd(q4, k4, v4, do4, lse_tot, delta, use_pallas: bool,
             causal=False, q_offset=0, k_offset=0):
    """One hop's flash backward: fp32 (dq_partial, dk_block, dv_block)
    given the TOTAL logsumexp and delta — the flash backward never
    differentiates through the merge (p_i = exp(s_i - lse_total) directly;
    shared impl in ops/pallas/flash_attention._flash_bwd_impl)."""
    from ..ops.pallas.flash_attention import _flash_bwd_impl, pick_block

    b, tq, h, d = q4.shape
    tk = k4.shape[1]
    dq3, dk3, dv3 = _flash_bwd_impl(
        _to3(q4), _to3(k4), _to3(v4), _to3(do4), lse_tot, delta,
        kv_len=tk, block_q=pick_block(tq), block_k=pick_block(tk),
        use_pallas=use_pallas, out_dtype=jnp.float32, causal=causal,
        q_offset=q_offset, k_offset=k_offset)
    return _to4(dq3, b, h), _to4(dk3, b, h), _to4(dv3, b, h)


def make_ring_flash_attention(mesh: Mesh, axis: str = "seq",
                              causal: bool = False,
                              use_pallas: bool | None = None) -> Callable:
    """Ring attention whose per-hop block core is the Pallas flash kernel.

    Composition of the two long-context mechanisms: the sequence is sharded
    T/N per device (ring hops via ``ppermute`` over ``axis``), and within
    each hop the resident [Tq_local, Tk_block] attention runs as the fused
    flash kernel (ops/pallas/flash_attention.py) instead of a dense einsum
    — neither the [T, T] nor even a [T/N, T/N] score matrix reaches HBM.

    Forward: each hop's flash fwd yields a normalized partial (o_i, lse_i);
    partials merge associatively (out = sum_i exp(lse_i - M) o_i /
    sum_i exp(lse_i - M), lse = M + log-sum). Backward (custom VJP): the
    flash backward never differentiates the merge — with the TOTAL lse and
    delta = rowsum(dO * O), each hop's dq/dk/dv come from the same flash
    backward kernels, with dK/dV accumulators rotating in lockstep with
    their K/V blocks so each block's gradient arrives home after a full
    cycle (standard ring-attention backward).

    Off TPU (CPU tests) the hops run the identical-math jnp fallback; the
    kernels themselves are validated on-chip by tests/test_flash_attention.
    ``causal=True`` masks in GLOBAL positions: each hop passes its shard's
    q offset and the rotating block's k offset down to the kernels; a hop
    whose block is entirely in the future degenerates to lse ~ -1e30 and
    the merge weights it to zero. T/N must be a multiple of 128.
    """
    axis_size = mesh.shape[axis]
    if use_pallas is None:
        from ..ops.pallas.flash_attention import _on_tpu
        use_pallas = _on_tpu()
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    @jax.custom_vjp
    def local_ring(q, k, v):
        out, _ = _ring_fwd(q, k, v)
        return out

    def _ring_fwd(q, k, v):
        b, tl, h, d = q.shape
        bh = b * h
        m = jnp.full((bh, tl, 1), _NEG_INF, jnp.float32)
        l = jnp.zeros((bh, tl, 1), jnp.float32)
        acc = jnp.zeros((b, tl, h, d), jnp.float32)
        my = jax.lax.axis_index(axis)
        kk, vv = k, v
        for step in range(axis_size):
            src = (my - step) % axis_size  # home shard of the resident block
            if causal:
                # A block entirely in the future (src > my) contributes
                # nothing — skip its FLOPs instead of computing a hop the
                # merge will weight to zero ((N-1)/2 hops per shard).
                o_i, lse_i = jax.lax.cond(
                    src <= my,
                    lambda ops: _hop_fwd(*ops, use_pallas, True,
                                         my * tl, src * tl),
                    lambda ops: (jnp.zeros((b, tl, h, d), jnp.float32),
                                 jnp.full((bh, tl, 1), _NEG_INF,
                                          jnp.float32)),
                    (q, kk, vv))
            else:
                o_i, lse_i = _hop_fwd(q, kk, vv, use_pallas, False,
                                      my * tl, src * tl)
            m_new = jnp.maximum(m, lse_i)
            w_prev = jnp.exp(m - m_new)
            w_i = jnp.exp(lse_i - m_new)
            l = l * w_prev + w_i
            # [BH, T, 1] weights -> [B, T, H, 1] to scale the partials.
            def w4(w):
                return jnp.transpose(w.reshape(b, h, tl, 1), (0, 2, 1, 3))
            acc = acc * w4(w_prev) + o_i * w4(w_i)  # o_i already fp32
            m = m_new
            if step != axis_size - 1:
                kk = jax.lax.ppermute(kk, axis, perm)
                vv = jax.lax.ppermute(vv, axis, perm)
        l = jnp.maximum(l, 1e-30)
        lse_tot = m + jnp.log(l)
        out = (acc / jnp.transpose(l.reshape(b, h, tl, 1), (0, 2, 1, 3))
               ).astype(q.dtype)
        return out, lse_tot

    def fwd_rule(q, k, v):
        out, lse_tot = _ring_fwd(q, k, v)
        return out, (q, k, v, out, lse_tot)

    def bwd_rule(res, do):
        q, k, v, out, lse_tot = res
        b, tl, h, d = q.shape
        delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                        axis=-1)                       # [B, T, H]
        delta = jnp.transpose(delta, (0, 2, 1)).reshape(b * h, tl, 1)
        dq = jnp.zeros_like(q, jnp.float32)
        my = jax.lax.axis_index(axis)
        kk, vv = k, v
        dkk = jnp.zeros_like(k, jnp.float32)
        dvv = jnp.zeros_like(v, jnp.float32)
        for step in range(axis_size):
            src = (my - step) % axis_size
            if causal:
                dq_i, dk_i, dv_i = jax.lax.cond(
                    src <= my,
                    lambda ops: _hop_bwd(*ops, use_pallas, True,
                                         my * tl, src * tl),
                    lambda ops: (jnp.zeros((b, tl, h, d), jnp.float32),) * 3,
                    (q, kk, vv, do, lse_tot, delta))
            else:
                dq_i, dk_i, dv_i = _hop_bwd(q, kk, vv, do, lse_tot, delta,
                                            use_pallas, False,
                                            my * tl, src * tl)
            dq = dq + dq_i
            dkk = dkk + dk_i
            dvv = dvv + dv_i
            # Rotate blocks AND their gradient accumulators together; the
            # accumulators always rotate (N hops bring each one home with
            # every shard's contribution), the K/V blocks skip the final
            # rotation — they are never read again.
            if step != axis_size - 1:
                kk = jax.lax.ppermute(kk, axis, perm)
                vv = jax.lax.ppermute(vv, axis, perm)
            dkk = jax.lax.ppermute(dkk, axis, perm)
            dvv = jax.lax.ppermute(dvv, axis, perm)
        return (dq.astype(q.dtype), dkk.astype(k.dtype),
                dvv.astype(v.dtype))

    local_ring.defvjp(fwd_rule, bwd_rule)

    spec = P(None, axis)
    fn = shard_map(local_ring, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    return jax.jit(fn)


def dense_attention(q, k, v, causal: bool = False):
    """Reference dense softmax attention (for tests / single-device)."""
    d = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk",
                        q.astype(jnp.float32),
                        k.astype(jnp.float32)) / np.sqrt(d)
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)
