"""Parallelism: device meshes, sync SPMD data parallelism, tensor
parallelism (GSPMD sharding rules), ring-attention sequence parallelism."""

from .mesh import make_mesh, worker_axis_size
from .moe import init_moe_params, make_moe_ffn
from .multihost import (fetch_replicated, host_local_slice, make_global_mesh,
                        replicate_to_mesh, shard_batch_global)
from .multihost import initialize as initialize_multihost
from .pipeline import make_pipeline_apply, stack_stage_params
from .ring_attention import (dense_attention, make_ring_attention,
                             make_ring_flash_attention,
                             ring_attention_local)
from .sync_dp import make_sync_dp_step, shard_batch
from .tensor import param_shardings, shard_train_state, tp_spec_for_path

__all__ = [
    "make_mesh",
    "worker_axis_size",
    "initialize_multihost",
    "make_global_mesh",
    "host_local_slice",
    "shard_batch_global",
    "replicate_to_mesh",
    "fetch_replicated",
    "make_sync_dp_step",
    "shard_batch",
    "make_ring_attention",
    "make_ring_flash_attention",
    "ring_attention_local",
    "dense_attention",
    "param_shardings",
    "shard_train_state",
    "tp_spec_for_path",
    "make_pipeline_apply",
    "stack_stage_params",
    "make_moe_ffn",
    "init_moe_params",
]
