"""Parallelism: device meshes, sync SPMD data parallelism, tensor
parallelism (GSPMD sharding rules), ring-attention sequence parallelism."""

from .mesh import make_mesh, worker_axis_size
from .moe import init_moe_params, make_moe_ffn
from .pipeline import make_pipeline_apply, stack_stage_params
from .ring_attention import (dense_attention, make_ring_attention,
                             ring_attention_local)
from .sync_dp import make_sync_dp_step, shard_batch
from .tensor import param_shardings, shard_train_state, tp_spec_for_path

__all__ = [
    "make_mesh",
    "worker_axis_size",
    "make_sync_dp_step",
    "shard_batch",
    "make_ring_attention",
    "ring_attention_local",
    "dense_attention",
    "param_shardings",
    "shard_train_state",
    "tp_spec_for_path",
    "make_pipeline_apply",
    "stack_stage_params",
    "make_moe_ffn",
    "init_moe_params",
]
