"""Parallelism: device meshes and sync SPMD data parallelism."""

from .mesh import make_mesh, worker_axis_size
from .sync_dp import make_sync_dp_step, shard_batch

__all__ = ["make_mesh", "worker_axis_size", "make_sync_dp_step", "shard_batch"]
