"""Tensor parallelism as sharding rules (GSPMD/pjit style).

Net-new capability (the reference has no model sharding of any kind —
SURVEY.md §2 parallelism checklist, TP row). Megatron-style split for the
transformer blocks in models/vit.py:

- column-parallel: attention qkv kernel and MLP fc1 kernel split on their
  OUTPUT dim over the ``model`` mesh axis (each shard computes a slice of
  heads / hidden units); their biases split the same way,
- row-parallel: attention out kernel and MLP fc2 kernel split on their INPUT
  dim (the partial products are summed by an XLA-inserted all-reduce); their
  biases stay replicated,
- everything else (embeddings, layernorms, head) replicated.

We only *annotate* placements (NamedSharding per parameter path); XLA
inserts the collectives and overlaps them with compute. No manual
psum/all_gather appears anywhere in the model code.
"""

from __future__ import annotations

import re
from typing import Any

import jax
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.pytree import flatten_params
from .mesh import MODEL_AXIS

PyTree = Any

# (path regex, spec builder) — first match wins. Specs are for 2-D kernels
# [in, out] / 1-D biases of the ViT naming scheme (models/vit.py).
_TP_RULES: list[tuple[str, P]] = [
    (r".*attn/qkv/kernel$", P(None, MODEL_AXIS)),   # column
    (r".*attn/qkv/bias$", P(MODEL_AXIS)),
    (r".*attn/out/kernel$", P(MODEL_AXIS, None)),   # row
    (r".*mlp/fc1/kernel$", P(None, MODEL_AXIS)),    # column
    (r".*mlp/fc1/bias$", P(MODEL_AXIS)),
    (r".*mlp/fc2/kernel$", P(MODEL_AXIS, None)),    # row
]


def tp_spec_for_path(path: str) -> P:
    for pattern, spec in _TP_RULES:
        if re.match(pattern, path):
            return spec
    return P()  # replicated


def param_shardings(params: PyTree, mesh: Mesh) -> PyTree:
    """NamedSharding pytree matching ``params`` under the TP rules."""
    flat = flatten_params(params)
    specs = {k: tp_spec_for_path(k) for k in flat}
    from ..utils.pytree import unflatten_params
    spec_tree = unflatten_params(specs)
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), spec_tree,
        is_leaf=lambda x: isinstance(x, P))


def shard_train_state(state, mesh: Mesh):
    """Place a TrainState on the mesh: params per TP rules, optimizer state
    mirroring its corresponding parameter, step/scalars replicated."""
    p_shard = param_shardings(state.params, mesh)
    replicated = NamedSharding(mesh, P())

    params = jax.tree_util.tree_map(jax.device_put, state.params, p_shard)

    def place_opt(x):
        # optax.sgd momentum (trace) state mirrors the param tree; anything
        # param-shaped gets the param's sharding, scalars replicate.
        return x

    # opt_state: momentum/trace entries have the same tree structure as
    # params — map shardings where structures align, else replicate.
    def put_like_params(subtree):
        try:
            return jax.tree_util.tree_map(jax.device_put, subtree, p_shard)
        except (ValueError, TypeError):
            return jax.tree_util.tree_map(
                lambda x: jax.device_put(x, replicated), subtree)

    opt_state = tuple(
        type(entry)(**{
            f: put_like_params(getattr(entry, f))
            for f in entry._fields
        }) if hasattr(entry, "_fields") and entry._fields else
        jax.tree_util.tree_map(lambda x: jax.device_put(x, replicated), entry)
        for entry in state.opt_state
    ) if isinstance(state.opt_state, tuple) else jax.tree_util.tree_map(
        lambda x: jax.device_put(x, replicated), state.opt_state)

    batch_stats = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, replicated), state.batch_stats)
    return state.replace(
        params=params,
        opt_state=opt_state,
        batch_stats=batch_stats,
        step=jax.device_put(state.step, replicated),
    )
