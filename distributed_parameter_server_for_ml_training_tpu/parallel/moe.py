"""Expert parallelism: Switch-style top-1 MoE FFN over an ``expert`` axis.

Net-new capability (the reference has no MoE — SURVEY.md §2 checklist, EP
row). One expert per mesh slot; each device routes its resident tokens,
packs them into capacity-limited per-expert buffers, and two
``lax.all_to_all`` hops move tokens to their expert and back:

    route (local) -> dispatch [E, C, D] -> all_to_all -> my expert's FFN on
    [N, C, D] -> all_to_all back -> gate * combine (dropped tokens -> 0)

Capacity C bounds memory and keeps shapes static (XLA requirement); tokens
beyond an expert's capacity are dropped, which is standard Switch behavior —
in a transformer the residual connection carries them through unchanged.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import shard_map

EXPERT_AXIS = "expert"


def init_moe_params(rng, d_model: int, d_hidden: int, n_experts: int):
    """Router + stacked per-expert FFN params ([E, ...]; shard on 'expert')."""
    k1, k2, k3 = jax.random.split(rng, 3)
    scale1 = 1.0 / jnp.sqrt(d_model)
    scale2 = 1.0 / jnp.sqrt(d_hidden)
    return {
        "router": jax.random.normal(k1, (d_model, n_experts)) * scale1,
        "w1": jax.random.normal(k2, (n_experts, d_model, d_hidden)) * scale1,
        "b1": jnp.zeros((n_experts, d_hidden)),
        "w2": jax.random.normal(k3, (n_experts, d_hidden, d_model)) * scale2,
        "b2": jnp.zeros((n_experts, d_model)),
    }


def _moe_body(params, tokens, *, axis_name: str, axis_size: int,
              capacity: int, data_axis: str | None = None):
    """shard_map body. params: router replicated + my expert's slice [1,...].
    tokens: [n_local, D]. Returns ``([n_local, D], stats)`` where stats are
    GLOBAL routing statistics (pmean'd over the expert axis — and the data
    axis when composing dp x ep — replicated):

    - ``aux_loss``: the Switch load-balance loss E * sum_e f_e * P_e
      (f_e = fraction of tokens routed to e, hard counts; P_e = mean router
      probability). Differentiable through P_e; minimized (=1) at uniform
      routing — trainers weight it into the total loss.
    - ``load``: [E] f_e, ``importance``: [E] P_e,
    - ``drop_frac``: fraction of tokens dropped by the capacity limit.
    """
    n, d = tokens.shape
    e = axis_size

    # -- route locally (top-1 / Switch) --------------------------------------
    logits = tokens @ params["router"]          # [n, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)     # [n]
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=1)[:, 0]

    # position of each token within its expert's send buffer
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.int32)      # [n, E]
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1                # [n, E]
    pos = jnp.max(pos, axis=1)                                   # [n]
    keep = pos < capacity

    # -- routing stats + Switch auxiliary load-balance loss ------------------
    # dp x ep: each data-parallel group routes its own tokens; f_e/P_e
    # additionally pmean over the data axis BEFORE the product, so the
    # aux loss is the Switch loss of the GLOBALLY pooled statistics —
    # invariant to how tokens are grouped across dp (a dp x ep step sees
    # the same aux loss/grads as ep-only on the same global batch, which
    # test_dp_ep_gradients_include_data_psum asserts), and replicated
    # across the whole mesh for the P() out_spec.
    stat_axes = (axis_name,) if data_axis is None else (axis_name, data_axis)
    load = jax.lax.pmean(jnp.mean(onehot.astype(jnp.float32), axis=0),
                         stat_axes)                              # [E] f_e
    importance = jax.lax.pmean(jnp.mean(probs, axis=0),
                               stat_axes)                        # [E] P_e
    # f_e is constant w.r.t. params (argmax); gradients flow through P_e —
    # exactly the Switch Transformer formulation (eq. 4).
    aux_loss = e * jnp.sum(jax.lax.stop_gradient(load) * importance)
    drop_frac = jax.lax.pmean(
        1.0 - jnp.mean(keep.astype(jnp.float32)), stat_axes)
    stats = {"aux_loss": aux_loss, "load": load,
             "importance": importance, "drop_frac": drop_frac}

    # -- dispatch [E, C, D] --------------------------------------------------
    safe_pos = jnp.clip(pos, 0, capacity - 1)
    dispatch = jnp.zeros((e, capacity, d), tokens.dtype)
    dispatch = dispatch.at[expert_idx, safe_pos].add(
        tokens * keep[:, None].astype(tokens.dtype))

    # -- to experts, compute, and back ---------------------------------------
    recv = jax.lax.all_to_all(dispatch, axis_name, split_axis=0,
                              concat_axis=0, tiled=False)        # [N, C, D]
    w1 = params["w1"][0]
    b1 = params["b1"][0]
    w2 = params["w2"][0]
    b2 = params["b2"][0]
    h = jax.nn.gelu(recv @ w1 + b1)
    out = h @ w2 + b2                                            # [N, C, D]
    back = jax.lax.all_to_all(out, axis_name, split_axis=0,
                              concat_axis=0, tiled=False)        # [E, C, D]

    # -- combine -------------------------------------------------------------
    gathered = back[expert_idx, safe_pos]                        # [n, D]
    mask = (keep.astype(tokens.dtype) * gate.astype(tokens.dtype))[:, None]
    return gathered * mask, stats


def make_moe_ffn(mesh: Mesh, capacity: int,
                 axis: str = EXPERT_AXIS,
                 data_axis: str | None = None) -> Callable:
    """Build ``fn(params, tokens[B, D]) -> ([B, D], stats)`` with tokens
    sharded on the expert axis and experts one-per-slot. Differentiable;
    ``stats`` (replicated) carries the Switch aux loss + routing
    observability — see ``_moe_body``.

    dp x ep (round-4 VERDICT weak 4): with ``data_axis`` set, the mesh is
    ``(data, expert)`` — tokens shard over BOTH axes, each data group
    routes its tokens over ITS experts' slice of the mesh (the two
    ``all_to_all`` hops stay within the group's expert ring), and expert
    weights replicate across the data axis, so the shard_map transpose
    inserts the data-axis gradient psum — exactly how Switch Transformer
    composes EP with DP at pod scale."""
    axis_size = mesh.shape[axis]
    body = partial(_moe_body, axis_name=axis, axis_size=axis_size,
                   capacity=capacity, data_axis=data_axis)
    # Expert-stacked leaves shard their leading [E] dim on the expert axis
    # and replicate across data; the router replicates everywhere.
    param_specs = {
        "router": P(),
        "w1": P(axis), "b1": P(axis),
        "w2": P(axis), "b2": P(axis),
    }
    stats_specs = {"aux_loss": P(), "load": P(), "importance": P(),
                   "drop_frac": P()}
    tok_spec = P(axis) if data_axis is None else P((data_axis, axis))
    sharded = shard_map(
        body, mesh=mesh,
        in_specs=(param_specs, tok_spec),
        out_specs=(tok_spec, stats_specs),
        check_vma=False,
    )
    return jax.jit(sharded)


def dense_reference(params, tokens, capacity: int | None = None):
    """Single-device reference: every token through its top-1 expert (no
    capacity drops unless ``capacity`` given per-expert-per-shard semantics
    are not modeled — use generous capacity in comparisons)."""
    logits = tokens @ params["router"]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=1)[:, 0]
    h = jax.nn.gelu(jnp.einsum("nd,ndh->nh", tokens,
                               params["w1"][expert_idx])
                    + params["b1"][expert_idx])
    out = jnp.einsum("nh,nhd->nd", h, params["w2"][expert_idx]) \
        + params["b2"][expert_idx]
    return out * gate[:, None].astype(tokens.dtype)
