"""Multi-host SPMD: one global mesh across processes via ``jax.distributed``.

This is the TPU-native replacement for the reference's multi-*machine* layer
— 1 parameter-server task + N worker tasks on ECS behind an internal NLB
(terraform/main.tf:387-435), wired together by env-injected addresses
(main.tf:308-314). Here every process is a peer in one multi-controller SPMD
job: each calls :func:`initialize` (address wiring by env vars, same idiom as
the reference's ``PARAMETER_SERVER_ADDRESS``), the runtime forms one global
device view, and the *same* compiled sync step (parallel/sync_dp.py) runs on
a mesh spanning every host — gradient averaging rides ICI within a host and
DCN across hosts through the same ``lax.pmean``, with no server process and
no NLB.

Env contract (mirrors server.py:407-417 / worker.py:457-459 env-first
config):

    DPS_COORDINATOR   host:port of process 0 (like PARAMETER_SERVER_ADDRESS)
    DPS_NUM_PROCESSES total process count      (like TOTAL_WORKERS_EXPECTED)
    DPS_PROCESS_ID    this process's rank

On real TPU pods these are normally auto-detected by the TPU runtime and
``jax.distributed.initialize()`` needs no arguments; the env contract is for
CPU fleets and tests.
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS


def initialize(coordinator: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Join the multi-controller job. Arguments default from env
    (DPS_COORDINATOR / DPS_NUM_PROCESSES / DPS_PROCESS_ID); with no args and
    no env, defers entirely to JAX's auto-detection (TPU pods). The local
    device count comes from the backend (on CPU fleets set
    ``--xla_force_host_platform_device_count`` in XLA_FLAGS)."""
    coordinator = coordinator or os.environ.get("DPS_COORDINATOR")
    if num_processes is None and "DPS_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["DPS_NUM_PROCESSES"])
    if process_id is None and "DPS_PROCESS_ID" in os.environ:
        process_id = int(os.environ["DPS_PROCESS_ID"])
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)


def make_global_mesh(axis_names: tuple[str, ...] = (DATA_AXIS,)) -> Mesh:
    """Mesh over ALL processes' devices: the global-batch ``data`` axis spans
    hosts. Device order is process-major (process 0's devices first), so a
    contiguous global-batch slice per process matches the addressable
    shards."""
    devices = np.array(jax.devices())
    if len(axis_names) == 1:
        return Mesh(devices, axis_names)
    # trailing axis = per-process devices (model axis inside a host, data
    # across hosts): ('data', 'model') => (num_processes, local_count)
    local = jax.local_device_count()
    return Mesh(devices.reshape(len(devices) // local, local), axis_names)


def host_local_slice(x: np.ndarray) -> np.ndarray:
    """This process's contiguous slice of a globally-agreed batch (the
    reference's contiguous shard-by-worker-id, worker.py:166-179, at host
    granularity)."""
    per = x.shape[0] // jax.process_count()
    lo = jax.process_index() * per
    return x[lo:lo + per]


def shard_batch_global(mesh: Mesh, batch, axis: str = DATA_AXIS):
    """Multi-process version of sync_dp.shard_batch: every process passes the
    FULL global batch (identical on all processes, e.g. same seeded
    shuffle); each contributes only its local slice to the global array."""
    sharding = NamedSharding(mesh, P(axis))

    def put(x):
        x = np.asarray(x)
        return jax.make_array_from_process_local_data(
            sharding, host_local_slice(x), global_shape=x.shape)

    return jax.tree_util.tree_map(put, batch)


def replicate_to_mesh(mesh: Mesh, tree):
    """Replicate host-local values (identical on every process) onto the
    global mesh — the multi-host way to place the train state."""
    sharding = NamedSharding(mesh, P())

    def put(x):
        x = np.asarray(x)
        return jax.make_array_from_process_local_data(
            sharding, x, global_shape=x.shape)

    return jax.tree_util.tree_map(put, tree)


def fetch_replicated(tree):
    """Host-local numpy copy of a fully-replicated global pytree (every
    process holds a complete shard, so this is local)."""
    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)
