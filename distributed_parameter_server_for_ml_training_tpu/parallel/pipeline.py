"""Pipeline parallelism: GPipe-style microbatch schedule over a ``stage``
mesh axis.

Net-new capability (the reference has no pipeline parallelism — SURVEY.md §2
checklist). Design:

- the model is S identical stages; stage s's parameters live only on mesh
  slot s (each leaf stacked [S, ...] and sharded P('stage') — the shard_map
  body sees its own [1, ...] slice),
- M microbatches flow through a ring of ``ppermute`` hops: at tick t, stage
  s processes microbatch t-s; the whole schedule is S+M-1 ticks, every
  device executing every tick (SPMD) with validity masking,
- jax autodiff differentiates straight through the unrolled schedule (the
  transpose of ppermute is the reverse ppermute), so pipelined *training*
  falls out for free — no hand-written backward schedule.

Memory (round-4 VERDICT item 5 — the round-3 scheme replicated the FULL
[M, mb, ...] input AND output on every stage device and stored every
activation of the unrolled schedule for the backward):

- ``shard_io=True`` (default): inputs and outputs are SHARDED over the
  microbatch dim along the stage axis — each device holds M/S
  microbatches. Stage 0 receives each microbatch from its home shard via
  a single-pair ``ppermute`` at its tick; the last stage ships each
  finished microbatch to its home shard the same way (replacing the
  all-replicating final psum). Per-device IO footprint drops S-fold.
- ``remat=True`` (default): ``stage_fn`` runs under ``jax.checkpoint``,
  so the backward recomputes intra-stage activations instead of storing
  S+M-1 ticks' worth — per-device activation memory is O(tick boundary),
  not O(schedule).

Measured (experiments/measure_pp_memory.py, ViT-B/16 @224 tokens,
batch 512, 4 stages x 8 microbatches): see
experiments/results/pp_memory.json.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import shard_map

STAGE_AXIS = "stage"


def _pipeline_body(stage_params, x_mb, *, stage_fn: Callable,
                   axis_name: str, axis_size: int, shard_io: bool):
    """shard_map body. stage_params: this stage's [1, ...] param slice.

    ``shard_io=False``: x_mb is the full [M, mb, ...] (replicated); returns
    replicated [M, mb, ...] via one final psum.
    ``shard_io=True``: x_mb is this device's [M/S, mb, ...] chunk; returns
    the device's output chunk (microbatch j lives on shard j // (M/S)).
    """
    s = jax.lax.axis_index(axis_name)
    n_stages = axis_size
    last = n_stages - 1
    chunk = x_mb.shape[0]
    m = chunk * n_stages if shard_io else chunk
    my_params = jax.tree_util.tree_map(lambda p: p[0], stage_params)

    perm_fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    carry = jnp.zeros_like(x_mb[0])  # activation arriving at my stage
    outputs = jnp.zeros_like(x_mb)

    for t in range(n_stages + m - 1):
        mb_idx = t - s  # which microbatch my stage works on this tick
        active = (mb_idx >= 0) & (mb_idx < m)

        # Stage 0 reads fresh input; later stages use the carried
        # activation.
        if not shard_io:
            fresh = x_mb[jnp.clip(mb_idx, 0, m - 1)]
        elif t < m:
            # Microbatch t enters the pipe: its home shard sends its local
            # slot to stage 0 (single-pair permute; other devices receive
            # zeros, and the value is read only where s == 0).
            home = t // chunk
            send = x_mb[t % chunk]
            fresh = (send if home == 0
                     else jax.lax.ppermute(send, axis_name, [(home, 0)]))
        else:
            fresh = jnp.zeros_like(carry)  # pipe is draining
        x_in = jnp.where(s == 0, fresh, carry)

        # Bubble ticks SKIP the stage compute: ``active`` is a per-device
        # scalar and stage_fn contains no collectives, so lax.cond lowers to
        # a real branch — (S-1)/(S+M-1) of the ticks do no FLOPs instead of
        # computing masked garbage.
        y = jax.lax.cond(active,
                         lambda x: stage_fn(my_params, x),
                         lambda x: jnp.zeros_like(x), x_in)

        out_idx = t - (n_stages - 1)  # static: which microbatch finished
        if 0 <= out_idx < m:
            if shard_io:
                # Ship the finished microbatch from the last stage to its
                # home shard (one pair); the home stores it locally.
                oh = out_idx // chunk
                y_home = (y if oh == last
                          else jax.lax.ppermute(y, axis_name, [(last, oh)]))
                outputs = outputs.at[out_idx % chunk].add(
                    jnp.where(s == oh, y_home, jnp.zeros_like(y_home)))
            else:
                outputs = outputs.at[out_idx].add(
                    jnp.where(s == last, y, jnp.zeros_like(y)))

        # Ship activations one stage forward for the next tick.
        carry = jax.lax.ppermute(y, axis_name, perm_fwd)

    if shard_io:
        return outputs           # each shard holds its own chunk
    return jax.lax.psum(outputs, axis_name)


def stack_stage_params(per_stage_params: list) -> jax.Array:
    """[S] list of same-structure param trees -> stacked tree [S, ...]."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def make_pipeline_apply(mesh: Mesh, stage_fn: Callable,
                        num_microbatches: int,
                        axis: str = STAGE_AXIS,
                        data_axis: str | None = None,
                        shard_io: bool | None = None,
                        remat: bool = True) -> Callable:
    """Build ``apply(stacked_params, x) -> y`` running the pipeline.

    ``stage_fn(params, x) -> y`` is one stage (shapes preserved). ``x`` is
    the full batch [B, ...]; it is split into ``num_microbatches`` equal
    microbatches internally. Differentiable w.r.t. params and x.

    ``shard_io`` shards the microbatch dim over the stage axis; default
    (None) = on whenever M divides by the stage count, off otherwise
    (degenerate M < S pipelines). ``remat`` wraps the stage in
    ``jax.checkpoint`` — default ON (see module docstring for the memory
    math). shard_io=False, remat=False reproduces the round-3 replicating
    schedule (the before/after measurement in
    experiments/measure_pp_memory.py does).

    Composition (round-2 VERDICT item 7): with ``data_axis`` set, each
    microbatch additionally shards along that mesh axis — data parallelism
    through the stage ring, the gradient all-reduce over ``data_axis``
    falling out of the shard_map transpose. Any OTHER mesh axis (e.g.
    ``model``) stays in GSPMD auto mode inside the body, so stage params
    carrying Megatron shardings get their matmuls tensor-partitioned by XLA
    — dp x tp x pp from one shard_map.
    """
    axis_size = mesh.shape[axis]
    if shard_io is None:
        shard_io = num_microbatches % axis_size == 0
    elif shard_io and num_microbatches % axis_size:
        raise ValueError(
            f"shard_io needs microbatches ({num_microbatches}) divisible "
            f"by the stage count ({axis_size})")
    fn = jax.checkpoint(stage_fn) if remat else stage_fn
    body = partial(_pipeline_body, stage_fn=fn, axis_name=axis,
                   axis_size=axis_size, shard_io=shard_io)
    manual = {axis} | ({data_axis} if data_axis else set())
    mb_axis = axis if shard_io else None
    x_spec = P(mb_axis, data_axis)
    sharded = shard_map(
        body, mesh=mesh,
        # params stacked on the stage axis; further (auto-axis) sharding of
        # the leaves rides on the arrays themselves.
        in_specs=(P(axis), x_spec),
        out_specs=x_spec,
        axis_names=manual,
        check_vma=False,
    )

    @jax.jit
    def apply(stacked_params, x):
        b = x.shape[0]
        assert b % num_microbatches == 0, (b, num_microbatches)
        mb = b // num_microbatches
        x_mb = x.reshape(num_microbatches, mb, *x.shape[1:])
        y_mb = sharded(stacked_params, x_mb)
        return y_mb.reshape(b, *y_mb.shape[2:])

    return apply


# ---------------------------------------------------------------------------
# 1F1B: a fused forward/backward schedule (round-4 VERDICT weak 5).
#
# The GPipe schedule above runs ALL forward ticks, then jax autodiff replays
# them in reverse — 2(S+M-1) ticks total, with every stage stashing one
# input per microbatch (O(M) activations/device under remat). Classic 1F1B
# interleaves: a stage runs microbatch j's backward as soon as it is ready,
# capping in-flight microbatches at S-s — O(S) stashed activations instead
# of O(M), at the SAME tick count (non-interleaved 1F1B and GPipe both take
# 2(M+S-1) unit ticks; the bubble fraction (S-1)/(S+M-1) is identical —
# 1F1B's win is memory, which buys a LARGER M at fixed memory, which is
# what actually shrinks the bubble).
#
# TPU-honest caveat, measured in experiments/measure_pp_schedule.py: in a
# lockstep SPMD program the per-tick ring collectives synchronize all
# stages, so a mixed tick (some stages forward, some backward) costs
# max(t_fwd, t_bwd) for EVERYONE. Megatron-style 1F1B assumes asynchronous
# point-to-point sends between per-stage controllers; under a single jit
# program the memory win is real but mixed ticks dilute the wall-clock.
# Both schedules are recorded side by side in pp_schedule.json.
# ---------------------------------------------------------------------------


def build_1f1b_schedule(n_stages: int, n_microbatches: int) -> dict:
    """Simulate the 1F1B schedule and return per-tick tables.

    Greedy policy (prefer backward; forward gated by the classic in-flight
    cap of S-s) reproduces the standard non-interleaved 1F1B timeline. The
    builder VERIFIES the schedule as it simulates: in-order processing,
    arrival-before-use, depth-S stash slots (mb % S) never collide, and
    every unit runs exactly once — a bug here raises instead of silently
    mis-training.

    Returns ``{"ticks": T, "act": [T,S] (0 idle/1 fwd/2 bwd),
    "mb": [T,S], "fwd_in": [T,S] (mb arriving on the fwd ring, -1 none),
    "bwd_in": [T,S]}``.
    """
    import numpy as np

    S, M = n_stages, n_microbatches
    act, mb_t, fwd_in, bwd_in = [], [], [], []
    # Per-stage simulator state.
    pend_f = [set() for _ in range(S)]   # arrived fwd inputs (mb ids)
    pend_b = [set() for _ in range(S)]   # arrived output-grads
    pend_f[0] = set(range(M))            # stage 0 reads x directly
    fwd_next = [0] * S                   # in-order forward
    bwd_next = [0] * S                   # in-order backward
    in_flight = [0] * S                  # fwd done, bwd not yet
    # (stage, kind, slot) -> occupying mb, for collision verification
    live: dict = {}
    arrivals_f: dict = {}                # (t, s) -> mb
    arrivals_b: dict = {}
    t = 0
    while any(n < M for n in bwd_next):
        if t > 4 * (S + M):
            raise AssertionError("1F1B schedule did not converge")
        # Deliver arrivals scheduled for this tick into buffers.
        row_fin, row_bin = [-1] * S, [-1] * S
        for s in range(S):
            j = arrivals_f.pop((t, s), None)
            if j is not None:
                key = (s, "x", j % S)
                assert key not in live, f"x slot collision at {key}"
                live[key] = j
                pend_f[s].add(j)
                row_fin[s] = j
            j = arrivals_b.pop((t, s), None)
            if j is not None:
                key = (s, "g", j % S)
                assert key not in live, f"g slot collision at {key}"
                live[key] = j
                pend_b[s].add(j)
                row_bin[s] = j
        row_a, row_m = [0] * S, [-1] * S
        for s in range(S):
            j = bwd_next[s]
            if j < M and j in pend_b[s]:
                # Backward unit: consumes the stashed input + grad slots.
                row_a[s], row_m[s] = 2, j
                pend_b[s].discard(j)
                for kind in ("x", "g"):
                    key = (s, kind, j % S)
                    if key in live:          # stage 0 stashes x too
                        del live[key]
                bwd_next[s] += 1
                in_flight[s] -= 1
                if s > 0:
                    arrivals_b[(t + 1, s - 1)] = j
                continue
            j = fwd_next[s]
            if (j < M and j in pend_f[s]
                    and in_flight[s] < S - s):
                row_a[s], row_m[s] = 1, j
                pend_f[s].discard(j)
                if s == 0:
                    # Stage 0 stashes its own input for the later vjp.
                    key = (s, "x", j % S)
                    assert key not in live, f"x slot collision at {key}"
                    live[key] = j
                fwd_next[s] += 1
                in_flight[s] += 1
                if s < S - 1:
                    arrivals_f[(t + 1, s + 1)] = j
                else:
                    # Last stage computes dy at its fwd tick; its own
                    # backward becomes ready next tick.
                    key = (s, "g", j % S)
                    assert key not in live, f"g slot collision at {key}"
                    live[key] = j
                    pend_b[s].add(j)  # delivered locally, not via ring
        act.append(row_a)
        mb_t.append(row_m)
        fwd_in.append(row_fin)
        bwd_in.append(row_bin)
        t += 1
    assert not live, f"undelivered buffers: {live}"
    for s in range(S):
        assert fwd_next[s] == M and bwd_next[s] == M
    return {"ticks": t,
            "act": np.asarray(act, np.int32),
            "mb": np.asarray(mb_t, np.int32),
            "fwd_in": np.asarray(fwd_in, np.int32),
            "bwd_in": np.asarray(bwd_in, np.int32)}


def _1f1b_body(stage_params, x_mb, y_mb, *, stage_fn, loss_fn, tables,
               axis_name, axis_size):
    """shard_map body for the fused 1F1B training step.

    Buffers (per device, depth S = the 1F1B in-flight cap, slot = mb % S):
      x_buf — stage inputs: arrived-but-unprocessed forward activations,
              kept after the forward unit as the vjp's residual (remat:
              the backward unit recomputes the stage from its input);
      g_buf — output-gradients awaiting the backward unit (the last stage
              seeds its own slot with dy at its forward tick).
    """
    s = jax.lax.axis_index(axis_name)
    last = axis_size - 1
    my_params = jax.tree_util.tree_map(lambda p: p[0], stage_params)
    act, mbt = tables["act"], tables["mb"]
    fwd_in, bwd_in = tables["fwd_in"], tables["bwd_in"]

    feat_shape = x_mb.shape[1:]
    x_buf = jnp.zeros((axis_size,) + feat_shape, x_mb.dtype)
    g_buf = jnp.zeros((axis_size,) + feat_shape, x_mb.dtype)
    fwd_msg = jnp.zeros(feat_shape, x_mb.dtype)
    bwd_msg = jnp.zeros(feat_shape, x_mb.dtype)
    grad_acc = jax.tree_util.tree_map(jnp.zeros_like, my_params)
    loss_acc = jnp.zeros((), jnp.float32)

    perm_fwd = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    perm_bwd = [(i, (i - 1) % axis_size) for i in range(axis_size)]

    def fwd_unit(operand):
        params, x_in, labels, is_last = operand
        y = stage_fn(params, x_in)
        # Last stage only: per-microbatch loss + dy seed, same tick (the
        # inner cond keeps the loss head off the other stages' fwd ticks).
        lval, dy = jax.lax.cond(
            is_last,
            lambda yy: jax.value_and_grad(
                lambda v: loss_fn(v, labels))(yy),
            lambda yy: (jnp.zeros((), jnp.float32), jnp.zeros_like(yy)),
            y)
        return y, lval, dy

    def bwd_unit(operand):
        params, x_in, g_in = operand
        _, pull = jax.vjp(lambda p, xx: stage_fn(p, xx), params, x_in)
        dp, dx = pull(g_in)
        return dp, dx

    for t in range(tables["ticks"]):
        my_a = jnp.asarray(act[t])[s]
        my_mb = jnp.asarray(mbt[t])[s]
        slot = jnp.maximum(my_mb, 0) % axis_size

        # Arrivals from LAST tick's rings land before this tick's compute.
        fin = jnp.asarray(fwd_in[t])[s]
        x_buf = x_buf.at[jnp.maximum(fin, 0) % axis_size].set(
            jnp.where(fin >= 0, fwd_msg, x_buf[jnp.maximum(fin, 0)
                                               % axis_size]))
        bin_ = jnp.asarray(bwd_in[t])[s]
        g_buf = g_buf.at[jnp.maximum(bin_, 0) % axis_size].set(
            jnp.where(bin_ >= 0, bwd_msg, g_buf[jnp.maximum(bin_, 0)
                                                % axis_size]))

        # ---- forward unit (one stage_fn application when my_a == 1) ----
        x_in = jnp.where(s == 0,
                         x_mb[jnp.clip(my_mb, 0, x_mb.shape[0] - 1)],
                         x_buf[slot])
        labels = y_mb[jnp.clip(my_mb, 0, y_mb.shape[0] - 1)]
        y, lval, dy = jax.lax.cond(
            my_a == 1,
            fwd_unit,
            lambda op: (jnp.zeros(feat_shape, x_mb.dtype),
                        jnp.zeros((), jnp.float32),
                        jnp.zeros(feat_shape, x_mb.dtype)),
            (my_params, x_in, labels, s == last))
        is_f = my_a == 1
        # Stash the input for the backward's recompute (all stages).
        x_buf = x_buf.at[slot].set(jnp.where(is_f, x_in, x_buf[slot]))
        # Last stage seeds its own g_buf with dy and accumulates the loss.
        seed = is_f & (s == last)
        g_buf = g_buf.at[slot].set(jnp.where(seed, dy, g_buf[slot]))
        loss_acc = loss_acc + jnp.where(seed, lval, 0.0)

        # ---- backward unit (one vjp when my_a == 2) --------------------
        dp, dx = jax.lax.cond(
            my_a == 2,
            bwd_unit,
            lambda op: (jax.tree_util.tree_map(jnp.zeros_like, my_params),
                        jnp.zeros(feat_shape, x_mb.dtype)),
            (my_params, x_buf[slot], g_buf[slot]))
        grad_acc = jax.tree_util.tree_map(lambda a, d: a + d, grad_acc, dp)

        # ---- rings (one fwd hop + one bwd hop per tick) ----------------
        fwd_msg = jax.lax.ppermute(y, axis_name, perm_fwd)
        bwd_msg = jax.lax.ppermute(dx, axis_name, perm_bwd)

    m = x_mb.shape[0]
    loss = jax.lax.psum(loss_acc, axis_name) / m
    grads = jax.tree_util.tree_map(lambda g: g[None] / m, grad_acc)
    return loss, grads


def _check_homogeneous_stage(stage_fn: Callable, stacked_params, x,
                             num_microbatches: int) -> None:
    """Both schedules route every stage's output into the next stage's
    input slot (and, in 1F1B, into shared x/g ring buffers sized from the
    input), so ``stage_fn`` MUST map a microbatch to the same shape and
    dtype. A heterogeneous stage used to surface only at trace time as an
    opaque ``lax.cond`` branch-shape mismatch (round-5 ADVICE); this
    shape-level check (``jax.eval_shape`` — no FLOPs, no tracing of the
    schedule) names the actual contract instead."""
    mb = x.shape[0] // num_microbatches
    x_sds = jax.ShapeDtypeStruct((mb,) + tuple(x.shape[1:]), x.dtype)
    one_stage = jax.tree_util.tree_map(
        lambda p: jax.ShapeDtypeStruct(tuple(p.shape[1:]), p.dtype),
        stacked_params)
    out = jax.eval_shape(stage_fn, one_stage, x_sds)
    if not hasattr(out, "shape") or tuple(out.shape) != tuple(x_sds.shape) \
            or out.dtype != x_sds.dtype:
        got = (f"{getattr(out, 'dtype', '?')}{list(getattr(out, 'shape', []))}"
               if hasattr(out, "shape") else type(out).__name__)
        raise ValueError(
            f"pipeline stages must be homogeneous: stage_fn must map a "
            f"microbatch of {x_sds.dtype}{list(x_sds.shape)} to the same "
            f"shape/dtype (its output feeds the next stage's input and "
            f"the fixed-shape ring buffers), but it returned {got}. "
            f"Fold any shape change (embedding, head) inside a stage.")


def make_pipeline_train_step(mesh: Mesh, stage_fn: Callable,
                             loss_fn: Callable, num_microbatches: int,
                             schedule: str = "gpipe",
                             axis: str = STAGE_AXIS,
                             remat: bool = True) -> Callable:
    """Uniform training-step builder over both schedules:
    ``step(stacked_params, x, y) -> (loss, stacked_grads)``.

    ``loss_fn(y_pred_mb, y_mb) -> scalar`` (mean over the microbatch);
    the step returns the mean over microbatches, so both schedules
    compute the identical loss and parameter gradients (asserted in
    tests/test_pipeline.py).

    - ``schedule='gpipe'``: the forward pipeline above + jax autodiff.
    - ``schedule='1f1b'``: the fused manual schedule (same tick count,
      O(S) instead of O(M) stashed activations — see module comment).

    ``stage_fn`` must be shape/dtype-preserving per microbatch (validated
    up front on the first call per input signature — a heterogeneous
    stage raises a clear error instead of an opaque ``lax.cond`` trace
    failure).
    """
    axis_size = mesh.shape[axis]

    def _validated(step_fn: Callable, seen: set = None) -> Callable:
        seen = set() if seen is None else seen

        def step(stacked_params, x, y):
            key = (tuple(x.shape), str(x.dtype))
            if key not in seen:
                _check_homogeneous_stage(stage_fn, stacked_params, x,
                                         num_microbatches)
                seen.add(key)
            return step_fn(stacked_params, x, y)

        return step

    if schedule == "gpipe":
        apply = make_pipeline_apply(mesh, stage_fn, num_microbatches,
                                    axis=axis, shard_io=False, remat=remat)

        def total_loss(params, x, y):
            y_pred = apply(params, x)
            m = num_microbatches
            y_pred_mb = y_pred.reshape(m, -1, *y_pred.shape[1:])
            y_mb = y.reshape(m, -1, *y.shape[1:])
            losses = jax.vmap(loss_fn)(y_pred_mb, y_mb)
            return jnp.mean(losses)

        return _validated(jax.jit(jax.value_and_grad(total_loss)))

    if schedule != "1f1b":
        raise ValueError(f"schedule must be gpipe|1f1b, got {schedule!r}")
    if not remat:
        raise ValueError(
            "schedule='1f1b' is inherently rematerializing: each backward "
            "unit recomputes its stage from the stashed input (jax.vjp); "
            "remat=False has no non-recomputing implementation here")
    tables = build_1f1b_schedule(axis_size, num_microbatches)
    body = partial(_1f1b_body, stage_fn=stage_fn, loss_fn=loss_fn,
                   tables=tables, axis_name=axis, axis_size=axis_size)
    sharded = shard_map(
        body, mesh=mesh,
        in_specs=(P(axis), P(), P()),
        out_specs=(P(), P(axis)),
        check_vma=False)

    @jax.jit
    def step(stacked_params, x, y):
        b = x.shape[0]
        assert b % num_microbatches == 0, (b, num_microbatches)
        mb = b // num_microbatches
        x_mb = x.reshape(num_microbatches, mb, *x.shape[1:])
        y_mb = y.reshape(num_microbatches, mb, *y.shape[1:])
        return sharded(stacked_params, x_mb, y_mb)

    return _validated(step)
