"""Pipeline parallelism: GPipe-style microbatch schedule over a ``stage``
mesh axis.

Net-new capability (the reference has no pipeline parallelism — SURVEY.md §2
checklist). Design:

- the model is S identical stages; stage s's parameters live only on mesh
  slot s (each leaf stacked [S, ...] and sharded P('stage') — the shard_map
  body sees its own [1, ...] slice),
- M microbatches flow through a ring of ``ppermute`` hops: at tick t, stage
  s processes microbatch t-s; the whole schedule is S+M-1 ticks, every
  device executing every tick (SPMD) with validity masking,
- jax autodiff differentiates straight through the unrolled schedule (the
  transpose of ppermute is the reverse ppermute), so pipelined *training*
  falls out for free — no hand-written backward schedule.

The input batch is replicated; outputs are returned replicated (each
microbatch's result is psum-broadcast from the last stage).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

STAGE_AXIS = "stage"


def _pipeline_body(stage_params, x_mb, *, stage_fn: Callable,
                   axis_name: str, axis_size: int):
    """shard_map body. stage_params: this stage's [1, ...] param slice.
    x_mb: [M, mb, ...] microbatches (replicated). Returns [M, mb, ...]
    outputs (replicated via ONE psum from the last stage at the end)."""
    s = jax.lax.axis_index(axis_name)
    n_stages = axis_size
    m = x_mb.shape[0]
    my_params = jax.tree_util.tree_map(lambda p: p[0], stage_params)

    perm_fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    carry = jnp.zeros_like(x_mb[0])  # activation arriving at my stage
    outputs = jnp.zeros_like(x_mb)

    for t in range(n_stages + m - 1):
        mb_idx = t - s  # which microbatch my stage works on this tick
        active = (mb_idx >= 0) & (mb_idx < m)
        # Stage 0 reads fresh input; later stages use the carried activation.
        fresh = x_mb[jnp.clip(mb_idx, 0, m - 1)]
        x_in = jnp.where(s == 0, fresh, carry)
        # Bubble ticks SKIP the stage compute: ``active`` is a per-device
        # scalar and stage_fn contains no collectives, so lax.cond lowers to
        # a real branch — (S-1)/(S+M-1) of the ticks do no FLOPs instead of
        # computing masked garbage.
        y = jax.lax.cond(active,
                         lambda x: stage_fn(my_params, x),
                         lambda x: jnp.zeros_like(x), x_in)

        # Stash the last stage's finished microbatch locally; everyone else
        # contributes zeros and ONE final psum replicates all outputs (the
        # per-tick broadcast this replaces cost S+M-2 extra collectives).
        out_idx = t - (n_stages - 1)  # static: which microbatch finished
        if 0 <= out_idx < m:
            is_last = s == n_stages - 1
            outputs = outputs.at[out_idx].add(
                jnp.where(is_last, y, jnp.zeros_like(y)))

        # Ship activations one stage forward for the next tick.
        carry = jax.lax.ppermute(y, axis_name, perm_fwd)

    return jax.lax.psum(outputs, axis_name)


def stack_stage_params(per_stage_params: list) -> jax.Array:
    """[S] list of same-structure param trees -> stacked tree [S, ...]."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def make_pipeline_apply(mesh: Mesh, stage_fn: Callable,
                        num_microbatches: int,
                        axis: str = STAGE_AXIS,
                        data_axis: str | None = None) -> Callable:
    """Build ``apply(stacked_params, x) -> y`` running the pipeline.

    ``stage_fn(params, x) -> y`` is one stage (shapes preserved). ``x`` is
    the full batch [B, ...]; it is split into ``num_microbatches`` equal
    microbatches internally. Differentiable w.r.t. params and x.

    Composition (round-2 VERDICT item 7): with ``data_axis`` set, each
    microbatch additionally shards along that mesh axis — data parallelism
    through the stage ring, the gradient all-reduce over ``data_axis``
    falling out of the shard_map transpose. Any OTHER mesh axis (e.g.
    ``model``) stays in GSPMD auto mode inside the body, so stage params
    carrying Megatron shardings get their matmuls tensor-partitioned by XLA
    — dp x tp x pp from one shard_map.
    """
    axis_size = mesh.shape[axis]
    body = partial(_pipeline_body, stage_fn=stage_fn, axis_name=axis,
                   axis_size=axis_size)
    manual = {axis} | ({data_axis} if data_axis else set())
    x_spec = P(None, data_axis) if data_axis else P()
    sharded = jax.shard_map(
        body, mesh=mesh,
        # params stacked on the stage axis; further (auto-axis) sharding of
        # the leaves rides on the arrays themselves.
        in_specs=(P(axis), x_spec),
        out_specs=x_spec,
        axis_names=manual,
        check_vma=False,
    )

    @jax.jit
    def apply(stacked_params, x):
        b = x.shape[0]
        assert b % num_microbatches == 0, (b, num_microbatches)
        mb = b // num_microbatches
        x_mb = x.reshape(num_microbatches, mb, *x.shape[1:])
        y_mb = sharded(stacked_params, x_mb)
        return y_mb.reshape(b, *y_mb.shape[2:])

    return apply
