"""Pipeline parallelism: GPipe-style microbatch schedule over a ``stage``
mesh axis.

Net-new capability (the reference has no pipeline parallelism — SURVEY.md §2
checklist). Design:

- the model is S identical stages; stage s's parameters live only on mesh
  slot s (each leaf stacked [S, ...] and sharded P('stage') — the shard_map
  body sees its own [1, ...] slice),
- M microbatches flow through a ring of ``ppermute`` hops: at tick t, stage
  s processes microbatch t-s; the whole schedule is S+M-1 ticks, every
  device executing every tick (SPMD) with validity masking,
- jax autodiff differentiates straight through the unrolled schedule (the
  transpose of ppermute is the reverse ppermute), so pipelined *training*
  falls out for free — no hand-written backward schedule.

Memory (round-4 VERDICT item 5 — the round-3 scheme replicated the FULL
[M, mb, ...] input AND output on every stage device and stored every
activation of the unrolled schedule for the backward):

- ``shard_io=True`` (default): inputs and outputs are SHARDED over the
  microbatch dim along the stage axis — each device holds M/S
  microbatches. Stage 0 receives each microbatch from its home shard via
  a single-pair ``ppermute`` at its tick; the last stage ships each
  finished microbatch to its home shard the same way (replacing the
  all-replicating final psum). Per-device IO footprint drops S-fold.
- ``remat=True`` (default): ``stage_fn`` runs under ``jax.checkpoint``,
  so the backward recomputes intra-stage activations instead of storing
  S+M-1 ticks' worth — per-device activation memory is O(tick boundary),
  not O(schedule).

Measured (experiments/measure_pp_memory.py, ViT-B/16 @224 tokens,
batch 512, 4 stages x 8 microbatches): see
experiments/results/pp_memory.json.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

STAGE_AXIS = "stage"


def _pipeline_body(stage_params, x_mb, *, stage_fn: Callable,
                   axis_name: str, axis_size: int, shard_io: bool):
    """shard_map body. stage_params: this stage's [1, ...] param slice.

    ``shard_io=False``: x_mb is the full [M, mb, ...] (replicated); returns
    replicated [M, mb, ...] via one final psum.
    ``shard_io=True``: x_mb is this device's [M/S, mb, ...] chunk; returns
    the device's output chunk (microbatch j lives on shard j // (M/S)).
    """
    s = jax.lax.axis_index(axis_name)
    n_stages = axis_size
    last = n_stages - 1
    chunk = x_mb.shape[0]
    m = chunk * n_stages if shard_io else chunk
    my_params = jax.tree_util.tree_map(lambda p: p[0], stage_params)

    perm_fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    carry = jnp.zeros_like(x_mb[0])  # activation arriving at my stage
    outputs = jnp.zeros_like(x_mb)

    for t in range(n_stages + m - 1):
        mb_idx = t - s  # which microbatch my stage works on this tick
        active = (mb_idx >= 0) & (mb_idx < m)

        # Stage 0 reads fresh input; later stages use the carried
        # activation.
        if not shard_io:
            fresh = x_mb[jnp.clip(mb_idx, 0, m - 1)]
        elif t < m:
            # Microbatch t enters the pipe: its home shard sends its local
            # slot to stage 0 (single-pair permute; other devices receive
            # zeros, and the value is read only where s == 0).
            home = t // chunk
            send = x_mb[t % chunk]
            fresh = (send if home == 0
                     else jax.lax.ppermute(send, axis_name, [(home, 0)]))
        else:
            fresh = jnp.zeros_like(carry)  # pipe is draining
        x_in = jnp.where(s == 0, fresh, carry)

        # Bubble ticks SKIP the stage compute: ``active`` is a per-device
        # scalar and stage_fn contains no collectives, so lax.cond lowers to
        # a real branch — (S-1)/(S+M-1) of the ticks do no FLOPs instead of
        # computing masked garbage.
        y = jax.lax.cond(active,
                         lambda x: stage_fn(my_params, x),
                         lambda x: jnp.zeros_like(x), x_in)

        out_idx = t - (n_stages - 1)  # static: which microbatch finished
        if 0 <= out_idx < m:
            if shard_io:
                # Ship the finished microbatch from the last stage to its
                # home shard (one pair); the home stores it locally.
                oh = out_idx // chunk
                y_home = (y if oh == last
                          else jax.lax.ppermute(y, axis_name, [(last, oh)]))
                outputs = outputs.at[out_idx % chunk].add(
                    jnp.where(s == oh, y_home, jnp.zeros_like(y_home)))
            else:
                outputs = outputs.at[out_idx].add(
                    jnp.where(s == last, y, jnp.zeros_like(y)))

        # Ship activations one stage forward for the next tick.
        carry = jax.lax.ppermute(y, axis_name, perm_fwd)

    if shard_io:
        return outputs           # each shard holds its own chunk
    return jax.lax.psum(outputs, axis_name)


def stack_stage_params(per_stage_params: list) -> jax.Array:
    """[S] list of same-structure param trees -> stacked tree [S, ...]."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def make_pipeline_apply(mesh: Mesh, stage_fn: Callable,
                        num_microbatches: int,
                        axis: str = STAGE_AXIS,
                        data_axis: str | None = None,
                        shard_io: bool | None = None,
                        remat: bool = True) -> Callable:
    """Build ``apply(stacked_params, x) -> y`` running the pipeline.

    ``stage_fn(params, x) -> y`` is one stage (shapes preserved). ``x`` is
    the full batch [B, ...]; it is split into ``num_microbatches`` equal
    microbatches internally. Differentiable w.r.t. params and x.

    ``shard_io`` shards the microbatch dim over the stage axis; default
    (None) = on whenever M divides by the stage count, off otherwise
    (degenerate M < S pipelines). ``remat`` wraps the stage in
    ``jax.checkpoint`` — default ON (see module docstring for the memory
    math). shard_io=False, remat=False reproduces the round-3 replicating
    schedule (the before/after measurement in
    experiments/measure_pp_memory.py does).

    Composition (round-2 VERDICT item 7): with ``data_axis`` set, each
    microbatch additionally shards along that mesh axis — data parallelism
    through the stage ring, the gradient all-reduce over ``data_axis``
    falling out of the shard_map transpose. Any OTHER mesh axis (e.g.
    ``model``) stays in GSPMD auto mode inside the body, so stage params
    carrying Megatron shardings get their matmuls tensor-partitioned by XLA
    — dp x tp x pp from one shard_map.
    """
    axis_size = mesh.shape[axis]
    if shard_io is None:
        shard_io = num_microbatches % axis_size == 0
    elif shard_io and num_microbatches % axis_size:
        raise ValueError(
            f"shard_io needs microbatches ({num_microbatches}) divisible "
            f"by the stage count ({axis_size})")
    fn = jax.checkpoint(stage_fn) if remat else stage_fn
    body = partial(_pipeline_body, stage_fn=fn, axis_name=axis,
                   axis_size=axis_size, shard_io=shard_io)
    manual = {axis} | ({data_axis} if data_axis else set())
    mb_axis = axis if shard_io else None
    x_spec = P(mb_axis, data_axis)
    sharded = jax.shard_map(
        body, mesh=mesh,
        # params stacked on the stage axis; further (auto-axis) sharding of
        # the leaves rides on the arrays themselves.
        in_specs=(P(axis), x_spec),
        out_specs=x_spec,
        axis_names=manual,
        check_vma=False,
    )

    @jax.jit
    def apply(stacked_params, x):
        b = x.shape[0]
        assert b % num_microbatches == 0, (b, num_microbatches)
        mb = b // num_microbatches
        x_mb = x.reshape(num_microbatches, mb, *x.shape[1:])
        y_mb = sharded(stacked_params, x_mb)
        return y_mb.reshape(b, *y_mb.shape[2:])

    return apply
