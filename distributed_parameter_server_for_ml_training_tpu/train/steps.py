"""Jitted train/eval step factories (single-chip; the SPMD and async paths
build on these).

Reference parity: the worker hot loop zero_grad -> forward -> CE loss ->
backward (src/workers/worker.py:333-348) plus the server apply
(server.py:126-143) become ONE compiled XLA program: normalize + augment +
fwd + bwd + update, fused by XLA, bfloat16 on the MXU when the model is so
configured.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax

from ..data.cifar import augment_batch, normalize, standardize, to_float
from .train_state import TrainState


def cross_entropy_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy with integer labels (worker.py:131 used
    nn.CrossEntropyLoss)."""
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def _variables(params, batch_stats):
    """BatchNorm-free models (ViT) carry an empty batch_stats collection."""
    v = {"params": params}
    if batch_stats:
        v["batch_stats"] = batch_stats
    return v


def _forward_loss(state: TrainState, params, images, labels):
    outputs, mutated = state.apply_fn(
        _variables(params, state.batch_stats),
        images, train=True, mutable=["batch_stats"],
    )
    loss = cross_entropy_loss(outputs, labels)
    return loss, (outputs, mutated.get("batch_stats", {}))


def collect_moe_stats(intermediates: dict) -> list[dict]:
    """All ``moe_stats`` entries sown by SwitchMoEMlp layers
    (models/vit.py), in module-tree order — one dict per MoE layer."""
    found: list[dict] = []

    def walk(node):
        if isinstance(node, dict):
            for k, v in node.items():
                if k == "moe_stats":
                    found.extend(v)   # sow stores a tuple of entries
                else:
                    walk(v)

    walk(intermediates)
    return found


def make_train_step(augment: bool = True,
                    moe_aux_weight: float | None = None) -> Callable:
    """Build ``train_step(state, images_u8, labels, rng) -> (state, metrics)``.

    ``images_u8`` is the raw uint8 batch; normalization and augmentation
    happen on device inside the compiled program.

    ``moe_aux_weight is not None`` (MoE models): routing stats sown by
    each SwitchMoEMlp layer are collected — metrics gain ``moe_aux_loss``,
    ``moe_load_imbalance`` (max/mean expert load) and ``moe_drop_frac`` —
    and the Switch load-balance loss (mean across layers) is weighted into
    the training loss. Weight 0.0 keeps the observability with balancing
    OFF (the recorded contrast runs use it).
    """

    def train_step(state: TrainState, images_u8: jax.Array,
                   labels: jax.Array, rng: jax.Array):
        rng = jax.random.fold_in(rng, state.step)
        # torchvision order (worker.py:145-154): RandomCrop/Flip on raw
        # pixels (zero pad = black) -> ToTensor -> Normalize. The crop/flip
        # gathers run on the uint8 pixels — bit-identical floats to casting
        # first (pure index permutations, zero pad in either domain) at 1/4
        # the gather bandwidth; the two batched gathers once cost ~45% of
        # the ResNet-18 step.
        images = images_u8
        if augment:
            images = augment_batch(rng, images)
        images = standardize(to_float(images))

        if moe_aux_weight is not None:
            def loss_fn(p):
                outputs, mutated = state.apply_fn(
                    _variables(p, state.batch_stats), images, train=True,
                    mutable=["batch_stats", "intermediates"],
                )
                layers = collect_moe_stats(
                    mutated.get("intermediates", {}))
                aux = (jnp.mean(jnp.stack([s["aux_loss"] for s in layers]))
                       if layers else jnp.float32(0.0))
                ce = cross_entropy_loss(outputs, labels)
                loss = ce + moe_aux_weight * aux
                return loss, (outputs,
                              mutated.get("batch_stats", {}),
                              {"ce": ce, "aux": aux, "layers": layers})
            grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
            (loss, (logits, new_stats, moe)), grads = grad_fn(state.params)
        else:
            grad_fn = jax.value_and_grad(
                lambda p: _forward_loss(state, p, images, labels),
                has_aux=True)
            (loss, (logits, new_stats)), grads = grad_fn(state.params)
            moe = None

        state = state.apply_gradients(grads=grads)
        state = state.replace(batch_stats=new_stats)
        accuracy = jnp.mean(jnp.argmax(logits, -1) == labels)
        metrics = {"loss": loss, "accuracy": accuracy}
        if moe is not None and moe["layers"]:
            load = jnp.stack([s["load"] for s in moe["layers"]])  # [L, E]
            metrics.update({
                "loss": moe["ce"],            # comparable across modes
                "moe_aux_loss": moe["aux"],
                "moe_load_imbalance": jnp.mean(
                    jnp.max(load, axis=1) / jnp.maximum(
                        jnp.mean(load, axis=1), 1e-9)),
                "moe_drop_frac": jnp.mean(jnp.stack(
                    [s["drop_frac"] for s in moe["layers"]])),
            })
        return state, metrics

    return train_step


def make_grad_step(model, augment: bool = True) -> Callable:
    """Build the *worker-local* step: forward/backward WITHOUT the update.

    This is the async-mode analogue of the reference worker's
    ``train_local_batch`` (worker.py:333-348): zero_grad -> forward -> CE
    loss -> backward, with the parameter update left to the parameter store
    (server.py:126-143). Returns
    ``grad_step(params, batch_stats, images_u8, labels, rng, step)
    -> (grads, new_batch_stats, loss, accuracy)``, jit-compiled once and
    shared by all worker threads (same shapes => one executable).
    """

    @jax.jit
    def grad_step(params, batch_stats, images_u8, labels, rng, step):
        rng = jax.random.fold_in(rng, step)
        # Augment on the raw uint8 pixels (see make_train_step): same
        # floats, 1/4 the gather bandwidth.
        images = images_u8
        if augment:
            images = augment_batch(rng, images)
        images = standardize(to_float(images))

        def loss_fn(p):
            outputs, mutated = model.apply(
                _variables(p, batch_stats),
                images, train=True, mutable=["batch_stats"],
            )
            loss = cross_entropy_loss(outputs, labels)
            return loss, (outputs, mutated.get("batch_stats", {}))

        (loss, (logits, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        accuracy = jnp.mean(jnp.argmax(logits, -1) == labels)
        return grads, new_stats, loss, accuracy

    return grad_step


def make_fused_local_step(model, augment: bool = True) -> Callable:
    """Build the DONATED fused worker-local step for ``local_sgd`` mode:
    grads + SGD apply + window-accumulator update as ONE compiled program.

    ``fused_step(params, accum, batch_stats, images_u8, labels, rng,
    step, lr) -> (new_params, new_accum, new_batch_stats, loss, accuracy)``
    with ``donate_argnums=(0, 1, 2)``: params, the gradient accumulator,
    and batch_stats are donated, so XLA updates them in place — no
    param-sized allocation and no device->host->device round-trip inside
    the K-step window. The worker trains along its LOCAL trajectory
    (params -= lr * grads each batch, the same plain-SGD apply the server
    runs) and pushes the window's accumulated gradient sum at the
    boundary; with K=1 the accumulator carries exactly one batch's
    gradients at the fetched params, so the pushed payload matches
    'faithful' mode bit-for-bit (up to +0/-0 on exactly-zero gradient
    entries: the accumulator's ``0 + g``). ``lr`` is traced (one
    executable serves any learning rate).
    """

    from functools import partial

    @partial(jax.jit, donate_argnums=(0, 1, 2))
    def fused_step(params, accum, batch_stats, images_u8, labels, rng,
                   step, lr):
        rng = jax.random.fold_in(rng, step)
        images = images_u8
        if augment:
            images = augment_batch(rng, images)
        images = standardize(to_float(images))

        def loss_fn(p):
            outputs, mutated = model.apply(
                _variables(p, batch_stats),
                images, train=True, mutable=["batch_stats"],
            )
            loss = cross_entropy_loss(outputs, labels)
            return loss, (outputs, mutated.get("batch_stats", {}))

        (loss, (logits, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        new_params = jax.tree_util.tree_map(
            lambda p, g: p - lr * g, params, grads)
        new_accum = jax.tree_util.tree_map(
            lambda a, g: a + g, accum, grads)
        accuracy = jnp.mean(jnp.argmax(logits, -1) == labels)
        return new_params, new_accum, new_stats, loss, accuracy

    return fused_step


def make_eval_step() -> Callable:
    """Build ``eval_step(state, images_u8, labels) -> (correct, total)``.

    Top-1 over the full test set, matching worker.py:313-331 /
    baseline_training.py:181-199.
    """

    def eval_step(state: TrainState, images_u8: jax.Array, labels: jax.Array):
        images = normalize(images_u8)
        logits = state.apply_fn(
            _variables(state.params, state.batch_stats),
            images, train=False)
        correct = jnp.sum(jnp.argmax(logits, -1) == labels)
        return correct, labels.shape[0]

    return eval_step
