"""Distributed trainer drivers: the run recipes of the reference, in-process.

`SyncTrainer` is the TPU-native sync mode: N logical workers = N mesh slots,
one SPMD step per global batch (parallel/sync_dp.py). It subsumes the
reference's server+N-worker deployment for sync runs — there is no server.

`AsyncTrainer` wires the host-CPU ParameterStore to N worker threads
(ps/worker.py), reproducing the async_Nworkers experiment configs
(EXPERIMENT_GUIDE.md:95-111).

Both emit the METRICS_JSON lines the reference's ETL expects (SURVEY.md §5.5).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass

import jax
import numpy as np

from ..data.cifar import Dataset, make_batches
from ..parallel.mesh import make_mesh
from ..parallel.sync_dp import make_sync_dp_step, shard_batch
from ..ps.store import ParameterStore, StoreConfig
from ..ps.worker import WorkerConfig, run_workers
from ..utils.metrics import emit_metrics_json
from ..utils.pytree import flatten_params
from .optimizers import server_sgd
from .steps import make_eval_step
from .train_state import create_train_state


@dataclass
class DistributedConfig:
    mode: str = "sync"             # SERVER_MODE (server.py:407-417)
    num_workers: int = 4           # TOTAL_WORKERS_EXPECTED
    learning_rate: float = 0.1     # server lr (server.py:413)
    num_epochs: int = 3            # worker.py:466 default
    batch_size: int = 128          # per worker (worker.py:462)
    sync_steps: int = 1            # K (worker.py:468)
    k_step_mode: str = "faithful"
    staleness_bound: int = 5       # server.py:418
    compression: str = "bf16"      # sync all-reduce dtype
    strict_rounds: bool = False
    elastic: bool = False          # elastic membership (StoreConfig.elastic)
    worker_timeout: float | None = None  # liveness expiry (seconds)
    # Overlapped comms pipeline + version-gated delta fetches for the
    # PS-worker path (ps/worker.py WorkerConfig fields of the same names);
    # the SPMD sync trainer has no RPCs to overlap.
    overlap: bool = False
    delta_fetch: bool = True
    # Async store backend: 'python' (host numpy), 'native' (C++ arena), or
    # 'device' (HBM-resident — zero host-link bytes per worker step; the
    # only backend that runs reference-scale async on a remote-attached
    # chip).
    store_backend: str = "python"
    augment: bool = True
    num_classes: int = 100
    dtype: str = "bfloat16"
    model: str = "resnet18"        # models/registry.py name
    seed: int = 0


class SyncTrainer:
    """Sync data-parallel training over a device mesh (no server process).

    Multi-host: when the process has already joined a multi-controller job
    (``parallel.initialize_multihost``; ``jax.process_count() > 1``), the
    mesh spans every host's devices, each process contributes its contiguous
    slice of the global batch, and the same compiled step runs everywhere —
    the TPU-native version of the reference's multi-machine deployment
    (terraform/main.tf:387-435), with DCN in place of the NLB.
    """

    def __init__(self, dataset: Dataset, config: DistributedConfig | None = None):
        self.config = cfg = config or DistributedConfig()
        self.dataset = dataset
        self.multihost = jax.process_count() > 1
        if self.multihost:
            from ..parallel.multihost import make_global_mesh
            self.mesh = make_global_mesh()
            # logical workers == global mesh slots in multi-host mode
            if cfg.num_workers != jax.device_count() \
                    and jax.process_index() == 0:
                print(f"multihost: overriding --workers {cfg.num_workers} "
                      f"-> {jax.device_count()} (one logical worker per "
                      f"device across {jax.process_count()} processes); "
                      f"global batch = batch_size x {jax.device_count()}")
            cfg.num_workers = jax.device_count()
        else:
            self.mesh = make_mesh(cfg.num_workers)
        import jax.numpy as jnp

        from ..models import get_model
        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        self.model = get_model(cfg.model, num_classes=cfg.num_classes,
                               dtype=dtype, axis_name="data",
                               image_size=dataset.x_train.shape[1])
        h, w = dataset.x_train.shape[1:3]
        self.state = create_train_state(
            self.model, jax.random.PRNGKey(cfg.seed),
            server_sgd(cfg.learning_rate), input_shape=(1, h, w, 3))
        if self.multihost:
            from ..parallel.multihost import replicate_to_mesh
            self.state = replicate_to_mesh(self.mesh, self.state)
        self._step = make_sync_dp_step(self.mesh,
                                       compression=cfg.compression,
                                       augment=cfg.augment)
        self._eval_step = jax.jit(make_eval_step())
        self.epoch_times: list[float] = []
        self.test_accuracies: list[float] = []
        self.global_steps = 0

    def _shard(self, batch):
        if self.multihost:
            from ..parallel.multihost import shard_batch_global
            return shard_batch_global(self.mesh, batch)
        return shard_batch(self.mesh, batch)

    def train(self, emit_metrics: bool = False,
              checkpoint_dir: str | None = None,
              resume: bool = False) -> dict:
        cfg = self.config
        global_batch = cfg.batch_size * cfg.num_workers
        rng = jax.random.PRNGKey(cfg.seed + 1)

        # Orbax checkpoint per epoch (the recovery story the reference only
        # planned: DEPLOYMENT.md:309, <30 s target in baseline_summary.json).
        mgr = None
        start_epoch = 0
        if checkpoint_dir:
            from ..checkpoint import CheckpointManager
            mgr = CheckpointManager(checkpoint_dir)
            if resume and mgr.latest_step() is not None:
                self.state = mgr.restore(self.state)
                steps_per_epoch = max(
                    1, len(self.dataset.x_train) // global_batch)
                self.global_steps = int(self.state.step)
                start_epoch = self.global_steps // steps_per_epoch
                if jax.process_index() == 0:
                    print(f"resumed from step {self.global_steps} "
                          f"(epoch {start_epoch + 1})")

        # Live telemetry (telemetry/): the sync trainer IS the whole
        # server+workers deployment here, so one set of mode-labeled
        # instruments gives the snapshot stream its throughput series.
        from ..telemetry import (GoodputAccount, get_registry,
                                 now as _tnow, trace_span)
        reg = get_registry()
        tm_step_s = reg.histogram("dps_trainer_step_seconds", mode="sync")
        tm_steps = reg.counter("dps_trainer_steps_total", mode="sync")
        tm_images = reg.counter("dps_trainer_images_total", mode="sync")
        tm_epoch = reg.gauge("dps_trainer_epoch", mode="sync")
        tm_acc = reg.gauge("dps_trainer_test_accuracy", mode="sync")
        tm_gstep = reg.gauge("dps_store_global_step", backend="spmd")

        # Goodput ledger (telemetry/goodput.py): the sync trainer's wall
        # classifies into compute / checkpoint / other — no comms phases
        # exist outside the compiled program, so a large residual here
        # means host-side input/bookkeeping drag.
        gp = GoodputAccount(reg)
        gp.start_wall()

        t_start = time.time()
        per_worker_epochs = []   # per epoch: {"loss": [N], "accuracy": [N]}
        for epoch in range(start_epoch, cfg.num_epochs):
            t0 = time.time()
            losses = []
            wl, wa = [], []
            for xb, yb in make_batches(self.dataset.x_train,
                                       self.dataset.y_train, global_batch,
                                       seed=cfg.seed * 997 + epoch):
                bi, bl = self._shard((xb, yb))
                t_step = _tnow()
                # Root span per SPMD step: there are no comms phases here
                # (the all-reduce is inside the compiled program), so the
                # trace's value is the step-time series itself — same
                # dispatch-to-return caveat as the histogram below.
                with trace_span("trainer.step", root=True, mode="sync",
                                step=self.global_steps, epoch=epoch), \
                        gp.span("compute"):
                    self.state, m = self._step(self.state, bi, bl, rng)
                losses.append(m["loss"])
                # Span = dispatch-to-return; appending m["loss"] keeps a
                # handle the epoch print later forces, and the per-epoch
                # wall time (t0 delta) bounds any async-dispatch slack.
                tm_step_s.observe(_tnow() - t_step)
                tm_steps.inc()
                tm_images.inc(len(xb))
                if not self.multihost:
                    # Multihost: the [N] vectors span processes and can't
                    # be fetched locally; per-worker rows stay derived.
                    wl.append(m["worker_loss"])
                    wa.append(m["worker_accuracy"])
                self.global_steps += 1
                tm_gstep.set(self.global_steps)
                gp.tick_wall()
            if wl:
                per_worker_epochs.append({
                    "loss": np.mean(np.asarray(wl, np.float32), axis=0),
                    "accuracy": np.mean(np.asarray(wa, np.float32), axis=0),
                })
            # In multihost mode only rank 0 pays for the full test pass —
            # the state is replicated, so the others' evals would be
            # identical duplicated work on the critical path.
            if self.multihost and jax.process_index() != 0:
                acc = float("nan")
            else:
                with gp.span("compute"):
                    acc = self.evaluate()
            self.epoch_times.append(time.time() - t0)
            self.test_accuracies.append(acc)
            tm_epoch.set(epoch + 1)
            if acc == acc:  # skip non-evaluating multihost ranks' NaN
                tm_acc.set(acc)
            if jax.process_index() == 0:
                print(f"[sync x{cfg.num_workers}] epoch {epoch + 1}: "
                      f"loss {float(np.mean([float(l) for l in losses])):.4f} "
                      f"test {acc:.2%} ({self.epoch_times[-1]:.1f}s)")
            if mgr is not None and jax.process_index() == 0:
                # State is replicated; process 0's copy is the full model.
                with gp.span("checkpoint"):
                    mgr.save(self.state)
            gp.tick_wall()
        total = time.time() - t_start
        if mgr is not None:
            mgr.close()

        server_metrics = {
            "mode": "sync",
            "total_workers": cfg.num_workers,
            "total_training_time_seconds": round(total, 2),
            "global_steps_completed": self.global_steps,
            "total_parameter_updates": self.global_steps,
            "gradients_processed": self.global_steps * cfg.num_workers,
            "average_update_time_seconds": round(
                total / max(self.global_steps, 1), 6),
            "updates_per_second": round(self.global_steps / total, 3),
            "learning_rate": cfg.learning_rate,
        }
        if emit_metrics and jax.process_index() == 0:
            emit_metrics_json(server_metrics)
            for wid in range(cfg.num_workers):
                # Per-worker rows: train loss/accuracy are MEASURED per
                # mesh slot (each worker's own shard, from the sharded
                # step); time and test-accuracy fields are properties of
                # the single SPMD program / replicated model — identical
                # for every worker BY CONSTRUCTION, not independently
                # measured, and marked so (round-4 VERDICT item 10; the
                # round-3 rows were N indistinguishable copies).
                row = {
                    "worker_id": wid,
                    "total_workers": cfg.num_workers,
                    "total_training_time_seconds": round(total, 2),
                    "average_epoch_time_seconds": round(
                        float(np.mean(self.epoch_times)), 2),
                    "epoch_times_seconds": [round(t, 2)
                                            for t in self.epoch_times],
                    "final_test_accuracy": self.test_accuracies[-1],
                    "all_test_accuracies": self.test_accuracies,
                    "shared_model_metrics": True,
                    "local_steps_completed": self.global_steps,
                    "batch_size": cfg.batch_size,
                    "learning_rate": cfg.learning_rate,
                    "num_epochs": cfg.num_epochs,
                }
                if per_worker_epochs:
                    row.update({
                        "train_loss_per_epoch": [
                            round(float(pe["loss"][wid]), 4)
                            for pe in per_worker_epochs],
                        "train_accuracy_per_epoch": [
                            round(float(pe["accuracy"][wid]), 4)
                            for pe in per_worker_epochs],
                        "measured_per_worker_fields": [
                            "train_loss_per_epoch",
                            "train_accuracy_per_epoch"],
                    })
                emit_metrics_json(row)
        return server_metrics

    def evaluate(self) -> float:
        state = self.state
        if self.multihost:
            # The state is fully replicated, so every process holds a
            # complete copy — fetch it and evaluate locally (no collective).
            from ..parallel.multihost import fetch_replicated
            state = fetch_replicated(self.state)
        correct = total = 0
        for xb, yb in make_batches(self.dataset.x_test, self.dataset.y_test,
                                   1000, shuffle=False,
                                   drop_remainder=False):
            c, t = self._eval_step(state, xb, yb)
            correct += int(c)
            total += int(t)
        return correct / max(total, 1)


class AsyncTrainer:
    """Async bounded-staleness training: host-CPU store + N worker threads."""

    def __init__(self, dataset: Dataset, config: DistributedConfig | None = None):
        self.config = cfg = config or DistributedConfig()
        self.dataset = dataset
        import jax.numpy as jnp

        from ..models import get_model
        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        self.model = get_model(cfg.model, num_classes=cfg.num_classes,
                               dtype=dtype,
                               image_size=dataset.x_train.shape[1])
        h, w = dataset.x_train.shape[1:3]
        variables = self.model.init(
            jax.random.PRNGKey(cfg.seed),
            np.zeros((1, h, w, 3), np.float32), train=False)
        from ..ps import make_store
        self.store = make_store(
            cfg.store_backend, flatten_params(variables["params"]),
            StoreConfig(mode=cfg.mode, total_workers=cfg.num_workers,
                        learning_rate=cfg.learning_rate,
                        staleness_bound=cfg.staleness_bound,
                        strict_rounds=cfg.strict_rounds,
                        elastic=cfg.elastic,
                        worker_timeout=cfg.worker_timeout))

    def train(self, emit_metrics: bool = False,
              checkpoint_dir: str | None = None,
              resume: bool = False,
              checkpoint_interval: float = 30.0) -> dict:
        cfg = self.config
        ckpt = None
        if checkpoint_dir:
            from ..checkpoint import (PeriodicStoreCheckpointer,
                                      restore_store)
            if resume and os.path.isdir(checkpoint_dir) and any(
                    f.endswith(".npz") for f in os.listdir(checkpoint_dir)):
                step = restore_store(self.store, checkpoint_dir)
                print(f"resumed store from global step {step}")
            ckpt = PeriodicStoreCheckpointer(self.store, checkpoint_dir,
                                             interval=checkpoint_interval)
            ckpt.start()
        try:
            results = run_workers(
                self.store, self.model, self.dataset, cfg.num_workers,
                WorkerConfig(batch_size=cfg.batch_size,
                             num_epochs=cfg.num_epochs,
                             sync_steps=cfg.sync_steps,
                             k_step_mode=cfg.k_step_mode,
                             overlap=cfg.overlap,
                             delta_fetch=cfg.delta_fetch,
                             augment=cfg.augment, seed=cfg.seed,
                             # With expiry on, workers must prove liveness
                             # even while their first step COMPILES (which
                             # can exceed the timeout): the heartbeat fetch
                             # starts before compilation.
                             heartbeat_interval=(cfg.worker_timeout / 3
                                                 if cfg.worker_timeout
                                                 else 0.0)))
        finally:
            if ckpt is not None:
                ckpt.stop(final_snapshot=True)
        server_metrics = self.store.metrics()
        if emit_metrics:
            emit_metrics_json(server_metrics)
            wc = WorkerConfig(batch_size=cfg.batch_size,
                              num_epochs=cfg.num_epochs)
            for r in results:
                emit_metrics_json(r.metrics(cfg.num_workers,
                                            cfg.learning_rate, wc))
        return server_metrics
