"""Optimizers, reproducing the reference's (inconsistent) choices explicitly.

The reference has TWO optimizer configurations with a documented discrepancy
(SURVEY.md §2.12): the parameter server applies plain ``p -= lr * g``
(server.py:133, lr 0.1) while workers *configure* SGD(momentum=0.9,
weight_decay=5e-4) but never call ``optimizer.step()`` — momentum and weight
decay are dead in distributed mode. The single-machine baseline uses the full
SGD(momentum 0.9, wd 5e-4) + MultiStepLR([10,15], gamma 0.1)
(baseline/baseline_training.py:223-224).

We reproduce both *deliberately*: :func:`server_sgd` is the distributed-mode
optimizer (matching the server math), :func:`baseline_optimizer` is the
baseline recipe, and callers may opt into the full recipe for distributed
training too (the "corrected" choice the reference never made).
"""

from __future__ import annotations

from typing import Sequence

import optax


def server_sgd(learning_rate: float = 0.1) -> optax.GradientTransformation:
    """Plain SGD: exactly the server update ``p -= lr * g`` (server.py:133)."""
    return optax.sgd(learning_rate)


def baseline_optimizer(
    learning_rate: float = 0.1,
    momentum: float = 0.9,
    weight_decay: float = 5e-4,
    milestones: Sequence[int] = (10, 15),
    gamma: float = 0.1,
    steps_per_epoch: int = 1,
) -> optax.GradientTransformation:
    """SGD(momentum, wd) + MultiStepLR, matching baseline_training.py:223-224.

    torch semantics: weight decay is added to the raw gradient *before* the
    momentum buffer update, hence ``add_decayed_weights`` ahead of ``sgd``.
    ``milestones`` are epochs; the piecewise schedule operates on steps.
    """
    boundaries = {int(m) * int(steps_per_epoch): gamma for m in milestones}
    schedule = optax.piecewise_constant_schedule(learning_rate, boundaries)
    return optax.chain(
        optax.add_decayed_weights(weight_decay),
        optax.sgd(schedule, momentum=momentum),
    )
