"""Trainable tensor- and pipeline-parallel modes (net-new vs the reference).

The reference has no model sharding of any kind (SURVEY.md §2 parallelism
checklist: TP/PP rows "No"); round 1 built the primitives
(parallel/tensor.py sharding rules, parallel/pipeline.py GPipe schedule) and
proved numerics — this module makes them USABLE: full train loops with the
standard epoch/eval/metrics surface, selectable from the CLI
(``train --mode tp`` / ``--mode pp``).

TPTrainer — GSPMD data x model:
    ViT parameters are placed per the Megatron split rules and the batch is
    sharded along ``data``; ONE jitted train step runs both parallelisms,
    with XLA inserting the gradient all-reduce (data) and the activation
    all-reduces (model). No collective appears in model code.

PipelineTrainer — GPipe over real ViT block groups:
    The shape-changing prologue (patch embed + cls + pos) and epilogue
    (final LN + head) run replicated; the encoder's ``depth`` blocks are
    grouped into S shape-preserving stages (models/vit.py:EncoderStage)
    whose parameters live one-per-mesh-slot, exactly the layout
    parallel/pipeline.py ships around the ring. jax autodiff through the
    schedule gives pipelined training without a hand-written backward pass.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..data.cifar import (Dataset, augment_batch, make_batches, standardize,
                          to_float)
from ..models.vit import EncoderStage, ViTEpilogue, ViTPrologue
from ..parallel.mesh import make_mesh
from ..parallel.pipeline import make_pipeline_apply, stack_stage_params
from ..parallel.tensor import shard_train_state
from ..train.optimizers import server_sgd
from ..train.steps import cross_entropy_loss, make_eval_step, make_train_step
from ..train.train_state import create_train_state
from ..utils.metrics import emit_metrics_json
from .train_state import TrainState

# ViT shapes by registry name, CIFAR-resolution patch sizes.
VIT_SHAPES = {
    "vit_tiny": dict(patch_size=4, hidden_dim=192, depth=4, num_heads=3),
    "vit_b16": dict(patch_size=16, hidden_dim=768, depth=12, num_heads=12),
}


@dataclass
class ModelParallelConfig:
    model: str = "vit_tiny"
    num_workers: int = 4           # data-parallel degree (tp) / stages (pp)
    tp_degree: int = 2             # model-axis size (tp mode)
    pp_microbatches: int = 8       # GPipe M (pp mode)
    # Composed axes for pp mode (round-2 VERDICT item 7): microbatches
    # additionally shard over a 'data' axis, and stage params Megatron-split
    # over a 'model' axis — mesh (dp, tp, stages), dp x tp x pp in one step.
    dp_degree: int = 1
    pp_tp_degree: int = 1
    # MoE (moe mode): per-expert buffer = capacity_factor x the
    # even-routing load; Switch aux-loss weight (0 disables balancing).
    moe_capacity_factor: float = 2.0
    moe_aux_weight: float = 0.01
    learning_rate: float = 0.1
    num_epochs: int = 3
    batch_size: int = 128          # GLOBAL batch
    augment: bool = True
    num_classes: int = 100
    dtype: str = "bfloat16"
    seed: int = 0


class _EpochTrainer:
    """Shared epoch loop for the model-parallel trainers: batching, eval,
    per-epoch Orbax checkpointing / --resume, METRICS_JSON fields. Subclasses
    set ``mode``, implement ``_train_batch`` / ``evaluate`` /
    ``_extra_metrics``, and may override ``_after_restore`` to re-place
    restored params on the mesh."""

    mode = "?"

    def __init__(self, dataset: Dataset, config: ModelParallelConfig):
        self.config = config
        self.dataset = dataset
        self.epoch_times: list[float] = []
        self.test_accuracies: list[float] = []
        self.global_steps = 0

    def _train_batch(self, xb, yb, rng):
        raise NotImplementedError

    def evaluate(self) -> float:
        raise NotImplementedError

    def _extra_metrics(self) -> dict:
        return {}

    def _label(self) -> str:
        return self.mode

    def _after_restore(self) -> None:
        """Re-place restored (host) params on the mesh."""

    def _make_steps(self, forward):
        """Build the jitted (train_step, eval_step) pair around a pure
        ``forward(params, images_std) -> logits``: shared uint8->augment->
        standardize preprocessing, CE loss, grad + SGD apply."""
        augment = self.config.augment

        def train_step(state, images_u8, labels, rng_key):
            rng_key = jax.random.fold_in(rng_key, state.step)
            # uint8-domain augment: same floats, 1/4 the gather bandwidth
            # (train/steps.py).
            images = images_u8
            if augment:
                images = augment_batch(rng_key, images)
            images = standardize(to_float(images))

            def loss_fn(p):
                logits = forward(p, images)
                return cross_entropy_loss(logits, labels), logits

            (loss, logits), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params)
            state = state.apply_gradients(grads=grads)
            acc = jnp.mean(jnp.argmax(logits, -1) == labels)
            return state, {"loss": loss, "accuracy": acc}

        def eval_step(params, images_u8, labels):
            logits = forward(params, standardize(to_float(images_u8)))
            return (jnp.sum(jnp.argmax(logits, -1) == labels),
                    labels.shape[0])

        return (jax.jit(train_step, donate_argnums=0), jax.jit(eval_step))

    def train(self, emit_metrics: bool = False,
              checkpoint_dir: str | None = None,
              resume: bool = False) -> dict:
        cfg = self.config
        steps_per_epoch = max(1, len(self.dataset.x_train) // cfg.batch_size)
        mgr = None
        start_epoch = 0
        if checkpoint_dir:
            from ..checkpoint import CheckpointManager
            mgr = CheckpointManager(checkpoint_dir)
            if resume and mgr.latest_step() is not None:
                self.state = mgr.restore(self.state)
                self._after_restore()
                self.global_steps = int(self.state.step)
                start_epoch = self.global_steps // steps_per_epoch
                print(f"resumed from step {self.global_steps} "
                      f"(epoch {start_epoch + 1})")

        rng = jax.random.PRNGKey(cfg.seed + 1)
        t_start = time.time()
        for epoch in range(start_epoch, cfg.num_epochs):
            t0 = time.time()
            losses = []
            for xb, yb in make_batches(self.dataset.x_train,
                                       self.dataset.y_train, cfg.batch_size,
                                       seed=cfg.seed * 997 + epoch):
                self.state, m = self._train_batch(xb, yb, rng)
                losses.append(m["loss"])
                self.global_steps += 1
            acc = self.evaluate()
            self.epoch_times.append(time.time() - t0)
            self.test_accuracies.append(acc)
            print(f"[{self._label()}] epoch {epoch + 1}: "
                  f"loss {float(np.mean([float(l) for l in losses])):.4f} "
                  f"test {acc:.2%} ({self.epoch_times[-1]:.1f}s)")
            if mgr is not None:
                mgr.save(self.state)
        total = time.time() - t_start
        if mgr is not None:
            mgr.close()
        metrics = {
            "mode": self.mode,
            "total_workers": cfg.num_workers,
            "total_training_time_seconds": round(total, 2),
            "global_steps_completed": self.global_steps,
            "total_parameter_updates": self.global_steps,
            "learning_rate": cfg.learning_rate,
            "final_test_accuracy": (self.test_accuracies[-1]
                                    if self.test_accuracies else 0.0),
            "all_test_accuracies": self.test_accuracies,
            **self._extra_metrics(),
        }
        if emit_metrics:
            emit_metrics_json(metrics)
        return metrics


class TPTrainer(_EpochTrainer):
    """Data x tensor parallel ViT training via GSPMD sharding annotations."""

    mode = "tp"

    def __init__(self, dataset: Dataset, config: ModelParallelConfig | None = None):
        from jax.sharding import NamedSharding, PartitionSpec as P

        super().__init__(dataset, config or ModelParallelConfig())
        cfg = self.config
        if cfg.model not in VIT_SHAPES:
            raise ValueError(
                f"--mode tp supports transformer models {tuple(VIT_SHAPES)}; "
                f"BatchNorm models need the shard_map sync path (--mode sync)")
        dp, tp = cfg.num_workers, cfg.tp_degree
        devs = jax.devices()
        if dp * tp > len(devs):
            raise ValueError(f"dp {dp} x tp {tp} > {len(devs)} devices")
        self.mesh = make_mesh(dp, axis_names=("data", "model"),
                              devices=devs[:dp * tp])

        from ..models import get_model
        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        h, w = dataset.x_train.shape[1:3]
        self.model = get_model(cfg.model, num_classes=cfg.num_classes,
                               dtype=dtype, image_size=h)
        state = create_train_state(self.model, jax.random.PRNGKey(cfg.seed),
                                   server_sgd(cfg.learning_rate),
                                   input_shape=(1, h, w, 3))
        # Megatron placement: qkv/fc1 column-split, out/fc2 row-split over
        # 'model'; everything else replicated (parallel/tensor.py rules).
        self.state = shard_train_state(state, self.mesh)
        self._step = jax.jit(make_train_step(augment=cfg.augment),
                             donate_argnums=0)
        self._eval_step = jax.jit(make_eval_step())
        self._batch_sharding = NamedSharding(self.mesh, P("data"))

    def _label(self) -> str:
        return f"tp {self.config.num_workers}x{self.config.tp_degree}"

    def _extra_metrics(self) -> dict:
        return {"tp_degree": self.config.tp_degree}

    def _after_restore(self) -> None:
        self.state = shard_train_state(self.state, self.mesh)

    def _train_batch(self, xb, yb, rng):
        return self._step(self.state,
                          jax.device_put(xb, self._batch_sharding),
                          jax.device_put(yb, self._batch_sharding), rng)

    def evaluate(self) -> float:
        correct = total = 0
        for xb, yb in make_batches(self.dataset.x_test, self.dataset.y_test,
                                   1000, shuffle=False,
                                   drop_remainder=False):
            c, t = self._eval_step(self.state, xb, yb)
            correct += int(c)
            total += int(t)
        return correct / max(total, 1)


class PipelineTrainer(_EpochTrainer):
    """GPipe training of ViT: encoder block groups as pipeline stages.

    Composes with data and tensor parallelism on a (data, model, stage)
    mesh: ``dp_degree`` shards each microbatch, ``pp_tp_degree``
    Megatron-splits the stage params over 'model' (GSPMD auto axis inside
    the pipeline shard_map). Defaults (1, 1) are plain pp.
    """

    mode = "pp"

    def __init__(self, dataset: Dataset, config: ModelParallelConfig | None = None):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        super().__init__(dataset, config or ModelParallelConfig())
        cfg = self.config
        shape = VIT_SHAPES.get(cfg.model)
        if shape is None:
            raise ValueError(
                f"--mode pp supports ViT models {tuple(VIT_SHAPES)}")
        n_stages = cfg.num_workers
        dp, tp = cfg.dp_degree, cfg.pp_tp_degree
        if shape["depth"] % n_stages:
            raise ValueError(f"depth {shape['depth']} not divisible by "
                             f"{n_stages} stages")
        if cfg.pp_microbatches > len(dataset.x_test):
            raise ValueError(
                f"test set ({len(dataset.x_test)}) smaller than "
                f"pp_microbatches ({cfg.pp_microbatches}) — eval would be "
                f"empty")
        mb = cfg.batch_size // cfg.pp_microbatches
        if cfg.batch_size % cfg.pp_microbatches or (dp > 1 and mb % dp):
            raise ValueError(
                f"batch {cfg.batch_size} must split into "
                f"{cfg.pp_microbatches} microbatches of a size divisible "
                f"by dp_degree {dp}")
        devs = jax.devices()
        if dp * tp * n_stages > len(devs):
            raise ValueError(f"dp {dp} x tp {tp} x {n_stages} stages > "
                             f"{len(devs)} devices")
        self.mesh = Mesh(
            np.array(devs[:dp * tp * n_stages]).reshape(dp, tp, n_stages),
            ("data", "model", "stage"))

        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        h, w = dataset.x_train.shape[1:3]
        self.prologue = ViTPrologue(patch_size=shape["patch_size"],
                                    hidden_dim=shape["hidden_dim"],
                                    dtype=dtype)
        self.stage = EncoderStage(num_blocks=shape["depth"] // n_stages,
                                  num_heads=shape["num_heads"], dtype=dtype)
        self.epilogue = ViTEpilogue(num_classes=cfg.num_classes, dtype=dtype)

        rng = jax.random.PRNGKey(cfg.seed)
        sample = jnp.zeros((1, h, w, 3), jnp.float32)
        pro_p = self.prologue.init(rng, sample)["params"]
        tokens = self.prologue.apply({"params": pro_p}, sample)
        stage_ps = [
            self.stage.init(jax.random.fold_in(rng, 100 + s), tokens)["params"]
            for s in range(n_stages)
        ]
        epi_p = self.epilogue.init(jax.random.fold_in(rng, 7),
                                   tokens)["params"]
        params = {
            "prologue": pro_p,
            "stages": stack_stage_params(stage_ps),  # [S, ...] per leaf
            "epilogue": epi_p,
        }
        self._replicated = NamedSharding(self.mesh, P())
        self._batch_sharding = NamedSharding(self.mesh, P("data"))
        params = self._place_params(params)

        self.state = TrainState.create(
            apply_fn=None, params=params, batch_stats={},
            tx=server_sgd(cfg.learning_rate))

        pipe_apply = make_pipeline_apply(
            self.mesh,
            lambda p, x: self.stage.apply({"params": p}, x),
            num_microbatches=cfg.pp_microbatches,
            data_axis="data")
        prologue, epilogue = self.prologue, self.epilogue

        def forward(params, images):
            tokens = prologue.apply({"params": params["prologue"]}, images)
            tokens = pipe_apply(params["stages"], tokens)
            return epilogue.apply({"params": params["epilogue"]}, tokens)

        self._step, self._eval_step = self._make_steps(forward)

    def _place_params(self, params: dict) -> dict:
        """Stage params one-per-slot on 'stage' — composed with the Megatron
        'model'-axis split on their trailing dims when pp_tp_degree > 1;
        prologue/epilogue replicate."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.tensor import tp_spec_for_path
        from ..utils.pytree import flatten_params, unflatten_params

        flat = flatten_params(params["stages"], as_numpy=False)
        placed_stages = {}
        for path, leaf in flat.items():
            tp_spec = (tp_spec_for_path(path)
                       if self.config.pp_tp_degree > 1 else P())
            spec = P("stage", *tp_spec)
            placed_stages[path] = jax.device_put(
                leaf, NamedSharding(self.mesh, spec))
        placed = {"stages": unflatten_params(placed_stages)}
        for k in ("prologue", "epilogue"):
            placed[k] = jax.tree_util.tree_map(
                lambda x: jax.device_put(x, self._replicated), params[k])
        return placed

    def _label(self) -> str:
        cfg = self.config
        composed = (f" x dp{cfg.dp_degree}" if cfg.dp_degree > 1 else "") + \
                   (f" x tp{cfg.pp_tp_degree}" if cfg.pp_tp_degree > 1
                    else "")
        return (f"pp {cfg.num_workers} stages "
                f"x{cfg.pp_microbatches} microbatches{composed}")

    def _extra_metrics(self) -> dict:
        return {"pp_microbatches": self.config.pp_microbatches,
                "dp_degree": self.config.dp_degree,
                "pp_tp_degree": self.config.pp_tp_degree}

    def _after_restore(self) -> None:
        self.state = self.state.replace(
            params=self._place_params(self.state.params))

    def _train_batch(self, xb, yb, rng):
        return self._step(self.state,
                          jax.device_put(xb, self._batch_sharding),
                          jax.device_put(yb, self._batch_sharding), rng)

    def evaluate(self) -> float:
        cfg = self.config
        correct = total = 0
        # Eval batch must divide into the microbatch count, each microbatch
        # must divide across the 'data' axis, and it must fit the test set
        # (init validated test set >= one microbatch group).
        m = cfg.pp_microbatches * max(1, cfg.dp_degree)
        bs = min((1000 // m) * m, (len(self.dataset.x_test) // m) * m)
        bs = max(bs, m)
        for xb, yb in make_batches(self.dataset.x_test, self.dataset.y_test,
                                   bs, shuffle=False, drop_remainder=True):
            c, t = self._eval_step(self.state.params, xb, yb)
            correct += int(c)
            total += int(t)
        return correct / max(total, 1)


# ---------------------------------------------------------------------------
# SP: sequence-parallel ViT (ring attention) as a trainable mode
# ---------------------------------------------------------------------------

class SPTrainer(_EpochTrainer):
    """Sequence-parallel training of the REGISTRY ViT: every encoder block's
    attention runs as RING attention over a ``seq`` mesh axis
    (parallel/ring_attention.py wired into models/vit.py:SelfAttention via
    ``attention_fn``), so no device ever holds a full [T, T] score matrix or
    the full K/V sequence.

    The long-context capability the reference entirely lacks (SURVEY.md
    §5.7), on the real model family: ``--mode sp --model vit_tiny|vit_b16``.
    ``pool='gap'`` (mean-pool head, no CLS token) keeps the sequence length
    a multiple of the shard count.
    """

    mode = "sp"

    def __init__(self, dataset: Dataset, config: ModelParallelConfig | None = None):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..models.vit import ViT
        from ..parallel.ring_attention import make_ring_attention

        super().__init__(dataset, config or ModelParallelConfig())
        cfg = self.config
        shape = VIT_SHAPES.get(cfg.model)
        if shape is None:
            raise ValueError(
                f"--mode sp supports ViT models {tuple(VIT_SHAPES)}")
        devs = jax.devices()
        n_shards = cfg.num_workers
        if n_shards > len(devs):
            raise ValueError(f"{n_shards} seq shards > {len(devs)} devices")
        h, w = dataset.x_train.shape[1:3]
        patch = shape["patch_size"]
        self.tokens = (h // patch) * (w // patch)
        if self.tokens % n_shards:
            raise ValueError(f"{self.tokens} tokens not divisible by "
                             f"{n_shards} sequence shards")
        self.mesh = make_mesh(n_shards, axis_names=("seq",),
                              devices=devs[:n_shards])
        # Long-context configs run the fused ring x flash composition —
        # flash kernels per hop, ppermute between — but ONLY when the
        # per-hop block length clears BOTH the Pallas tile constraint
        # (128-multiple, pick_block) and the MEASURED dense/flash
        # crossover (flash_preferred): round 3 showed flash LOSING to
        # the XLA-fused dense core below it (ViT-B/16 @224, 197 tokens:
        # 28.4% vs 43.8% MFU), so divisibility alone is not a reason to
        # select the fused kernel.
        per_shard = self.tokens // n_shards
        from ..ops.pallas.flash_attention import flash_preferred
        if per_shard % 128 == 0 and flash_preferred(per_shard):
            from ..parallel.ring_attention import make_ring_flash_attention
            ring = make_ring_flash_attention(self.mesh, axis="seq")
        else:
            ring = make_ring_attention(self.mesh, axis="seq", causal=False)

        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        self.model = ViT(patch_size=patch, hidden_dim=shape["hidden_dim"],
                         depth=shape["depth"], num_heads=shape["num_heads"],
                         num_classes=cfg.num_classes, dtype=dtype,
                         pool="gap", attention_fn=ring)
        state = create_train_state(self.model, jax.random.PRNGKey(cfg.seed),
                                   server_sgd(cfg.learning_rate),
                                   input_shape=(1, h, w, 3))
        # Weights replicate; only activations shard (along T, inside the
        # ring shard_map).
        self.state = jax.device_put(state, NamedSharding(self.mesh, P()))
        self._step = jax.jit(make_train_step(augment=cfg.augment),
                             donate_argnums=0)
        self._eval_step = jax.jit(make_eval_step())

    def _label(self) -> str:
        return (f"sp {self.config.model} {self.config.num_workers} "
                f"seq shards (T={self.tokens})")

    def _extra_metrics(self) -> dict:
        return {"seq_shards": self.config.num_workers,
                "tokens": self.tokens}

    def _train_batch(self, xb, yb, rng):
        return self._step(self.state, xb, yb, rng)

    def evaluate(self) -> float:
        correct = total = 0
        for xb, yb in make_batches(self.dataset.x_test, self.dataset.y_test,
                                   1000, shuffle=False,
                                   drop_remainder=False):
            c, t = self._eval_step(self.state, xb, yb)
            correct += int(c)
            total += int(t)
        return correct / max(total, 1)


# ---------------------------------------------------------------------------
# EP: Switch-MoE ViT as a trainable mode
# ---------------------------------------------------------------------------

class MoETrainer(_EpochTrainer):
    """Expert-parallel training of the REGISTRY ViT: each encoder block's
    dense MLP is replaced by the Switch top-1 MoE
    (models/vit.py:SwitchMoEMlp over parallel/moe.py) on an ``expert`` mesh
    axis — one expert per device, two all_to_all hops per layer. The batch
    shards along the same axis (tokens route ACROSS it), exactly as Switch
    Transformer composes EP with DP. ``--mode moe --model vit_tiny|vit_b16``.
    """

    mode = "moe"

    def __init__(self, dataset: Dataset, config: ModelParallelConfig | None = None):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..models.vit import ViT
        from ..parallel.moe import make_moe_ffn

        super().__init__(dataset, config or ModelParallelConfig())
        cfg = self.config
        shape = VIT_SHAPES.get(cfg.model)
        if shape is None:
            raise ValueError(
                f"--mode moe supports ViT models {tuple(VIT_SHAPES)}")
        devs = jax.devices()
        n_exp = cfg.num_workers
        dp = max(1, cfg.dp_degree)
        n_shards = n_exp * dp
        if n_shards > len(devs):
            raise ValueError(f"{n_exp} experts x dp {dp} > "
                             f"{len(devs)} devices")
        if cfg.batch_size % n_shards:
            raise ValueError(f"batch {cfg.batch_size} not divisible by "
                             f"{n_shards} token shards (experts x dp; "
                             f"the batch shards over both axes)")
        if len(dataset.x_test) < cfg.batch_size:
            raise ValueError(
                f"test set ({len(dataset.x_test)}) smaller than the batch "
                f"size ({cfg.batch_size}) — eval runs at the training batch "
                f"size (expert capacity is sized for it) and would be empty")
        # dp x ep (round-4 VERDICT weak 4): mesh (data, expert); each data
        # group routes its tokens over its own expert ring, expert weights
        # replicate over data (gradient psum from the shard_map transpose).
        if dp > 1:
            self.mesh = make_mesh(dp, axis_names=("data", "expert"),
                                  devices=devs[:n_shards])
            data_axis = "data"
            self._batch_spec = ("data", "expert")
        else:
            self.mesh = make_mesh(n_exp, axis_names=("expert",),
                                  devices=devs[:n_exp])
            data_axis = None
            self._batch_spec = "expert"
        self.dp_degree = dp
        h, w = dataset.x_train.shape[1:3]
        patch = shape["patch_size"]
        self.tokens = (h // patch) * (w // patch)
        d = shape["hidden_dim"]
        # Capacity: capacity_factor x the even-routing load per expert
        # per token shard (--moe-capacity-factor; Switch's knob).
        tokens_per_shard = cfg.batch_size * self.tokens // n_shards
        self.capacity = max(
            8, int(cfg.moe_capacity_factor * tokens_per_shard / n_exp))

        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        self.model = ViT(patch_size=patch, hidden_dim=d,
                         depth=shape["depth"], num_heads=shape["num_heads"],
                         num_classes=cfg.num_classes, dtype=dtype,
                         pool="gap",
                         moe_fn=make_moe_ffn(self.mesh,
                                             capacity=self.capacity,
                                             data_axis=data_axis),
                         moe_experts=n_exp)
        state = create_train_state(self.model, jax.random.PRNGKey(cfg.seed),
                                   server_sgd(cfg.learning_rate),
                                   input_shape=(1, h, w, 3))
        self.state = state.replace(params=self._place_params(state.params))
        self._step = jax.jit(
            make_train_step(augment=cfg.augment,
                            moe_aux_weight=cfg.moe_aux_weight),
            donate_argnums=0)
        self._eval_step = jax.jit(make_eval_step())
        self._batch_sharding = NamedSharding(self.mesh, P(self._batch_spec))
        self._moe_step_metrics: list[dict] = []

    def _place_params(self, params: dict) -> dict:
        """Expert-stacked SwitchMoEMlp leaves (w1/b1/w2/b2 under a 'moe'
        module) one-per-slot; router and everything else replicated
        (matches make_moe_ffn's in_specs)."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        exp = NamedSharding(self.mesh, P("expert"))
        rep = NamedSharding(self.mesh, P())

        def place(path, leaf):
            sharded = "/moe/" in path and path.rsplit("/", 1)[1] in (
                "w1", "b1", "w2", "b2")
            return jax.device_put(leaf, exp if sharded else rep)

        from ..utils.pytree import flatten_params, unflatten_params
        flat = flatten_params(params, as_numpy=False)
        return unflatten_params(
            {k: place(k, v) for k, v in flat.items()})

    def _label(self) -> str:
        return f"moe {self.config.model} {self.config.num_workers} experts"

    def _extra_metrics(self) -> dict:
        out = {"n_experts": self.config.num_workers,
               "expert_capacity": self.capacity,
               "moe_dp_degree": self.dp_degree,
               "moe_aux_weight": self.config.moe_aux_weight,
               "moe_capacity_factor": self.config.moe_capacity_factor}
        hist = [{k: float(v) for k, v in m.items()}
                for m in self._moe_step_metrics if m]
        if hist:
            # Device scalars accumulated per step; float()ed only here so
            # the train loop never blocks on the metrics stream.
            last = hist[-1]
            out.update({
                "moe_aux_loss": round(last["moe_aux_loss"], 4),
                "moe_load_imbalance": round(last["moe_load_imbalance"], 3),
                "moe_drop_frac": round(last["moe_drop_frac"], 4),
                "moe_load_imbalance_mean": round(float(np.mean(
                    [m["moe_load_imbalance"] for m in hist])), 3),
                "moe_drop_frac_mean": round(float(np.mean(
                    [m["moe_drop_frac"] for m in hist])), 4),
            })
        return out

    def _after_restore(self) -> None:
        self.state = self.state.replace(
            params=self._place_params(self.state.params))

    def _train_batch(self, xb, yb, rng):
        state, m = self._step(self.state,
                              jax.device_put(xb, self._batch_sharding),
                              jax.device_put(yb, self._batch_sharding), rng)
        self._moe_step_metrics.append(
            {k: m[k] for k in ("moe_aux_loss", "moe_load_imbalance",
                               "moe_drop_frac") if k in m})
        return state, m

    def evaluate(self) -> float:
        cfg = self.config
        correct = total = 0
        # Eval at the TRAINING batch size: expert capacity was sized for
        # that token load — a bigger eval batch would silently drop the
        # overflow tokens and understate accuracy.
        bs = cfg.batch_size
        for xb, yb in make_batches(self.dataset.x_test, self.dataset.y_test,
                                   bs, shuffle=False, drop_remainder=True):
            c, t = self._eval_step(self.state, xb, yb)
            correct += int(c)
            total += int(t)
        return correct / max(total, 1)
