"""Single-machine baseline trainer (reference: baseline/baseline_training.py).

Same recipe — ResNet-18/CIFAR-100, batch 128, SGD(momentum 0.9, wd 5e-4),
MultiStepLR([10,15], gamma 0.1), per-epoch train/test metrics and plots
(baseline_training.py:201-260) — but the epoch body is one jit-compiled
device program per batch instead of a Python/torch CPU loop; the reference
needed ~17 min/epoch on an M1 CPU (BASELINE.md), a v5e chip does it in ~3 s.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..data.cifar import Dataset, make_batches

from ..utils.metrics import emit_metrics_json
from .optimizers import baseline_optimizer, server_sgd
from .steps import make_eval_step, make_train_step
from .train_state import create_train_state


@dataclass
class BaselineConfig:
    batch_size: int = 128          # baseline_training.py:203
    num_epochs: int = 3            # baseline_training.py:204
    learning_rate: float = 0.1     # baseline_training.py:205
    momentum: float = 0.9          # baseline_training.py:223
    weight_decay: float = 5e-4
    milestones: tuple = (10, 15)   # baseline_training.py:224
    gamma: float = 0.1
    augment: bool = True
    num_classes: int = 100
    dtype: str = "bfloat16"        # TPU-first default; 'float32' for parity
    plain_sgd: bool = False        # True = the distributed server optimizer
    model: str = "resnet18"        # models/registry.py name
    seed: int = 0
    # True = run each epoch as ONE compiled program over a device-resident
    # dataset (train/device_loop.py) — epochs at compute speed even on a
    # remotely-attached chip. False = per-batch host dispatch (the
    # reference's DataLoader shape, baseline_training.py:149-179).
    device_loop: bool = False


@dataclass
class TrainingMetrics:
    """Per-epoch records (baseline_training.py:97-147 TrainingMetrics)."""

    epochs: list = field(default_factory=list)
    train_losses: list = field(default_factory=list)
    train_accuracies: list = field(default_factory=list)
    test_accuracies: list = field(default_factory=list)
    epoch_times: list = field(default_factory=list)

    def add_epoch(self, epoch, loss, train_acc, test_acc, seconds):
        self.epochs.append(epoch)
        self.train_losses.append(float(loss))
        self.train_accuracies.append(float(train_acc))
        self.test_accuracies.append(float(test_acc))
        self.epoch_times.append(float(seconds))

    def plot_results(self, path: str) -> None:
        """4-panel summary plot (baseline_training.py:110-147)."""
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        fig, axes = plt.subplots(2, 2, figsize=(12, 8))
        axes[0, 0].plot(self.epochs, self.train_losses, "o-")
        axes[0, 0].set_title("Training loss")
        axes[0, 1].plot(self.epochs, self.train_accuracies, "o-",
                        label="train")
        axes[0, 1].plot(self.epochs, self.test_accuracies, "s-", label="test")
        axes[0, 1].set_title("Accuracy (%)")
        axes[0, 1].legend()
        axes[1, 0].bar(self.epochs, self.epoch_times)
        axes[1, 0].set_title("Epoch time (s)")
        axes[1, 1].axis("off")
        summary = (f"final test acc: "
                   f"{self.test_accuracies[-1]:.2f}%\n"
                   f"total time: {sum(self.epoch_times):.1f}s"
                   if self.epochs else "no epochs")
        axes[1, 1].text(0.1, 0.5, summary, fontsize=12)
        for ax in axes.flat:
            ax.set_xlabel("epoch")
        fig.tight_layout()
        fig.savefig(path, dpi=120)
        plt.close(fig)


class BaselineTrainer:
    """The reference's baseline_training.py main loop as a class."""

    def __init__(self, dataset: Dataset, config: BaselineConfig | None = None,
                 model=None):
        self.config = cfg = config or BaselineConfig()
        self.dataset = dataset
        steps_per_epoch = max(
            1, len(dataset.x_train) // cfg.batch_size)
        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        from ..models import get_model
        self.model = model or get_model(cfg.model,
                                        num_classes=cfg.num_classes,
                                        dtype=dtype,
                                        image_size=dataset.x_train.shape[1])
        tx = (server_sgd(cfg.learning_rate) if cfg.plain_sgd
              else baseline_optimizer(
                  cfg.learning_rate, cfg.momentum, cfg.weight_decay,
                  cfg.milestones, cfg.gamma, steps_per_epoch))
        h, w = dataset.x_train.shape[1:3]
        self.state = create_train_state(
            self.model, jax.random.PRNGKey(cfg.seed), tx,
            input_shape=(1, h, w, 3))
        self._train_step = jax.jit(make_train_step(augment=cfg.augment),
                                   donate_argnums=0)
        self._eval_step = jax.jit(make_eval_step())
        self._device_loop = None
        if cfg.device_loop:
            from .device_loop import DeviceEpochLoop
            self._device_loop = DeviceEpochLoop(
                dataset, make_train_step(augment=cfg.augment),
                batch_size=cfg.batch_size)
        self.metrics = TrainingMetrics()

    def train_epoch(self, epoch: int) -> tuple[float, float]:
        """One epoch (baseline_training.py:149-179). Returns (loss, acc%)."""
        cfg = self.config
        rng = jax.random.PRNGKey(cfg.seed + 1)
        losses, accs = [], []
        for xb, yb in make_batches(self.dataset.x_train,
                                   self.dataset.y_train, cfg.batch_size,
                                   seed=cfg.seed * 997 + epoch):
            self.state, m = self._train_step(self.state, xb, yb, rng)
            losses.append(m["loss"])
            accs.append(m["accuracy"])
        losses = [float(x) for x in losses]
        accs = [float(x) for x in accs]
        return float(np.mean(losses)), 100.0 * float(np.mean(accs))

    def test_epoch(self) -> float:
        """Full test-set top-1 in % (baseline_training.py:181-199)."""
        correct = total = 0
        for xb, yb in make_batches(self.dataset.x_test, self.dataset.y_test,
                                   1000, shuffle=False,
                                   drop_remainder=False):
            c, t = self._eval_step(self.state, xb, yb)
            correct += int(c)
            total += int(t)
        return 100.0 * correct / max(total, 1)

    def train(self, plot_path: str | None = None,
              emit_metrics: bool = False,
              checkpoint_dir: str | None = None,
              resume: bool = False) -> TrainingMetrics:
        cfg = self.config
        mgr = None
        start_epoch = 1
        if checkpoint_dir:
            from ..checkpoint import CheckpointManager
            mgr = CheckpointManager(checkpoint_dir)
            if resume and mgr.latest_step() is not None:
                self.state = mgr.restore(self.state)
                steps_per_epoch = max(
                    1, len(self.dataset.x_train) // cfg.batch_size)
                start_epoch = int(self.state.step) // steps_per_epoch + 1
                print(f"resumed from step {int(self.state.step)} "
                      f"(epoch {start_epoch})")
        for epoch in range(start_epoch, cfg.num_epochs + 1):
            t0 = time.time()
            if self._device_loop is not None:
                self.state, em = self._device_loop.run_epoch(
                    self.state,
                    jax.random.fold_in(jax.random.PRNGKey(cfg.seed + 1),
                                       epoch))
                loss = em["train_loss"]
                train_acc = 100.0 * em["train_accuracy"]
                test_acc = 100.0 * em["test_accuracy"]
            else:
                loss, train_acc = self.train_epoch(epoch)
                test_acc = self.test_epoch()
            dt = time.time() - t0
            self.metrics.add_epoch(epoch, loss, train_acc, test_acc, dt)
            print(f"epoch {epoch}/{cfg.num_epochs}: loss {loss:.4f} "
                  f"train {train_acc:.2f}% test {test_acc:.2f}% "
                  f"({dt:.1f}s)")
            if mgr is not None:
                mgr.save(self.state)
        if mgr is not None:
            mgr.close()
        if plot_path:
            self.metrics.plot_results(plot_path)
        if emit_metrics:
            emit_metrics_json({
                "role": "baseline",
                "num_epochs": cfg.num_epochs,
                "batch_size": cfg.batch_size,
                "learning_rate": cfg.learning_rate,
                "total_training_time_seconds": round(
                    sum(self.metrics.epoch_times), 2),
                "epoch_times_seconds": [round(t, 2)
                                        for t in self.metrics.epoch_times],
                "final_test_accuracy": self.metrics.test_accuracies[-1],
                "all_test_accuracies": self.metrics.test_accuracies,
                "final_train_loss": self.metrics.train_losses[-1],
            })
        return self.metrics
