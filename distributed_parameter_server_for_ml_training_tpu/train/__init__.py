"""Training runtimes: single-chip baseline, sync SPMD, async PS workers."""

from .train_state import TrainState, create_train_state
from .optimizers import server_sgd, baseline_optimizer
from .steps import make_train_step, make_eval_step, cross_entropy_loss

__all__ = [
    "TrainState",
    "create_train_state",
    "server_sgd",
    "baseline_optimizer",
    "make_train_step",
    "make_eval_step",
    "cross_entropy_loss",
]
