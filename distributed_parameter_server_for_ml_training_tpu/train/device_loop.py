"""On-device epoch loop: a whole training epoch as ONE compiled program.

The reference's trainer re-fed every batch from a host DataLoader each epoch
(baseline_training.py:149-179), which is fine on a local CPU but pathological
for a remotely-attached accelerator: each dispatch pays link latency, and the
batch bytes pay link bandwidth. Here the dataset is uploaded ONCE
(CIFAR-100's 50k uint8 images are ~150 MB — trivial for HBM), and each epoch
runs as one XLA program:

    device-side shuffle (jax.random.permutation)
    -> lax.scan over jitted train steps (gathered uint8 batches)
    -> lax.scan over the test set for top-1
    -> scalar metrics out.

Only a handful of scalars cross the host<->device link per epoch, so epoch
time approaches pure compute (~1.7 s for ResNet-18/CIFAR-100 at the measured
~30k images/s/chip) regardless of link quality.

Epoch semantics match data/cifar.py's host iterator: full shuffle, then
``n // batch_size`` full batches with the remainder dropped
(worker.py:182-187 used DataLoader(shuffle=True, drop_last default False —
the reference *kept* ragged last batches; we drop them for static shapes and
document the difference: <0.3% of data at batch 128).
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Iterable, Iterator

import jax
import jax.numpy as jnp
import numpy as np

from ..data.cifar import Dataset


def prefetch_to_device(batches: Iterable, depth: int = 2,
                       device_put: Callable = jax.device_put) -> Iterator:
    """Host->device double buffering for a host batch iterator.

    Keeps ``depth`` batches' transfers in flight: ``jax.device_put``
    returns immediately (async dispatch), so batch N+1's host->device
    copy overlaps the consumer's compute on batch N instead of serializing
    in front of it — the input-side half of the double-buffered-transfer
    story (the gradient pull's half lives in the worker's comms pipeline;
    ps/worker.py). Yields ``(xb, yb)`` device pairs in the source order;
    values are exactly the source's (``device_put`` is a bitwise copy).
    ``depth=0`` degrades to a plain pass-through of host batches.
    """
    it = iter(batches)
    if depth <= 0:
        yield from it
        return
    buf: deque = deque()
    try:
        while len(buf) < depth:
            xb, yb = next(it)
            buf.append((device_put(xb), device_put(yb)))
    except StopIteration:
        pass  # fewer batches than the pipeline depth
    while buf:
        out = buf.popleft()
        nxt = next(it, None)
        if nxt is not None:
            buf.append((device_put(nxt[0]), device_put(nxt[1])))
        yield out


class DeviceEpochLoop:
    """Compiled epoch runner over a device-resident dataset.

    ``step_fn(state, images_u8, labels, rng) -> (state, {'loss','accuracy'})``
    is any train step with the standard signature (single-chip
    ``make_train_step`` or a sharded sync-DP step).
    """

    def __init__(self, dataset: Dataset, step_fn: Callable, *,
                 batch_size: int, eval_batch_size: int = 1000,
                 device_put: Callable = jnp.asarray):
        self.batch_size = batch_size
        n = (len(dataset.x_train) // batch_size) * batch_size
        self.steps_per_epoch = n // batch_size
        if self.steps_per_epoch == 0:
            raise ValueError("dataset smaller than one batch")
        self._n = n
        x_tr = device_put(np.ascontiguousarray(dataset.x_train))
        y_tr = device_put(np.ascontiguousarray(
            dataset.y_train.astype(np.int32)))

        # Pad the test set to a multiple of eval_batch_size with label -1
        # (argmax is always >= 0, so padding never counts as correct).
        n_te = len(dataset.x_test)
        pad = (-n_te) % eval_batch_size
        x_te = np.concatenate(
            [dataset.x_test,
             np.zeros((pad,) + dataset.x_test.shape[1:], np.uint8)])
        y_te = np.concatenate(
            [dataset.y_test.astype(np.int32), np.full((pad,), -1, np.int32)])
        eb = eval_batch_size
        x_te = device_put(x_te.reshape(-1, eb, *x_te.shape[1:]))
        y_te = device_put(y_te.reshape(-1, eb))
        self._n_test = n_te

        steps, bs = self.steps_per_epoch, batch_size

        n_total = len(dataset.x_train)
        n_test = self._n_test
        self._data = (x_tr, y_tr, x_te, y_te)

        # The dataset arrays are jit ARGUMENTS, not closure captures: a
        # closed-over array is embedded in the HLO as a constant, which makes
        # every dataset a fresh cache key (and hashes 150 MB per compile).
        # As arguments the executable is data-independent and the persistent
        # compilation cache hits across datasets and processes.
        def epoch(state, key, x_tr, y_tr, x_te, y_te):
            # Permute the FULL set, then keep the first n indices: the ragged
            # tail is dropped at random each epoch (as the host iterator's
            # shuffle-then-truncate does), not excluded permanently.
            perm = jax.random.permutation(key, n_total)[:n].reshape(steps, bs)

            def train_body(st, idx):
                xb = jnp.take(x_tr, idx, axis=0)
                yb = jnp.take(y_tr, idx, axis=0)
                st, m = step_fn(st, xb, yb, key)
                return st, (m["loss"], m["accuracy"])

            state, (losses, accs) = jax.lax.scan(train_body, state, perm)

            def eval_body(carry, batch):
                xb, yb = batch
                from .steps import _variables
                from ..data.cifar import normalize
                logits = state.apply_fn(
                    _variables(state.params, state.batch_stats),
                    normalize(xb), train=False)
                return carry + jnp.sum(jnp.argmax(logits, -1) == yb), None

            correct, _ = jax.lax.scan(
                eval_body, jnp.zeros((), jnp.int32), (x_te, y_te))
            metrics = {
                "train_loss": jnp.mean(losses),
                "train_accuracy": jnp.mean(accs),
                "test_accuracy": correct / n_test,
            }
            return state, metrics

        self._epoch = jax.jit(epoch, donate_argnums=0)

    def run_epoch(self, state, key):
        """One epoch; returns (state, scalar metrics dict). The input state
        is donated."""
        state, metrics = self._epoch(state, key, *self._data)
        return state, {k: float(v) for k, v in metrics.items()}
