"""Train state: params + optimizer state + BatchNorm statistics.

The reference keeps canonical weights as a ``{name: np.ndarray}`` dict on the
server (src/parameter_server/server.py:96) and reloads them into a torch
module on every fetch (src/workers/worker.py:241-252). Here the canonical
state is a single pytree, resident on device, threaded functionally through
the compiled step.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from flax import struct
from flax.training import train_state


class TrainState(train_state.TrainState):
    batch_stats: Any = struct.field(default=None)


def create_train_state(model: nn.Module, rng: jax.Array,
                       tx: optax.GradientTransformation,
                       input_shape=(1, 32, 32, 3)) -> TrainState:
    variables = model.init(rng, jnp.ones(input_shape, jnp.float32), train=False)
    return TrainState.create(
        apply_fn=model.apply,
        params=variables["params"],
        batch_stats=variables.get("batch_stats", {}),
        tx=tx,
    )
