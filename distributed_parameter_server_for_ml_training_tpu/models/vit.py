"""Vision Transformer (ViT) in flax.linen — the non-conv MXU path.

Covers the driver-added ViT-B/16 / CIFAR-100 config (BASELINE.json
configs[4]). The reference has no transformer at all (its model layer is the
copy-pasted ResNet-18, SURVEY.md §2.6), so this file is net-new capability,
designed TPU-first:

- all compute lands on the MXU as large batched matmuls (patch embed as a
  strided conv, fused qkv projection, einsum attention),
- compute dtype configurable (bfloat16 default path), params fp32,
- kernels are laid out so Megatron-style tensor parallelism is a pure
  sharding decision (parallel/tensor.py): qkv & mlp-in split column-wise on
  the 'model' axis, out & mlp-out row-wise — XLA inserts the all-reduces,
- attention can run ring-parallel over a sequence axis (parallel/
  ring_attention.py) for long-context training; at CIFAR resolution the
  sequence is tiny (2x2 patches + cls = 5 tokens) and runs dense.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

Dtype = Any


class MlpBlock(nn.Module):
    mlp_dim: int
    out_dim: int
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(self.mlp_dim, dtype=self.dtype, param_dtype=jnp.float32,
                     name="fc1")(x)
        x = nn.gelu(x)
        x = nn.Dense(self.out_dim, dtype=self.dtype, param_dtype=jnp.float32,
                     name="fc2")(x)
        return x


class SwitchMoEMlp(nn.Module):
    """Switch-style top-1 MoE replacing the dense MLP of an encoder block.

    The routing/dispatch math lives in parallel/moe.py (shard_map over the
    ``expert`` axis, two all_to_all hops); this module owns the flax params —
    router replicated, per-expert FFN stacked [E, ...] so a trainer shards
    leaf axis 0 one-expert-per-device. Net-new vs the reference (no MoE
    anywhere, SURVEY.md §2 checklist EP row).
    """

    moe_fn: Callable            # from parallel/moe.make_moe_ffn(mesh, cap)
    n_experts: int
    hidden_dim: int             # per-expert FFN hidden width

    @nn.compact
    def __call__(self, x):
        b, t, d = x.shape
        e, dh = self.n_experts, self.hidden_dim
        params = {
            "router": self.param("router",
                                 nn.initializers.normal(d ** -0.5),
                                 (d, e), jnp.float32),
            "w1": self.param("w1", nn.initializers.normal(d ** -0.5),
                             (e, d, dh), jnp.float32),
            "b1": self.param("b1", nn.initializers.zeros, (e, dh),
                             jnp.float32),
            "w2": self.param("w2", nn.initializers.normal(dh ** -0.5),
                             (e, dh, d), jnp.float32),
            "b2": self.param("b2", nn.initializers.zeros, (e, d),
                             jnp.float32),
        }
        # Batch-major flatten: contiguous token shards line up with batch
        # shards on the same mesh axis (tokens route ACROSS it).
        y, stats = self.moe_fn(params, x.reshape(b * t, d).astype(jnp.float32))
        # Aux loss + routing observability ride the 'intermediates'
        # collection (one sown entry per MoE layer); train steps built with
        # moe_aux_weight > 0 collect them (train/steps.py). A no-op when
        # the collection isn't mutable (eval).
        self.sow("intermediates", "moe_stats", stats)
        return y.reshape(b, t, d).astype(x.dtype)


class SelfAttention(nn.Module):
    """Multi-head self-attention with a fused qkv projection.

    einsum formulation keeps everything MXU-shaped; the qkv/out kernels are
    the TP split points (see parallel/tensor.py rules). ``attention_fn``
    swaps the dense softmax for an alternative core with the same
    [B, T, H, D] x3 -> [B, T, H, D] contract — ring attention
    (parallel/ring_attention.py) for sequence parallelism, or the Pallas
    flash kernel (ops/pallas/flash_attention.py).
    """

    num_heads: int
    dtype: Dtype = jnp.float32
    attention_fn: Callable | None = None

    @nn.compact
    def __call__(self, x):
        b, t, d = x.shape
        assert d % self.num_heads == 0, (d, self.num_heads)
        head_dim = d // self.num_heads

        qkv = nn.Dense(3 * d, dtype=self.dtype, param_dtype=jnp.float32,
                       name="qkv")(x)
        qkv = qkv.reshape(b, t, 3, self.num_heads, head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]

        if self.attention_fn is not None:
            out = self.attention_fn(q, k, v).reshape(b, t, d)
        else:
            from ..ops.attention import dense_core
            out = dense_core(q, k, v).reshape(b, t, d)
        return nn.Dense(d, dtype=self.dtype, param_dtype=jnp.float32,
                        name="out")(out)


class EncoderBlock(nn.Module):
    num_heads: int
    mlp_ratio: int = 4
    dtype: Dtype = jnp.float32
    attention_fn: Callable | None = None
    moe_fn: Callable | None = None     # set => Switch-MoE MLP (with experts)
    moe_experts: int = 0
    moe_hidden: int | None = None

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        y = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                         name="ln1")(x)
        x = x + SelfAttention(self.num_heads, dtype=self.dtype,
                              attention_fn=self.attention_fn,
                              name="attn")(y)
        y = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                         name="ln2")(x)
        if self.moe_fn is not None:
            x = x + SwitchMoEMlp(self.moe_fn, self.moe_experts,
                                 self.moe_hidden or self.mlp_ratio * d,
                                 name="moe")(y)
        else:
            x = x + MlpBlock(self.mlp_ratio * d, d, dtype=self.dtype,
                             name="mlp")(y)
        return x


class ViT(nn.Module):
    """ViT with learned position embeddings.

    ``pool='cls'`` (default) prepends a CLS token and classifies from it;
    ``pool='gap'`` mean-pools the patch tokens instead — no CLS token, so
    the sequence length stays a power of two and divides evenly across a
    ``seq`` (ring attention) or ``expert`` (MoE) mesh axis.

    ``attention_fn`` / ``moe_*`` thread down to every EncoderBlock: the
    registry models become sequence-parallel or expert-parallel by
    construction, not by a separate toy architecture (round-2 VERDICT
    item 4).
    """

    patch_size: int = 16
    hidden_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    mlp_ratio: int = 4
    num_classes: int = 100
    dtype: Dtype = jnp.float32
    pool: str = "cls"                       # 'cls' | 'gap'
    attention_fn: Callable | None = None
    moe_fn: Callable | None = None
    moe_experts: int = 0
    moe_hidden: int | None = None

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        assert self.pool in ("cls", "gap"), self.pool
        b, h, w, c = x.shape
        assert h % self.patch_size == 0 and w % self.patch_size == 0, (
            f"image {h}x{w} not divisible by patch {self.patch_size}")
        x = x.astype(self.dtype)
        # Patch embedding: conv with stride == kernel == patch size, i.e. one
        # matmul per patch on the MXU.
        x = nn.Conv(self.hidden_dim,
                    (self.patch_size, self.patch_size),
                    strides=(self.patch_size, self.patch_size),
                    padding="VALID", dtype=self.dtype,
                    param_dtype=jnp.float32, name="patch_embed")(x)
        x = x.reshape(b, -1, self.hidden_dim)
        n_tokens = x.shape[1] + (1 if self.pool == "cls" else 0)

        if self.pool == "cls":
            cls = self.param("cls_token", nn.initializers.zeros,
                             (1, 1, self.hidden_dim), jnp.float32)
            x = jnp.concatenate(
                [jnp.broadcast_to(cls, (b, 1, self.hidden_dim)
                                  ).astype(self.dtype), x], axis=1)
        pos = self.param("pos_embed",
                         nn.initializers.normal(stddev=0.02),
                         (1, n_tokens, self.hidden_dim), jnp.float32)
        x = x + pos.astype(self.dtype)

        for i in range(self.depth):
            x = EncoderBlock(self.num_heads, self.mlp_ratio,
                             dtype=self.dtype,
                             attention_fn=self.attention_fn,
                             moe_fn=self.moe_fn,
                             moe_experts=self.moe_experts,
                             moe_hidden=self.moe_hidden,
                             name=f"block_{i}")(x)
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                         name="ln_final")(x)
        x = x[:, 0] if self.pool == "cls" else x.mean(axis=1)
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


class ViTPrologue(nn.Module):
    """Patch embed + CLS + position embeddings — the shape-changing entry of
    ViT, run replicated OUTSIDE the pipeline (stages must preserve shapes).
    Splitting here matches the ViT structure above exactly (same layer
    names), so a pipelined model is parameter-compatible per stage."""

    patch_size: int = 4
    hidden_dim: int = 192
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b = x.shape[0]
        x = x.astype(self.dtype)
        x = nn.Conv(self.hidden_dim,
                    (self.patch_size, self.patch_size),
                    strides=(self.patch_size, self.patch_size),
                    padding="VALID", dtype=self.dtype,
                    param_dtype=jnp.float32, name="patch_embed")(x)
        x = x.reshape(b, -1, self.hidden_dim)
        n_tokens = x.shape[1] + 1
        cls = self.param("cls_token", nn.initializers.zeros,
                         (1, 1, self.hidden_dim), jnp.float32)
        x = jnp.concatenate(
            [jnp.broadcast_to(cls, (b, 1, self.hidden_dim)).astype(self.dtype),
             x], axis=1)
        pos = self.param("pos_embed", nn.initializers.normal(stddev=0.02),
                         (1, n_tokens, self.hidden_dim), jnp.float32)
        return x + pos.astype(self.dtype)


class EncoderStage(nn.Module):
    """A contiguous group of encoder blocks: ONE pipeline stage.

    Shape-preserving [B, T, D] -> [B, T, D], so S identical stages stack
    into the [S, ...] parameter layout parallel/pipeline.py ships around the
    ring.
    """

    num_blocks: int
    num_heads: int
    mlp_ratio: int = 4
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        for i in range(self.num_blocks):
            x = EncoderBlock(self.num_heads, self.mlp_ratio,
                             dtype=self.dtype, name=f"block_{i}")(x)
        return x


class ViTEpilogue(nn.Module):
    """Final LayerNorm + CLS head — the shape-changing exit, replicated."""

    num_classes: int = 100
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                         name="ln_final")(x)
        x = x[:, 0]
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


def ViT_B16(num_classes: int = 100, dtype: Dtype = jnp.float32) -> ViT:
    """ViT-B/16: 12 layers, 768 hidden, 12 heads (~85.7M params)."""
    return ViT(patch_size=16, hidden_dim=768, depth=12, num_heads=12,
               num_classes=num_classes, dtype=dtype)


def ViT_Tiny(num_classes: int = 100, dtype: Dtype = jnp.float32,
             patch_size: int = 4) -> ViT:
    """Small ViT for tests and CIFAR-resolution runs (32/4 -> 64 tokens)."""
    return ViT(patch_size=patch_size, hidden_dim=192, depth=4, num_heads=3,
               num_classes=num_classes, dtype=dtype)
