"""CIFAR-style ResNet family in flax.linen.

Architecture parity target (reference: src/parameter_server/server.py:21-76,
src/workers/worker.py:21-76, baseline/baseline_training.py:37-95 — the same
classes copy-pasted three times): 3x3 stem, stride 1, NO maxpool, four stages
of BasicBlocks [2,2,2,2], BatchNorm everywhere, global average pool, Linear
head. At ``num_classes=100`` the parameter count must be exactly 11,220,132
(reference: baseline/results/baseline_summary.json ``model_specs.parameters``).

TPU-first notes:
- compute dtype is configurable (``dtype=jnp.bfloat16`` keeps the MXU fed;
  parameters and BN statistics stay float32 via ``param_dtype``),
- ``axis_name`` enables cross-replica BatchNorm statistics under ``shard_map``
  — the reference accidentally froze BN running stats in distributed mode
  (SURVEY.md §7 hard part (b)); here syncing them is the default sane choice
  and freezing is reproducible by simply not passing the axis name.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

Dtype = Any


class BasicBlock(nn.Module):
    """Two 3x3 convs + identity shortcut (1x1 conv when shape changes)."""

    features: int
    strides: int = 1
    dtype: Dtype = jnp.float32
    axis_name: str | None = None

    @nn.compact
    def __call__(self, x: jax.Array, train: bool) -> jax.Array:
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            axis_name=self.axis_name,
        )
        conv = partial(
            nn.Conv, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32
        )

        residual = x
        y = conv(self.features, (3, 3), strides=(self.strides, self.strides),
                 padding=((1, 1), (1, 1)))(x)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.features, (3, 3), padding=((1, 1), (1, 1)))(y)
        y = norm()(y)

        if residual.shape[-1] != self.features or self.strides != 1:
            residual = conv(self.features, (1, 1),
                            strides=(self.strides, self.strides))(residual)
            residual = norm()(residual)

        return nn.relu(y + residual)


class Bottleneck(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck block (for ResNet-50)."""

    features: int  # bottleneck width; output is 4x this
    strides: int = 1
    dtype: Dtype = jnp.float32
    axis_name: str | None = None

    expansion: int = 4

    @nn.compact
    def __call__(self, x: jax.Array, train: bool) -> jax.Array:
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            axis_name=self.axis_name,
        )
        conv = partial(
            nn.Conv, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32
        )
        out_features = self.features * self.expansion

        residual = x
        y = conv(self.features, (1, 1))(x)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.features, (3, 3), strides=(self.strides, self.strides),
                 padding=((1, 1), (1, 1)))(y)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(out_features, (1, 1))(y)
        y = norm()(y)

        if residual.shape[-1] != out_features or self.strides != 1:
            residual = conv(out_features, (1, 1),
                            strides=(self.strides, self.strides))(residual)
            residual = norm()(residual)

        return nn.relu(y + residual)


class ResNet(nn.Module):
    """ResNet with a CIFAR stem (3x3, stride 1, no maxpool — the reference's
    architecture, server.py:43-76) or an ImageNet stem (7x7 stride 2 + 3x3
    maxpool stride 2) for large-resolution configs: without the 4x stem
    downsampling, 224px inputs keep 224x224 feature maps into stage 0 and a
    batch-128 train step needs ~37 GB of HBM.

    ``s2d_stem`` (with ``imagenet_stem``) computes the SAME function as the
    7x7/2 stem via a 2x2 space-to-depth transform + 4x4/1 conv (the MLPerf
    TPU formulation): a 3-channel stride-2 conv tiles terribly onto the
    128x128 MXU, while the s2d form contracts 4x4x12=192 inputs per output
    — ``s2d_stem_kernel`` maps 7x7 weights into the exact-equivalent 4x4
    layout (asserted by tests/test_models.py)."""

    stage_sizes: Sequence[int]
    block_cls: type = BasicBlock
    num_classes: int = 100
    num_filters: int = 64
    dtype: Dtype = jnp.float32
    axis_name: str | None = None
    imagenet_stem: bool = False
    s2d_stem: bool = False
    # Truncate after N stages and return the feature map (no pool/head):
    # profiling prefixes of the REAL architecture
    # (experiments/analyze_resnet50.py) without duplicating the
    # stem/stage schedule. None = the full classifier.
    max_stages: int | None = None

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        x = x.astype(self.dtype)
        if self.imagenet_stem and self.s2d_stem:
            b, h, w, c = x.shape
            assert h % 2 == 0 and w % 2 == 0, (h, w)
            xs = x.reshape(b, h // 2, 2, w // 2, 2, c)
            xs = xs.transpose(0, 1, 3, 2, 4, 5).reshape(
                b, h // 2, w // 2, 4 * c)
            # padding (2,1): output row i needs s2d rows i-2..i+1
            # (derivation at s2d_stem_kernel).
            x = nn.Conv(self.num_filters, (4, 4), strides=(1, 1),
                        padding=((2, 1), (2, 1)), use_bias=False,
                        dtype=self.dtype, param_dtype=jnp.float32,
                        name="stem_conv_s2d")(xs)
        elif self.imagenet_stem:
            x = nn.Conv(self.num_filters, (7, 7), strides=(2, 2),
                        padding=((3, 3), (3, 3)), use_bias=False,
                        dtype=self.dtype, param_dtype=jnp.float32,
                        name="stem_conv")(x)
        else:
            x = nn.Conv(self.num_filters, (3, 3), padding=((1, 1), (1, 1)),
                        use_bias=False, dtype=self.dtype,
                        param_dtype=jnp.float32, name="stem_conv")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.dtype,
                         param_dtype=jnp.float32, axis_name=self.axis_name,
                         name="stem_bn")(x)
        x = nn.relu(x)
        if self.imagenet_stem:
            x = nn.max_pool(x, (3, 3), strides=(2, 2),
                            padding=((1, 1), (1, 1)))
        stages = (self.stage_sizes if self.max_stages is None
                  else self.stage_sizes[:self.max_stages])
        for stage, n_blocks in enumerate(stages):
            for block in range(n_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = self.block_cls(
                    self.num_filters * 2**stage,
                    strides=strides,
                    dtype=self.dtype,
                    axis_name=self.axis_name,
                )(x, train)
        if self.max_stages is not None:
            return x
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


def s2d_stem_kernel(w):
    """Map 7x7/2 stem weights [7,7,C,F] to the exact-equivalent 4x4/1
    space-to-depth kernel [4,4,4C,F].

    Derivation: o[i,j] = sum_{di,dj in [-3,3]} w[di+3,dj+3] x[2i+di,2j+dj].
    In 2x2-s2d coordinates x[2i+di] lives at s2d row r with phase pr where
    2i+di = 2(i+r-2)+pr, i.e. di = 2r+pr-4 for r in 0..3, pr in {0,1} —
    so the receptive field is 4 s2d rows (i-2..i+1), stride 1, padding
    (2,1); entries with di outside [-3,3] (r=0, pr=0) are zero. Channel
    block order matches the model's reshape: (pr*2+pc)*C + ci.
    """
    import numpy as np

    w = np.asarray(w)
    kh, kw, c, f = w.shape
    assert (kh, kw) == (7, 7), (kh, kw)
    out = np.zeros((4, 4, 4 * c, f), w.dtype)
    for r in range(4):
        for pr in range(2):
            di = 2 * r + pr - 1          # = (2r + pr - 4) + 3
            if not 0 <= di < 7:
                continue
            for q in range(4):
                for pc in range(2):
                    dj = 2 * q + pc - 1
                    if not 0 <= dj < 7:
                        continue
                    blk = (pr * 2 + pc) * c
                    out[r, q, blk:blk + c, :] = w[di, dj]
    return out


def ResNet18(num_classes: int = 100, dtype: Dtype = jnp.float32,
             axis_name: str | None = None,
             imagenet_stem: bool = False, s2d_stem: bool = False) -> ResNet:
    return ResNet(stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock,
                  num_classes=num_classes, dtype=dtype, axis_name=axis_name,
                  imagenet_stem=imagenet_stem, s2d_stem=s2d_stem)


def ResNet50(num_classes: int = 1000, dtype: Dtype = jnp.float32,
             axis_name: str | None = None,
             imagenet_stem: bool = False, s2d_stem: bool = False) -> ResNet:
    """ResNet-50. The CIFAR stem is the default (matching the reference's
    only architecture); pass ``imagenet_stem=True`` for large-resolution
    inputs — the registry does this automatically for image_size >= 96."""
    return ResNet(stage_sizes=(3, 4, 6, 3), block_cls=Bottleneck,
                  num_classes=num_classes, dtype=dtype, axis_name=axis_name,
                  imagenet_stem=imagenet_stem, s2d_stem=s2d_stem)


def count_params(params) -> int:
    return sum(p.size for p in jax.tree_util.tree_leaves(params))
