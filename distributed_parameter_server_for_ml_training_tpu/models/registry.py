"""Model registry: the BASELINE.json config matrix by name.

Configs covered (BASELINE.json ``configs``):
  - resnet18  — ResNet-18 / CIFAR-100 (the reference's only model)
  - resnet50  — ResNet-50 (pod-scale sync config; ImageNet-1k shapes)
  - vit_b16   — ViT-B/16 (transformer / non-conv MXU path)
  - vit_tiny  — small ViT for CIFAR-resolution runs and tests
"""

from __future__ import annotations

import jax.numpy as jnp

from .resnet import ResNet18, ResNet50
from .vit import ViT_B16, ViT_Tiny

_REGISTRY = {
    # ResNets switch to the ImageNet stem (7x7/2 + maxpool/2) at large
    # resolutions: the CIFAR stem carries full-resolution feature maps into
    # stage 0 and needs ~37 GB HBM for one 224px batch-128 train step.
    "resnet18": lambda num_classes, dtype, axis_name, image_size: ResNet18(
        num_classes=num_classes, dtype=dtype, axis_name=axis_name,
        imagenet_stem=image_size >= 96),
    "resnet50": lambda num_classes, dtype, axis_name, image_size: ResNet50(
        num_classes=num_classes, dtype=dtype, axis_name=axis_name,
        imagenet_stem=image_size >= 96),
    "vit_b16": lambda num_classes, dtype, axis_name, image_size: ViT_B16(
        num_classes=num_classes, dtype=dtype),
    "vit_tiny": lambda num_classes, dtype, axis_name, image_size: ViT_Tiny(
        num_classes=num_classes, dtype=dtype),
}

MODEL_NAMES = tuple(_REGISTRY)


def get_model(name: str, num_classes: int = 100, dtype=jnp.bfloat16,
              axis_name: str | None = None, image_size: int = 32):
    """Build a model by registry name. ViT models ignore ``axis_name``
    (LayerNorm needs no cross-replica sync; BN models use it).
    ``image_size`` selects resolution-dependent choices (ResNet-50 stem)."""
    if name not in _REGISTRY:
        raise ValueError(f"unknown model {name!r}; have {MODEL_NAMES}")
    return _REGISTRY[name](num_classes, dtype, axis_name, image_size)
