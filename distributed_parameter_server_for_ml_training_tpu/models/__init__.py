"""Model zoo: CIFAR-style ResNets and ViT (flax.linen)."""

from .registry import MODEL_NAMES, get_model
from .resnet import ResNet, ResNet18, ResNet50, count_params
from .vit import ViT, ViT_B16, ViT_Tiny

__all__ = ["ResNet", "ResNet18", "ResNet50", "count_params",
           "ViT", "ViT_B16", "ViT_Tiny", "get_model", "MODEL_NAMES"]
