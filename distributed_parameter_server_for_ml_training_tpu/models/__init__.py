"""Model zoo: CIFAR-style ResNets (flax.linen)."""

from .resnet import ResNet, ResNet18, ResNet50, count_params

__all__ = ["ResNet", "ResNet18", "ResNet50", "count_params"]
