"""Command-line interface: the reference's run recipes, mapped 1:1.

Reference surface (SURVEY.md §2.18): argparse with env-var defaults —
server: ``--mode/--workers/--lr/--port/--staleness-bound`` (env SERVER_MODE,
TOTAL_WORKERS_EXPECTED, SERVER_PORT; server.py:405-433); worker:
``--server/--worker-name/--epochs/--batch-size/--lr/--sync-steps`` (env
PARAMETER_SERVER_ADDRESS; worker.py:455-482); plus baseline_training.py.

Commands::

    python -m distributed_parameter_server_for_ml_training_tpu.cli train \
        --mode sync --workers 4 --epochs 3            # in-process cluster
    python -m ....cli train --mode baseline           # single-chip baseline
    python -m ....cli serve --mode async --workers 8  # gRPC PS (multi-host)
    python -m ....cli worker --server host:8000       # gRPC remote worker
    python -m ....cli supervise --workers 4 -- --server host:8000
                                                      # self-healing fleet
    python -m ....cli status --url http://host:9400   # cluster health view
    python -m ....cli replica --primary host:8000     # read-only fetch replica
    python -m ....cli loadgen --targets host:8000     # fetch-path QPS probe
    python -m ....cli reshard --primaries a,b --donor 0 --recipient 1 \
        --slots 24:32                                 # live shard migration
    python -m ....cli infer --target host:8001        # serve-tier inference

The in-process ``train`` command replaces the reference's entire
terraform/ECS deployment for single-host experiments: what took a Fargate
cluster (terraform/main.tf) is N mesh slots (sync) or N threads (async).
"""

from __future__ import annotations

import argparse
import os
import sys
from contextlib import contextmanager


def _env(name: str, default, cast=str):
    v = os.environ.get(name)
    return cast(v) if v is not None else default


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="distributed_parameter_server_for_ml_training_tpu",
        description="TPU-native sync/async data-parallel training")
    sub = p.add_subparsers(dest="command", required=True)

    def add_platform(q):
        q.add_argument("--platform", choices=["default", "cpu"],
                       default="default",
                       help="force the JAX backend (the axon site hook pins "
                            "JAX_PLATFORMS, so env overrides don't work; "
                            "'cpu' is needed when another process holds the "
                            "TPU, e.g. multi-process serve/worker runs)")

    def add_telemetry(q):
        q.add_argument("--telemetry", action="store_true",
                       default=bool(_env("DPS_TELEMETRY", 0, int)),
                       help="emit periodic METRICS_JSON "
                            "'kind=snapshot' lines (live counters/gauges/"
                            "histograms; same regex convention as the exit "
                            "line, docs/OBSERVABILITY.md)")
        q.add_argument("--telemetry-interval", type=float,
                       default=_env("DPS_TELEMETRY_INTERVAL", 5.0, float),
                       help="seconds between snapshot lines")
        q.add_argument("--metrics-port", type=int,
                       default=_env("DPS_METRICS_PORT", None, int),
                       help="serve Prometheus /metrics + /healthz + "
                            "/debug/trace on this port (0 = pick a free "
                            "port; omit = disabled)")
        q.add_argument("--trace", action="store_true",
                       default=bool(_env("DPS_TRACE", 0, int)),
                       help="record per-step trace spans into the "
                            "in-process flight recorder (propagated "
                            "worker->server over the wire; dumped on "
                            "SIGTERM/crash/exit and via /debug/trace — "
                            "docs/OBSERVABILITY.md)")
        q.add_argument("--trace-buffer", type=int,
                       default=_env("DPS_TRACE_BUFFER", 4096, int),
                       help="flight-recorder ring size (spans kept per "
                            "process; oldest evicted)")
        q.add_argument("--trace-dump-dir",
                       default=_env("DPS_TRACE_DUMP_DIR", None),
                       help="write the recorder tail as JSON here on "
                            "SIGTERM/unhandled-fault/atexit "
                            "(trace-<role>-<pid>-<reason>.json)")
        q.add_argument("--journal-dir",
                       default=_env("DPS_JOURNAL_DIR", None),
                       help="durable telemetry journal directory "
                            "(segmented JSONL; snapshots + alert/"
                            "remediation/directive/migration/checkpoint "
                            "events — docs/OBSERVABILITY.md 'Incident "
                            "forensics'; omit = disabled)")

    def add_common(q):
        add_platform(q)
        add_telemetry(q)
        q.add_argument("--lr", type=float,
                       default=_env("LEARNING_RATE", 0.1, float),
                       help="server SGD learning rate (server.py:413)")
        q.add_argument("--epochs", type=int,
                       default=_env("NUM_EPOCHS", 3, int))
        q.add_argument("--batch-size", type=int,
                       default=_env("BATCH_SIZE", 128, int),
                       help="per-worker batch size (worker.py:462)")
        q.add_argument("--data-dir", default=os.environ.get("CIFAR100_DIR"))
        q.add_argument("--synthetic", action="store_true",
                       help="force the synthetic dataset (no-network envs)")
        q.add_argument("--num-train", type=int, default=None,
                       help="truncate train set (quick runs)")
        q.add_argument("--num-test", type=int, default=None,
                       help="truncate test set (quick runs)")
        q.add_argument("--no-augment", action="store_true")
        q.add_argument("--dtype", choices=["bfloat16", "float32"],
                       default="bfloat16")
        q.add_argument("--model",
                       choices=["resnet18", "resnet50", "vit_b16",
                                "vit_tiny"],
                       default="resnet18")
        q.add_argument("--dataset", choices=["cifar100", "imagenet-synth"],
                       default="cifar100",
                       help="imagenet-synth = ImageNet-shaped synthetic "
                            "(ResNet-50 pod config)")
        q.add_argument("--image-size", type=int, default=224,
                       help="imagenet-synth resolution")
        q.add_argument("--seed", type=int, default=0)
        q.add_argument("--emit-metrics", action="store_true",
                       help="print METRICS_JSON lines (server.py:367)")

    t = sub.add_parser("train", help="in-process training run")
    t.add_argument("--mode",
                   choices=["baseline", "sync", "async", "tp", "pp", "sp",
                            "moe"],
                   default=_env("SERVER_MODE", "sync"),
                   help="baseline/sync/async reproduce the reference's "
                        "modes; tp = data x tensor parallel (GSPMD ViT), "
                        "pp = GPipe pipeline over ViT block groups, "
                        "sp = ring-attention ViT sequence parallelism, "
                        "moe = Switch-MoE ViT expert parallelism "
                        "(all four honor --model)")
    t.add_argument("--workers", type=int,
                   default=_env("TOTAL_WORKERS_EXPECTED", 4, int))
    t.add_argument("--tp-degree", type=int, default=2,
                   help="model-axis size for --mode tp")
    t.add_argument("--pp-microbatches", type=int, default=8,
                   help="GPipe microbatch count for --mode pp")
    t.add_argument("--dp-degree", type=int, default=1,
                   help="--mode pp: shard each microbatch over a 'data' "
                        "mesh axis (dp x pp composition)")
    t.add_argument("--pp-tp-degree", type=int, default=1,
                   help="--mode pp: Megatron-split stage params over a "
                        "'model' mesh axis (dp x tp x pp composition)")
    t.add_argument("--moe-capacity-factor", type=float, default=2.0,
                   help="--mode moe: per-expert buffer = factor x the "
                        "even-routing load (Switch capacity factor)")
    t.add_argument("--moe-aux-weight", type=float, default=0.01,
                   help="--mode moe: Switch load-balance aux-loss weight "
                        "(0 disables balancing)")
    t.add_argument("--staleness-bound", type=int,
                   default=_env("STALENESS_BOUND", 5, int))
    t.add_argument("--sync-steps", type=int,
                   default=_env("SYNC_STEPS", 1, int),
                   help="K-step local SGD interval (worker.py:468)")
    t.add_argument("--k-step-mode", choices=["faithful", "accumulate"],
                   default="faithful")
    t.add_argument("--overlap", action="store_true",
                   default=bool(_env("DPS_OVERLAP", 0, int)),
                   help="overlapped comms pipeline (PS-store modes): "
                        "push + prefetch on a background thread while the "
                        "training thread computes; identical RPC sequence "
                        "to the serial loop, pays off with --sync-steps>1 "
                        "(docs/WIRE_PROTOCOL.md)")
    t.add_argument("--no-delta-fetch", action="store_true",
                   help="disable version-gated delta fetches (have_step/"
                        "NOT_MODIFIED handshake); full params on every "
                        "fetch, reference parity")
    t.add_argument("--compression", choices=["none", "bf16", "fp16", "int8"],
                   default="bf16",
                   help="sync all-reduce precision (int8 = quantized "
                        "reduce-scatter ring, ~half bf16's ICI bytes)")
    t.add_argument("--strict-rounds", action="store_true",
                   help="corrected sync-round semantics (vs quirk 3)")
    t.add_argument("--elastic", action="store_true",
                   help="elastic membership: id-slot reuse on join, sync "
                        "rounds sized to live workers (vs reference "
                        "restart pollution, README.md:368-371)")
    t.add_argument("--worker-timeout", type=float, default=None,
                   help="expire workers unseen for this many seconds")
    t.add_argument("--store-backend",
                   choices=["python", "native", "device"],
                   default="python",
                   help="async parameter-store backend: host numpy, C++ "
                        "arena, or HBM-resident (zero host-link bytes per "
                        "step)")
    t.add_argument("--plot", default=None, help="save a results plot (png)")
    t.add_argument("--checkpoint-dir", default=None,
                   help="save checkpoints each epoch (gap-fill, SURVEY §5.4)")
    t.add_argument("--resume", action="store_true",
                   help="resume from the newest checkpoint in "
                        "--checkpoint-dir")
    t.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler (XLA-level) trace of the "
                        "training loop into this directory — opens in "
                        "TensorBoard/Perfetto beside the framework-level "
                        "--trace spans (docs/OBSERVABILITY.md)")
    t.add_argument("--multihost", action="store_true",
                   help="join a multi-process SPMD job before training "
                        "(sync mode): one global mesh across hosts")
    t.add_argument("--coordinator",
                   default=_env("DPS_COORDINATOR", None),
                   help="process-0 address host:port (env DPS_COORDINATOR); "
                        "omit on TPU pods for auto-detection")
    t.add_argument("--num-processes", type=int,
                   default=_env("DPS_NUM_PROCESSES", None, int))
    t.add_argument("--process-id", type=int,
                   default=_env("DPS_PROCESS_ID", None, int))
    add_common(t)

    s = sub.add_parser("serve", help="gRPC parameter server (multi-host)")
    s.add_argument("--mode", choices=["sync", "async"],
                   default=_env("SERVER_MODE", "sync"))
    s.add_argument("--workers", type=int,
                   default=_env("TOTAL_WORKERS_EXPECTED", 4, int))
    s.add_argument("--port", type=int, default=_env("SERVER_PORT", 8000, int))
    s.add_argument("--staleness-bound", type=int,
                   default=_env("STALENESS_BOUND", 5, int))
    s.add_argument("--lr", type=float,
                   default=_env("LEARNING_RATE", 0.1, float))
    s.add_argument("--num-classes", type=int, default=100)
    s.add_argument("--model",
                   choices=["resnet18", "resnet50", "vit_b16", "vit_tiny"],
                   default="resnet18",
                   help="must match the workers' --model (the store is "
                        "keyed by parameter names)")
    s.add_argument("--image-size", type=int, default=32,
                   help="input resolution used to init the store's params")
    s.add_argument("--seed", type=int, default=0)
    s.add_argument("--emit-metrics", action="store_true")
    s.add_argument("--elastic", action="store_true",
                   help="elastic membership (id reuse + live round sizing); "
                        "live membership rides Register/Fetch replies so "
                        "remote workers reshard at epoch boundaries")
    s.add_argument("--worker-timeout", type=float, default=None)
    s.add_argument("--push-codec",
                   choices=["default", "fp16", "int8", "int4", "topk",
                            "adaptive", "none"],
                   default="default",
                   help="wire codec workers apply before push: 'default' "
                        "= backend's choice (fp16 for python/native, none "
                        "for device); int8 (python + native backends) "
                        "halves fp16's bytes again; int4 (packed nibbles, "
                        "~8x under fp32), topk (sparse triples), and "
                        "adaptive (per-layer int8/int4/topk from link "
                        "pressure) are python-backend codecs paired with "
                        "worker-side error feedback "
                        "(docs/WIRE_PROTOCOL.md)")
    s.add_argument("--no-compressed-domain", action="store_true",
                   help="decode every quantized push to fp32 before "
                        "aggregating (the legacy path) instead of "
                        "accumulating in the quantized domain and "
                        "dequantizing once per round")
    s.add_argument("--fetch-codec", choices=["none", "bf16", "fp16"],
                   default="none",
                   help="wire codec for FETCHED parameters (default none = "
                        "reference parity: fp32 fetches, its dominant wire "
                        "term, server.py:222). bf16/fp16 halve params-in "
                        "bytes; clients decompress after fetch")
    s.add_argument("--store-backend",
                   choices=["python", "native", "device"],
                   default="python",
                   help="store implementation behind the service: host "
                        "numpy, C++ arena (the multi-host host-side hot "
                        "path the native core was built for), or "
                        "HBM-resident")
    s.add_argument("--checkpoint-dir",
                   default=_env("DPS_CHECKPOINT_DIR", None),
                   help="durable server state (docs/ROBUSTNESS.md): "
                        "periodic atomic snapshots of params + step + "
                        "aggregation config + the push-token journal, "
                        "plus a final snapshot on SIGTERM/exit")
    s.add_argument("--checkpoint-interval", type=float,
                   default=_env("DPS_CHECKPOINT_INTERVAL", 30.0, float),
                   help="seconds between periodic store snapshots")
    s.add_argument("--restore", action="store_true",
                   help="resume from the newest snapshot in "
                        "--checkpoint-dir: params + global step restored, "
                        "push-token journal re-seeded so pre-crash push "
                        "retries still dedupe")
    s.add_argument("--faults", default=_env("DPS_FAULTS_SERVER", None),
                   help="deterministic server-side fault injection spec "
                        "(comms/faults.py), e.g. "
                        "'seed=7;push.drop_reply@n=3;any.kill@n=40'")
    s.add_argument("--sync-quorum", type=float,
                   default=_env("DPS_SYNC_QUORUM", None, float),
                   help="quorum sync rounds (docs/ROBUSTNESS.md): a round "
                        "completes once this many DISTINCT workers of the "
                        "live round target have pushed — >= 1 is a count, "
                        "< 1 a fraction (ceil). Stragglers' late pushes "
                        "reconcile via staleness semantics. Implies "
                        "--strict-rounds counting; omit = full barrier")
    s.add_argument("--round-deadline", type=float,
                   default=_env("DPS_ROUND_DEADLINE", None, float),
                   help="per-round deadline in seconds, armed at the "
                        "round's first push: on expiry the round "
                        "completes with whatever arrived (composes with "
                        "--sync-quorum; omit = none)")
    s.add_argument("--remediate", action="store_true",
                   default=bool(_env("DPS_REMEDIATE", 0, int)),
                   help="turn cluster alerts into actions "
                        "(docs/ROBUSTNESS.md): straggler_lag -> quorum-"
                        "exclude + rebalance directive, nonfinite loss/"
                        "grad -> quarantine + refetch directive, "
                        "dead_worker -> respawn request (executed by "
                        "cli supervise next to the workers)")
    s.add_argument("--remediate-dry-run", action="store_true",
                   help="run the remediation engine but execute nothing: "
                        "every decision is recorded/counted with outcome "
                        "dry_run (policy rehearsal)")
    s.add_argument("--remediation-cooldown", type=float,
                   default=_env("DPS_REMEDIATION_COOLDOWN", 30.0, float),
                   help="minimum seconds between repeated remediation "
                        "actions for the same (action, worker)")
    s.add_argument("--quarantine-secs", type=float,
                   default=_env("DPS_QUARANTINE_SECS", 30.0, float),
                   help="server-side push-refusal window of the "
                        "quarantine action")
    s.add_argument("--no-health-monitor", action="store_true",
                   help="disable the cluster health monitor (worker health "
                        "reports, rule engine, /cluster endpoint, /healthz "
                        "readiness flip — docs/OBSERVABILITY.md); on by "
                        "default")
    s.add_argument("--incidents-dir",
                   default=_env("DPS_INCIDENTS_DIR", None),
                   help="auto-freeze a forensic bundle here when a "
                        "critical alert fires (journal window, /cluster "
                        "snapshot, flight-recorder tail; per-rule "
                        "cooldown dedupe — docs/OBSERVABILITY.md "
                        "'Incident forensics'; needs the health monitor)")
    s.add_argument("--incident-window", type=float,
                   default=_env("DPS_INCIDENT_WINDOW", 120.0, float),
                   help="seconds of journal history frozen per bundle")
    s.add_argument("--incident-cooldown", type=float,
                   default=_env("DPS_INCIDENT_COOLDOWN", 120.0, float),
                   help="per-rule dedupe window: an alert storm yields "
                        "one bundle per rule per cooldown")
    s.add_argument("--health-interval", type=float,
                   default=_env("DPS_HEALTH_INTERVAL", 5.0, float),
                   help="seconds between cluster health evaluations (and "
                        "'kind=cluster' stream records when --telemetry)")
    s.add_argument("--dead-after", type=float,
                   default=_env("DPS_DEAD_AFTER", 30.0, float),
                   help="seconds of silence before the monitor declares a "
                        "worker dead (critical alert; independent of "
                        "--worker-timeout membership expiry)")
    s.add_argument("--straggler-lag", type=int,
                   default=_env("DPS_STRAGGLER_LAG", 100, int),
                   help="steps behind the fastest reporting worker before "
                        "the straggler_lag rule fires (the remediation "
                        "engine's quorum-exclude trigger)")
    s.add_argument("--shard-index", type=int,
                   default=_env("DPS_SHARD_INDEX", 0, int),
                   help="this server's slot in a sharded deployment "
                        "(docs/SHARDING.md): it owns the consistent-hash "
                        "key range slot_range(index, count) and holds only "
                        "those parameters")
    s.add_argument("--shard-count", type=int,
                   default=_env("DPS_SHARD_COUNT", 1, int),
                   help="total shard primaries in the deployment; 1 = "
                        "unsharded (default, reference parity)")
    s.add_argument("--shard-peers",
                   default=_env("DPS_SHARD_PEERS", None),
                   help="comma list of ALL shard primary addresses in "
                        "shard order (host:port, length --shard-count); "
                        "published to workers as the shard map at "
                        "registration. Required when --shard-count > 1")
    s.add_argument("--autoscale", action="store_true",
                   help="grow/shrink a local replica fleet from measured "
                        "fetch QPS (telemetry/autoscale.py): spawns "
                        "`cli replica` children against this primary, "
                        "ticked by the health monitor")
    s.add_argument("--autoscale-min", type=int, default=0,
                   help="replica floor the autoscaler keeps alive")
    s.add_argument("--autoscale-max", type=int, default=4,
                   help="replica ceiling")
    s.add_argument("--autoscale-qps-high", type=float, default=50.0,
                   help="windowed fetch QPS above which the fleet grows")
    s.add_argument("--autoscale-qps-low", type=float, default=5.0,
                   help="windowed fetch QPS below which it shrinks "
                        "(hysteresis band with --autoscale-qps-high)")
    s.add_argument("--autoscale-cooldown", type=float, default=10.0,
                   help="minimum seconds between scaling actions")
    s.add_argument("--autoscale-max-tier", type=int, default=1,
                   help="deepest tier a grown replica may land at "
                        "(docs/SHARDING.md \"Fan-out trees\"): 1 = flat "
                        "star (every replica under the primary); >1 "
                        "spawns under the hottest eligible interior "
                        "node")
    s.add_argument("--autoscale-fanout", type=int, default=2,
                   help="per-node child budget when growing a tree — a "
                        "node already feeding this many children stops "
                        "being an eligible parent")
    s.add_argument("--autoscale-dry-run", action="store_true",
                   help="decide and record scaling actions without "
                        "spawning or retiring anything")
    s.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler (XLA-level) trace of the "
                        "server's apply/aggregation hot path into this "
                        "directory (same bracket as train/worker; parse "
                        "with `cli perf profile`)")
    s.add_argument("--jobs", default=_env("DPS_JOBS", None),
                   help="multi-job tenancy (docs/TENANCY.md): declare "
                        "extra jobs beside the implicit 'default' one, "
                        "each with its own parameter namespace, "
                        "aggregation config, membership, and checkpoint "
                        "lineage. Grammar: 'name[:k=v,...];...', e.g. "
                        "'vision:weight=3,mode=sync,sync_quorum=2;"
                        "ranker:weight=1,mode=async'. Enables the "
                        "weighted-fair admission scheduler "
                        "(per-job QoS) and the per-job /cluster view")
    s.add_argument("--no-slo", action="store_true",
                   help="disable the serve-tier SLO evaluator (on by "
                        "default with the health monitor): multi-window "
                        "error-budget burn over the server-side RPC "
                        "latency/error metrics -> slo_burn_fast/"
                        "slo_burn_slow alerts, GET /cluster 'slo' block "
                        "(docs/OBSERVABILITY.md)")
    s.add_argument("--slo-fetch-p99-ms", type=float,
                   default=_env("DPS_SLO_FETCH_P99_MS", 100.0, float),
                   help="fetch latency objective: 99%% of FetchParameters "
                        "under this many milliseconds")
    s.add_argument("--slo-availability", type=float,
                   default=_env("DPS_SLO_AVAILABILITY", 0.99, float),
                   help="availability objective for fetch and push "
                        "(good fraction, e.g. 0.99)")
    s.add_argument("--slo-fast-window", type=float,
                   default=_env("DPS_SLO_FAST_WINDOW", 60.0, float),
                   help="fast burn window seconds (slo_burn_fast, "
                        "critical)")
    s.add_argument("--slo-slow-window", type=float,
                   default=_env("DPS_SLO_SLOW_WINDOW", 300.0, float),
                   help="slow burn window seconds (slo_burn_slow, "
                        "warning)")
    s.add_argument("--slo-fast-burn", type=float,
                   default=_env("DPS_SLO_FAST_BURN", 14.4, float),
                   help="burn-rate threshold over the fast window")
    s.add_argument("--slo-slow-burn", type=float,
                   default=_env("DPS_SLO_SLOW_BURN", 6.0, float),
                   help="burn-rate threshold over the slow window")
    s.add_argument("--no-memory-telemetry", action="store_true",
                   help="disable the periodic memory sampler (on by "
                        "default with the health monitor): host RSS + "
                        "device HBM gauges, the windowed leak-slope "
                        "verdict in GET /cluster 'memory', and the "
                        "memory_growth health rule "
                        "(docs/OBSERVABILITY.md 'Goodput observatory')")
    s.add_argument("--profile-triggers", action="store_true",
                   help="trigger-driven continuous profiling "
                        "(docs/OBSERVABILITY.md): an slo_burn edge or a "
                        "fleet goodput-fraction drop captures a bounded "
                        "jax.profiler window, attributes it per op "
                        "class, and appends a PROFILE_*.json record to "
                        "--profiles-dir (per-rule cooldown dedupe; "
                        "needs the health monitor)")
    s.add_argument("--profiles-dir",
                   default=_env("DPS_PROFILES_DIR", "profiles"),
                   help="profile ledger directory for --profile-triggers")
    s.add_argument("--profile-window", type=float,
                   default=_env("DPS_PROFILE_WINDOW", 1.5, float),
                   help="seconds of device activity each triggered "
                        "capture brackets")
    s.add_argument("--profile-cooldown", type=float,
                   default=_env("DPS_PROFILE_COOLDOWN", 600.0, float),
                   help="per-rule dedupe window: a degradation storm "
                        "yields one capture per rule per cooldown")
    s.add_argument("--goodput-drop-threshold", type=float,
                   default=_env("DPS_GOODPUT_DROP", 0.5, float),
                   help="fleet goodput fraction whose falling edge "
                        "triggers a capture (previous tick at or above, "
                        "this tick below)")
    add_platform(s)
    add_telemetry(s)

    e = sub.add_parser("experiments",
                       help="run the sync/async x workers matrix "
                            "(reference §6 tables) and plot")
    e.add_argument("--modes", default="sync,async")
    e.add_argument("--worker-counts", default="4,8")
    e.add_argument("--out-dir", default="experiments/results")
    e.add_argument("--backend", choices=["python", "native", "device"],
                   default="python",
                   help="'device' keeps store tensors in accelerator HBM "
                        "(zero host<->device traffic per step)")
    e.add_argument("--no-plots", action="store_true")
    # Pod-log ingestion (analysis/pod_logs.py): one command turns a
    # `tpu-pod.sh train` run into a reference-schema experiment JSON —
    # the reference's CloudWatch ETL loop (parse_cloudwatch_logs.py:34-87)
    # over ssh + terraform-output discovery.
    e.add_argument("--ingest-pod", action="store_true",
                   help="collect METRICS_JSON logs from a TPU pod instead "
                        "of running the local matrix")
    e.add_argument("--pod-name", help="pod to ingest (else --tf-dir "
                                      "discovery)")
    e.add_argument("--pod-zone")
    e.add_argument("--tf-dir", default="deploy/terraform",
                   help="terraform dir for pod_name/pod_zone discovery")
    e.add_argument("--experiment-name", default="pod_run")
    e.add_argument("--pod-log-path", default="~/dps_train.log")
    add_common(e)

    w = sub.add_parser("worker", help="gRPC remote worker")
    w.add_argument("--server",
                   default=_env("PARAMETER_SERVER_ADDRESS",
                                "localhost:8000"),
                   help="PS address (worker.py:457-459)")
    w.add_argument("--shards", default=_env("DPS_SHARDS", None),
                   help="sharded deployment: comma list of shard primary "
                        "addresses (or just the shard-0 seed — the rest "
                        "are adopted from its shard map). Pushes/fetches "
                        "fan out per shard and reassemble "
                        "(docs/SHARDING.md); overrides --server")
    w.add_argument("--worker-name", default=_env("WORKER_NAME", ""))
    w.add_argument("--job", default=_env("DPS_JOB", None),
                   help="job this worker trains (docs/TENANCY.md): "
                        "rides registration and every push/fetch "
                        "envelope, capability-gated — against a server "
                        "without --jobs the worker lands in the "
                        "'default' job unchanged")
    w.add_argument("--sync-steps", type=int,
                   default=_env("SYNC_STEPS", 1, int))
    w.add_argument("--k-step-mode", choices=["faithful", "accumulate"],
                   default="faithful")
    w.add_argument("--heartbeat", type=float, default=0.0,
                   help="liveness ping interval in seconds (pair with the "
                        "server's --worker-timeout); 0 disables")
    w.add_argument("--overlap", action="store_true",
                   default=bool(_env("DPS_OVERLAP", 0, int)),
                   help="overlapped comms pipeline: push + prefetch on a "
                        "background thread while compute runs; pays off "
                        "with --sync-steps>1 (docs/WIRE_PROTOCOL.md)")
    w.add_argument("--no-delta-fetch", action="store_true",
                   help="disable version-gated delta fetches (full params "
                        "on every fetch, reference parity)")
    w.add_argument("--no-error-feedback", action="store_true",
                   help="disable the error-feedback residual carry the "
                        "quantized push codecs (int8/int4/topk/adaptive) "
                        "use by default (docs/WIRE_PROTOCOL.md)")
    w.add_argument("--topk-frac", type=float,
                   default=_env("DPS_TOPK_FRAC", 0.01, float),
                   help="fraction of entries a topk push keeps per tensor "
                        "(largest magnitude)")
    w.add_argument("--reconnect-timeout", type=float,
                   default=_env("DPS_RECONNECT_TIMEOUT", 0.0, float),
                   help="session resume window in seconds "
                        "(docs/ROBUSTNESS.md): on exhausted RPC retries "
                        "the worker re-registers, re-fetches at the "
                        "restored server step, and reconciles its "
                        "in-flight gradient instead of dying; 0 disables")
    w.add_argument("--faults", default=_env("DPS_FAULTS_CLIENT", None),
                   help="deterministic client-side fault injection spec "
                        "(comms/faults.py), e.g. "
                        "'seed=7;push.unavailable@p=0.1'")
    w.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler (XLA-level) trace of the "
                        "worker loop into this directory (TensorBoard/"
                        "Perfetto; pairs with --trace span traces)")
    add_common(w)

    sv = sub.add_parser(
        "supervise",
        help="spawn and babysit N `cli worker` processes: respawn on "
             "death with exponential backoff + crash-loop latch "
             "(docs/ROBUSTNESS.md). Everything after `--` is passed to "
             "every child worker verbatim")
    sv.add_argument("--workers", type=int,
                    default=_env("DPS_SUPERVISE_WORKERS", 2, int),
                    help="worker process slots to run")
    sv.add_argument("--no-respawn", action="store_true",
                    help="just run the children once (no self-healing)")
    sv.add_argument("--respawn-backoff", type=float,
                    default=_env("DPS_RESPAWN_BACKOFF", 1.0, float),
                    help="first respawn delay; doubles per consecutive "
                         "crash up to --respawn-backoff-max")
    sv.add_argument("--respawn-backoff-max", type=float, default=30.0)
    sv.add_argument("--healthy-after", type=float, default=5.0,
                    help="a child alive this long resets its slot's "
                         "backoff and crash-loop count")
    sv.add_argument("--crash-loop-after", type=int, default=3,
                    help="consecutive fast crashes before a slot latches "
                         "(stops respawning, nonzero exit)")
    sv.add_argument("--slot-faults", action="append", default=[],
                    metavar="SLOT:SPEC",
                    help="fault spec for one slot's FIRST spawn only "
                         "(chaos drills: respawns run clean), e.g. "
                         "'0:seed=7;push.kill@n=2'; repeatable")
    sv.add_argument("--slot-env", action="append", default=[],
                    metavar="SLOT:KEY=VALUE",
                    help="env var for one slot's first spawn only, e.g. "
                         "'1:DPS_NAN_STEP=4'; repeatable")
    sv.add_argument("--autoscale-job", default=None,
                    help="worker autoscaling (docs/TENANCY.md): poll the "
                         "server's per-job /cluster view and grow/shrink "
                         "this supervisor's slot count with the named "
                         "job's admission-queue/straggler pressure "
                         "(worker_grow/worker_shrink actions). Pass the "
                         "job's --job flag in the child worker args too")
    sv.add_argument("--autoscale-url", default=None,
                    help="base URL of the serve process's metrics "
                         "endpoint (e.g. http://host:9400); required "
                         "with --autoscale-job")
    sv.add_argument("--autoscale-min", type=int, default=1,
                    help="worker-slot floor the autoscaler keeps alive")
    sv.add_argument("--autoscale-max", type=int, default=4,
                    help="worker-slot ceiling")
    sv.add_argument("--autoscale-depth-high", type=float, default=4.0,
                    help="admission queue depth above which the fleet "
                         "grows (after --autoscale-sustain ticks)")
    sv.add_argument("--autoscale-depth-low", type=float, default=1.0,
                    help="queue depth below which it shrinks "
                         "(hysteresis band with --autoscale-depth-high)")
    sv.add_argument("--autoscale-sustain", type=int, default=3,
                    help="consecutive polls a condition must hold "
                         "before acting")
    sv.add_argument("--autoscale-cooldown", type=float, default=15.0,
                    help="minimum seconds between scaling actions")
    sv.add_argument("--autoscale-poll", type=float, default=2.0,
                    help="seconds between /cluster pressure polls")
    add_platform(sv)
    add_telemetry(sv)
    sv.add_argument("worker_args", nargs=argparse.REMAINDER,
                    help="-- followed by the `cli worker` args every "
                         "child runs with (--worker-name is added per "
                         "slot)")

    r = sub.add_parser(
        "replica",
        help="read-only fetch replica behind one shard primary "
             "(docs/SHARDING.md): subscribes over delta-fetch, serves "
             "cached parameter bytes, refuses when stale, redirects "
             "writes to the primary")
    r.add_argument("--primary", required=True,
                   help="address (host:port) of the shard primary this "
                        "replica mirrors (writes always redirect here)")
    r.add_argument("--parent", default=None,
                   help="subscribe source when different from the "
                        "primary — point it at ANOTHER replica to form "
                        "a fan-out tree (docs/SHARDING.md \"Fan-out "
                        "trees\"); the tier is learned from the "
                        "parent's replies")
    r.add_argument("--port", type=int, default=_env("DPS_PORT", 0, int),
                   help="replica serve port (0 = pick a free port)")
    r.add_argument("--shard-id", type=int, default=0,
                   help="shard slot of the primary (stamped on replies "
                        "and the announce)")
    r.add_argument("--advertise", default=None,
                   help="address to announce to the primary (defaults to "
                        "localhost:<bound port>)")
    r.add_argument("--metrics-advertise", default=None,
                   help="metrics endpoint address to announce alongside "
                        "it (defaults to localhost:<bound --metrics-port> "
                        "when one is serving) — published in the "
                        "primary's /cluster view so `cli observe` "
                        "discovers this replica as a scrape target")
    r.add_argument("--poll-interval", type=float,
                   default=_env("DPS_REPLICA_POLL", 0.05, float),
                   help="seconds between delta-fetch refreshes against "
                        "the primary (NOT_MODIFIED when idle)")
    r.add_argument("--staleness-bound", type=float,
                   default=_env("DPS_REPLICA_STALENESS", None, float),
                   help="max seconds since the last successful refresh "
                        "before fetches are refused with a redirect to "
                        "the primary (default: derived from the tier — "
                        "5s x tier, so edge tiers tolerate "
                        "proportionally more lag)")
    r.add_argument("--reparent-after", type=int, default=3,
                   help="consecutive refresh failures before this "
                        "replica re-parents via the cached topology "
                        "(prefer the dead parent's tier, fall back to "
                        "the primary)")
    r.add_argument("--reparent-cooldown", type=float, default=5.0,
                   help="hysteresis: minimum seconds between re-parent "
                        "moves, so a flapping parent cannot make "
                        "children ricochet around the tree")
    r.add_argument("--canary", action="store_true",
                   help="serve the canary-gated inference workload "
                        "(docs/SHARDING.md \"Serve tier\"): keep a step "
                        "history, split `infer` fetches stable/canary, "
                        "promote or roll back on client quality feedback")
    r.add_argument("--canary-fraction", type=float, default=0.05,
                   help="share of infer requests routed to the canary "
                        "step (default 5%%)")
    r.add_argument("--canary-min-samples", type=int, default=20,
                   help="quality samples each arm needs before a "
                        "promote/rollback decision")
    r.add_argument("--canary-tolerance", type=float, default=0.0,
                   help="promote while canary mean quality >= stable "
                        "mean - tolerance; below that, roll back")
    r.add_argument("--faults", default=None,
                   help="seeded fault spec for the replica tier (env "
                        "DPS_FAULTS_REPLICA; comms/faults.py grammar): "
                        "`refresh.*` rules hit the subscription poll, "
                        "`subscribe.*` rules this replica's own serving "
                        "handler")
    add_telemetry(r)

    lg = sub.add_parser(
        "loadgen",
        help="fetch-path load generator: hammer FetchParameters on one "
             "or more targets and print aggregate QPS as LOADGEN_JSON "
             "(docs/SHARDING.md)")
    lg.add_argument("--targets", required=True,
                    help="comma list of fetch targets (primaries and/or "
                         "replicas), host:port each; threads round-robin "
                         "over the list")
    lg.add_argument("--duration", type=float, default=5.0,
                    help="seconds to run")
    lg.add_argument("--concurrency", type=int, default=4,
                    help="total client threads (each with its own "
                         "channel)")
    lg.add_argument("--job", default=None,
                    help="stamp fetches with a job id (docs/TENANCY.md); "
                         "a comma list round-robins threads over the "
                         "jobs and the LOADGEN_JSON gains a per-job "
                         "QPS/latency breakdown")
    lg.add_argument("--fetch-mode", choices=["full", "delta", "infer"],
                    default="full",
                    help="full = whole model every fetch; delta = poll "
                         "at the current step (header-only NOT_MODIFIED "
                         "steady state); infer = the inference-serving "
                         "workload against a canary replica, with "
                         "per-arm counts/latency/quality in the result")
    lg.add_argument("--scale-out", type=int, default=0,
                    help="distributed generation: launch N coordinated "
                         "generator PROCESSES (each running this exact "
                         "workload) and print ONE merged LOADGEN_JSON — "
                         "percentiles come from the bucket-exact "
                         "histogram union, never averaged (0 = run "
                         "in-process, the default)")

    rs = sub.add_parser(
        "reshard",
        help="live shard migration coordinator (docs/SHARDING.md "
             "\"Migration protocol\"): move a slot range between two "
             "ADJACENT primaries — export+journal on the donor, import "
             "on the recipient, apply the bumped map everywhere, commit "
             "the drop — with zero downtime and exactly-once preserved")
    rs.add_argument("--primaries", required=True,
                    help="ordered comma list of ALL shard primaries "
                         "(index = shard id), the same list the serve "
                         "processes were given as --shard-peers")
    rs.add_argument("--donor", type=int, required=True,
                    help="shard id giving up the slot range")
    rs.add_argument("--recipient", type=int, required=True,
                    help="shard id receiving it (must be donor±1: ranges "
                         "stay contiguous per shard)")
    rs.add_argument("--slots", required=True, metavar="LO:HI",
                    help="slot range [LO,HI) to move; must sit at the "
                         "donor's boundary facing the recipient")
    rs.add_argument("--json", action="store_true",
                    help="print only the RESHARD_JSON line")
    rs.add_argument("--migration-id", default=None,
                    help="explicit migration id (defaults to a random "
                         "one); the durable ledger key --resume/--abort "
                         "match against (docs/ROBUSTNESS.md)")
    rs.add_argument("--lease-ttl", type=float, default=30.0,
                    help="donor freeze lease in seconds: if the "
                         "coordinator dies before publishing the map, "
                         "the donor auto-unfreezes and aborts after "
                         "this long (default 30)")
    rs.add_argument("--resume", action="store_true",
                    help="inspect the primaries' migration ledger and "
                         "deterministically roll the crashed migration "
                         "forward (map already publishing) or back "
                         "(pre-publish / lease expired)")
    rs.add_argument("--abort", action="store_true",
                    help="roll back an in-flight migration: recipient "
                         "drops its adopted copy, donor unfreezes, map "
                         "untouched (refused once the map started "
                         "publishing — use --resume)")
    rs.add_argument("--crash-after",
                    choices=["export", "import", "apply_first",
                             "apply_all"],
                    default=None,
                    help="chaos drill hook: hard-exit the coordinator "
                         "immediately after this phase boundary "
                         "(experiments/run_reshard_chaos_demo.py)")

    inf = sub.add_parser(
        "infer",
        help="one-shot inference client against the serve tier "
             "(docs/SHARDING.md \"Serve tier\"): send `infer` fetches, "
             "print which arm and step served each, optionally report a "
             "quality score back")
    inf.add_argument("--target", required=True,
                     help="replica (or primary) address, host:port")
    inf.add_argument("--count", type=int, default=1,
                     help="number of inference requests to send")
    inf.add_argument("--quality", type=float, default=None,
                     help="quality score to report for each served "
                          "response (feeds the canary decision); omit to "
                          "send no feedback")
    inf.add_argument("--json", action="store_true",
                     help="print only the INFER_JSON line")

    st = sub.add_parser(
        "status",
        help="cluster health dashboard: render a serve process's "
             "GET /cluster as a terminal table (docs/OBSERVABILITY.md)")
    st.add_argument("--url", default=_env("DPS_STATUS_URL", None),
                    help="base URL of the server's metrics endpoint, e.g. "
                         "http://host:9400 (env DPS_STATUS_URL); overrides "
                         "--host/--metrics-port")
    st.add_argument("--host", default="127.0.0.1",
                    help="metrics endpoint host (with --metrics-port)")
    st.add_argument("--metrics-port", type=int,
                    default=_env("DPS_METRICS_PORT", None, int),
                    help="the serve process's --metrics-port")
    st.add_argument("--watch", type=float, default=0.0, metavar="SECONDS",
                    help="redraw every N seconds until interrupted "
                         "(0 = one shot)")
    st.add_argument("--json", action="store_true",
                    help="print the raw /cluster JSON instead of the table")
    st.add_argument("--via-fleet", default=None, metavar="URL",
                    help="render the dashboard from a fleet collector's "
                         "GET /fleet snapshot (cli observe) instead of "
                         "one primary's /cluster — the first primary's "
                         "cluster blocks plus fleet-scope SLO/alerts; "
                         "blocks the fleet view lacks degrade exactly "
                         "like a server without them")

    ob = sub.add_parser(
        "observe",
        help="fleet observatory collector (docs/OBSERVABILITY.md "
             "\"Fleet observatory\"): scrape every fleet process's "
             "/metrics + /cluster on an interval into a bounded ring "
             "TSDB, roll them up (bucket-exact histogram merges), and "
             "serve GET /fleet — a standalone process, off every hot "
             "path, that survives primary restarts")
    ob.add_argument("--targets", required=True,
                    help="comma list of metrics endpoints (host:port) to "
                         "seed the scrape set; replicas announcing a "
                         "metrics address via /cluster are discovered "
                         "automatically")
    ob.add_argument("--port", type=int, default=_env("DPS_FLEET_PORT", 0,
                                                     int),
                    help="port to serve GET /fleet on (0 = pick free)")
    ob.add_argument("--interval", type=float, default=2.0,
                    help="seconds between scrape ticks")
    ob.add_argument("--timeout", type=float, default=1.5,
                    help="per-target per-request scrape timeout; a dead "
                         "target marks its series stale, never blocks "
                         "the tick")
    ob.add_argument("--ring-depth", type=int, default=120,
                    help="samples kept per series ring (bounded memory)")
    ob.add_argument("--slo-fetch-p99-ms", type=float, default=100.0,
                    help="fleet fetch-latency objective threshold")
    ob.add_argument("--slo-availability", type=float, default=0.99,
                    help="fleet availability objective target")
    ob.add_argument("--slo-fast-window", type=float, default=60.0,
                    help="fast burn window (s) for the fleet-scope SLO "
                         "evaluation over MERGED series")
    ob.add_argument("--slo-slow-window", type=float, default=300.0,
                    help="slow burn window (s)")
    ob.add_argument("--journal-dir",
                    default=_env("DPS_JOURNAL_DIR", None),
                    help="journal every tick's merged /fleet view (minus "
                         "history rings) + slo_burn edges into this "
                         "durable journal directory — the `cli top "
                         "--replay` / `cli query` source")
    ob.add_argument("--incidents-dir",
                    default=_env("DPS_INCIDENTS_DIR", None),
                    help="auto-freeze a forensic bundle here on critical "
                         "fleet alerts / SLO-burn edges (journal window, "
                         "/fleet snapshot, target trace dumps; "
                         "docs/OBSERVABILITY.md 'Incident forensics')")
    ob.add_argument("--incident-window", type=float, default=120.0,
                    help="seconds of journal history frozen per bundle")
    ob.add_argument("--incident-cooldown", type=float, default=120.0,
                    help="per-rule dedupe window: an alert storm yields "
                         "one bundle per rule per cooldown")

    tp = sub.add_parser(
        "top",
        help="live fleet dashboard over a collector's GET /fleet "
             "(per-tier rows, fleet QPS, replica lag, merged-series SLO "
             "burn, alert feed, sparklines); exit codes match `cli "
             "status`: 0 healthy, 1 unreachable, 2 critical, 3 "
             "critical-but-healing")
    tp.add_argument("--url", default=_env("DPS_FLEET_URL", None),
                    help="base URL of the fleet collector, e.g. "
                         "http://host:9500 (env DPS_FLEET_URL)")
    tp.add_argument("--watch", type=float, default=0.0, metavar="SECONDS",
                    help="redraw every N seconds until interrupted "
                         "(0 = one shot)")
    tp.add_argument("--json", action="store_true",
                    help="print the raw /fleet JSON instead of the "
                         "dashboard")
    tp.add_argument("--replay", default=None, metavar="JOURNAL_DIR",
                    help="scrub a PAST run on the same dashboard: read "
                         "fleet_tick records from a journal directory "
                         "(cli observe --journal-dir) instead of polling "
                         "a live /fleet; --watch steps frames at that "
                         "interval, one-shot renders the final frame")

    inc = sub.add_parser(
        "incident",
        help="incident forensics over auto-captured bundles "
             "(docs/OBSERVABILITY.md 'Incident forensics'): list "
             "bundles, show a manifest, or reconstruct the causal "
             "fault->alert->remediation->resolution timeline from the "
             "on-disk journal — no live process needed")
    incsub = inc.add_subparsers(dest="incident_command", required=True)
    inc_common = {
        "--dir": dict(default=_env("DPS_INCIDENTS_DIR", "incidents"),
                      help="incidents directory (bundles live in "
                           "<dir>/<id>/; env DPS_INCIDENTS_DIR)"),
        "--json": dict(action="store_true",
                       help="machine-readable output"),
    }
    incl = incsub.add_parser("list", help="one row per bundle")
    incs = incsub.add_parser("show",
                             help="manifest + bundle contents for one id")
    incs.add_argument("id", help="bundle id (or unique prefix)")
    incr = incsub.add_parser(
        "report",
        help="merge the bundle's frozen journal window with the "
             "journal's post-edge segments and render the ordered "
             "cross-process postmortem timeline")
    incr.add_argument("id", nargs="?", default=None,
                      help="bundle id or unique prefix (default: the "
                           "newest bundle)")
    incr.add_argument("--journal-dir", default=None,
                      help="override the journal directory recorded in "
                           "the manifest (bundle moved hosts)")
    for q in (incl, incs, incr):
        for flag, kw in inc_common.items():
            q.add_argument(flag, **kw)

    qy = sub.add_parser(
        "query",
        help="retro-query a durable journal: list/aggregate series over "
             "a time range with union-exact percentiles (bucket-exact "
             "histogram merges across processes), or re-run the SLO "
             "burn evaluation over history (same windows as the live "
             "evaluator)")
    qy.add_argument("--journal", required=True,
                    help="journal directory (or one segment file)")
    qy.add_argument("--series", default=None,
                    help="substring filter on metric keys (e.g. "
                         "'rpc_server_latency')")
    qy.add_argument("--since", type=float, default=None,
                    help="window start (unix seconds; percentiles and "
                         "counter deltas are computed window-exact "
                         "against the last snapshot at or before it)")
    qy.add_argument("--until", type=float, default=None,
                    help="window end (unix seconds; default newest)")
    qy.add_argument("--last", type=float, default=None, metavar="SECONDS",
                    help="shorthand: window = newest snapshot minus N "
                         "seconds (overrides --since)")
    qy.add_argument("--percentiles", action="store_true",
                    help="p50/p95/p99 per selected histogram series, "
                         "merged union-exact across processes")
    qy.add_argument("--slo", action="store_true",
                    help="retroactive SLO burn evaluation over the "
                         "journal's snapshot history (fast + slow "
                         "windows, telemetry/slo.py semantics); exit "
                         "code 2 when any critical window breached")
    qy.add_argument("--slo-fetch-p99-ms", type=float, default=100.0,
                    help="fetch-latency objective threshold")
    qy.add_argument("--slo-availability", type=float, default=0.99,
                    help="availability objective target")
    qy.add_argument("--slo-fast-window", type=float, default=60.0,
                    help="fast burn window (s)")
    qy.add_argument("--slo-slow-window", type=float, default=300.0,
                    help="slow burn window (s)")
    qy.add_argument("--goodput", action="store_true",
                    help="retroactive goodput ledger over the window: "
                         "per-category wall seconds (counter deltas "
                         "merged across processes), goodput fraction, "
                         "residual — answers 'what fraction of the "
                         "window was productive' from the journal alone")
    qy.add_argument("--incidents", default=None, metavar="DIR",
                    help="with --goodput: join incident bundles from DIR "
                         "and attribute badput seconds to each bundle's "
                         "capture window (per-incident cost accounting)")
    qy.add_argument("--goodput-tolerance", type=float, default=0.02,
                    help="residual fraction above which the goodput "
                         "report flags the ledger unreconciled "
                         "(default: 0.02)")
    qy.add_argument("--json", action="store_true",
                    help="machine-readable output (QUERY_JSON line)")

    gp = sub.add_parser(
        "goodput",
        help="live goodput ledger from a running process's /metrics.json: "
             "per-category wall-clock accounting "
             "(docs/OBSERVABILITY.md 'Goodput observatory'), goodput "
             "fraction, residual; exit 1 when the endpoint is "
             "unreachable")
    gp.add_argument("--url", default=_env("DPS_METRICS_URL", None),
                    help="base URL of the metrics endpoint, e.g. "
                         "http://host:9100 (env DPS_METRICS_URL; "
                         "or use --host/--metrics-port)")
    gp.add_argument("--host", default="127.0.0.1",
                    help="metrics host when --url is not given")
    gp.add_argument("--metrics-port", type=int, default=9100,
                    help="metrics port when --url is not given")
    gp.add_argument("--tolerance", type=float, default=0.02,
                    help="residual fraction above which the ledger is "
                         "flagged unreconciled (default: 0.02)")
    gp.add_argument("--json", action="store_true",
                    help="machine-readable output (GOODPUT_JSON line)")

    pf = sub.add_parser(
        "perf",
        help="perf observatory (docs/OBSERVABILITY.md): attribute a "
             "--profile-dir capture into per-op-class device time "
             "(`profile`), or run the bench-ledger regression watch "
             "(`check`)")
    pfsub = pf.add_subparsers(dest="perf_command", required=True)
    pfp = pfsub.add_parser(
        "profile",
        help="parse a jax.profiler capture into device-time attribution "
             "tables, optionally joined with flight-recorder dumps into "
             "one end-to-end artifact")
    pfp.add_argument("--profile-dir", required=True,
                     help="the --profile-dir a train/serve/bench run "
                          "captured into")
    pfp.add_argument("--trace-dump-dir", default=None,
                     help="flight-recorder dump dir (--trace-dump-dir of "
                          "the same run): joins the host-phase "
                          "critical-path report and reconciles step wall "
                          "vs attributed device time")
    pfp.add_argument("--device-kind", default=None,
                     help="override the device kind recorded in the "
                          "artifact (default: jax.devices()[0] if jax "
                          "imports)")
    pfp.add_argument("--out", default=None,
                     help="write the merged JSON artifact here")
    pfp.add_argument("--json", action="store_true",
                     help="print the JSON artifact instead of the table")
    pfp.add_argument("--keep-traces", action="store_true",
                     help="keep the raw Chrome traces in --profile-dir "
                          "after a successful attribution (default: "
                          "prune them — the artifact is the durable "
                          "record; traces are kept automatically when "
                          "attribution fails so they stay debuggable)")
    pfd = pfsub.add_parser(
        "diff",
        help="regression attribution: diff two attribution artifacts "
             "(cli perf profile --out, or profile-ledger records) into "
             "a per-op-class delta table — which op class got slower, "
             "what appeared/vanished, how the residual moved; refuses "
             "artifacts with mismatched attribution bases")
    pfd.add_argument("baseline", help="baseline artifact JSON path")
    pfd.add_argument("candidate", help="candidate artifact JSON path")
    pfd.add_argument("--tolerance", type=float, default=0.01,
                     help="fractional |delta|/baseline below which a "
                          "class is reported unchanged (default: 0.01)")
    pfd.add_argument("--json", action="store_true",
                     help="machine-readable diff instead of the table")
    pfc = pfsub.add_parser(
        "check",
        help="bench regression watch over the committed BENCH_*/"
             "MULTICHIP_* ledger (tools/benchwatch; exit 0 pass, "
             "1 malformed, 2 regression)")
    pfc.add_argument("--root", default=None,
                     help="ledger directory (default: the repo checkout "
                          "root)")
    pfc.add_argument("--tolerance", type=float, default=0.05,
                     help="allowed fractional drop (default: 0.05)")
    pfc.add_argument("--baseline-window", type=int, default=3,
                     help="usable runs in the baseline median")
    pfc.add_argument("--recent-window", type=int, default=1,
                     help="usable runs in the recent median")
    pfc.add_argument("--format", choices=("md", "json"), default="md",
                     help="verdict format (default: md)")
    pfc.add_argument("--validate-only", action="store_true",
                     help="schema-validate the ledger and stop")
    pfc.add_argument("--profiles-root", default=None,
                     help="committed profile ledger directory (default: "
                          "<root>/profiles when it exists)")

    ln = sub.add_parser(
        "lint",
        help="framework-aware static analysis (tools/dpslint): lock "
             "discipline, hot-path allocations, capability gating, JAX "
             "pitfalls, catalog drift (docs/STATIC_ANALYSIS.md)")
    ln.add_argument("--json", action="store_true",
                    help="emit a JSON report instead of human lines")
    ln.add_argument("--baseline", default=None,
                    help="alternate baseline file (default: the reviewed "
                         "register at tools/dpslint/baseline.json)")

    return p


@contextmanager
def _telemetry_session(args, role: str):
    """Start/stop the opt-in telemetry surfaces around a command body:
    the periodic snapshot emitter (``--telemetry``), the Prometheus/
    debug endpoint (``--metrics-port``), and the tracing flight recorder
    (``--trace``/``--trace-buffer``/``--trace-dump-dir``). The emitter's
    final flush runs even on failure — a crashed run still leaves its
    last complete totals in the log (the round-5 bench lesson: never die
    with nothing written) — and the shutdown hooks extend that guarantee
    to SIGTERM: the recorder tail is dumped and the snapshot emitter
    flushes its final interval instead of silently dropping it."""
    emitter = http_server = journal = None
    tracing = getattr(args, "trace", False)
    dump_dir = getattr(args, "trace_dump_dir", None)
    journal_dir = getattr(args, "journal_dir", None)
    if tracing:
        from .telemetry import enable_tracing
        enable_tracing(buffer=getattr(args, "trace_buffer", 4096),
                       role=role)
    if journal_dir:
        # Durable journal (ISSUE 18): installed process-globally BEFORE
        # the command body so every chokepoint (alert edges, directives,
        # migrations, checkpoints, fault arming) journals from the
        # first event on; independent of --telemetry.
        from .telemetry import JournalWriter, set_journal
        journal = JournalWriter(journal_dir, role=role)
        set_journal(journal)
    if tracing or dump_dir or journal \
            or getattr(args, "telemetry", False):
        from .telemetry import install_shutdown_hooks
        install_shutdown_hooks(dump_dir=dump_dir, role=role)
    port = getattr(args, "metrics_port", None)
    if port is not None:
        from .telemetry import register_build_info, start_metrics_server
        register_build_info()  # fleet-wide scrape correlation gauge
        http_server, bound = start_metrics_server(port=port)
        # Stash the bound port so the command body can announce its
        # metrics endpoint (replicas publish it through the primary's
        # /cluster view for fleet discovery, telemetry/fleet.py).
        args._metrics_bound = bound
        print(f"telemetry: serving /metrics on :{bound}", file=sys.stderr,
              flush=True)
    if getattr(args, "telemetry", False):
        from .telemetry import (SnapshotEmitter, add_shutdown_flush,
                                register_build_info)
        register_build_info()
        emitter = SnapshotEmitter(
            interval=getattr(args, "telemetry_interval", 5.0),
            role=role, journal=journal).start()
        # SIGTERM/atexit flush: the final snapshot of a terminating
        # process is never lost (ISSUE 3 satellite; flush_now is a no-op
        # once stop() below already emitted the final line). With a
        # journal attached the same hook also seals the active segment
        # (ISSUE 18): a SIGTERM'd process leaves a crash-consistent,
        # fsync'd tail.
        add_shutdown_flush(emitter.flush_now)
    if journal is not None and emitter is None:
        # No emitter to piggyback on: seal the journal directly from
        # the SIGTERM/atexit shutdown path.
        from .telemetry import add_shutdown_flush
        add_shutdown_flush(journal.seal)
    try:
        yield
    finally:
        if emitter is not None:
            from .telemetry import remove_shutdown_flush
            emitter.stop(final=True)
            remove_shutdown_flush(emitter.flush_now)
        if journal is not None:
            from .telemetry import remove_shutdown_flush, set_journal
            set_journal(None)
            journal.seal()
            if emitter is None:
                remove_shutdown_flush(journal.seal)
        if http_server is not None:
            http_server.shutdown()


@contextmanager
def _profiler_session(profile_dir: str | None):
    """``--profile-dir``: bracket the hot loop with
    ``jax.profiler.start_trace``/``stop_trace`` (via utils/tracing.py) so
    an XLA-level timeline (MXU utilization, HBM traffic, collectives)
    lands beside the framework-level span traces. No-op when unset."""
    if not profile_dir:
        yield
        return
    import os as _os
    _os.makedirs(profile_dir, exist_ok=True)
    from .utils.tracing import trace
    print(f"profiler: tracing into {profile_dir}", file=sys.stderr,
          flush=True)
    with trace(profile_dir):
        yield


def _load_dataset(args):
    from .data import load_cifar100, synthetic_cifar100
    from .data.cifar import synthetic_imagenet

    if getattr(args, "dataset", "cifar100") == "imagenet-synth":
        ds = synthetic_imagenet(
            n_train=getattr(args, "num_train", None) or 10_000,
            n_test=getattr(args, "num_test", None) or 1_000,
            image_size=getattr(args, "image_size", 224))
    elif getattr(args, "synthetic", False):
        ds = synthetic_cifar100()
    else:
        ds = load_cifar100(getattr(args, "data_dir", None))
    if getattr(args, "num_train", None):
        ds.x_train = ds.x_train[:args.num_train]
        ds.y_train = ds.y_train[:args.num_train]
    if getattr(args, "num_test", None):
        ds.x_test = ds.x_test[:args.num_test]
        ds.y_test = ds.y_test[:args.num_test]
    return ds


def cmd_train(args) -> int:
    with _telemetry_session(args, "trainer"):
        return _cmd_train(args)


def _cmd_train(args) -> int:
    if getattr(args, "multihost", False):
        if args.mode != "sync":
            raise SystemExit("--multihost applies to --mode sync (async "
                             "multi-host uses serve/worker over gRPC)")
        from .parallel.multihost import initialize as initialize_multihost
        initialize_multihost(args.coordinator, args.num_processes,
                             args.process_id)

    dataset = _load_dataset(args)
    if dataset.synthetic and getattr(args, "dataset",
                                     "cifar100") == "cifar100" \
            and not getattr(args, "synthetic", False):
        print("note: CIFAR-100 not found on disk; using the synthetic "
              "dataset", file=sys.stderr)

    num_classes = dataset.num_classes

    if args.mode == "baseline":
        from .train.baseline import BaselineConfig, BaselineTrainer
        cfg = BaselineConfig(batch_size=args.batch_size,
                             num_epochs=args.epochs,
                             learning_rate=args.lr,
                             augment=not args.no_augment,
                             dtype=args.dtype, model=args.model,
                             num_classes=num_classes, seed=args.seed)
        trainer = BaselineTrainer(dataset, cfg)
        with _profiler_session(getattr(args, "profile_dir", None)):
            trainer.train(plot_path=args.plot,
                          emit_metrics=args.emit_metrics,
                          checkpoint_dir=args.checkpoint_dir,
                          resume=args.resume)
        return 0

    if args.mode in ("tp", "pp", "sp", "moe"):
        from .train.model_parallel import (ModelParallelConfig, MoETrainer,
                                           PipelineTrainer, SPTrainer,
                                           TPTrainer)
        mp_cfg = ModelParallelConfig(
            model=args.model, num_workers=args.workers,
            tp_degree=args.tp_degree,
            pp_microbatches=args.pp_microbatches,
            dp_degree=args.dp_degree,
            pp_tp_degree=args.pp_tp_degree,
            moe_capacity_factor=args.moe_capacity_factor,
            moe_aux_weight=args.moe_aux_weight,
            learning_rate=args.lr, num_epochs=args.epochs,
            batch_size=args.batch_size, augment=not args.no_augment,
            num_classes=num_classes, dtype=args.dtype, seed=args.seed)
        trainer = {"tp": TPTrainer, "pp": PipelineTrainer,
                   "sp": SPTrainer, "moe": MoETrainer}[args.mode](
            dataset, mp_cfg)
        with _profiler_session(getattr(args, "profile_dir", None)):
            metrics = trainer.train(emit_metrics=args.emit_metrics,
                                    checkpoint_dir=args.checkpoint_dir,
                                    resume=args.resume)
        print(f"done: {metrics}", file=sys.stderr)
        return 0

    if args.mode == "sync" and (args.elastic or args.worker_timeout):
        print("note: --elastic/--worker-timeout apply to the store-based "
              "modes (async, serve/worker); SPMD sync has no membership — "
              "a mesh slot cannot die independently", file=sys.stderr)

    from .train.distributed import (AsyncTrainer, DistributedConfig,
                                    SyncTrainer)
    cfg = DistributedConfig(
        mode=args.mode, num_workers=args.workers, learning_rate=args.lr,
        num_epochs=args.epochs, batch_size=args.batch_size,
        sync_steps=args.sync_steps, k_step_mode=args.k_step_mode,
        staleness_bound=args.staleness_bound, compression=args.compression,
        strict_rounds=args.strict_rounds, elastic=args.elastic,
        worker_timeout=args.worker_timeout,
        overlap=args.overlap, delta_fetch=not args.no_delta_fetch,
        store_backend=args.store_backend, augment=not args.no_augment,
        dtype=args.dtype, model=args.model, num_classes=num_classes,
        seed=args.seed)
    trainer = (SyncTrainer if args.mode == "sync" else AsyncTrainer)(
        dataset, cfg)
    with _profiler_session(getattr(args, "profile_dir", None)):
        metrics = trainer.train(emit_metrics=args.emit_metrics,
                                checkpoint_dir=args.checkpoint_dir,
                                resume=args.resume)
    print(f"done: {metrics}", file=sys.stderr)
    return 0


def cmd_serve(args) -> int:
    with _telemetry_session(args, "server"):
        return _cmd_serve(args)


def _cmd_serve(args) -> int:
    import time

    import jax
    import numpy as np

    from .comms.service import ParameterService, serve
    from .models import get_model
    from .ps import make_store
    from .ps.store import StoreConfig
    from .utils.metrics import emit_metrics_json
    from .utils.pytree import flatten_params

    if args.push_codec in ("int4", "topk", "adaptive") \
            and args.store_backend != "python":
        raise SystemExit(
            f"--push-codec {args.push_codec} needs --store-backend python "
            f"(the {args.store_backend} backend speaks none|fp16|int8)")
    quorum_flags = (getattr(args, "sync_quorum", None) is not None
                    or getattr(args, "round_deadline", None) is not None)
    if quorum_flags and args.mode != "sync":
        raise SystemExit("--sync-quorum/--round-deadline apply to "
                         "--mode sync (async has no rounds)")
    if quorum_flags and args.store_backend == "native":
        raise SystemExit("--sync-quorum/--round-deadline need "
                         "--store-backend python|device (the C++ arena "
                         "runs its own round loop)")

    shard_index = int(getattr(args, "shard_index", 0))
    shard_count = int(getattr(args, "shard_count", 1))
    shard_peers = getattr(args, "shard_peers", None)
    sharding = None
    # A 1-shard server with --shard-peers is a degenerate-but-real
    # topology: no partitioning, but the shard map, replica membership,
    # and lag gauges go live (the read-replica tier without sharding).
    if shard_count > 1 or shard_peers:
        from .ps.sharding import ShardInfo, partition_keys
        if not 0 <= shard_index < shard_count:
            raise SystemExit(f"--shard-index {shard_index} out of range "
                             f"for --shard-count {shard_count}")
        primaries = [a for a in (shard_peers or "").split(",") if a]
        if len(primaries) != shard_count:
            raise SystemExit(f"--shard-peers must list exactly "
                             f"--shard-count={shard_count} addresses "
                             f"(got {len(primaries)})")
        sharding = ShardInfo(shard_index, shard_count, primaries)

    model = get_model(args.model, num_classes=args.num_classes,
                      image_size=args.image_size)
    size = args.image_size
    variables = model.init(jax.random.PRNGKey(args.seed),
                           np.zeros((1, size, size, 3), np.float32),
                           train=False)
    flat = flatten_params(variables["params"])
    if sharding is not None:
        # This primary holds ONLY its consistent-hash key range — workers
        # fan pushes/fetches out per shard and reassemble the full model
        # client-side (docs/SHARDING.md).
        total = len(flat)
        mine = set(partition_keys(flat, shard_count)[shard_index])
        flat = {k: v for k, v in flat.items() if k in mine}
        print(f"shard {shard_index}/{shard_count}: owning "
              f"{len(flat)}/{total} of the model's tensors",
              file=sys.stderr)
    store = make_store(
        args.store_backend, flat,
        StoreConfig(mode=args.mode, total_workers=args.workers,
                    learning_rate=args.lr,
                    staleness_bound=args.staleness_bound,
                    elastic=args.elastic,
                    worker_timeout=args.worker_timeout,
                    push_codec=(None if args.push_codec == "default"
                                else args.push_codec),
                    fetch_codec=args.fetch_codec,
                    compressed_domain=not getattr(
                        args, "no_compressed_domain", False),
                    sync_quorum=getattr(args, "sync_quorum", None),
                    round_deadline=getattr(args, "round_deadline", None),
                    shard_index=shard_index, shard_count=shard_count))
    jobs_mgr = None
    jobs_spec = getattr(args, "jobs", None)
    if jobs_spec:
        # Multi-job tenancy (docs/TENANCY.md): the primary store becomes
        # the implicit 'default' job; each declared job gets its own
        # store (namespace + aggregation config + membership) seeded
        # from the primary's current params.
        from .ps.tenancy import JobManager, parse_jobs_spec
        if sharding is not None:
            raise SystemExit("--jobs does not compose with --shard-count "
                             "yet (a job is a set of slots; run one "
                             "tenancy server per shard group)")
        if args.store_backend != "python":
            raise SystemExit("--jobs needs --store-backend python "
                             "(per-job stores)")
        try:
            jobs_mgr = JobManager(store, parse_jobs_spec(jobs_spec))
        except ValueError as e:
            raise SystemExit(f"--jobs: {e}") from e
        print(f"tenancy: jobs {', '.join(jobs_mgr.names())} "
              f"(weighted-fair QoS on)", file=sys.stderr, flush=True)
    monitor = None
    if not getattr(args, "no_health_monitor", False):
        # Cluster health monitor (docs/OBSERVABILITY.md): aggregates the
        # workers' piggybacked health reports with membership state, runs
        # the rule engine, serves GET /cluster, and flips /healthz to 503
        # while a critical alert is active. On by default — it is the
        # observe-only layer; --no-health-monitor opts out (and stops the
        # capability being advertised to workers at all).
        from .telemetry import (ClusterMonitor, HealthThresholds,
                                set_cluster_monitor)
        monitor = ClusterMonitor(
            store,
            HealthThresholds(
                dead_after_s=getattr(args, "dead_after", 30.0),
                straggler_lag_steps=getattr(args, "straggler_lag", 100)),
            interval=getattr(args, "health_interval", 5.0),
            emit_stream=bool(getattr(args, "telemetry", False)))
        set_cluster_monitor(monitor)
        monitor.start()
        if sharding is not None:
            # Shard identity + replica lag ride the same /cluster payload
            # cli status renders (docs/SHARDING.md, docs/OBSERVABILITY.md).
            monitor.sharding = sharding
        if jobs_mgr is not None:
            # Per-job membership/last_seen union + the "jobs" view block
            # + the worker-row job column (docs/TENANCY.md).
            monitor.jobs = jobs_mgr
        if not getattr(args, "no_slo", False):
            # Serve-tier SLOs (docs/OBSERVABILITY.md): multi-window
            # error-budget burn over the server-side RPC histograms,
            # evaluated on the monitor's tick -> slo_burn_fast/
            # slo_burn_slow alerts + the /cluster "slo" block.
            from .telemetry import SloEvaluator, default_objectives
            monitor.slo = SloEvaluator(
                default_objectives(
                    fetch_p99_ms=getattr(args, "slo_fetch_p99_ms", 100.0),
                    availability=getattr(args, "slo_availability", 0.99)),
                fast_window_s=getattr(args, "slo_fast_window", 60.0),
                slow_window_s=getattr(args, "slo_slow_window", 300.0),
                fast_burn_threshold=getattr(args, "slo_fast_burn", 14.4),
                slow_burn_threshold=getattr(args, "slo_slow_burn", 6.0))
            print(f"slo: evaluator on (fetch p99 "
                  f"{monitor.slo.objectives[0].threshold_s*1e3:.0f}ms, "
                  f"availability "
                  f"{monitor.slo.objectives[1].target:.3g})",
                  file=sys.stderr, flush=True)
    svc = ParameterService(store, faults=getattr(args, "faults", None),
                           monitor=monitor, sharding=sharding,
                           jobs=jobs_mgr)
    if getattr(args, "remediate", False) \
            or getattr(args, "remediate_dry_run", False):
        # Remediation policy engine (docs/ROBUSTNESS.md): turns the
        # monitor's alert edges into actions against the store (quorum
        # exclusion) and the service (quarantine, directives). Opt-in —
        # detection stays observe-only by default.
        if monitor is None:
            raise SystemExit("--remediate needs the health monitor "
                             "(drop --no-health-monitor)")
        from .telemetry import RemediationEngine, RemediationPolicy
        engine = RemediationEngine(
            store, service=svc,
            policy=RemediationPolicy(
                dry_run=bool(getattr(args, "remediate_dry_run", False)),
                cooldown_s=getattr(args, "remediation_cooldown", 30.0),
                quarantine_s=getattr(args, "quarantine_secs", 30.0)))
        monitor.remediation = engine
        monitor.add_listener(engine.handle_events)
        # The synchronous half of the quarantine action: a push whose own
        # health report flags non-finite values is refused before it can
        # poison the aggregate (the async quarantine would arrive one
        # apply too late). Dry-run rehearses without it.
        svc.reject_nonfinite = not engine.policy.dry_run
        print(f"remediation: engine on "
              f"(dry_run={engine.policy.dry_run})", file=sys.stderr,
              flush=True)
    if getattr(args, "faults", None):
        # The seeded fault plan is the postmortem's root-cause record:
        # journal it at arm time so `cli incident report` can open the
        # narrative with the fault that caused everything after it.
        from .telemetry import journal_event
        journal_event("fault", spec=args.faults, side="server")
    incidents_dir = getattr(args, "incidents_dir", None)
    if incidents_dir:
        # Incident capture (docs/OBSERVABILITY.md "Incident forensics"):
        # a critical alert edge freezes journal window + /cluster view +
        # flight-recorder tail into incidents/<id>/, deduped per rule.
        if monitor is None:
            raise SystemExit("--incidents-dir needs the health monitor "
                             "(drop --no-health-monitor)")
        from .telemetry import IncidentCapture, get_journal, get_recorder
        capture = IncidentCapture(
            incidents_dir, journal=get_journal(),
            # evaluate=False: the capture runs INSIDE monitor.evaluate()
            # (listener callback, _eval_lock held) — re-evaluating here
            # self-deadlocks and hangs every later /cluster request. The
            # cached state is the as-of-the-edge view anyway.
            views_fn=lambda: {
                "cluster": monitor.cluster_view(evaluate=False)},
            traces_fn=lambda trigger: [
                (f"flight-server-{os.getpid()}.json",
                 get_recorder().dump_payload("incident"))],
            window_s=getattr(args, "incident_window", 120.0),
            cooldown_s=getattr(args, "incident_cooldown", 120.0),
            role="server")
        monitor.add_listener(capture.on_alert_events)
        print(f"incidents: capture armed -> {incidents_dir}",
              file=sys.stderr, flush=True)
    if monitor is not None \
            and not getattr(args, "no_memory_telemetry", False):
        # Memory telemetry (docs/OBSERVABILITY.md "Goodput observatory"):
        # periodic host-RSS + device-HBM sampling on the monitor's tick,
        # a windowed leak-slope verdict in /cluster "memory", and the
        # memory_growth rule fed through the same alert pipeline.
        from .telemetry import MemoryMonitor
        monitor.memory = MemoryMonitor()
    if getattr(args, "profile_triggers", False):
        # Trigger-driven continuous profiling: slo_burn edges (listener)
        # and fleet goodput-fraction drops (fed each evaluation pass)
        # freeze a bounded jax.profiler window into the PROFILE ledger,
        # deduped per rule like incident capture.
        if monitor is None:
            raise SystemExit("--profile-triggers needs the health "
                             "monitor (drop --no-health-monitor)")
        from .telemetry import ProfileTrigger
        ptrig = ProfileTrigger(
            getattr(args, "profiles_dir", "profiles"),
            window_s=getattr(args, "profile_window", 1.5),
            cooldown_s=getattr(args, "profile_cooldown", 600.0),
            goodput_drop_threshold=getattr(args, "goodput_drop_threshold",
                                           0.5),
            role="server")
        monitor.add_listener(ptrig.on_alert_events)
        monitor.profile_trigger = ptrig
        print(f"profiles: trigger engine armed -> {ptrig.profiles_dir} "
              f"(window {ptrig.window_s:.1f}s, cooldown "
              f"{ptrig.cooldown_s:.0f}s)", file=sys.stderr, flush=True)
    if getattr(args, "autoscale", False) and monitor is None:
        raise SystemExit("--autoscale needs the health monitor "
                         "(drop --no-health-monitor)")
    ckpt_dir = getattr(args, "checkpoint_dir", None)
    ckpt = None
    restored = None
    if getattr(args, "restore", False):
        if not ckpt_dir:
            raise SystemExit("--restore needs --checkpoint-dir")
        from .checkpoint import load_store_record, restore_server_state
        try:
            # Adopt the snapshot's aggregation semantics: a restarted
            # server must resume the RUN it crashed out of, not silently
            # start a different one because a flag defaulted differently.
            # Loaded ONCE and passed through to the restore below, so the
            # adopted config and the restored params/journal come from
            # the same record even if a newer snapshot lands in between.
            record = load_store_record(ckpt_dir)
            _, meta = record
        except FileNotFoundError:
            # A restart policy passes --restore unconditionally; the very
            # first boot has nothing to restore and starts fresh.
            print(f"restore: no snapshot in {ckpt_dir}; starting fresh",
                  file=sys.stderr)
            meta = None
        if meta is not None:
            agg = meta.get("aggregation", {})
            for field in ("mode", "learning_rate", "staleness_bound"):
                if field in agg \
                        and getattr(store.config, field) != agg[field]:
                    print(f"restore: adopting snapshot {field}="
                          f"{agg[field]!r} (flags said "
                          f"{getattr(store.config, field)!r})",
                          file=sys.stderr)
                    setattr(store.config, field, agg[field])
            step, journal_n = restore_server_state(store, svc, ckpt_dir,
                                                   record=record)
            restored = step
            print(f"restored store at step {step} "
                  f"(+{journal_n} journaled push tokens) from {ckpt_dir}",
                  file=sys.stderr)
        if jobs_mgr is not None:
            # Per-job lineage (docs/TENANCY.md): each job restores from
            # its OWN subdirectory; check_job_identity refuses a
            # snapshot that belongs to another job.
            from .checkpoint import restore_server_state as _restore_job
            from .ps.tenancy import DEFAULT_JOB as _DJ
            for jname in jobs_mgr.names():
                if jname == _DJ:
                    continue
                jdir = os.path.join(ckpt_dir, f"job-{jname}")
                try:
                    jstep, jn = _restore_job(jobs_mgr.store_for(jname),
                                             svc, jdir)
                except FileNotFoundError:
                    continue
                print(f"restored job {jname!r} at step {jstep} "
                      f"(+{jn} journaled push tokens) from {jdir}",
                      file=sys.stderr)
    job_ckpts = []
    if ckpt_dir:
        import functools

        from .checkpoint import PeriodicStoreCheckpointer
        from .telemetry import add_shutdown_flush, install_shutdown_hooks
        from .ps.tenancy import DEFAULT_JOB as _DJ
        # With tenancy on, the primary's snapshot journals ONLY the
        # default job's tokens — each job's lineage carries its own
        # (byte-verifiable zero cross-job leakage, docs/TENANCY.md).
        primary_journal = (svc.journal_snapshot if jobs_mgr is None
                           else functools.partial(svc.journal_snapshot,
                                                  job=_DJ))
        ckpt = PeriodicStoreCheckpointer(
            store, ckpt_dir,
            interval=getattr(args, "checkpoint_interval", 30.0),
            journal_fn=primary_journal,
            migration_fn=svc.migration_snapshot)
        ckpt.start()
        # SIGTERM drains the store's end state through the same shutdown
        # path that dumps the flight recorder — a terminated server
        # resumes exactly where it was killed (docs/ROBUSTNESS.md).
        install_shutdown_hooks(role="server")
        add_shutdown_flush(ckpt.flush_now)
        if jobs_mgr is not None:
            for jname in jobs_mgr.names():
                if jname == _DJ:
                    continue
                jc = PeriodicStoreCheckpointer(
                    jobs_mgr.store_for(jname),
                    os.path.join(ckpt_dir, f"job-{jname}"),
                    interval=getattr(args, "checkpoint_interval", 30.0),
                    journal_fn=functools.partial(svc.journal_snapshot,
                                                 job=jname))
                jc.start()
                add_shutdown_flush(jc.flush_now)
                job_ckpts.append(jc)
    server, port = serve(store, port=args.port, service=svc)
    pool = None
    if getattr(args, "autoscale", False):
        # Replica autoscaler (docs/SHARDING.md "Serve tier"): the policy
        # head rides the monitor's background tick; the pool spawns
        # `cli replica` children against THIS primary's bound port.
        from .ps.supervisor import ReplicaPool, build_replica_argv
        from .telemetry import AutoscalePolicy, ReplicaAutoscaler
        primary_addr = f"localhost:{port}"
        replica_args = ["--shard-id", str(shard_index)]
        pool = ReplicaPool(
            lambda idx, parent=None: build_replica_argv(
                primary_addr, replica_args, idx, parent=parent))
        monitor.autoscaler = ReplicaAutoscaler(
            pool,
            AutoscalePolicy(
                qps_high=getattr(args, "autoscale_qps_high", 50.0),
                qps_low=getattr(args, "autoscale_qps_low", 5.0),
                min_replicas=getattr(args, "autoscale_min", 0),
                max_replicas=getattr(args, "autoscale_max", 4),
                cooldown_s=getattr(args, "autoscale_cooldown", 10.0),
                max_tier=getattr(args, "autoscale_max_tier", 1),
                fanout=getattr(args, "autoscale_fanout", 2),
                dry_run=bool(getattr(args, "autoscale_dry_run", False))),
            sharding=sharding)
        print(f"autoscale: on (replicas "
              f"{monitor.autoscaler.policy.min_replicas}.."
              f"{monitor.autoscaler.policy.max_replicas}, "
              f"max_tier={monitor.autoscaler.policy.max_tier}, "
              f"dry_run={monitor.autoscaler.policy.dry_run})",
              file=sys.stderr, flush=True)
    print(f"parameter server up on :{port} "
          f"(mode={store.config.mode}, workers={args.workers}, "
          f"backend={args.store_backend}"
          + (f", restored_step={restored}" if restored is not None else "")
          + (f", shard={shard_index}/{shard_count}"
             if sharding is not None else "")
          + (f", jobs={len(jobs_mgr.names())}"
             if jobs_mgr is not None else "")
          + (", faults=on" if svc.faults is not None else "")
          + ")", file=sys.stderr)
    try:
        # server.py:399-403 sleep-forever loop, but exiting cleanly once all
        # registered workers report JobFinished — and, with --worker-timeout,
        # expiring silent workers each tick (failure-detection reaper).
        # --profile-dir brackets the whole serving window so the XLA-level
        # timeline covers the apply/aggregation hot path (`cli perf
        # profile` parses the dump).
        with _profiler_session(getattr(args, "profile_dir", None)):
            while not store.wait_all_finished(timeout=1.0):
                expired = (store.expire_stale_workers()
                           if jobs_mgr is None
                           else jobs_mgr.expire_stale_workers())
                if expired:
                    print(f"expired silent workers: {expired}",
                          file=sys.stderr)
                    if monitor is not None:
                        # Dead-worker alerts fire on the very next
                        # evaluation instead of waiting out the
                        # report-age threshold.
                        monitor.note_expired(expired)
        time.sleep(0.5)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop(grace=2.0)
        if pool is not None:
            pool.stop()
        if monitor is not None:
            from .telemetry import set_cluster_monitor
            monitor.stop(final=True)
            set_cluster_monitor(None)
        if ckpt is not None:
            from .telemetry import remove_shutdown_flush
            remove_shutdown_flush(ckpt.flush_now)
            err = ckpt.stop(final_snapshot=True)
            if err is not None:
                print(f"warning: last periodic snapshot failed: {err!r}",
                      file=sys.stderr)
            for jc in job_ckpts:
                remove_shutdown_flush(jc.flush_now)
                jerr = jc.stop(final_snapshot=True)
                if jerr is not None:
                    print(f"warning: job snapshot failed: {jerr!r}",
                          file=sys.stderr)
    if args.emit_metrics:
        emit_metrics_json(store.metrics())
    return 0


def cmd_worker(args) -> int:
    with _telemetry_session(args, "worker"):
        return _cmd_worker(args)


def _cmd_worker(args) -> int:
    from .comms.client import RemoteStore
    from .models import get_model
    from .ps.worker import PSWorker, WorkerConfig
    from .utils.metrics import emit_metrics_json

    dataset = _load_dataset(args)
    shards = getattr(args, "shards", None)
    job = getattr(args, "job", None)
    if shards:
        if job:
            raise SystemExit("--job does not compose with --shards "
                             "(tenancy and sharding run on separate "
                             "servers, docs/TENANCY.md)")
        from .comms.sharded import ShardedRemoteStore
        store = ShardedRemoteStore(shards,
                                   faults=getattr(args, "faults", None))
    else:
        store = RemoteStore(args.server,
                            faults=getattr(args, "faults", None),
                            job=job or None)
    import jax.numpy as jnp
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    # Honor --model/--dataset like cmd_train does — a mismatched architecture
    # would push parameter names the server's store doesn't know.
    model = get_model(args.model, num_classes=dataset.num_classes,
                      dtype=dtype,
                      image_size=dataset.x_train.shape[1])
    cfg = WorkerConfig(batch_size=args.batch_size, num_epochs=args.epochs,
                       sync_steps=args.sync_steps,
                       k_step_mode=args.k_step_mode,
                       augment=not args.no_augment, seed=args.seed,
                       heartbeat_interval=args.heartbeat,
                       overlap=args.overlap,
                       delta_fetch=not args.no_delta_fetch,
                       reconnect_timeout=args.reconnect_timeout,
                       error_feedback=not args.no_error_feedback,
                       topk_frac=args.topk_frac)
    worker = PSWorker(store, model, dataset, cfg,
                      worker_name=args.worker_name)
    with _profiler_session(getattr(args, "profile_dir", None)):
        worker.start()
        worker.join()
    if worker.result.error is not None:
        raise worker.result.error
    if args.emit_metrics:
        emit_metrics_json(worker.result.metrics(
            total_workers=0, learning_rate=args.lr, config=cfg))
    store.close()
    return 0


def cmd_supervise(args) -> int:
    with _telemetry_session(args, "supervisor"):
        return _cmd_supervise(args)


def _parse_slot_map(pairs: list[str], what: str) -> dict:
    out: dict = {}
    for raw in pairs:
        slot, sep, rest = raw.partition(":")
        if not sep or not slot.isdigit():
            raise SystemExit(f"bad {what} {raw!r} (want SLOT:{what})")
        out[int(slot)] = rest
    return out


def _cmd_supervise(args) -> int:
    from .ps.supervisor import (SupervisorConfig, WorkerSupervisor,
                                build_worker_argv, install_signal_stop)

    worker_args = list(args.worker_args)
    if worker_args and worker_args[0] == "--":
        worker_args = worker_args[1:]
    if not worker_args:
        raise SystemExit("supervise: pass the child worker args after "
                         "`--` (at least --server HOST:PORT)")
    slot_faults = _parse_slot_map(args.slot_faults, "SPEC")
    slot_env = {}
    for slot, kv in _parse_slot_map(args.slot_env, "KEY=VALUE").items():
        key, sep, val = kv.partition("=")
        if not sep:
            raise SystemExit(f"bad --slot-env value {kv!r}")
        slot_env.setdefault(slot, {})[key] = val
    # Children inherit the CPU pin when the supervisor got one — a
    # respawned worker must not fight the serve process for the TPU.
    if getattr(args, "platform", "default") == "cpu" \
            and "--platform" not in worker_args:
        worker_args += ["--platform", "cpu"]

    def argv_for(slot: int, attempt: int):
        return build_worker_argv(worker_args, slot,
                                 first_spawn_faults=slot_faults,
                                 first_spawn_env=slot_env,
                                 attempt=attempt)

    sup = WorkerSupervisor(argv_for, args.workers, SupervisorConfig(
        respawn=not args.no_respawn,
        backoff_initial=args.respawn_backoff,
        backoff_max=args.respawn_backoff_max,
        healthy_after=args.healthy_after,
        crash_loop_after=args.crash_loop_after))
    install_signal_stop(sup)
    print(f"supervisor: {args.workers} worker slot(s), "
          f"respawn={'on' if not args.no_respawn else 'off'}",
          file=sys.stderr, flush=True)
    scaler_thread = None
    scaler_stop = None
    if getattr(args, "autoscale_job", None):
        # Worker autoscaling (docs/TENANCY.md): the policy head polls
        # the serve process's per-job /cluster view for admission-queue
        # and straggler pressure; this supervisor's slot count is the
        # actuator (worker_grow/worker_shrink).
        if not getattr(args, "autoscale_url", None):
            raise SystemExit("--autoscale-job needs --autoscale-url "
                             "(the serve process's metrics endpoint)")
        import json as _json
        import threading as _threading
        from urllib.request import urlopen

        from .telemetry.remediation import (WorkerAutoscalePolicy,
                                            WorkerAutoscaler)
        cluster_url = args.autoscale_url.rstrip("/") + "/cluster"
        scale_job = args.autoscale_job

        def pressure() -> dict:
            view = _json.loads(urlopen(cluster_url, timeout=5).read())
            row = (view.get("jobs") or {}).get(scale_job) or {}
            members = set(row.get("workers") or [])
            stragglers = sum(
                1 for a in view.get("alerts") or []
                if a.get("rule") == "straggler_lag"
                and a.get("worker") in members)
            return {"queue_depth": row.get("waiting") or 0,
                    "stragglers": stragglers,
                    "workers": len(members)}

        scaler = WorkerAutoscaler(
            scale_job, pressure, supervisor=sup,
            policy=WorkerAutoscalePolicy(
                depth_high=args.autoscale_depth_high,
                depth_low=args.autoscale_depth_low,
                sustain_ticks=args.autoscale_sustain,
                min_workers=args.autoscale_min,
                max_workers=args.autoscale_max,
                cooldown_s=args.autoscale_cooldown))
        scaler_stop = _threading.Event()

        def _scale_loop() -> None:
            while not scaler_stop.wait(args.autoscale_poll):
                scaler.tick()  # never raises

        scaler_thread = _threading.Thread(target=_scale_loop,
                                          daemon=True,
                                          name="worker-autoscaler")
        print(f"worker-autoscale: job={scale_job} slots "
              f"{args.autoscale_min}..{args.autoscale_max} "
              f"depth {args.autoscale_depth_low:g}/"
              f"{args.autoscale_depth_high:g} "
              f"sustain={args.autoscale_sustain}",
              file=sys.stderr, flush=True)
    sup.start()
    if scaler_thread is not None:
        scaler_thread.start()
    try:
        return sup.run()
    finally:
        if scaler_stop is not None:
            scaler_stop.set()
            scaler_thread.join(timeout=5.0)


def _replica_tree_lines(sh: dict, indent: str = "  ") -> list[str]:
    """Render a sharding block's replica rows as the fan-out tree
    (docs/SHARDING.md "Fan-out trees"): children indent under their
    parent with tier + lag, depth-first in address order. Rows whose
    parent is neither a live replica nor a primary render under an
    explicit ``orphaned`` header naming the gone parent — a killed or
    stale interior node shows its stranded children instead of
    flattening them away. Pre-tree rows (no ``parent``/``tier``) all
    root at the primary, reproducing the old flat listing."""
    rows = sh.get("replicas", []) or []
    primaries = set(sh.get("primaries", []) or [])
    by_addr = {r.get("address"): r for r in rows if r.get("address")}
    children: dict[str, list] = {}
    roots, orphans = [], {}
    for r in rows:
        parent = r.get("parent")
        if parent is None or parent in primaries:
            roots.append(r)
        elif parent in by_addr:
            children.setdefault(parent, []).append(r)
        else:
            orphans.setdefault(parent, []).append(r)

    def row_line(r: dict, depth: int) -> str:
        qps = r.get("fetch_qps")
        return (f"{indent}{'  ' * depth}replica {r.get('address')}"
                + (f" [tier {r['tier']}]" if "tier" in r else "")
                + f": step={r.get('step')} "
                f"lag={r.get('lag_steps')} step(s), "
                f"announced {r.get('announce_age_s', 0):.1f}s ago"
                + (f", {qps:g} fetch/s" if qps else "")
                + (f" (via {r['via']})" if "via" in r else ""))

    lines: list[str] = []

    def walk(r: dict, depth: int, seen: set) -> None:
        addr = r.get("address")
        if addr in seen:  # defensive: a cyclic view must not hang
            return
        seen.add(addr)
        lines.append(row_line(r, depth))
        for c in sorted(children.get(addr, []),
                        key=lambda x: str(x.get("address"))):
            walk(c, depth + 1, seen)

    seen: set = set()
    for r in sorted(roots, key=lambda x: str(x.get("address"))):
        walk(r, 0, seen)
    # Subtrees hanging off a live interior node already walked above;
    # whatever never got visited hangs off a DEAD parent — show it.
    for parent in sorted(orphans):
        stranded = [r for r in orphans[parent]
                    if r.get("address") not in seen]
        if not stranded:
            continue
        lines.append(f"{indent}orphaned (parent {parent} gone):")
        for r in sorted(stranded, key=lambda x: str(x.get("address"))):
            walk(r, 1, seen)
    tiers = sh.get("tiers") or {}
    if any("tier" in r for r in rows) and tiers:
        roll = "; ".join(
            f"tier {t}: {v.get('replicas', 0)} replica(s), "
            f"max_lag={v.get('max_lag_steps', 0)}, "
            f"{v.get('fetch_qps', 0):g} fetch/s"
            for t, v in sorted(tiers.items(), key=lambda kv: kv[0]))
        lines.append(f"{indent}tiers: {roll}")
    return lines


def _render_status(view: dict) -> str:
    """The ``cli status`` terminal dashboard: cluster header, per-worker
    table, active alerts. Pure text in, text out (tested directly)."""
    sev_mark = {"critical": "CRIT", "warning": "WARN", "info": "INFO"}
    totals = view.get("alerts_total", {})
    gpf = view.get("goodput_fraction")
    header = (f"cluster: mode={view.get('mode', '?')} "
              f"global_step={view.get('global_step', 0)} "
              f"workers={len(view.get('workers', []))} "
              + (f"goodput={gpf * 100:.1f}% "
                 if isinstance(gpf, (int, float))
                 and not isinstance(gpf, bool) else "")
              + f"alerts: critical={totals.get('critical', 0)} "
              f"warning={totals.get('warning', 0)} "
              f"info={totals.get('info', 0)}")
    # The job column renders only when the server is tenancy-enabled
    # (worker rows carry "job") — a pre-tenancy /cluster payload draws
    # the exact pre-tenancy table. The goodput column follows the same
    # degradation discipline: absent from pre-goodput workers' reports,
    # absent from the table.
    has_jobs = any("job" in r for r in view.get("workers", []))
    has_goodput = any("goodput_fraction" in r
                      for r in view.get("workers", []))
    cols = [("worker", 7)] \
        + ([("job", 10)] if has_jobs else []) \
        + [("alive", 6), ("step", 8), ("epoch", 6),
           ("loss", 10), ("grad_norm", 11), ("ex/s", 9)] \
        + ([("goodput", 8)] if has_goodput else []) \
        + [("pipe", 5),
           ("codec", 19), ("reconn", 7), ("hb_err", 7), ("age_s", 7)]
    lines = [header, "-" * len(header)]
    rnd = view.get("round")
    if rnd:
        # Quorum-round state (docs/ROBUSTNESS.md): target vs received,
        # who is excluded, what closed the last round.
        extras = []
        if rnd.get("excluded"):
            extras.append(f"excluded={rnd['excluded']}")
        if rnd.get("deadline_s"):
            extras.append(f"deadline={rnd['deadline_s']:g}s"
                          + ("*" if rnd.get("deadline_armed") else ""))
        if rnd.get("last_trigger"):
            extras.append(f"last={rnd['last_trigger']}")
        lines.append(f"round: received {rnd.get('received', 0)}"
                     f"/{rnd.get('quorum', '?')} "
                     f"(target {rnd.get('target', '?')}"
                     + (", " + ", ".join(extras) if extras else "") + ")")
    lines.append("".join(f"{name:>{w}}" for name, w in cols))

    def cell(v, width, fmt=None):
        if v is None:
            return f"{'-':>{width}}"
        try:
            return f"{(fmt(v) if fmt else v)!s:>{width}}"
        except (TypeError, ValueError):
            return f"{'-':>{width}}"

    for row in view.get("workers", []):
        age = row.get("report_age_s", row.get("last_seen_age_s"))
        loss = row.get("loss")
        if loss is None and not row.get("loss_finite", True):
            loss = "NaN"
        gn = row.get("grad_norm")
        if gn is None and not row.get("grad_finite", True):
            gn = "NaN"
        lines.append("".join([
            cell(row.get("worker"), 7),
            *([cell(row.get("job"), 10)] if has_jobs else []),
            cell("yes" if row.get("alive") else "NO", 6),
            cell(row.get("step"), 8),
            cell(row.get("epoch"), 6),
            cell(loss, 10, lambda v: v if isinstance(v, str)
                 else f"{v:.4f}"),
            cell(gn, 11, lambda v: v if isinstance(v, str)
                 else f"{v:.4g}"),
            cell(row.get("examples_per_s"), 9,
                 lambda v: f"{v:.1f}"),
            *([cell(row.get("goodput_fraction"), 8,
                    lambda v: f"{v * 100:.1f}%")] if has_goodput else []),
            cell(row.get("pipeline_depth"), 5),
            cell(row.get("push_codec"), 19),
            cell(row.get("reconnects"), 7),
            cell(row.get("heartbeat_errors"), 7),
            cell(age, 7, lambda v: f"{v:.1f}"),
        ]))
    alerts = view.get("alerts", [])
    if alerts:
        lines.append("")
        lines.append("active alerts:")
        for a in alerts:
            who = "cluster" if a.get("worker") is None \
                else f"worker {a['worker']}"
            lines.append(f"  [{sev_mark.get(a.get('severity'), '????')}] "
                         f"{a.get('rule')} ({who}): {a.get('message')}")
    else:
        lines.append("")
        lines.append("no active alerts")
    rem = view.get("remediation")
    if rem:
        active = rem.get("active", [])
        tag = " (dry-run)" if rem.get("dry_run") else ""
        lines.append("")
        if active:
            lines.append(f"active remediations{tag}:")
            for r in active:
                who = "cluster" if r.get("worker") is None \
                    else f"worker {r['worker']}"
                lines.append(f"  [{r.get('outcome', '?').upper()}] "
                             f"{r.get('action')} ({who}) <- "
                             f"{r.get('rule')}")
        else:
            lines.append(f"remediation engine on{tag}: no active actions")
        q = rem.get("quarantined")
        if q:
            lines.append("  quarantined pushes: " + ", ".join(
                f"worker {w} ({s:.0f}s left)" for w, s in q.items()))
    sh = view.get("sharding")
    if sh:
        # Shard identity + replica lag (docs/SHARDING.md): which slot of
        # the partition this server is, and how far each announced read
        # replica trails it.
        lines.append("")
        lines.append(f"shard: {sh.get('shard_id', '?')}"
                     f"/{sh.get('shard_count', '?')} "
                     f"map_version={sh.get('map_version', '?')} "
                     f"replicas={len(sh.get('replicas', []))}")
        lines.extend(_replica_tree_lines(sh))
        mig = sh.get("migration")
        if mig:
            # In-flight migration ledger (docs/ROBUSTNESS.md "Migration
            # failure matrix"). Absent block (idle, or a server predating
            # the ledger) renders nothing — degradation-pinned like the
            # slo block.
            lease = mig.get("lease_remaining_s")
            lease_s = "" if lease is None else f" lease={lease:g}s"
            lines.append(
                f"  migration {mig.get('id')}: {mig.get('role')} "
                f"phase={mig.get('phase')} "
                f"slots=[{mig.get('slot_lo')},{mig.get('slot_hi')}) "
                f"frozen={mig.get('frozen_slots', 0)}{lease_s}")
    slo = view.get("slo")
    if slo:
        # Serve-tier SLOs (docs/OBSERVABILITY.md): per-objective
        # quantiles + window burn rates. Absent block (older server, or
        # --no-slo) renders nothing — forward/backward compatible by
        # construction, pinned by the degradation test.
        lines.append("")
        lines.append("slo objectives:")
        for obj in slo.get("objectives", []):
            wins = obj.get("windows", {})
            burns = []
            for rule in sorted(wins):
                w = wins[rule]
                mark = " BREACH" if w.get("breaching") else ""
                burns.append(f"{w.get('window_s', 0):g}s burn "
                             f"{w.get('burn', 0):g}x{mark}")
            thr = (f" p99<={obj['threshold_ms']:g}ms"
                   if obj.get("threshold_ms") is not None else "")
            p99 = obj.get("p99_ms")
            p99_s = "-" if p99 is None else f"{p99:g}ms"
            lines.append(f"  {obj.get('name')}: "
                         f"target={obj.get('target')}{thr} "
                         f"p99={p99_s} n={obj.get('total', 0)} "
                         f"({'; '.join(burns) if burns else 'no windows'})")
        breaches = slo.get("breaches", [])
        if breaches:
            for b in breaches:
                lines.append(
                    f"  [{sev_mark.get(b.get('severity'), '????')}] "
                    f"{b.get('rule')}: {b.get('objective')} burning "
                    f"{b.get('burn')}x budget over "
                    f"{b.get('window_s', 0):g}s "
                    f"({b.get('bad')}/{b.get('total')} bad)")
    jb = view.get("jobs")
    if jb:
        # Tenancy view (docs/TENANCY.md): one line per job — aggregation
        # config, live workers, and the weighted-fair QoS counters when
        # the admission scheduler is on. Absent block (pre-tenancy
        # server) renders nothing.
        lines.append("")
        lines.append("jobs:")
        for name in sorted(jb, key=lambda n: jb[n].get("index", 0)):
            row = jb[name]
            qos = ""
            if "inflight" in row:
                qos = (f" inflight={row.get('inflight')} "
                       f"waiting={row.get('waiting')} "
                       f"fair_share={row.get('fair_share')}")
            spec = ""
            if "weight" in row:
                spec = (f" weight={row.get('weight'):g} "
                        f"max_inflight={row.get('max_inflight')}")
            lines.append(
                f"  {name}: mode={row.get('mode')} "
                f"step={row.get('global_step')} "
                f"workers={len(row.get('workers') or [])} "
                f"slots={len(row.get('slots') or [])}{spec}{qos}")
    wa = view.get("worker_autoscale")
    if wa:
        acts = wa.get("actions") or {}
        lines.append("")
        lines.append(
            f"worker autoscale: job={wa.get('job')} "
            f"bounds {wa.get('min')}..{wa.get('max')} "
            f"depth {wa.get('depth_low'):g}/{wa.get('depth_high'):g} "
            f"grew={acts.get('worker_grow', 0)} "
            f"shrank={acts.get('worker_shrink', 0)}")
    return "\n".join(lines)


def _cluster_view_from_fleet(fleet: dict) -> dict:
    """Synthesize a ``/cluster``-shaped view from a ``/fleet`` snapshot
    so ``cli status --via-fleet`` renders the EXISTING dashboard from
    merged fleet data: worker rows and jobs come from the inventory
    tiers, the alert feed is the fleet-wide one (each alert tagged with
    its source target), the slo block is the fleet-scope evaluation
    over MERGED series, and mode/global_step come from the first
    primary. Blocks the fleet view lacks (round, sharding, remediation)
    are simply absent — ``_render_status`` degrades over them exactly
    as it does for an older server, which is the pinned behavior."""
    tiers = fleet.get("tiers") or {}
    primaries = tiers.get("primaries") or []
    first = primaries[0] if primaries else {}
    alerts = fleet.get("alerts") or []
    totals = {"critical": 0, "warning": 0, "info": 0}
    for a in alerts:
        sev = a.get("severity")
        if sev in totals:
            totals[sev] += 1
    view = {
        "ts": fleet.get("ts"),
        "role": "fleet",
        "mode": first.get("mode"),
        "global_step": first.get("global_step"),
        "workers": tiers.get("workers") or [],
        "alerts": alerts,
        "alerts_total": totals,
    }
    if fleet.get("slo"):
        view["slo"] = fleet["slo"]
    if tiers.get("jobs"):
        view["jobs"] = tiers["jobs"]
    return view


def cmd_status(args) -> int:
    """One-shot (or ``--watch``) render of a serve process's ``/cluster``
    view. Exit codes: 0 healthy, 2 when a CRITICAL alert is active (so a
    cron/script can gate on it), 3 when critical alerts are active BUT
    the remediation engine holds active actions against them — degraded
    but healing (docs/ROBUSTNESS.md): a restart policy should hold off
    and let the self-healing run —, 1 when the endpoint is unreachable or
    has no monitor. SLO breaches ride the same semantics: slo_burn_fast
    is a critical alert (exit 2/3), slo_burn_slow a warning (exit 0) —
    paging on fast burn only is the multi-window point. A server without
    an "slo" block (older build, --no-slo) renders everything else
    unchanged. ``--via-fleet URL`` renders the same dashboard from a
    fleet collector's merged ``/fleet`` snapshot instead — same exit
    codes, evaluated over the whole fleet."""
    import json as _json
    import time as _time
    from urllib.error import HTTPError, URLError
    from urllib.request import urlopen

    via_fleet = getattr(args, "via_fleet", None)
    if via_fleet:
        base = via_fleet
        if not base.startswith(("http://", "https://")):
            base = "http://" + base
        url = base.rstrip("/") + "/fleet"
    else:
        base = args.url
        if not base:
            if args.metrics_port is None:
                print("status: need --url or --metrics-port",
                      file=sys.stderr)
                return 1
            base = f"http://{args.host}:{args.metrics_port}"
        url = base.rstrip("/") + "/cluster"

    def poll() -> tuple[int, dict | None]:
        try:
            raw = _json.loads(urlopen(url, timeout=5).read())
        except HTTPError as e:
            print(f"status: {url} -> HTTP {e.code} "
                  f"({e.read().decode(errors='replace')[:200]})",
                  file=sys.stderr)
            return 1, None
        except (URLError, OSError, ValueError) as e:
            print(f"status: cannot reach {url}: {e}", file=sys.stderr)
            return 1, None
        view = _cluster_view_from_fleet(raw) if via_fleet else raw
        if args.json:
            print(_json.dumps(raw, indent=2))
        else:
            print(_render_status(view))
        critical = view.get("alerts_total", {}).get("critical", 0)
        if via_fleet and not critical:
            # On a primary, slo_burn_fast raises a critical alert via
            # the monitor, so alerts_total already covers it; fleet-
            # scope breaches live only in the slo block.
            critical = any(b.get("severity") == "critical"
                           for b in (raw.get("slo") or {})
                           .get("breaches", []))
        if not critical:
            return 0, view
        # Degraded-but-healing: critical alerts with a live remediation
        # working on them exit 3, not 2 — distinguishable for restart
        # policies that should let the self-healing run its course. A
        # dry-run engine records decisions but executes NOTHING, so it
        # must not claim healing (a policy holding off would wait
        # forever).
        if via_fleet:
            healing = bool(raw.get("remediation_active"))
        else:
            rem = view.get("remediation", {})
            healing = bool(rem.get("active")) and not rem.get("dry_run")
        return (3 if healing else 2), view

    if args.watch <= 0:
        rc, _ = poll()
        return rc
    rc = 0
    try:
        while True:
            print("\x1b[2J\x1b[H", end="")  # clear screen, home cursor
            rc, _ = poll()
            print(f"\n(watching {url} every {args.watch:g}s — Ctrl-C to "
                  f"stop)")
            _time.sleep(args.watch)
    except KeyboardInterrupt:
        pass
    return rc


def cmd_observe(args) -> int:
    """The fleet observatory collector process (standalone: off every
    serve hot path, survives primary restarts). Scrapes, rolls up, and
    serves ``GET /fleet`` until interrupted."""
    import threading as _threading

    from .telemetry.fleet import FleetCollector, start_fleet_server
    from .telemetry.registry import MetricsRegistry
    from .telemetry.slo import default_objectives

    targets = [t.strip() for t in args.targets.split(",") if t.strip()]
    if not targets:
        print("observe: --targets needs at least one endpoint",
              file=sys.stderr)
        return 1
    registry = MetricsRegistry()
    journal = None
    if getattr(args, "journal_dir", None):
        # Durable fleet journal (ISSUE 18): one fleet_tick record per
        # scrape (the merged view minus history rings) + slo_burn
        # edges — the `cli top --replay` / `cli query` source.
        from .telemetry.journal import JournalWriter
        journal = JournalWriter(args.journal_dir, role="observer",
                                registry=registry)
    incidents = None
    if getattr(args, "incidents_dir", None):
        from .telemetry.incidents import IncidentCapture
        incidents = IncidentCapture(
            args.incidents_dir, journal=journal,
            window_s=getattr(args, "incident_window", 120.0),
            cooldown_s=getattr(args, "incident_cooldown", 120.0),
            role="observer", registry=registry)
    collector = FleetCollector(
        targets, interval_s=args.interval, timeout_s=args.timeout,
        ring_depth=args.ring_depth,
        registry=registry,
        objectives=default_objectives(
            fetch_p99_ms=args.slo_fetch_p99_ms,
            availability=args.slo_availability),
        fast_window_s=args.slo_fast_window,
        slow_window_s=args.slo_slow_window,
        journal=journal, incidents=incidents)
    if incidents is not None:
        # Bundle context comes from the collector itself: the merged
        # /fleet view, and flight-recorder dumps pulled over HTTP from
        # the (still-reachable) implicated targets.
        incidents.views_fn = lambda: {"fleet": collector.view()}
        incidents.traces_fn = \
            lambda trigger: _fleet_trace_dumps(collector)
        print(f"observe: incident capture armed -> {args.incidents_dir}",
              file=sys.stderr, flush=True)
    server, port = start_fleet_server(collector, port=args.port)
    print(f"observe up on :{port} ({len(targets)} seed target(s), "
          f"interval={args.interval:g}s, timeout={args.timeout:g}s)",
          file=sys.stderr, flush=True)
    stop = _threading.Event()
    try:
        collector.run_forever(stop)
    except KeyboardInterrupt:
        pass
    finally:
        stop.set()
        server.shutdown()
        if journal is not None:
            journal.seal()
    return 0


def _fleet_trace_dumps(collector, limit: int = 4) -> list:
    """Best-effort ``/debug/trace`` pulls from the fleet's reachable
    targets for an incident bundle's ``traces/`` directory."""
    import json as _json
    import urllib.request as _request
    out = []
    try:
        view = collector.view()
    except Exception:  # noqa: BLE001 — capture context is best-effort
        return out
    for row in view.get("targets", []):
        if len(out) >= limit:
            break
        base = row.get("target")
        if not base or not row.get("ok"):
            continue
        try:
            with _request.urlopen(base + "/debug/trace",
                                  timeout=collector.timeout_s) as r:
                payload = _json.loads(r.read().decode())
        except Exception:  # noqa: BLE001 — dead target = no dump
            continue
        name = base.split("//", 1)[-1].replace(":", "-").replace("/", "_")
        out.append((f"trace-{name}.json", payload))
    return out


_SPARK_CHARS = "▁▂▃▄▅▆▇█"


def _sparkline(values, width: int = 40) -> str:
    """Ring history -> a fixed-width unicode sparkline (None samples —
    e.g. p99 before any fetch — are skipped)."""
    vals = [float(v) for v in values if v is not None][-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _SPARK_CHARS[0] * len(vals)
    return "".join(_SPARK_CHARS[min(7, int((v - lo) / span * 8))]
                   for v in vals)


def _top_exit_code(view: dict) -> int:
    """``cli status``-consistent: 0 healthy, 2 critical (a critical
    alert anywhere in the fleet, or a fleet-scope fast-burn breach),
    3 critical-but-healing (some primary's remediation engine is live
    and not dry-run)."""
    critical = any(a.get("severity") == "critical"
                   for a in view.get("alerts", []))
    critical = critical or any(
        b.get("severity") == "critical"
        for b in (view.get("slo") or {}).get("breaches", []))
    if not critical:
        return 0
    return 3 if view.get("remediation_active") else 2


def _render_top(view: dict) -> str:
    """The ``cli top`` fleet dashboard: header + sparklines + per-tier
    rows + fleet SLO burn + alert feed. Pure text in, text out (tested
    directly, like ``_render_status``)."""
    sev_mark = {"critical": "CRIT", "warning": "WARN", "info": "INFO"}
    targets = view.get("targets", [])
    n_ok = sum(1 for t in targets if t.get("ok"))
    scrape = view.get("scrape", {})
    hist = view.get("history", {})
    p99s = [v for v in hist.get("p99_ms", []) if v is not None]
    p99 = p99s[-1] if p99s else None
    header = (f"fleet: targets {n_ok}/{len(targets)} up "
              f"qps={view.get('fleet_qps', 0):g} "
              f"p99={'-' if p99 is None else f'{p99:g}ms'} "
              f"series={view.get('series_count', 0)} "
              f"tick#{view.get('ticks', 0)} "
              f"(scrape {scrape.get('last_ms', 0):g}ms)")
    lines = [header, "-" * len(header)]
    for name, label in (("fleet_qps", "qps"), ("p99_ms", "p99ms"),
                        ("scrape_ms", "scrape")):
        ring = hist.get(name, [])
        cur = [v for v in ring if v is not None]
        lines.append(f"  {label:>7} {_sparkline(ring):<40} "
                     f"{cur[-1] if cur else '-'}")
    prim = (view.get("tiers") or {}).get("primaries") or []
    if prim:
        lines.append("")
        lines.append("primaries:")
        for row in prim:
            shard = ("" if row.get("shard_id") is None
                     else f" shard={row['shard_id']}"
                          f" map_v{row.get('map_version', '?')}")
            lines.append(
                f"  {row.get('target')}: "
                f"{'up' if row.get('ok') else 'STALE'} "
                f"mode={row.get('mode')} step={row.get('global_step')}"
                f"{shard} alerts={row.get('alerts', 0)}")
    tier_view = view.get("tiers") or {}
    reps = tier_view.get("replicas") or []
    if reps:
        lines.append("")
        lines.append("replicas:")
        # Reuse the fan-out-tree renderer on the fleet rows: primaries
        # here must be gRPC addresses (the rows' ``parent`` namespace),
        # not the scrape targets the fleet polls.
        lines.extend(_replica_tree_lines({
            "replicas": reps,
            "primaries": tier_view.get("primary_addresses") or [],
            "tiers": tier_view.get("replica_tiers") or {},
        }))
    workers = (view.get("tiers") or {}).get("workers") or []
    if workers:
        lines.append("")
        lines.append(f"workers ({len(workers)}):")
        for w in workers:
            job = f" job={w['job']}" if w.get("job") else ""
            rep = w.get("report") or {}
            step = rep.get("step", w.get("step"))
            # Goodput column (degradation-pinned: absent from a
            # pre-goodput worker's report, absent from the row).
            gpf = rep.get("goodput_fraction", w.get("goodput_fraction"))
            gp = (f" goodput={gpf * 100:.1f}%"
                  if isinstance(gpf, (int, float))
                  and not isinstance(gpf, bool) else "")
            lines.append(
                f"  worker {w.get('worker')}: "
                f"{'alive' if w.get('alive') else 'DOWN'}"
                f"{job} step={step}{gp} (via {w.get('via')})")
    jobs = (view.get("tiers") or {}).get("jobs") or {}
    if jobs:
        lines.append("")
        lines.append("jobs:")
        for name in sorted(jobs):
            row = jobs[name]
            lines.append(
                f"  {name}: mode={row.get('mode')} "
                f"step={row.get('global_step')} "
                f"workers={len(row.get('workers') or [])} "
                f"(via {row.get('via')})")
    stale = [t for t in targets if not t.get("ok")]
    if stale:
        lines.append("")
        lines.append("stale targets:")
        for t in stale:
            lines.append(f"  {t.get('target')}: "
                         f"{t.get('consecutive_failures')} consecutive "
                         f"failure(s) — {t.get('last_error')}")
    slo = view.get("slo") or {}
    if slo.get("objectives"):
        lines.append("")
        lines.append("fleet slo (merged series):")
        for obj in slo["objectives"]:
            wins = obj.get("windows", {})
            burns = []
            for rule in sorted(wins):
                w = wins[rule]
                mark = " BREACH" if w.get("breaching") else ""
                burns.append(f"{w.get('window_s', 0):g}s burn "
                             f"{w.get('burn', 0):g}x{mark}")
            p99o = obj.get("p99_ms")
            lines.append(
                f"  {obj.get('name')}: target={obj.get('target')} "
                f"p99={'-' if p99o is None else f'{p99o:g}ms'} "
                f"n={obj.get('total', 0)} "
                f"({'; '.join(burns) if burns else 'no windows'})")
    alerts = view.get("alerts", [])
    if alerts:
        lines.append("")
        lines.append("active alerts:")
        for a in alerts:
            who = "cluster" if a.get("worker") is None \
                else f"worker {a['worker']}"
            lines.append(
                f"  [{sev_mark.get(a.get('severity'), '????')}] "
                f"{a.get('rule')} ({who} @ {a.get('target')}): "
                f"{a.get('message')}")
    else:
        lines.append("")
        lines.append("no active alerts")
    return "\n".join(lines)


def _merge_top_history(local: dict | None, view: dict,
                       last_ticks: int | None,
                       depth: int = 600) -> dict:
    """Client half of the ``?since=<tick>`` protocol (ISSUE 18): merge
    one ``/fleet`` payload into the locally-kept history rings.

    A capable server echoes ``history_since`` and ships only the
    entries after that tick — append them. An older server ignores the
    query and ships its full rings — detected by the missing marker (or
    a tick counter that went BACKWARDS: collector restart) and degraded
    to full replacement, pre-ISSUE-18 behaviour. Returns the rings and
    mutates ``view["history"]`` to the merged view for rendering."""
    from collections import deque
    incremental = (local is not None
                   and view.get("history_since") == last_ticks
                   and last_ticks is not None
                   and view.get("ticks", 0) >= last_ticks)
    if not incremental:
        local = {k: deque(rows, maxlen=depth)
                 for k, rows in (view.get("history") or {}).items()}
    else:
        for k, rows in (view.get("history") or {}).items():
            ring = local.setdefault(k, deque(maxlen=depth))
            ring.extend(rows)
    view["history"] = {k: list(v) for k, v in local.items()}
    return local


def _top_replay(args) -> int:
    """``cli top --replay <journal>``: scrub a past run on the same
    dashboard from the observer's ``fleet_tick`` journal records. The
    journaled views carry no history rings (that is what keeps
    journal_bytes_per_tick flat); the rings are rebuilt here by
    accumulating the per-tick scalars, so sparklines match what a live
    watcher saw."""
    import json as _json
    import time as _time

    from .telemetry.journal import JournalReader

    reader = JournalReader(args.replay)
    frames = reader.records(types=("fleet_tick",))
    if not frames:
        print(f"top: no fleet_tick records in {args.replay}",
              file=sys.stderr)
        return 1
    hist = {"fleet_qps": [], "p99_ms": [], "scrape_ms": []}
    views = []
    for rec in frames:
        v = dict(rec.get("view") or {})
        hist["fleet_qps"].append(v.get("fleet_qps"))
        p99 = None
        for obj in (v.get("slo") or {}).get("objectives", []):
            if "p99_ms" in obj:
                p99 = obj["p99_ms"]
                break
        hist["p99_ms"].append(p99)
        hist["scrape_ms"].append((v.get("scrape") or {}).get("last_ms"))
        v["history"] = {k: list(rows) for k, rows in hist.items()}
        views.append(v)
    span = frames[-1].get("ts", 0.0) - frames[0].get("ts", 0.0)
    if args.json:
        print(_json.dumps(views[-1], indent=2))
        return _top_exit_code(views[-1])
    if args.watch <= 0:
        print(_render_top(views[-1]))
        print(f"\n(replayed {len(views)} tick(s) spanning {span:.1f}s "
              f"from {args.replay})")
        return _top_exit_code(views[-1])
    rc = 0
    try:
        for i, v in enumerate(views):
            print("\x1b[2J\x1b[H", end="")  # clear screen, home cursor
            print(_render_top(v))
            print(f"\n(replay frame {i + 1}/{len(views)} from "
                  f"{args.replay} — Ctrl-C to stop)")
            rc = _top_exit_code(v)
            if i < len(views) - 1:
                _time.sleep(args.watch)
    except KeyboardInterrupt:
        pass
    return rc


def cmd_top(args) -> int:
    """Live fleet dashboard over a collector's ``GET /fleet`` (or a
    journal replay with ``--replay``). Exit codes match ``cli status``
    (see ``_top_exit_code``); 1 when the collector is unreachable."""
    import json as _json
    import time as _time
    from urllib.error import HTTPError, URLError
    from urllib.request import urlopen

    if getattr(args, "replay", None):
        return _top_replay(args)
    base = args.url
    if not base:
        print("top: need --url (or DPS_FLEET_URL)", file=sys.stderr)
        return 1
    if not base.startswith(("http://", "https://")):
        base = "http://" + base
    url = base.rstrip("/") + "/fleet"
    state = {"hist": None, "ticks": None}

    def poll() -> int:
        # After the first full fetch, ask only for the history delta
        # (?since=<tick>); degradation-pinned — _merge_top_history
        # falls back to full replacement against older servers.
        q = f"?since={state['ticks']}" if state["ticks"] is not None \
            else ""
        try:
            view = _json.loads(urlopen(url + q, timeout=5).read())
        except (HTTPError, URLError, OSError, ValueError) as e:
            print(f"top: cannot reach {url}: {e}", file=sys.stderr)
            return 1
        state["hist"] = _merge_top_history(state["hist"], view,
                                           state["ticks"])
        state["ticks"] = view.get("ticks")
        if args.json:
            print(_json.dumps(view, indent=2))
        else:
            print(_render_top(view))
        return _top_exit_code(view)

    if args.watch <= 0:
        return poll()
    rc = 0
    try:
        while True:
            print("\x1b[2J\x1b[H", end="")  # clear screen, home cursor
            rc = poll()
            print(f"\n(watching {url} every {args.watch:g}s — Ctrl-C "
                  f"to stop)")
            _time.sleep(args.watch)
    except KeyboardInterrupt:
        pass
    return rc


def cmd_experiments(args) -> int:
    with _telemetry_session(args, "experiments"):
        return _cmd_experiments(args)


def _cmd_experiments(args) -> int:
    if args.ingest_pod:
        from .analysis.pod_logs import ingest_pod

        out = os.path.join(args.out_dir, f"{args.experiment_name}.json")
        record = ingest_pod(
            args.experiment_name, name=args.pod_name, zone=args.pod_zone,
            tf_dir=args.tf_dir,
            log_path=args.pod_log_path, out_path=out)
        n_workers = record["worker_metrics_aggregated"].get("num_workers", 0)
        print(f"ingested {n_workers} worker record(s) + "
              f"{'server' if record['server_metrics'] else 'no server'} "
              f"metrics from pod -> {out}", file=sys.stderr)
        return 0

    from .analysis import run_matrix

    dataset = _load_dataset(args)
    run_matrix(dataset, args.out_dir,
               modes=tuple(args.modes.split(",")),
               worker_counts=tuple(int(x)
                                   for x in args.worker_counts.split(",")),
               epochs=args.epochs, batch_size=args.batch_size, lr=args.lr,
               backend=args.backend, plots=not args.no_plots,
               augment=not args.no_augment, seed=args.seed)
    return 0


def cmd_replica(args) -> int:
    with _telemetry_session(args, "replica"):
        return _cmd_replica(args)


def _cmd_replica(args) -> int:
    import time

    from .comms.replica import ReplicaServer

    metrics_adv = getattr(args, "metrics_advertise", None)
    if metrics_adv is None and getattr(args, "_metrics_bound", None):
        metrics_adv = f"localhost:{args._metrics_bound}"
    rep = ReplicaServer(args.primary, port=args.port,
                        shard_id=args.shard_id,
                        advertise=args.advertise,
                        metrics_advertise=metrics_adv,
                        poll_interval=args.poll_interval,
                        staleness_bound_s=args.staleness_bound,
                        canary=bool(getattr(args, "canary", False)),
                        canary_fraction=getattr(args, "canary_fraction",
                                                0.05),
                        canary_min_samples=getattr(
                            args, "canary_min_samples", 20),
                        canary_tolerance=getattr(args, "canary_tolerance",
                                                 0.0),
                        faults=getattr(args, "faults", None),
                        parent=getattr(args, "parent", None),
                        reparent_after=getattr(args, "reparent_after", 3),
                        reparent_cooldown_s=getattr(
                            args, "reparent_cooldown", 5.0))
    port = rep.start()
    print(f"replica up on :{port} (primary={args.primary}, "
          f"parent={rep.parent}, tier={rep.tier}, "
          f"shard={args.shard_id}, "
          f"staleness_bound={rep.staleness_bound_s:g}s"
          + ("" if args.staleness_bound is not None else " (tier-derived)")
          + (f", canary=1/{rep.canary.period}" if rep.canary is not None
             else "")
          + ")", file=sys.stderr, flush=True)
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        rep.stop()
    return 0


def cmd_loadgen(args) -> int:
    import json as _json

    from .comms.loadgen import run_loadgen, run_loadgen_scaled

    scale_out = int(getattr(args, "scale_out", 0) or 0)
    if scale_out > 0:
        result = run_loadgen_scaled(args.targets,
                                    duration_s=args.duration,
                                    concurrency=args.concurrency,
                                    mode=args.fetch_mode,
                                    job=getattr(args, "job", None),
                                    scale_out=scale_out)
    else:
        result = run_loadgen(args.targets, duration_s=args.duration,
                             concurrency=args.concurrency,
                             mode=args.fetch_mode,
                             job=getattr(args, "job", None))
    print("LOADGEN_JSON " + _json.dumps(result), flush=True)
    lat = result["latency_ms"]
    print(f"{result['qps']:.1f} fetch/s aggregate over "
          f"{len(result['targets'])} target(s) "
          f"({result['fetches_err']} errors, "
          f"{result['mb_per_s']:.2f} MB/s in, latency p50/p95/p99 "
          f"{lat['p50']:g}/{lat['p95']:g}/{lat['p99']:g} ms)"
          + (f" [merged from {result.get('reports')} generator "
             f"processes]" if scale_out > 0 else ""),
          file=sys.stderr)
    for arm, row in (result.get("arms") or {}).items():
        print(f"  arm={arm}: {row['ok']} served, "
              f"quality={row['quality_mean']}, steps="
              f"{row['serving_steps']}", file=sys.stderr)
    for jname, row in (result.get("jobs") or {}).items():
        jlat = row["latency_ms"]
        print(f"  job={jname}: {row['qps']:.1f} fetch/s "
              f"({row['err']} errors, p50/p99 "
              f"{jlat['p50']:g}/{jlat['p99']:g} ms)", file=sys.stderr)
    return 0 if result["fetches_ok"] > 0 else 1


def _reshard_crash_if(args, point: str) -> None:
    """Deterministic coordinator kill at a phase boundary (the chaos
    demo's four crash points). Hard exit — no cleanup, exactly what a
    crashed coordinator leaves behind."""
    if getattr(args, "crash_after", None) == point:
        print(f"RESHARD_CRASH_POINT {point}", flush=True)
        os._exit(21)


def _reshard_plan(smeta: dict, donor: int, recipient: int,
                  lo: int, hi: int, n: int, mig_id: str,
                  lease_ttl: float) -> dict:
    """Compute the FULL migration plan — post-move partition and target
    map version — from the donor's live map (a ``status`` reply), before
    anything is frozen. The plan rides every subsequent op as the
    ``migration`` meta field, so each primary's ledger record carries
    everything a resumed coordinator needs."""
    live = smeta.get("shard_map") or {}
    ranges = [tuple(sh["slot_range"]) for sh in live.get("shards", [])]
    if len(ranges) != n:
        raise SystemExit(f"donor's shard map lists {len(ranges)} "
                         f"shards, expected {n}")
    dlo, dhi = ranges[donor]
    rlo, rhi = ranges[recipient]
    if not dlo <= lo < hi <= dhi:
        raise SystemExit(f"slots [{lo},{hi}) not owned by donor "
                         f"{donor} (owns [{dlo},{dhi}))")
    # The moved range must sit at the donor boundary FACING the
    # recipient, so both stay contiguous after the handoff.
    if recipient == donor + 1:
        if hi != dhi:
            raise SystemExit(f"moving to shard {recipient} needs "
                             f"HI == donor's upper bound {dhi}")
        ranges[donor] = (dlo, lo)
        ranges[recipient] = (lo, rhi)
    else:
        if lo != dlo:
            raise SystemExit(f"moving to shard {recipient} needs "
                             f"LO == donor's lower bound {dlo}")
        ranges[donor] = (hi, dhi)
        ranges[recipient] = (rlo, hi)
    return {"id": mig_id, "slot_lo": lo, "slot_hi": hi,
            "ranges": [list(r) for r in ranges],
            "map_version": int(live.get("version", 0)) + 1,
            "lease_ttl": float(lease_ttl)}


def _reshard_apply_order(stores, donor: int, recipient: int) -> list:
    """Publish order: donor FIRST (its apply is the commit point — the
    lease stops applying and the migration becomes roll-forward-only),
    recipient second, bystanders after."""
    order = [donor, recipient] + [i for i in range(len(stores))
                                  if i not in (donor, recipient)]
    return [(i, stores[i]) for i in order]


def _reshard_publish(stores, donor: int, recipient: int, plan: dict,
                     args) -> dict:
    """Phases 3+4: apply_ranges everywhere (idempotent server-side, so
    a resumed coordinator re-applies safely) then commit on the donor.
    Returns the commit reply meta."""
    first = True
    for _i, s in _reshard_apply_order(stores, donor, recipient):
        s.reshard_op("apply_ranges", ranges=plan["ranges"],
                     map_version=plan["map_version"], migration=plan)
        if first:
            first = False
            _reshard_crash_if(args, "apply_first")
    _reshard_crash_if(args, "apply_all")
    cmeta, _ = stores[donor].reshard_op(
        "commit", slot_lo=plan["slot_lo"], slot_hi=plan["slot_hi"],
        migration=plan)
    return cmeta


def _reshard_run(stores, donor: int, recipient: int, plan: dict,
                 args) -> int:
    """The full protocol under a ledger plan: export -> import ->
    lease re-check -> publish (apply donor-first) -> commit."""
    import json as _json

    lo, hi = plan["slot_lo"], plan["slot_hi"]
    # 1. Export: the donor freezes [lo,hi) (pushes touching those slots
    #    are disowned from this instant), journals the migration record
    #    with its lease deadline, and hands back a consistent params
    #    subset + its push journal.
    emeta, payload = stores[donor].reshard_op(
        "export", slot_lo=lo, slot_hi=hi, migration=plan)
    _reshard_crash_if(args, "export")
    # 2. Import: recipient adopts the params AND the donor's journal
    #    entries, so a worker replaying a pre-handoff push token against
    #    the new owner still answers `duplicate`.
    imeta, _ = stores[recipient].reshard_op(
        "import", payload=payload, journal=emeta.get("journal"),
        migration=plan)
    _reshard_crash_if(args, "import")
    # Lease re-check at the point of no return: if the donor's freeze
    # expired while export/import ran (slow transfer, paused
    # coordinator), the donor already unfroze and took pushes for
    # [lo,hi) — publishing the map now would hand those writes to the
    # recipient's STALE copy. Abort the recipient instead; the cluster
    # is exactly where it started.
    smeta, _ = stores[donor].reshard_op("status")
    mig = smeta.get("migration")
    if not (isinstance(mig, dict) and mig.get("id") == plan["id"]):
        stores[recipient].reshard_op("abort", migration=plan)
        print(f"RESHARD_LEASE_LOST migration={plan['id']} donor lease "
              f"expired before publish; recipient rolled back, map "
              f"untouched", file=sys.stderr, flush=True)
        return 3
    cmeta = _reshard_publish(stores, donor, recipient, plan, args)
    result = {"migration": plan["id"], "donor": donor,
              "recipient": recipient, "slots": [lo, hi],
              "map_version": plan["map_version"],
              "export_step": emeta.get("export_step"),
              "exported": emeta.get("exported"),
              "adopted": imeta.get("adopted"),
              "journal_loaded": imeta.get("journal_loaded"),
              "dropped": cmeta.get("dropped"),
              "ranges": [list(r) for r in plan["ranges"]]}
    print("RESHARD_JSON " + _json.dumps(result), flush=True)
    if not args.json:
        print(f"moved slots [{lo},{hi}) shard {donor} -> {recipient} "
              f"at step {result['export_step']} "
              f"({result['adopted']} tensors, "
              f"{result['journal_loaded']} journal entries; "
              f"map v{plan['map_version']})", file=sys.stderr)
    return 0


def _reshard_resume(stores, donor: int, recipient: int, lo: int,
                    hi: int, args) -> int:
    """Crash-point oracle (docs/ROBUSTNESS.md "Migration failure
    matrix"): read both primaries' ledger records and deterministically
    finish or undo the migration.

    - donor record in ``export`` phase (map never published, lease
      live): ROLL FORWARD from the top — re-export is idempotent (the
      frozen range took no applies) and refreshes the lease.
    - donor record in ``apply_ranges`` phase (map publishing): ROLL
      FORWARD the tail only — re-running export/import here would graft
      the donor's stale copy over writes the recipient already owns.
    - donor record GONE but recipient record present: the lease expired
      (donor auto-unfroze and kept serving) — ROLL BACK the recipient.
    - no records anywhere: nothing in flight (committed or fully
      aborted); report and exit clean."""
    import json as _json

    dmeta, _ = stores[donor].reshard_op("status")
    rmeta, _ = stores[recipient].reshard_op("status")
    drec = dmeta.get("migration")
    rrec = rmeta.get("migration")
    drec = drec if isinstance(drec, dict) else None
    rrec = rrec if isinstance(rrec, dict) else None
    rec = drec or rrec
    if rec is None:
        result = {"outcome": "none", "donor": donor,
                  "recipient": recipient}
        print("RESHARD_RESUME_JSON " + _json.dumps(result), flush=True)
        if not args.json:
            print("no migration in flight on either primary (already "
                  "committed, or rolled back by lease expiry)",
                  file=sys.stderr)
        return 0
    # Rebuild the coordinator's plan from the ledger record — the
    # primaries journaled everything at export/import time.
    plan = {"id": str(rec["id"]), "slot_lo": int(rec["slot_lo"]),
            "slot_hi": int(rec["slot_hi"]),
            "ranges": [list(r) for r in (rec.get("ranges") or [])],
            "map_version": int(rec.get("map_version") or 0),
            "lease_ttl": float(args.lease_ttl)}
    if drec is None:
        # Lease expired: the donor unfroze, kept ownership, and may have
        # applied pushes to [lo,hi) since — the recipient's copy is
        # stale by construction. Roll back.
        ameta, _ = stores[recipient].reshard_op("abort", migration=plan)
        result = {"outcome": "rolled_back", "migration": plan["id"],
                  "dropped": ameta.get("dropped")}
        print("RESHARD_RESUME_JSON " + _json.dumps(result), flush=True)
        if not args.json:
            print(f"migration {plan['id']}: donor lease expired — "
                  f"recipient rolled back ({ameta.get('dropped')} "
                  f"params dropped), map untouched", file=sys.stderr)
        return 0
    if drec.get("phase") == "export":
        rc = _reshard_run(stores, donor, recipient, plan, args)
        outcome = "rolled_forward" if rc == 0 else "rolled_back"
        print("RESHARD_RESUME_JSON " + _json.dumps(
            {"outcome": outcome, "migration": plan["id"],
             "from_phase": "export"}), flush=True)
        return rc
    # Map already publishing: finish apply everywhere + commit.
    cmeta = _reshard_publish(stores, donor, recipient, plan, args)
    result = {"outcome": "rolled_forward", "migration": plan["id"],
              "from_phase": "apply_ranges",
              "map_version": plan["map_version"],
              "dropped": cmeta.get("dropped")}
    print("RESHARD_RESUME_JSON " + _json.dumps(result), flush=True)
    if not args.json:
        print(f"migration {plan['id']}: map v{plan['map_version']} "
              f"re-published everywhere, donor committed "
              f"({cmeta.get('dropped')} params dropped)",
              file=sys.stderr)
    return 0


def _reshard_abort_cmd(stores, donor: int, recipient: int, args) -> int:
    """Operator-driven roll-back. Refused once the donor's map publish
    began (phase ``apply_ranges``): from there the recipient owns
    writes, and undoing the publish would lose them — --resume rolls
    forward instead."""
    import json as _json

    dmeta, _ = stores[donor].reshard_op("status")
    drec = dmeta.get("migration")
    if isinstance(drec, dict) and drec.get("phase") == "apply_ranges":
        print(f"migration {drec.get('id')} already publishing its map — "
              f"abort refused, run --resume to roll forward",
              file=sys.stderr)
        return 4
    # Recipient first (drop the copy while the donor still owns and
    # serves the range), donor second (unfreeze).
    ameta, _ = stores[recipient].reshard_op("abort")
    bmeta, _ = stores[donor].reshard_op("abort")
    result = {"outcome": "aborted",
              "recipient_dropped": ameta.get("dropped"),
              "donor_aborted": bmeta.get("aborted")}
    print("RESHARD_ABORT_JSON " + _json.dumps(result), flush=True)
    if not args.json:
        print(f"migration aborted: recipient dropped "
              f"{ameta.get('dropped')} params, donor unfroze, map "
              f"untouched", file=sys.stderr)
    return 0


def cmd_reshard(args) -> int:
    """Live migration coordinator (docs/SHARDING.md \"Migration
    protocol\", docs/ROBUSTNESS.md \"Migration failure matrix\"):
    status -> plan -> export -> import -> lease re-check ->
    apply_ranges (donor first) -> commit. Every op carries the full
    plan under a migration id, each primary journals its phase through
    the checkpoint machinery, and the donor's freeze holds a TTL lease
    — so a coordinator killed at ANY boundary is recoverable with
    ``--resume`` (deterministic roll-forward/roll-back) and a
    never-resumed crash self-heals by lease expiry."""
    import uuid

    from .comms.client import RemoteStore

    try:
        lo, hi = (int(x) for x in args.slots.split(":"))
    except ValueError:
        raise SystemExit(f"--slots must be LO:HI, got {args.slots!r}")
    primaries = [a for a in args.primaries.split(",") if a]
    donor, recipient = int(args.donor), int(args.recipient)
    n = len(primaries)
    if not (0 <= donor < n and 0 <= recipient < n):
        raise SystemExit(f"--donor/--recipient out of range for "
                         f"{n} primaries")
    if abs(donor - recipient) != 1:
        raise SystemExit("recipient must be adjacent to donor "
                         "(donor±1): per-shard slot ranges stay "
                         "contiguous (docs/SHARDING.md)")
    if args.resume and args.abort:
        raise SystemExit("--resume and --abort are mutually exclusive")
    stores = [RemoteStore(a) for a in primaries]
    try:
        if args.abort:
            return _reshard_abort_cmd(stores, donor, recipient, args)
        if args.resume:
            return _reshard_resume(stores, donor, recipient, lo, hi,
                                   args)
        mig_id = args.migration_id or f"mig-{uuid.uuid4().hex[:10]}"
        smeta, _ = stores[donor].reshard_op("status")
        plan = _reshard_plan(smeta, donor, recipient, lo, hi, n,
                             mig_id, args.lease_ttl)
        return _reshard_run(stores, donor, recipient, plan, args)
    finally:
        for s in stores:
            s.close()


def cmd_infer(args) -> int:
    """One-shot inference client: raw stub like loadgen (no RemoteStore
    — the reply's tensor payload is deliberately never decoded)."""
    import json as _json
    import time

    import grpc as _grpc

    from .comms.service import (GRPC_OPTIONS, SERVICE_NAME, pack_msg,
                                unpack_msg)

    ident = lambda b: b  # noqa: E731
    channel = _grpc.insecure_channel(args.target, options=GRPC_OPTIONS)
    stub = channel.unary_unary(f"/{SERVICE_NAME}/FetchParameters",
                               request_serializer=ident,
                               response_deserializer=ident)
    served = []
    meta: dict = {"infer": True}
    try:
        for _ in range(max(1, int(args.count))):
            t0 = time.perf_counter()
            reply = stub(pack_msg(meta), timeout=10.0)
            dt = time.perf_counter() - t0
            rmeta, payload = unpack_msg(reply)
            arm = rmeta.get("arm") or "stable"
            step = rmeta.get("serving_step",
                             rmeta.get("global_step"))
            served.append({"arm": arm, "serving_step": step,
                           "bytes": len(payload),
                           "latency_ms": round(dt * 1e3, 3)})
            meta = {"infer": True}
            if args.quality is not None and step is not None:
                meta["quality"] = {"arm": arm, "step": int(step),
                                   "value": float(args.quality)}
    finally:
        channel.close()
    print("INFER_JSON " + _json.dumps({"target": args.target,
                                       "served": served}), flush=True)
    if not args.json:
        for row in served:
            print(f"arm={row['arm']} step={row['serving_step']} "
                  f"{row['bytes']}B {row['latency_ms']}ms",
                  file=sys.stderr)
    return 0 if served else 1


def cmd_perf(args) -> int:
    if args.perf_command == "check":
        return _cmd_perf_check(args)
    if args.perf_command == "diff":
        return _cmd_perf_diff(args)
    return _cmd_perf_profile(args)


def _cmd_perf_check(args) -> int:
    """Delegate to tools/benchwatch — a repo-checkout tool like
    ``cli lint`` (the ledger and the watcher live beside the package,
    not in the wheel). Same exit codes as ``python -m tools.benchwatch``:
    0 pass, 1 malformed ledger, 2 regression."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if not os.path.isdir(os.path.join(root, "tools", "benchwatch")):
        print("cli perf check: tools/benchwatch not found — run from a "
              "repo checkout (the watcher is not shipped in the wheel)",
              file=sys.stderr)
        return 2
    if root not in sys.path:
        sys.path.insert(0, root)
    from tools.benchwatch.__main__ import main as benchwatch_main
    argv = ["--root", args.root or root,
            "--tolerance", str(args.tolerance),
            "--baseline-window", str(args.baseline_window),
            "--recent-window", str(args.recent_window),
            "--format", args.format]
    if args.validate_only:
        argv.append("--validate-only")
    if getattr(args, "profiles_root", None):
        argv += ["--profiles-root", args.profiles_root]
    return benchwatch_main(argv)


def _cmd_perf_profile(args) -> int:
    """Parse a ``--profile-dir`` capture into the merged perf-observatory
    artifact (analysis/device_profile.py): per-op-class device time,
    optionally joined with the flight-recorder critical-path report so
    step wall reconciles against attributed device time."""
    import json as _json

    from .analysis.device_profile import (attribute_profile,
                                          render_profile_table)
    critical = None
    dump_dir = getattr(args, "trace_dump_dir", None)
    if dump_dir:
        from .analysis.traces import (critical_path_report,
                                      find_trace_dumps, load_trace_dumps)
        dumps = find_trace_dumps(dump_dir)
        if dumps:
            critical = critical_path_report(load_trace_dumps(dumps))
        else:
            print(f"perf profile: no trace-*.json dumps in {dump_dir} — "
                  f"skipping the critical-path join", file=sys.stderr)
    device_kind = getattr(args, "device_kind", None)
    if device_kind is None:
        try:
            import jax
            device_kind = jax.devices()[0].device_kind
        except Exception:  # noqa: BLE001 — artifact stays usable jax-less
            device_kind = None
    report = attribute_profile(args.profile_dir, critical=critical,
                               device_kind=device_kind)
    if not report["trace_files"]:
        print(f"perf profile: no jax.profiler dumps under "
              f"{args.profile_dir} (expected plugins/profile/<run>/"
              f"*.trace.json.gz)", file=sys.stderr)
        return 1
    if getattr(args, "out", None):
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            _json.dump(report, f, indent=2)
        print(f"perf profile: artifact -> {args.out}", file=sys.stderr)
    if args.json:
        print(_json.dumps(report, indent=2))
    else:
        print(render_profile_table(report))
    # Raw Chrome traces are scratch once the attribution artifact exists
    # (ISSUE 20 satellite f): prune on success, keep on failure so a
    # basis=none / parse-error capture stays debuggable.
    if (not getattr(args, "keep_traces", False)
            and report["profile"].get("basis") not in (None, "none")
            and not report.get("parse_errors")):
        from .telemetry.profiler import prune_capture
        pruned = prune_capture(args.profile_dir)
        if pruned:
            print(f"perf profile: pruned {len(pruned)} raw trace "
                  f"file(s) from {args.profile_dir} (--keep-traces to "
                  f"keep)", file=sys.stderr)
    return 0


def _cmd_perf_diff(args) -> int:
    """``cli perf diff BASELINE CANDIDATE`` — per-op-class regression
    attribution between two recorded artifacts. Refuses to compare
    artifacts whose attribution bases differ (they measure different
    things; a refusal is more honest than a misleading table)."""
    import json as _json

    from .analysis.device_profile import diff_profiles, render_profile_diff

    arts = []
    for path in (args.baseline, args.candidate):
        try:
            with open(path) as f:
                arts.append(_json.load(f))
        except (OSError, ValueError) as e:
            print(f"perf diff: cannot read artifact {path}: {e}",
                  file=sys.stderr)
            return 1
    try:
        diff = diff_profiles(arts[0], arts[1],
                             unchanged_tolerance=args.tolerance)
    except ValueError as e:
        print(f"perf diff: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(_json.dumps(diff, indent=2))
    else:
        print(render_profile_diff(diff))
    return 0


def cmd_lint(args) -> int:
    """Delegate to tools/dpslint. The analyzer and its baseline live
    beside the package in the repo checkout (not in the wheel) — exactly
    like scripts/tier1.sh, ``cli lint`` is a checkout tool."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if not os.path.isdir(os.path.join(root, "tools", "dpslint")):
        print("cli lint: tools/dpslint not found — run from a repo "
              "checkout (the analyzer is not shipped in the wheel)",
              file=sys.stderr)
        return 2
    if root not in sys.path:
        sys.path.insert(0, root)
    from tools.dpslint.cli import main as dpslint_main
    argv = ["--json"] if args.json else []
    if args.baseline:
        argv += ["--baseline", args.baseline]
    return dpslint_main(argv)


def cmd_incident(args) -> int:
    """``cli incident list|show|report`` over auto-captured bundles —
    postmortems from disk alone (docs/OBSERVABILITY.md)."""
    import json as _json

    from .analysis.incidents import (build_timeline, list_incidents,
                                     load_incident, render_timeline)

    rows = list_incidents(args.dir)
    if args.incident_command == "list":
        if args.json:
            print(_json.dumps(rows, indent=2, default=str))
            return 0
        if not rows:
            print(f"no incident bundles under {args.dir}")
            return 0
        print(f"{'ID':<44} {'RULE':<16} {'SEV':<9} {'RECORDS':>7} "
              f"{'FILES':>5}")
        for m in rows:
            trig = m.get("trigger") or {}
            print(f"{m.get('id', '?'):<44} "
                  f"{str(trig.get('rule', '-')):<16} "
                  f"{str(trig.get('severity', '-')):<9} "
                  f"{m.get('records', 0):>7} "
                  f"{len(m.get('files') or []):>5}")
        return 0
    wanted = getattr(args, "id", None)
    if wanted is None:
        if not rows:
            print(f"incident: no bundles under {args.dir}",
                  file=sys.stderr)
            return 1
        manifest = rows[-1]
    else:
        matches = [m for m in rows
                   if str(m.get("id", "")).startswith(wanted)]
        exact = [m for m in matches if m.get("id") == wanted]
        if exact:
            matches = exact
        if len(matches) != 1:
            print(f"incident: id {wanted!r} matches "
                  f"{len(matches)} bundle(s) under {args.dir}",
                  file=sys.stderr)
            return 1
        manifest = matches[0]
    bundle = manifest["path"]
    if args.incident_command == "show":
        if args.json:
            print(_json.dumps(manifest, indent=2, default=str))
        else:
            trig = manifest.get("trigger") or {}
            print(f"incident {manifest.get('id')}")
            print(f"  created   {manifest.get('created_ts')} "
                  f"(role {manifest.get('role')})")
            print(f"  trigger   {trig.get('rule')} "
                  f"[{trig.get('severity')}] "
                  f"worker={trig.get('worker')} "
                  f"value={trig.get('value')}")
            print(f"  window    {manifest.get('window_s')}s, "
                  f"{manifest.get('records')} journal record(s)")
            print(f"  journal   {manifest.get('journal_dir')}")
            for f in manifest.get("files") or []:
                print(f"  file      {f}")
        return 0
    # report: frozen window + the journal's post-edge continuation.
    data = load_incident(bundle,
                         journal_dir=getattr(args, "journal_dir", None))
    timeline = build_timeline(data["records"])
    if args.json:
        print(_json.dumps({"manifest": data["manifest"],
                           "timeline": timeline, "stats": data["stats"]},
                          indent=2, default=str))
    else:
        print(render_timeline(timeline, data["manifest"]))
    return 0


def _query_streams(records: list) -> dict:
    """Snapshot records grouped per process: (role, pid) -> time-sorted
    list (the journal reader already sorted globally)."""
    streams: dict = {}
    for rec in records:
        streams.setdefault((rec.get("role"), rec.get("pid")),
                           []).append(rec)
    return streams


def _hist_at(stream: list, key: str, ts: float | None) -> dict | None:
    """Newest snapshot's histogram ``key`` at or before ``ts`` (None =
    newest overall) — cumulative, so this IS the prefix total."""
    best = None
    for rec in stream:
        if ts is not None and rec.get("ts", 0.0) > ts:
            break
        h = (rec.get("histograms") or {}).get(key)
        if h is not None:
            best = h
    return best


def _window_hist(stream: list, key: str, since: float | None,
                 until: float | None) -> dict | None:
    """Window-exact bucket counts for one process: cumulative newest
    minus the cumulative baseline at-or-before the window start. This
    is the union-exact property the journal's cumulative snapshots buy:
    no rate estimation, just integer bucket subtraction."""
    newest = _hist_at(stream, key, until)
    if newest is None:
        return None
    out = {"le": list(newest.get("le") or []),
           "counts": [int(c) for c in newest.get("counts") or []],
           "sum": float(newest.get("sum", 0.0)),
           "count": int(newest.get("count", 0))}
    if since is not None:
        base = _hist_at(stream, key, since)
        if base is not None and list(base.get("le") or []) == out["le"]:
            out["counts"] = [max(0, a - int(b)) for a, b in
                             zip(out["counts"], base.get("counts") or [])]
            out["sum"] = max(0.0, out["sum"]
                             - float(base.get("sum", 0.0)))
            out["count"] = max(0, out["count"]
                               - int(base.get("count", 0)))
    return out


def _retro_slo(records: list, args) -> dict:
    """Retroactive SLO burn evaluation over journal history, reusing
    the live evaluator's window semantics (telemetry/slo.py): rebuild
    the fleet-summed (total, bad) sample sequence the collector keeps
    in memory, then slide the same fast/slow windows over it."""
    from .telemetry.registry import MetricsRegistry
    from .telemetry.slo import SloEvaluator, default_objectives

    objectives = default_objectives(
        fetch_p99_ms=args.slo_fetch_p99_ms,
        availability=args.slo_availability)
    windows = SloEvaluator(objectives, registry=MetricsRegistry(),
                           fast_window_s=args.slo_fast_window,
                           slow_window_s=args.slo_slow_window).windows
    streams = list(_query_streams(records).values())
    ticks = sorted({rec.get("ts", 0.0) for rec in records})
    samples = []
    for t in ticks:
        sample: dict = {}
        for obj in objectives:
            hkey = (f"dps_rpc_server_latency_seconds"
                    f"{{method={obj.method}}}")
            ekey = (f"dps_rpc_server_errors_total"
                    f"{{method={obj.method}}}")
            total = bad = 0
            found = False
            for stream in streams:
                h = _hist_at(stream, hkey, t)
                if h is None:
                    continue
                found = True
                n = int(h.get("count", 0))
                total += n
                err = 0
                for rec in stream:
                    if rec.get("ts", 0.0) > t:
                        break
                    err = int((rec.get("counters") or {})
                              .get(ekey, err))
                if obj.threshold_s is None:
                    bad += min(n, err)
                else:
                    good, _ = SloEvaluator._good_upto(h, obj.threshold_s)
                    bad += min(n, (n - good) + err)
            if found:
                sample[obj.name] = (total, bad)
        samples.append((t, sample))
    out: dict = {"samples": len(samples), "windows": {}}
    any_critical = False
    for win in windows:
        wrow: dict = {}
        for obj in objectives:
            max_burn = 0.0
            breach_ts: list = []
            for t, _ in samples:
                d = SloEvaluator._window_delta(samples, obj.name, t,
                                               win.window_s)
                if d is None or d["total"] < win.min_events:
                    continue
                burn = SloEvaluator._burn(obj, d["bad"], d["total"])
                max_burn = max(max_burn, burn)
                if burn >= win.burn_threshold:
                    breach_ts.append(t)
            breached = bool(breach_ts)
            if breached and win.severity == "critical":
                any_critical = True
            wrow[obj.name] = {
                "max_burn": round(max_burn, 2),
                "burn_threshold": win.burn_threshold,
                "breached": breached,
                "severity": win.severity,
                "first_breach_ts": breach_ts[0] if breach_ts else None,
                "last_breach_ts": breach_ts[-1] if breach_ts else None,
                "breach_samples": len(breach_ts),
            }
        out["windows"][win.rule] = {"window_s": win.window_s,
                                    "objectives": wrow}
    out["any_critical_breach"] = any_critical
    return out


def _goodput_counters_at(stream: list, ts: float | None) -> dict:
    """Per-process goodput counter prefix totals at-or-before ``ts``:
    the newest value of every ``dps_goodput_*`` counter key (cumulative,
    so the latest observation IS the prefix total — same property
    ``_hist_at`` leans on)."""
    from .telemetry.goodput import GOODPUT_METRIC, GOODPUT_WALL_METRIC

    out: dict = {}
    for rec in stream:
        if ts is not None and rec.get("ts", 0.0) > ts:
            break
        for key, val in (rec.get("counters") or {}).items():
            if key.startswith((GOODPUT_METRIC, GOODPUT_WALL_METRIC)):
                out[key] = val
    return out


def _retro_goodput(records: list, since: float | None,
                   until: float | None, tolerance: float = 0.02) -> dict:
    """Retroactive goodput ledger over a journal window: per-process
    counter deltas (newest-at-``until`` minus baseline-at-``since``,
    clamped like every other window-exact query) summed across
    processes, then folded through the same ``goodput_report`` math the
    live ``cli goodput`` uses — one code path, two time machines."""
    from .telemetry.goodput import delta_counters, report_from_counters

    merged: dict = {}
    processes = 0
    for stream in _query_streams(records).values():
        newest = _goodput_counters_at(stream, until)
        if not newest:
            continue
        base = _goodput_counters_at(stream, since) if since is not None \
            else {}
        delta = delta_counters(newest, base)
        if not any(v > 0 for v in delta.values()):
            continue
        processes += 1
        for key, val in delta.items():
            merged[key] = merged.get(key, 0.0) + val
    report = report_from_counters(merged, tolerance=tolerance)
    report["processes"] = processes
    return report


def _incident_badput(records: list, incidents_dir: str,
                     tolerance: float = 0.02) -> list:
    """Join incident bundles against the goodput ledger: for each
    bundle, the badput seconds inside its frozen capture window
    ``[created_ts - window_s, created_ts]`` — what the incident *cost*
    in non-productive wall, per category."""
    from .analysis.incidents import list_incidents

    rows = []
    for m in list_incidents(incidents_dir):
        created = m.get("created_ts")
        window_s = m.get("window_s")
        if not isinstance(created, (int, float)) \
                or not isinstance(window_s, (int, float)):
            continue
        rep = _retro_goodput(records, created - window_s, created,
                             tolerance=tolerance)
        trig = m.get("trigger") or {}
        rows.append({"id": m.get("id"),
                     "rule": trig.get("rule"),
                     "severity": trig.get("severity"),
                     "window": {"since": created - window_s,
                                "until": created},
                     "wall_s": rep["wall_s"],
                     "badput_s": rep["badput_s"],
                     "goodput_fraction": rep["goodput_fraction"],
                     "categories": rep["categories"]})
    return rows


def _render_goodput_report(report: dict, title: str = "goodput") -> str:
    """Shared renderer for the live (``cli goodput``) and retro
    (``cli query --goodput``) ledgers — same table, two time machines."""
    gpf = report.get("goodput_fraction")
    head = "-" if gpf is None else f"{gpf * 100:.1f}%"
    lines = [f"{title}: wall={report['wall_s']:.1f}s "
             f"goodput={head} badput={report['badput_s']:.1f}s"]
    lines.append(f"  {'CATEGORY':<20} {'SECONDS':>10} {'FRACTION':>9}")
    for cat, row in report.get("categories", {}).items():
        if row["seconds"] <= 0:
            continue
        lines.append(f"  {cat:<20} {row['seconds']:>10.2f} "
                     f"{row['fraction'] * 100:>8.1f}%")
    lines.append(f"  residual={report['residual_s']:.2f}s "
                 f"({report['residual_fraction'] * 100:.1f}% of wall, "
                 f"folded into 'other') "
                 f"overshoot={report['overshoot_s']:.2f}s "
                 f"reconciled={report['reconciled']}")
    return "\n".join(lines)


def cmd_goodput(args) -> int:
    """``cli goodput``: the live goodput ledger from one process's
    ``/metrics.json`` — what fraction of wall since start was
    productive, where the rest went (docs/OBSERVABILITY.md 'Goodput
    observatory'). Exit 1 when the endpoint is unreachable."""
    import json as _json
    from urllib.error import HTTPError, URLError
    from urllib.request import urlopen

    from .telemetry.goodput import report_from_counters

    base = args.url or f"http://{args.host}:{args.metrics_port}"
    if not base.startswith(("http://", "https://")):
        base = "http://" + base
    url = base.rstrip("/") + "/metrics.json"
    try:
        snap = _json.loads(urlopen(url, timeout=5).read())
    except (HTTPError, URLError, OSError, ValueError) as e:
        print(f"goodput: cannot reach {url}: {e}", file=sys.stderr)
        return 1
    report = report_from_counters(snap.get("counters") or {},
                                  tolerance=args.tolerance)
    if args.json:
        print("GOODPUT_JSON: " + _json.dumps(report))
        return 0
    if report["wall_s"] <= 0:
        print(f"goodput: no goodput counters at {url} — the process "
              f"has no GoodputAccount wall yet (worker/trainer roles "
              f"publish one)", file=sys.stderr)
        return 0
    print(_render_goodput_report(report, title=f"goodput @ {base}"))
    return 0


def cmd_query(args) -> int:
    """``cli query``: retro-query a durable journal — series listing,
    union-exact windowed percentiles, retroactive SLO burn."""
    import json as _json

    from .telemetry.journal import JournalReader
    from .telemetry.stats import histogram_quantile, merge_histograms

    reader = JournalReader(args.journal)
    snaps = reader.records(types=("snapshot", "fleet_tick"))
    snaps = [r for r in snaps if r.get("type") == "snapshot"
             or "histograms" in r]
    if not snaps:
        print(f"query: no snapshot records in {args.journal}",
              file=sys.stderr)
        return 1
    newest_ts = max(r.get("ts", 0.0) for r in snaps)
    until = args.until if args.until is not None else newest_ts
    since = args.since
    if args.last is not None:
        since = until - args.last
    in_range = [r for r in snaps if r.get("ts", 0.0) <= until]
    result: dict = {"journal": args.journal,
                    "window": {"since": since, "until": until},
                    "reader_stats": reader.stats}
    if args.slo:
        result["slo"] = _retro_slo(in_range, args)
    if args.goodput:
        result["goodput"] = _retro_goodput(
            in_range, since, until, tolerance=args.goodput_tolerance)
        if args.incidents:
            result["incident_badput"] = _incident_badput(
                in_range, args.incidents,
                tolerance=args.goodput_tolerance)
    streams = _query_streams(in_range)
    selected: dict = {}
    for stream in streams.values():
        for rec in stream:
            for kind in ("counters", "gauges", "histograms"):
                for key in (rec.get(kind) or {}):
                    if args.series and args.series not in key:
                        continue
                    selected.setdefault(kind, set()).add(key)
    if args.percentiles:
        pct_rows: dict = {}
        for key in sorted(selected.get("histograms", ())):
            parts = []
            for stream in streams.values():
                h = _window_hist(stream, key, since, until)
                if h is not None and h["count"] > 0:
                    parts.append(h)
            if not parts:
                continue
            try:
                merged = merge_histograms(parts)
            except ValueError:
                continue
            row = {"count": int(merged["count"]),
                   "processes": len(parts)}
            for pct, name in ((50, "p50"), (95, "p95"), (99, "p99")):
                q = histogram_quantile(merged["le"], merged["counts"],
                                       pct)
                row[name] = None if q is None else round(q, 6)
            pct_rows[key] = row
        result["percentiles"] = pct_rows
    else:
        series: dict = {}
        for kind in ("counters", "gauges", "histograms"):
            for key in sorted(selected.get(kind, ())):
                n = sum(1 for stream in streams.values()
                        if any(key in (rec.get(kind) or {})
                               for rec in stream))
                series[key] = {"kind": kind[:-1], "processes": n}
        result["series"] = series
    rc = 2 if args.slo and result["slo"]["any_critical_breach"] else 0
    if args.json:
        print("QUERY_JSON: " + _json.dumps(result, default=str))
        return rc
    print(f"journal {args.journal}: {reader.stats['records']} record(s) "
          f"in {reader.stats['segments']} segment(s) "
          f"({reader.stats['torn_tails']} torn tail(s), "
          f"{reader.stats['corrupt_lines']} corrupt line(s) skipped)")
    if "series" in result:
        print(f"{'SERIES':<64} {'KIND':<10} {'PROCS':>5}")
        for key, row in result["series"].items():
            print(f"{key:<64} {row['kind']:<10} {row['processes']:>5}")
    if "percentiles" in result:
        print(f"{'SERIES':<64} {'COUNT':>8} {'P50':>10} {'P95':>10} "
              f"{'P99':>10}")
        for key, row in result["percentiles"].items():
            def _fmt(v):
                return "-" if v is None else f"{v * 1e3:.2f}ms"
            print(f"{key:<64} {row['count']:>8} {_fmt(row['p50']):>10} "
                  f"{_fmt(row['p95']):>10} {_fmt(row['p99']):>10}")
    if "goodput" in result:
        print(_render_goodput_report(
            result["goodput"],
            title=f"retro goodput over "
                  f"{result['goodput']['processes']} process(es)"))
        for row in result.get("incident_badput", ()):
            gpf = row["goodput_fraction"]
            gpf = "-" if gpf is None else f"{gpf * 100:.1f}%"
            print(f"  incident {row['id']}: rule={row['rule']} "
                  f"badput={row['badput_s']:.1f}s of "
                  f"{row['wall_s']:.1f}s wall (goodput {gpf})")
    if "slo" in result:
        slo = result["slo"]
        print(f"retro SLO over {slo['samples']} sample(s):")
        for rule, wrow in slo["windows"].items():
            for obj, orow in wrow["objectives"].items():
                state = "BREACHED" if orow["breached"] else "ok"
                print(f"  {rule:<14} {obj:<20} max_burn="
                      f"{orow['max_burn']:<8} (threshold "
                      f"{orow['burn_threshold']}) {state}")
        print(f"  any critical breach: "
              f"{slo['any_critical_breach']}")
    return rc


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if getattr(args, "platform", "default") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    return {"train": cmd_train, "serve": cmd_serve, "worker": cmd_worker,
            "experiments": cmd_experiments, "supervise": cmd_supervise,
            "status": cmd_status, "replica": cmd_replica,
            "observe": cmd_observe, "top": cmd_top,
            "loadgen": cmd_loadgen, "reshard": cmd_reshard,
            "infer": cmd_infer, "lint": cmd_lint,
            "incident": cmd_incident, "query": cmd_query,
            "goodput": cmd_goodput,
            "perf": cmd_perf}[args.command](args)


if __name__ == "__main__":
    raise SystemExit(main())
