"""Push-codec microbench: NumPy reference vs device-resident codec.

ISSUE 14 satellite: the worker's push path now quantizes+packs ON DEVICE
(ops/device_codec.py) with the NumPy ``compress_push`` kept as fallback
and server-side decode. This sweep measures both implementations over a
layer-size ladder and every codec kind, and — because a fast codec that
drifts from the wire contract is worse than a slow one — byte-compares
the encoded wire frames per cell before recording a number. A cell with
non-identical bytes records ``bytes_identical: false`` and fails the
run's ``all_identical`` verdict (the slow test wrapper asserts it).

Timing discipline matches bench.py: per cell, one warmup encode
(compiles the whole-tree phase programs on the device side), then
``--repeats`` timed encodes with the best wall kept. The device number
includes ``finalize`` (the device->host pull of the packed bytes) —
that's what the worker actually pays before the wire. Error feedback is
OFF for both sides so every repeat encodes the same input.

Artifact: experiments/results/codec/codec_bench.json
Run:      python experiments/run_codec_bench.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

OUT = os.path.join(REPO, "experiments", "results", "codec")

SIZES = [4096, 65536, 262144, 1048576, 4194304]
KINDS = ["int8", "int4", "topk"]
TOPK_FRAC = 0.01


def _best(fn, repeats: int) -> float:
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def run_sweep(sizes, repeats: int) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_parameter_server_for_ml_training_tpu.comms import wire
    from distributed_parameter_server_for_ml_training_tpu.ops.compression \
        import compress_push
    from distributed_parameter_server_for_ml_training_tpu.ops.device_codec \
        import DeviceCodec

    platform = jax.devices()[0].platform
    rows = []
    for size in sizes:
        rng = np.random.default_rng(size)
        host = {"g": rng.normal(size=size).astype(np.float32)}
        dev = {"g": jnp.asarray(host["g"])}
        jax.block_until_ready(dev["g"])
        for kind in KINDS:
            plan = {"g": kind}
            codec = DeviceCodec(error_feedback=False, topk_frac=TOPK_FRAC)

            def numpy_encode():
                return compress_push(host, plan, topk_frac=TOPK_FRAC)

            def device_encode():
                return codec.finalize(codec.encode(dev, plan=plan))

            ref = numpy_encode()
            out = device_encode()  # warmup: compiles the phase programs
            blob_ref = wire.encode_tensor_dict(ref)
            blob_dev = wire.encode_tensor_dict(out)
            identical = blob_ref == blob_dev

            np_s = _best(numpy_encode, repeats)
            dev_s = _best(device_encode, repeats)
            mb = size * 4 / 1e6
            rows.append({
                "size": size,
                "kind": kind,
                "input_mb": round(mb, 3),
                "bytes_identical": identical,
                "wire_bytes": len(blob_dev),
                "numpy_s": round(np_s, 6),
                "device_s": round(dev_s, 6),
                "numpy_mb_per_s": round(mb / np_s, 1),
                "device_mb_per_s": round(mb / dev_s, 1),
                "device_speedup": round(np_s / dev_s, 3),
            })
            print(f"size {size:>8} {kind:>5}: numpy "
                  f"{rows[-1]['numpy_mb_per_s']:>8} MB/s, device "
                  f"{rows[-1]['device_mb_per_s']:>8} MB/s "
                  f"({'identical' if identical else 'BYTES DIFFER'})",
                  file=sys.stderr)
    return {
        "metric": "push_codec_encode_mb_per_s",
        "platform": platform,
        "repeats": repeats,
        "topk_frac": TOPK_FRAC,
        "rows": rows,
        "all_identical": all(r["bytes_identical"] for r in rows),
    }


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true",
                        help="small sizes + 2 repeats (test wrapper)")
    parser.add_argument("--repeats", type=int, default=5)
    parser.add_argument("--out", default=os.path.join(
        OUT, "codec_bench.json"))
    args = parser.parse_args()

    sizes = [4096, 65536] if args.quick else SIZES
    repeats = 2 if args.quick else args.repeats
    summary = run_sweep(sizes, repeats)
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(summary, f, indent=2)
        f.write("\n")
    print(json.dumps({"out": args.out,
                      "platform": summary["platform"],
                      "all_identical": summary["all_identical"]}))
    return 0 if summary["all_identical"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
