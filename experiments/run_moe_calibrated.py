"""Calibrated MoE run: Switch-MoE ViT with balanced routing, recorded.

Round-4 VERDICT item 3 'done' bar: a committed ``calibrated/`` MoE run
demonstrating balanced routing. Trains ``--mode moe`` (8 experts,
registry vit_tiny, Switch aux loss at the default weight) on the
calibrated compositional dataset, plus a short aux-weight=0 contrast run,
and records per-epoch expert-load imbalance + drop rate.

The MoE trainer needs one device per expert; this host has ONE TPU chip,
so the run uses the 8-device virtual CPU mesh (same collectives, honest
provenance in the record — the on-chip story for EP is the driver's
``dryrun_multichip``).

Run:  python experiments/run_moe_calibrated.py [--epochs N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                 os.path.join(REPO, ".jax_cache")))

import numpy as np  # noqa: E402


def run(aux_weight: float, epochs: int, ds) -> dict:
    from distributed_parameter_server_for_ml_training_tpu.train.model_parallel import (
        ModelParallelConfig, MoETrainer)

    cfg = ModelParallelConfig(
        model="vit_tiny", num_workers=8, num_epochs=epochs, batch_size=128,
        augment=False, num_classes=100, learning_rate=0.1,
        moe_aux_weight=aux_weight)
    trainer = MoETrainer(ds, cfg)
    t0 = time.time()
    metrics = trainer.train()
    metrics["wall_seconds"] = round(time.time() - t0, 1)

    # Per-epoch routing health from the per-step metric stream.
    steps = len(trainer._moe_step_metrics)
    spe = max(1, steps // epochs)
    per_epoch = []
    for e in range(epochs):
        chunk = trainer._moe_step_metrics[e * spe:(e + 1) * spe]
        if not chunk:
            break
        per_epoch.append({
            "epoch": e + 1,
            "load_imbalance": round(float(np.mean(
                [float(m["moe_load_imbalance"]) for m in chunk])), 3),
            "drop_frac": round(float(np.mean(
                [float(m["moe_drop_frac"]) for m in chunk])), 4),
            "aux_loss": round(float(np.mean(
                [float(m["moe_aux_loss"]) for m in chunk])), 4),
        })
    metrics["per_epoch_routing"] = per_epoch
    return metrics


def run_dense(epochs: int, ds) -> dict:
    """Dense-FFN vit_tiny under the IDENTICAL recipe (same optimizer,
    lr, batch size, batch order seed, step budget, eval) — the contrast
    that shows whether the MoE's 8x FFN parameters at equal per-token
    FLOPs buy quality (round-4 VERDICT weak 4: 'the MoE demonstration
    never shows MoE is worth having')."""
    import jax
    import jax.numpy as jnp

    from distributed_parameter_server_for_ml_training_tpu.data.cifar import (
        make_batches)
    from distributed_parameter_server_for_ml_training_tpu.models.vit import (
        ViT)
    from distributed_parameter_server_for_ml_training_tpu.train import (
        create_train_state, make_eval_step, make_train_step, server_sgd)
    from distributed_parameter_server_for_ml_training_tpu.train.model_parallel \
        import VIT_SHAPES, ModelParallelConfig

    # Build the dense arm FROM the same registry shape and the same
    # config defaults the MoE arm uses (dtype included) — matched by
    # construction, so an accuracy gap can't be an fp32-vs-bf16 or
    # shape-drift artifact.
    cfg = ModelParallelConfig()
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    model = ViT(**VIT_SHAPES["vit_tiny"], num_classes=100, dtype=dtype,
                pool="gap")
    state = create_train_state(model, jax.random.PRNGKey(cfg.seed),
                               server_sgd(0.1), input_shape=(1, 32, 32, 3))
    step = jax.jit(make_train_step(augment=False), donate_argnums=0)
    eval_step = jax.jit(make_eval_step())
    t0 = time.time()
    accs, steps_done = [], 0
    for epoch in range(epochs):
        # Same batch-order seed expression as _EpochTrainer's epoch loop.
        for xb, yb in make_batches(ds.x_train, ds.y_train, 128,
                                   seed=cfg.seed * 997 + epoch):
            state, _ = step(state, xb, yb.astype(np.int32),
                            jax.random.PRNGKey(steps_done))
            steps_done += 1
        correct = total = 0
        for i in range(0, len(ds.x_test), 256):
            xb = ds.x_test[i:i + 256]
            yb = ds.y_test[i:i + 256].astype(np.int32)
            c, n = eval_step(state, xb, yb)
            correct += float(c)
            total += int(n)
        accs.append(round(correct / total, 4))
        print(f"dense epoch {epoch + 1}: test_acc={accs[-1]}", flush=True)
    return {"final_test_accuracy": accs[-1], "all_test_accuracies": accs,
            "local_steps_completed": steps_done,
            "wall_seconds": round(time.time() - t0, 1),
            "arch": "vit_tiny dense MLP", "optimizer": "server_sgd(0.1)"}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--contrast-epochs", type=int, default=None,
                    help="aux-weight=0 contrast run length "
                         "(default: same as --epochs — full-run contrast)")
    ap.add_argument("--skip-dense", action="store_true")
    ap.add_argument("--out", default="moe_8experts.json",
                    help="output filename under experiments/results/"
                         "calibrated/ (a longer-budget rerun must not "
                         "overwrite the default record)")
    ap.add_argument("--train-size", type=int, default=8192,
                    help="subset of the calibrated dataset (CPU-mesh host)")
    args = ap.parse_args()

    from distributed_parameter_server_for_ml_training_tpu.data.cifar import (
        compositional_cifar100)

    ds = compositional_cifar100(n_train=args.train_size, n_test=2048)
    record = {
        "experiment_name": args.out.rsplit(".", 1)[0],
        "dataset": {"generator": "compositional_cifar100",
                    "synthetic": True, "n_train": args.train_size,
                    "n_test": 2048},
        "provenance": ("8-device virtual CPU mesh "
                       "(xla_force_host_platform_device_count; the single "
                       "attached TPU chip cannot host 8 experts)"),
        "config": {"model": "vit_tiny", "n_experts": 8, "batch_size": 128,
                   "learning_rate": 0.1, "capacity_factor": 2.0},
    }
    out = os.path.join(REPO, "experiments", "results", "calibrated",
                       os.path.basename(args.out))

    def save():
        with open(out, "w") as f:
            json.dump(record, f, indent=2, default=float)
            f.write("\n")

    # Validate the output path BEFORE the first ~40-minute cell: a bad
    # --out must fail in seconds, not after the training finishes.
    save()
    # Save after EVERY cell: a crash in a later cell must not lose a
    # 40-minute run (it did once).
    record["balanced_aux_0.01"] = run(0.01, args.epochs, ds)
    save()
    if not args.skip_dense:
        # Matched-recipe dense arm: same optimizer/lr/batch/steps; wall
        # clock reported separately (the MoE pays all_to_all + routing).
        record["dense_reference"] = run_dense(args.epochs, ds)
        save()
        moe_acc = record["balanced_aux_0.01"].get("final_test_accuracy")
        dense_acc = record["dense_reference"]["final_test_accuracy"]
        record["moe_vs_dense"] = {
            "matched": "registry shape, dtype, optimizer, lr, global "
                       "batch, batch-order seed, step budget, dataset",
            "moe_final_acc": moe_acc, "dense_final_acc": dense_acc,
            "moe_beats_or_matches_dense":
                (moe_acc is not None and dense_acc is not None
                 and float(moe_acc) >= float(dense_acc) - 0.005),
            "moe_wall_seconds":
                record["balanced_aux_0.01"].get("wall_seconds"),
            "dense_wall_seconds":
                record["dense_reference"]["wall_seconds"],
        }
        save()
    n_contrast = (args.contrast_epochs if args.contrast_epochs is not None
                  else args.epochs)
    if n_contrast > 0:
        record["contrast_aux_0"] = run(0.0, n_contrast, ds)
        save()
    print(f"wrote {out}")
    print("balanced per-epoch routing:",
          record["balanced_aux_0.01"]["per_epoch_routing"])
    if "moe_vs_dense" in record:
        print("moe vs dense:", record["moe_vs_dense"])
    if "contrast_aux_0" in record:
        print("contrast (aux off) routing:",
              record["contrast_aux_0"]["per_epoch_routing"])
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
