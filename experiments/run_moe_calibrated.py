"""Calibrated MoE run: Switch-MoE ViT with balanced routing, recorded.

Round-4 VERDICT item 3 'done' bar: a committed ``calibrated/`` MoE run
demonstrating balanced routing. Trains ``--mode moe`` (8 experts,
registry vit_tiny, Switch aux loss at the default weight) on the
calibrated compositional dataset, plus a short aux-weight=0 contrast run,
and records per-epoch expert-load imbalance + drop rate.

The MoE trainer needs one device per expert; this host has ONE TPU chip,
so the run uses the 8-device virtual CPU mesh (same collectives, honest
provenance in the record — the on-chip story for EP is the driver's
``dryrun_multichip``).

Run:  python experiments/run_moe_calibrated.py [--epochs N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                 os.path.join(REPO, ".jax_cache")))

import numpy as np  # noqa: E402


def run(aux_weight: float, epochs: int, ds) -> dict:
    from distributed_parameter_server_for_ml_training_tpu.train.model_parallel import (
        ModelParallelConfig, MoETrainer)

    cfg = ModelParallelConfig(
        model="vit_tiny", num_workers=8, num_epochs=epochs, batch_size=128,
        augment=False, num_classes=100, learning_rate=0.1,
        moe_aux_weight=aux_weight)
    trainer = MoETrainer(ds, cfg)
    t0 = time.time()
    metrics = trainer.train()
    metrics["wall_seconds"] = round(time.time() - t0, 1)

    # Per-epoch routing health from the per-step metric stream.
    steps = len(trainer._moe_step_metrics)
    spe = max(1, steps // epochs)
    per_epoch = []
    for e in range(epochs):
        chunk = trainer._moe_step_metrics[e * spe:(e + 1) * spe]
        if not chunk:
            break
        per_epoch.append({
            "epoch": e + 1,
            "load_imbalance": round(float(np.mean(
                [float(m["moe_load_imbalance"]) for m in chunk])), 3),
            "drop_frac": round(float(np.mean(
                [float(m["moe_drop_frac"]) for m in chunk])), 4),
            "aux_loss": round(float(np.mean(
                [float(m["moe_aux_loss"]) for m in chunk])), 4),
        })
    metrics["per_epoch_routing"] = per_epoch
    return metrics


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--contrast-epochs", type=int, default=2,
                    help="aux-weight=0 contrast run length")
    ap.add_argument("--train-size", type=int, default=8192,
                    help="subset of the calibrated dataset (CPU-mesh host)")
    args = ap.parse_args()

    from distributed_parameter_server_for_ml_training_tpu.data.cifar import (
        compositional_cifar100)

    ds = compositional_cifar100(n_train=args.train_size, n_test=2048)
    record = {
        "experiment_name": "moe_8experts",
        "dataset": {"generator": "compositional_cifar100",
                    "synthetic": True, "n_train": args.train_size,
                    "n_test": 2048},
        "provenance": ("8-device virtual CPU mesh "
                       "(xla_force_host_platform_device_count; the single "
                       "attached TPU chip cannot host 8 experts)"),
        "config": {"model": "vit_tiny", "n_experts": 8, "batch_size": 128,
                   "learning_rate": 0.1, "capacity_factor": 2.0},
    }
    out = os.path.join(REPO, "experiments", "results", "calibrated",
                       "moe_8experts.json")

    def save():
        with open(out, "w") as f:
            json.dump(record, f, indent=2, default=float)
            f.write("\n")

    # Save after EVERY cell: a crash in a later cell must not lose a
    # 40-minute run (it did once).
    record["balanced_aux_0.01"] = run(0.01, args.epochs, ds)
    save()
    record["contrast_aux_0"] = run(0.0, args.contrast_epochs, ds)
    save()
    print(f"wrote {out}")
    print("balanced per-epoch routing:",
          record["balanced_aux_0.01"]["per_epoch_routing"])
    print("contrast (aux off) routing:",
          record["contrast_aux_0"]["per_epoch_routing"])
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
