"""Recorded self-healing chaos demo (ISSUE 7 acceptance evidence).

Two cells under ``experiments/results/selfheal/``, every check
exit-code-verified (the PR 4-6 recorded-demo format):

**Cell A — quorum round semantics, in-process and deterministic.** A sync
store with ``sync_quorum=2`` of 3 and a 0.5 s round deadline, driven
through the real ``ParameterService`` byte path with push tokens: two fast
pushers close every round by quorum in milliseconds while the third never
shows up on time; its late pushes (stale basis) reconcile through the
async staleness semantics; a round with only ONE on-time push is closed by
the deadline timer within bounded wall time. The push-token journal
verifies every push applied **at most once** (no double-apply), and
``global_step`` equals rounds + accepted late applies exactly.

**Cell B — the self-healing soak, real processes over gRPC.** Three
serve + ``cli supervise`` (3 worker subprocess) scenarios with identical
topology (sync, quorum 2/3, 2 s round deadline, elastic membership):

- **control**: no faults — the clean convergence reference;
- **selfheal**: an injected **kill** (client-side ``push.kill@n=3`` on
  slot 0's first spawn; the supervisor respawns it clean), a
  **straggler** (``compute.delay_compute`` on slot 1), and a **NaN**
  burst (``DPS_NAN_STEP`` on slot 2) — with ``--remediate`` on the
  server and respawn on the supervisor;
- **norem**: the SAME faults with remediation off and respawn off — the
  degradation control.

Checks: the supervisor's ``dps_remediation_actions_total{action="respawn",
outcome="ok"}`` goes positive and the ``dead_worker`` alert FIRES then
RESOLVES (elastic slot reuse brings the replacement back under the dead
session's id); the NaN worker's poisoned push is refused
(``dps_service_quarantined_pushes_total`` > 0) and the quarantine action
is recorded; quorum/deadline round completions and staleness-reconciled
late pushes show up in the server's counters; the self-healing run
converges within tolerance of the fault-free control while the
no-remediation control degrades (the applied NaN collapses its accuracy).

Artifacts: ``selfheal_demo.json`` (summary + PASS/FAIL checks),
``quorum_bench.json``, per-scenario ``<name>_server_log.txt`` /
``<name>_supervise_log.txt`` / ``<name>_cluster.json`` /
``<name>_status.txt`` / ``<name>_alert_timeline.json``.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT_DIR = os.path.join(REPO, "experiments", "results", "selfheal")
PKG = "distributed_parameter_server_for_ml_training_tpu"
sys.path.insert(0, REPO)

QUORUM_DEADLINE_A = 0.5    # cell A round deadline (seconds)
ROUND_DEADLINE_B = 2.0     # cell B serve --round-deadline
SCENARIO_TIMEOUT = 900.0


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env(**extra) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _http(url: str, timeout: float = 5.0) -> str | None:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.read().decode()
    except Exception:
        return None


def _cluster(port: int) -> dict | None:
    raw = _http(f"http://127.0.0.1:{port}/cluster")
    if raw is None:
        return None
    try:
        return json.loads(raw)
    except ValueError:
        return None


def _metric_value(metrics_text: str | None, name: str,
                  labels: str = "") -> float | None:
    """Read one series from Prometheus text (labels rendered sorted)."""
    if not metrics_text:
        return None
    pat = re.compile(rf"^{re.escape(name + labels)} ([0-9.e+-]+)$", re.M)
    m = pat.search(metrics_text)
    return float(m.group(1)) if m else None


# ---------------------------------------------------------------------------
# Cell A: quorum rounds, deterministic in-process bench
# ---------------------------------------------------------------------------

def quorum_round_bench() -> tuple[dict, dict]:
    import numpy as np

    from distributed_parameter_server_for_ml_training_tpu.comms.service \
        import ParameterService, pack_msg, unpack_msg
    from distributed_parameter_server_for_ml_training_tpu.comms.wire \
        import encode_tensor_dict
    from distributed_parameter_server_for_ml_training_tpu.ps.store import (
        ParameterStore, StoreConfig)
    from distributed_parameter_server_for_ml_training_tpu.telemetry import (
        get_registry)

    store = ParameterStore(
        {"w": np.zeros(4096, np.float32)},
        StoreConfig(mode="sync", total_workers=3, sync_quorum=2,
                    round_deadline=QUORUM_DEADLINE_A, push_codec="none",
                    learning_rate=0.01))
    svc = ParameterService(store)
    wids = []
    for i in range(3):
        reply, _ = unpack_msg(svc.register_worker(
            pack_msg({"worker_name": f"bench-{i}",
                      "capabilities": ["directives"]}), None))
        wids.append(reply["worker_id"])
    grad = encode_tensor_dict({"w": np.ones(4096, np.float32)})

    def push(wid, basis, token):
        reply, _ = unpack_msg(svc.push_gradrients(
            pack_msg({"worker_id": wid, "fetched_step": basis,
                      "push_token": token}, grad), None))
        return bool(reply["accepted"])

    rounds = 6
    quorum_walls, late_accepted, pushes = [], 0, 0
    for r in range(rounds):
        basis = store.global_step
        t0 = time.perf_counter()
        for w in (0, 1):  # the two fast workers close the round by quorum
            pushes += 1
            push(wids[w], basis, f"fastw{w}r{r}:1")
        assert store.global_step == basis + 1, "quorum did not close round"
        quorum_walls.append(time.perf_counter() - t0)
        # The straggler arrives AFTER its round closed: stale basis ->
        # the late push reconciles via the async staleness path.
        pushes += 1
        if push(wids[2], basis, f"stragr{r}:1"):
            late_accepted += 1

    # Deadline round: only ONE on-time push; the timer must close it.
    basis = store.global_step
    t0 = time.perf_counter()
    pushes += 1
    push(wids[0], basis, "deadline-solo:1")
    deadline_cap = time.time() + 10.0
    while store.global_step == basis and time.time() < deadline_cap:
        time.sleep(0.01)
    deadline_wall = time.perf_counter() - t0

    reg = get_registry()
    late_counter = reg.counter("dps_store_late_pushes_total",
                               backend="python").value
    trig_quorum = reg.counter("dps_store_round_completions_total",
                              backend="python", trigger="quorum").value
    trig_deadline = reg.counter("dps_store_round_completions_total",
                                backend="python", trigger="deadline").value
    journal = svc.journal_snapshot()
    expected_step = rounds + late_accepted + 1  # + the deadline round

    record = {
        "config": {"total_workers": 3, "sync_quorum": 2,
                   "round_deadline_s": QUORUM_DEADLINE_A},
        "rounds": rounds,
        "quorum_round_walls_s": [round(w, 4) for w in quorum_walls],
        "max_quorum_round_wall_s": round(max(quorum_walls), 4),
        "deadline_round_wall_s": round(deadline_wall, 4),
        "late_pushes_sent": rounds,
        "late_pushes_accepted": late_accepted,
        "late_counter": late_counter,
        "round_completions": {"quorum": trig_quorum,
                              "deadline": trig_deadline},
        "pushes_total": pushes,
        "journal_entries": len(journal),
        "global_step": store.global_step,
        "expected_step": expected_step,
        "parameter_updates": store.stats.total_parameter_updates,
        "last_trigger": store.round_status()["last_trigger"],
    }
    checks = {
        # one injected straggler cannot stall the round: quorum closes it
        # in milliseconds, far inside the deadline
        "A_quorum_rounds_bounded":
            max(quorum_walls) < QUORUM_DEADLINE_A,
        # a round the quorum can't close is closed by the deadline timer
        # within bounded wall time
        "A_deadline_round_bounded":
            QUORUM_DEADLINE_A * 0.5 <= deadline_wall
            <= QUORUM_DEADLINE_A + 2.0,
        "A_deadline_trigger_counted": trig_deadline >= 1,
        "A_quorum_trigger_counted": trig_quorum >= rounds,
        # every late push reconciled via the staleness path (weighted
        # apply), none stashed into a later round
        "A_late_pushes_via_staleness":
            late_counter == rounds and late_accepted == rounds,
        # journal-verified exactly-once: every push recorded once, and the
        # step advanced exactly rounds + late applies (+ deadline round) —
        # a double apply would overshoot
        "A_no_double_apply_journal_verified":
            len(journal) == pushes
            and store.global_step == expected_step
            and store.stats.total_parameter_updates == expected_step,
    }
    return record, checks


# ---------------------------------------------------------------------------
# Cell B: serve + supervise soak scenarios
# ---------------------------------------------------------------------------

def _run_status(port: int) -> tuple[int | None, str]:
    try:
        p = subprocess.run(
            [sys.executable, "-m", f"{PKG}.cli", "status",
             "--metrics-port", str(port)],
            capture_output=True, text=True, env=_env(), cwd=REPO,
            timeout=60)
        return p.returncode, p.stdout + p.stderr
    except subprocess.TimeoutExpired:
        return None, "status timed out"


def _scenario(name: str, *, faults: bool, remediate: bool,
              respawn: bool) -> dict:
    grpc_port, metrics_port, sup_port = (_free_port(), _free_port(),
                                         _free_port())
    server_log_path = os.path.join(OUT_DIR, f"{name}_server_log.txt")
    sup_log_path = os.path.join(OUT_DIR, f"{name}_supervise_log.txt")
    server_log = open(server_log_path, "w")
    sup_log = open(sup_log_path, "w")

    serve_argv = [
        sys.executable, "-m", f"{PKG}.cli", "serve",
        "--mode", "sync", "--workers", "3", "--port", str(grpc_port),
        "--model", "vit_tiny", "--num-classes", "100",
        "--image-size", "32", "--platform", "cpu",
        "--sync-quorum", "2", "--round-deadline", str(ROUND_DEADLINE_B),
        "--elastic", "--worker-timeout", "3",
        "--dead-after", "4", "--health-interval", "0.5",
        "--straggler-lag", "8",
        "--telemetry", "--telemetry-interval", "1",
        "--metrics-port", str(metrics_port), "--emit-metrics",
    ]
    if remediate:
        serve_argv += ["--remediate", "--remediation-cooldown", "4",
                       "--quarantine-secs", "4"]
    server = subprocess.Popen(serve_argv, stdout=server_log,
                              stderr=subprocess.STDOUT, env=_env(),
                              cwd=REPO)
    deadline = time.time() + 120
    while _cluster(metrics_port) is None:
        if time.time() > deadline or server.poll() is not None:
            raise RuntimeError(f"{name}: server never came up")
        time.sleep(0.25)

    sup_argv = [
        sys.executable, "-m", f"{PKG}.cli", "supervise",
        "--workers", "3",
        # backoff > worker-timeout: the dead session's slot is expired
        # (and freed) BEFORE the replacement registers, so elastic reuse
        # hands it the same id and the dead_worker alert can resolve
        "--respawn-backoff", "5", "--respawn-backoff-max", "10",
        "--healthy-after", "3", "--crash-loop-after", "3",
        "--metrics-port", str(sup_port), "--platform", "cpu",
    ]
    if not respawn:
        sup_argv += ["--no-respawn"]
    if faults:
        sup_argv += [
            "--slot-faults", "0:seed=7;push.kill@n=3",
            "--slot-faults", "1:compute.delay_compute=0.3@every=1",
            "--slot-env", "2:DPS_NAN_STEP=6",
        ]
    sup_argv += [
        "--",
        "--server", f"localhost:{grpc_port}",
        "--model", "vit_tiny", "--synthetic",
        "--num-train", "1500", "--num-test", "96",
        "--epochs", "3", "--batch-size", "32",
        "--dtype", "float32", "--no-augment",
        "--heartbeat", "0.5", "--reconnect-timeout", "20",
        "--emit-metrics",
    ]
    sup = subprocess.Popen(sup_argv, stdout=sup_log,
                           stderr=subprocess.STDOUT, env=_env(), cwd=REPO)

    # Poll the live surfaces for the whole run: the evidence (alert
    # edges, remediation actions, counters) is captured MID-RUN.
    alert_rules_seen: dict[str, dict] = {}
    dead_worker_seen = dead_worker_resolved_after = False
    remediation_actions: dict[str, str] = {}
    last_view: dict | None = None
    last_server_metrics: str | None = None
    last_sup_metrics: str | None = None
    status_during: tuple[int | None, str] | None = None
    views = 0
    deadline = time.time() + SCENARIO_TIMEOUT
    while time.time() < deadline:
        view = _cluster(metrics_port)
        if view is not None:
            views += 1
            last_view = view
            active_rules = {a["rule"] for a in view.get("alerts", [])}
            for a in view.get("alerts", []):
                alert_rules_seen.setdefault(a["rule"], a)
            if "dead_worker" in active_rules:
                dead_worker_seen = True
                if status_during is None:
                    status_during = _run_status(metrics_port)
            elif dead_worker_seen:
                dead_worker_resolved_after = True
            for r in (view.get("remediation") or {}).get("recent", []):
                remediation_actions.setdefault(
                    f"{r['action']}:{r['worker']}", r["outcome"])
        m = _http(f"http://127.0.0.1:{metrics_port}/metrics",
                  timeout=3.0)
        if m:
            last_server_metrics = m
        sm = _http(f"http://127.0.0.1:{sup_port}/metrics", timeout=3.0)
        if sm:
            last_sup_metrics = sm
        if sup.poll() is not None and server.poll() is not None:
            break
        if sup.poll() is not None and status_during is None \
                and server.poll() is None:
            # workers done, server still draining: last status capture
            status_during = _run_status(metrics_port)
        time.sleep(0.3)

    try:
        sup.wait(timeout=60)
    except subprocess.TimeoutExpired:
        sup.terminate()
        try:
            sup.wait(timeout=30)
        except subprocess.TimeoutExpired:
            sup.kill()
    try:
        server.wait(timeout=120)
    except subprocess.TimeoutExpired:
        server.terminate()
        try:
            server.wait(timeout=30)
        except subprocess.TimeoutExpired:
            server.kill()
    server_log.close()
    sup_log.close()

    # Per-worker final accuracies from the workers' METRICS_JSON exit
    # lines (children share the supervise log).
    from distributed_parameter_server_for_ml_training_tpu.utils.metrics \
        import parse_metrics_lines
    sup_text = open(sup_log_path).read()
    accuracies = {}
    for rec in parse_metrics_lines(sup_text):
        if "final_test_accuracy" in rec:
            accuracies[rec.get("worker_name", "?")] = \
                rec["final_test_accuracy"]

    # Alert timeline from the server's "kind": "cluster" stream records.
    from distributed_parameter_server_for_ml_training_tpu.analysis import (
        alert_timeline)
    server_text = open(server_log_path).read()
    timeline = alert_timeline(server_text)
    with open(os.path.join(OUT_DIR, f"{name}_alert_timeline.json"),
              "w") as f:
        json.dump(timeline, f, indent=2)
    # The server log carries a 0.5 s-interval "kind": "cluster" stream —
    # megabytes of repetitive JSON. Keep it, compressed (the timeline
    # above is the extracted form).
    import gzip
    with gzip.open(server_log_path + ".gz", "wt") as f:
        f.write(server_text)
    os.remove(server_log_path)
    server_log_path += ".gz"
    with open(os.path.join(OUT_DIR, f"{name}_cluster.json"), "w") as f:
        json.dump(last_view or {}, f, indent=2)
    if status_during is not None:
        with open(os.path.join(OUT_DIR, f"{name}_status.txt"), "w") as f:
            f.write(f"# cli status exit code: {status_during[0]}\n\n"
                    f"{status_during[1]}")

    edges = {(e["rule"], e["state"]) for e in timeline}
    return {
        "name": name,
        "faults": faults, "remediate": remediate, "respawn": respawn,
        "grpc_port": grpc_port, "metrics_port": metrics_port,
        "server_rc": server.returncode, "supervisor_rc": sup.returncode,
        "views_captured": views,
        "alert_rules_seen": sorted(alert_rules_seen),
        "dead_worker_seen_live": dead_worker_seen,
        "dead_worker_resolved_live": dead_worker_resolved_after,
        "dead_worker_fired_edge": ("dead_worker", "fired") in edges,
        "dead_worker_resolved_edge": ("dead_worker", "resolved") in edges,
        "remediation_actions": remediation_actions,
        "status_during_fault_rc": (status_during or (None, ""))[0],
        "final_accuracies": accuracies,
        "metrics": {
            "respawn_ok": _metric_value(
                last_sup_metrics, "dps_remediation_actions_total",
                '{action="respawn",outcome="ok"}'),
            "quarantined_pushes": _metric_value(
                last_server_metrics,
                "dps_service_quarantined_pushes_total"),
            "round_quorum": _metric_value(
                last_server_metrics, "dps_store_round_completions_total",
                '{backend="python",trigger="quorum"}'),
            "round_deadline": _metric_value(
                last_server_metrics, "dps_store_round_completions_total",
                '{backend="python",trigger="deadline"}'),
            "late_pushes": _metric_value(
                last_server_metrics, "dps_store_late_pushes_total",
                '{backend="python"}'),
            "alerts_dead_worker": _metric_value(
                last_server_metrics, "dps_alerts_total",
                '{rule="dead_worker",severity="critical"}'),
        },
        "logs": [os.path.relpath(server_log_path, REPO),
                 os.path.relpath(sup_log_path, REPO)],
    }


def main() -> int:
    os.makedirs(OUT_DIR, exist_ok=True)
    t0 = time.time()

    bench, checks = quorum_round_bench()
    with open(os.path.join(OUT_DIR, "quorum_bench.json"), "w") as f:
        json.dump(bench, f, indent=2)
    print(f"cell A (quorum bench): max quorum round "
          f"{bench['max_quorum_round_wall_s']}s, deadline round "
          f"{bench['deadline_round_wall_s']}s, "
          f"{bench['late_pushes_accepted']} late pushes via staleness",
          flush=True)

    control = _scenario("control", faults=False, remediate=True,
                        respawn=True)
    selfheal = _scenario("selfheal", faults=True, remediate=True,
                         respawn=True)
    norem = _scenario("norem", faults=True, remediate=False,
                      respawn=False)

    def best_acc(s):
        return max(s["final_accuracies"].values(), default=0.0)

    acc_control, acc_selfheal, acc_norem = (best_acc(control),
                                            best_acc(selfheal),
                                            best_acc(norem))
    m = selfheal["metrics"]
    checks.update({
        # --- self-healing run ---
        "B_respawn_counter_positive": (m["respawn_ok"] or 0) > 0,
        "B_supervisor_clean_exit": selfheal["supervisor_rc"] == 0,
        "B_dead_worker_fired":
            selfheal["dead_worker_fired_edge"]
            or selfheal["dead_worker_seen_live"],
        "B_dead_worker_resolved":
            selfheal["dead_worker_resolved_edge"]
            or selfheal["dead_worker_resolved_live"],
        "B_nonfinite_alert_fired": any(
            r.startswith("nonfinite")
            for r in selfheal["alert_rules_seen"]),
        "B_quarantine_action_recorded": any(
            k.startswith("quarantine:")
            for k in selfheal["remediation_actions"]),
        "B_nan_push_refused": (m["quarantined_pushes"] or 0) > 0,
        "B_quorum_rounds_completed": (m["round_quorum"] or 0) > 0,
        "B_straggler_late_pushes_reconciled": (m["late_pushes"] or 0) > 0,
        "B_status_nonzero_during_fault":
            selfheal["status_during_fault_rc"] in (2, 3),
        # --- convergence triangle ---
        "B_all_three_slots_finished_selfheal":
            len(selfheal["final_accuracies"]) >= 3,
        "B_selfheal_converges_near_control":
            acc_selfheal >= acc_control - 0.15,
        "B_norem_degrades":
            acc_norem < acc_control - 0.2 and acc_norem < acc_selfheal,
        # --- control hygiene ---
        "B_control_no_critical_alerts": not any(
            r in ("dead_worker", "nonfinite_loss", "nonfinite_grad")
            for r in control["alert_rules_seen"]),
        "B_control_supervisor_clean": control["supervisor_rc"] == 0,
    })

    record = {
        "demo": "self-healing cluster (ISSUE 7)",
        "elapsed_seconds": round(time.time() - t0, 1),
        "checks": checks,
        "all_pass": all(checks.values()),
        "quorum_bench": bench,
        "scenarios": {"control": control, "selfheal": selfheal,
                      "norem": norem},
        "final_accuracies": {"control": acc_control,
                             "selfheal": acc_selfheal,
                             "norem": acc_norem},
    }
    with open(os.path.join(OUT_DIR, "selfheal_demo.json"), "w") as f:
        json.dump(record, f, indent=2)
    n_pass = sum(bool(v) for v in checks.values())
    print(f"selfheal demo: {n_pass}/{len(checks)} checks PASS "
          f"({record['elapsed_seconds']}s; acc control={acc_control:.4f} "
          f"selfheal={acc_selfheal:.4f} norem={acc_norem:.4f})")
    for name, ok in checks.items():
        print(f"  [{'PASS' if ok else 'FAIL'}] {name}")
    return 0 if record["all_pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
