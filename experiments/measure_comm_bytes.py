"""Record the sync-DP compression modes' wire-byte model and loss parity.

Round-4 VERDICT item 2 evidence: per-device ICI bytes of the gradient
all-reduce for compression none / bf16 / int8 across mesh sizes, measured
from the compiled HLO's collective ops (utils/hlo_bytes.py), plus a short
sync training run per mode on the calibrated dataset showing loss-curve
parity. Runs on the virtual CPU mesh (collectives are emitted identically;
on-chip byte counts follow the same HLO) and writes
experiments/results/comm_bytes.json + a markdown table for PERF.md.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

RESNET18_PARAMS = 11_220_132    # models/resnet.py, asserted in tests


def wire_bytes_table() -> list[dict]:
    from distributed_parameter_server_for_ml_training_tpu.utils.hlo_bytes import (
        sync_grad_mean_bytes)

    rows = []
    for n in (2, 4, 8):
        stats = sync_grad_mean_bytes(n, RESNET18_PARAMS)
        row = {"n_devices": n}
        for name in ("none", "bf16", "int8"):
            row[f"{name}_mb"] = round(stats[name]["total"] / 1e6, 3)
        if stats["bf16"].get("widened_on_cpu"):
            row["bf16_widened_on_cpu"] = True
        # round-3 formulation for comparison: all_gather of int8 values
        # (N x S x 1B per device via the (N-1)/N gather factor)
        row["int8_r3_allgather_mb"] = round(
            (n - 1) / n * n * RESNET18_PARAMS / 1e6, 3)
        row["int8_vs_bf16"] = round(row["int8_mb"] / row["bf16_mb"], 3)
        rows.append(row)
        print(rows[-1], flush=True)
    return rows


def loss_parity(epochs: int = 4) -> dict:
    """Short sync runs (4 workers) per compression mode on the calibrated
    dataset: final losses must sit within a few percent of 'none'."""
    from distributed_parameter_server_for_ml_training_tpu.data import (
        make_batches, synthetic_cifar100)
    from distributed_parameter_server_for_ml_training_tpu.models import ResNet
    from distributed_parameter_server_for_ml_training_tpu.parallel import (
        make_mesh, make_sync_dp_step, shard_batch)
    from distributed_parameter_server_for_ml_training_tpu.train import (
        create_train_state, server_sgd)

    mesh = make_mesh(4)
    d = synthetic_cifar100(n_train=2048, n_test=256, num_classes=100,
                           seed=3)
    model = ResNet(stage_sizes=(1, 1), num_filters=16, num_classes=100,
                   axis_name="data")
    curves = {}
    for comp in ("none", "bf16", "int8"):
        step = make_sync_dp_step(mesh, compression=comp, augment=False)
        st = create_train_state(model, jax.random.PRNGKey(0),
                                server_sgd(0.1))
        losses = []
        for epoch in range(epochs):
            ep = []
            for xb, yb in make_batches(d.x_train, d.y_train, 256,
                                       seed=epoch):
                sb = shard_batch(mesh, (xb, yb))
                st, m = step(st, sb[0], sb[1], jax.random.PRNGKey(epoch))
                ep.append(float(m["loss"]))
            losses.append(round(float(np.mean(ep)), 4))
        curves[comp] = losses
        print(f"loss curve {comp}: {losses}", flush=True)
    return curves


def main() -> int:
    out = {"wire_bytes_resnet18_grad": wire_bytes_table(),
           "loss_curves_sync4": loss_parity(),
           "model": ("per-device ICI bytes: none = 2(N-1)/N*4S, "
                     "bf16 = 2(N-1)/N*2S, int8 ring = 2(N-1)/N*S "
                     "(+scales/padding); round-3 int8 all_gather was "
                     "(N-1)*S - O(N) and above bf16 from N=4")}
    path = os.path.join(REPO, "experiments", "results", "comm_bytes.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(f"wrote {path}")
    print("\n| N | none MB | bf16 MB | int8 ring MB | int8 r3 gather MB "
          "| int8/bf16 |")
    print("|---|---|---|---|---|---|")
    for r in out["wire_bytes_resnet18_grad"]:
        print(f"| {r['n_devices']} | {r['none_mb']} | {r['bf16_mb']} | "
              f"{r['int8_mb']} | {r['int8_r3_allgather_mb']} | "
              f"{r['int8_vs_bf16']} |")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
