"""16-worker wire-matrix scale probe (VERDICT.md "What's missing" #3).

The reference records 16-worker tables and caps registration at 32
(README.md:454-464, server.py:424-426); our recorded wire matrix stops at
``async_8w``. This probe launches one ``cli serve --mode async --workers 16``
plus 16 real ``cli worker`` OS processes on THIS host and records — honestly,
either way — whether the host can actually run the 16-worker cell:

- completed/failed/timed-out worker counts and the wall clock,
- per-worker wire byte counters (the telemetry-PR byte evidence: every
  worker's METRICS_JSON row carries ``wire_bytes_out/in`` from
  RemoteStore's counters, and the serve process's snapshot stream carries
  ``dps_rpc_handler_bytes_total``),
- the host context (CPU count, load) that explains the result.

The outcome is merged into ``experiments/results/wire/wire_summary.json``
under ``"host_limits"`` — a measured record, not a silent stop at 8.

Usage::

    python experiments/probe_wire_scale.py [--workers 16] [--timeout 600]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
CLI = [sys.executable, "-m",
       "distributed_parameter_server_for_ml_training_tpu.cli"]
OUT = os.path.join(REPO, "experiments", "results", "wire")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env() -> dict:
    return dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1",
                JAX_COMPILATION_CACHE_DIR=os.path.join(REPO, ".jax_cache"))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=16)
    ap.add_argument("--timeout", type=float, default=600.0,
                    help="whole-probe wall budget; expiry IS a result")
    args = ap.parse_args()

    port = _free_port()
    t0 = time.time()
    # stdout -> FILES, not pipes: the serve process emits a multi-KB
    # snapshot line every 5 s for up to --timeout seconds; an undrained
    # 64 KB pipe would block the emitter mid-run and freeze the byte
    # evidence at whatever fit early. Files never block the writer.
    logdir = tempfile.mkdtemp(prefix="wire_scale_probe_")
    s_log = open(os.path.join(logdir, "server.log"), "w+b")
    server = subprocess.Popen(
        CLI + ["serve", "--mode", "async", "--workers", str(args.workers),
               "--port", str(port), "--model", "vit_tiny",
               "--num-classes", "100", "--image-size", "32",
               "--platform", "cpu", "--emit-metrics",
               "--telemetry", "--telemetry-interval", "5"],
        cwd=REPO, env=_env(),
        stdout=s_log, stderr=subprocess.STDOUT)

    workers = []
    w_logs = []
    for i in range(args.workers):
        w_log = open(os.path.join(logdir, f"worker{i}.log"), "w+b")
        w_logs.append(w_log)
        workers.append(subprocess.Popen(
            CLI + ["worker", "--server", f"localhost:{port}",
                   "--worker-name", f"scale-w{i}", "--model", "vit_tiny",
                   "--synthetic", "--num-train", str(32 * args.workers),
                   "--num-test", "32", "--epochs", "1",
                   "--batch-size", "32", "--platform", "cpu",
                   "--dtype", "float32", "--no-augment", "--emit-metrics"],
            cwd=REPO, env=_env(),
            stdout=w_log, stderr=subprocess.STDOUT))

    deadline = t0 + args.timeout
    completed, failed, timed_out = [], [], []
    w_rows = []
    def _read_log(f) -> str:
        f.flush()
        f.seek(0)
        return f.read().decode(errors="replace")

    for i, w in enumerate(workers):
        budget = max(1.0, deadline - time.time())
        try:
            w.wait(timeout=budget)
        except subprocess.TimeoutExpired:
            w.kill()
            w.wait()
            timed_out.append(i)
            continue
        text = _read_log(w_logs[i])
        from distributed_parameter_server_for_ml_training_tpu.utils.metrics import (  # noqa: E501
            parse_metrics_lines)
        rows = [m for m in parse_metrics_lines(text)
                if "worker_id" in m and m.get("kind") != "snapshot"]
        if w.returncode == 0 and rows:
            completed.append(i)
            w_rows.append(rows[-1])
        else:
            failed.append({"worker": i, "rc": w.returncode,
                           "tail": text.strip().splitlines()[-3:]})
    wall = time.time() - t0

    try:
        server.wait(timeout=60)
    except subprocess.TimeoutExpired:
        server.kill()
        server.wait()
    s_text = _read_log(s_log)
    s_log.close()
    for f in w_logs:
        f.close()
    from distributed_parameter_server_for_ml_training_tpu.utils.metrics import (
        parse_metrics_lines)
    server_rows = [m for m in parse_metrics_lines(s_text)
                   if m.get("kind") != "snapshot" and "mode" in m]
    snapshots = [m for m in parse_metrics_lines(s_text)
                 if m.get("kind") == "snapshot"]
    handler_bytes = {}
    if snapshots:
        handler_bytes = {
            k: v for k, v in snapshots[-1].get("counters", {}).items()
            if k.startswith("dps_rpc_handler_bytes_total")}

    ok = len(completed) == args.workers
    record = {
        "probe": f"async_{args.workers}w_scale",
        "date_host": {"cpu_count": os.cpu_count(),
                      "loadavg_end": os.getloadavg()},
        "can_run": ok,
        "workers_requested": args.workers,
        "workers_completed": len(completed),
        "workers_failed": failed,
        "workers_timed_out": timed_out,
        "wall_seconds": round(wall, 1),
        "timeout_budget_seconds": args.timeout,
        "byte_evidence": {
            "per_worker_wire_bytes_out": [r.get("wire_bytes_out")
                                          for r in w_rows],
            "per_worker_wire_bytes_in": [r.get("wire_bytes_in")
                                         for r in w_rows],
            "server_handler_bytes_final_snapshot": handler_bytes,
        },
        "server_metrics": server_rows[-1] if server_rows else {},
    }
    path = os.path.join(OUT, f"scale_probe_{args.workers}w.json")
    with open(path, "w") as f:
        json.dump(record, f, indent=2)
    print(json.dumps({k: record[k] for k in
                      ["can_run", "workers_completed", "workers_timed_out",
                       "wall_seconds"]}))
    print(f"probe record -> {path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
