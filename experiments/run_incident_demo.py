"""Recorded incident-forensics demo (ISSUE 18 acceptance evidence).

One journaled cluster — a primary with a seeded latency fault, a
supervised training worker, and a standalone ``cli observe`` collector —
all streaming typed events into ONE durable journal directory. The demo
then destroys the coordinator with SIGKILL and proves the postmortem
story can be reconstructed **from disk alone**:

**Phase A — journaled boot.** ``cli serve`` starts with ``--journal-dir``
+ ``--incidents-dir`` + ``--remediate`` and a seeded
``fetch.delay=0.12@p=0.8`` fault (journaled as the root-cause ``fault``
record at arm time). ``cli supervise`` babysits two training workers (two, so a kill never
empties the membership — an all-expired store reads as training
complete and exits the server);
``cli observe`` journals every fleet tick into the same directory.

**Phase B — breach and black-box capture.** A loadgen window pushes
fetch p99 over the 100 ms objective: the server-scope ``slo_burn_fast``
critical alert fires and the incident engine freezes a bundle into
``incidents/<id>/`` with no operator involved.

**Phase C — self-healing arc.** One of the two worker processes is
SIGKILLed:
``dead_worker`` fires (second bundle, distinct rule), the remediation
engine requests a respawn, the supervisor executes it (journaling the
``respawn`` record), and the rejoined worker resolves the alert — the
journal now holds a complete fault -> alert -> remediation -> resolution
arc across three processes.

**Phase D — storm dedupe.** The replacement worker is killed again
inside the incident cooldown: the new ``dead_worker`` edge must be
SUPPRESSED (one bundle per rule per cooldown,
``dps_incidents_suppressed_total`` counts the refire).

**Phase E — coordinator destroyed.** The primary dies by SIGKILL —
no flush, no sealing, a torn journal tail is fair game. Every other
process exits too.

**Phase F — forensics from disk alone.** With nothing left running:
``cli incident report --json`` rebuilds the ordered causal timeline
(all four phases, >= 2 distinct process roles); ``cli query --slo``
re-runs the burn evaluation over journal history and must agree with
the live breach verdict (exit code 2); ``cli top --replay`` renders the
final recorded frame; journal write overhead (measured per-append cost
x observed record rate) must stay under 2% of one core.

Artifacts: ``incident_demo.json`` (summary + PASS/FAIL checks), the
incident bundles, the journal directory snapshot stats, ``/cluster`` /
``/fleet`` captures, the rendered timeline, and process logs.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import signal
import statistics
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT_DIR = os.path.join(REPO, "experiments", "results", "incidents")
PKG = "distributed_parameter_server_for_ml_training_tpu"
sys.path.insert(0, REPO)

MODEL = "vit_tiny"
FAULT_SPEC = "fetch.delay=0.12@p=0.8"
SPAWN_RE = re.compile(r"SUPERVISOR_SPAWN slot=0 attempt=(\d+) pid=(\d+)")


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env(**extra) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONUNBUFFERED"] = "1"
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _http(url: str, timeout: float = 5.0) -> str | None:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.read().decode()
    except Exception:
        return None


def _get_json(url: str, timeout: float = 5.0) -> dict | None:
    raw = _http(url, timeout)
    if raw is None:
        return None
    try:
        return json.loads(raw)
    except ValueError:
        return None


def _spawn(argv: list, log_path: str, **env_extra):
    log = open(log_path, "a")
    proc = subprocess.Popen(argv, stdout=log, stderr=subprocess.STDOUT,
                            env=_env(**env_extra), cwd=REPO)
    return proc, log


def _stop(proc, log, grace: float = 15.0) -> int | None:
    if proc is not None and proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=grace)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=grace)
    if log is not None:
        log.close()
    return None if proc is None else proc.returncode


def _trim_log(path: str) -> None:
    """Strip the live ``METRICS_JSON`` stream from a recorded process
    log. The durable copy of every snapshot lives in the journal (that
    is the whole point of the demo) — re-committing megabytes of live
    lines beside it would bury the narrative SUPERVISOR_*/alert lines
    the postmortem reader actually greps."""
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        return
    kept = [ln for ln in lines if "METRICS_JSON:" not in ln]
    dropped = len(lines) - len(kept)
    if dropped:
        kept.append(f"[demo] trimmed {dropped} METRICS_JSON line(s); "
                    f"the durable copies are in journal/\n")
        with open(path, "w") as f:
            f.writelines(kept)


def _wait(pred, what: str, timeout: float = 120.0, poll: float = 0.25):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(poll)
    raise RuntimeError(f"timed out waiting for {what}")


def _loadgen(targets: list[str], duration: float,
             concurrency: int = 2) -> dict | None:
    cp = subprocess.run(
        [sys.executable, "-m", f"{PKG}.cli", "loadgen",
         "--targets", ",".join(targets), "--duration", str(duration),
         "--concurrency", str(concurrency), "--fetch-mode", "full"],
        capture_output=True, text=True, env=_env(), cwd=REPO,
        timeout=duration + 120)
    for line in cp.stdout.splitlines():
        if line.startswith("LOADGEN_JSON "):
            return json.loads(line[len("LOADGEN_JSON "):])
    return None


def _cli(argv: list, timeout: float = 120.0):
    cp = subprocess.run([sys.executable, "-m", f"{PKG}.cli"] + argv,
                        capture_output=True, text=True, env=_env(),
                        cwd=REPO, timeout=timeout)
    return cp.returncode, cp.stdout


def _worker_pid(sup_log_path: str, not_pid: int | None = None) -> int | None:
    """Latest slot-0 child pid from the supervisor's greppable spawn
    lines (the supervisor owns the child; /proc walking would race its
    respawn loop)."""
    try:
        text = open(sup_log_path).read()
    except OSError:
        return None
    pids = [int(m.group(2)) for m in SPAWN_RE.finditer(text)]
    if not_pid is not None:
        pids = [p for p in pids if p != not_pid]
    return pids[-1] if pids else None


def _active_rules(cluster: dict | None) -> set:
    return {a.get("rule") for a in (cluster or {}).get("alerts") or ()}


def _journal_overhead(journal_dir: str, elapsed_s: float,
                      payload: dict) -> dict:
    """Per-append cost (measured against a throwaway journal with the
    run's OWN snapshot payload) x the observed record rate."""
    from distributed_parameter_server_for_ml_training_tpu.telemetry \
        import JournalReader, JournalWriter, MetricsRegistry
    reader = JournalReader(journal_dir)
    reader.records()  # stats (incl. torn tails) fill during the read
    stats = reader.stats
    probe_dir = journal_dir + ".probe"
    w = JournalWriter(probe_dir, role="bench",
                      registry=MetricsRegistry())
    times = []
    try:
        for _ in range(300):
            t0 = time.perf_counter()
            w.append("snapshot", payload)
            times.append(time.perf_counter() - t0)
        w.seal()
    finally:
        shutil.rmtree(probe_dir, ignore_errors=True)
    per_write_s = statistics.median(times)
    rate = stats["records"] / max(1e-9, elapsed_s)
    return {
        "journal_stats": stats,
        "per_write_us": round(per_write_s * 1e6, 2),
        "records_per_s": round(rate, 3),
        "overhead_frac": rate * per_write_s,
    }


def main(argv=None) -> int:
    import argparse
    global OUT_DIR

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out-dir", default=OUT_DIR)
    args = ap.parse_args(argv)
    OUT_DIR = args.out_dir
    os.makedirs(OUT_DIR, exist_ok=True)
    quick = args.quick
    lg_s = 6.0 if quick else 10.0

    journal_dir = os.path.join(OUT_DIR, "journal")
    incidents_dir = os.path.join(OUT_DIR, "incidents")
    for d in (journal_dir, incidents_dir):
        shutil.rmtree(d, ignore_errors=True)

    t0 = time.time()
    checks: list[tuple[str, bool, str]] = []
    procs: list[tuple] = []
    sup = sup_log = None
    sup_log_path = os.path.join(OUT_DIR, "supervise.log")
    open(sup_log_path, "w").close()

    try:
        # -- phase A: journaled boot -----------------------------------------
        port, mport, fleet_port = (_free_port(), _free_port(),
                                   _free_port())
        primary, plog = _spawn(
            [sys.executable, "-m", f"{PKG}.cli", "serve",
             "--mode", "async", "--workers", "1",
             "--port", str(port), "--model", MODEL,
             "--num-classes", "100", "--image-size", "32",
             "--platform", "cpu", "--metrics-port", str(mport),
             "--health-interval", "0.5", "--elastic",
             "--worker-timeout", "4",
             "--telemetry", "--telemetry-interval", "0.5",
             "--journal-dir", journal_dir,
             "--incidents-dir", incidents_dir,
             "--incident-window", "900",
             "--incident-cooldown", "600",
             "--faults", FAULT_SPEC, "--remediate",
             "--trace", "--trace-buffer", "8192"],
            os.path.join(OUT_DIR, "primary.log"))
        procs.append((primary, plog))
        cluster_url = f"http://127.0.0.1:{mport}/cluster"
        _wait(lambda: _get_json(cluster_url), "the primary admin plane")

        obs, obs_log = _spawn(
            [sys.executable, "-m", f"{PKG}.cli", "observe",
             "--targets", f"127.0.0.1:{mport}",
             "--port", str(fleet_port),
             "--interval", "0.4", "--timeout", "1.0",
             "--journal-dir", journal_dir],
            os.path.join(OUT_DIR, "observe.log"))
        procs.append((obs, obs_log))
        fleet_url = f"http://127.0.0.1:{fleet_port}/fleet"
        _wait(lambda: _get_json(fleet_url), "the /fleet endpoint")

        sup, sup_log = _spawn(
            [sys.executable, "-m", f"{PKG}.cli", "supervise",
             "--workers", "2", "--healthy-after", "2",
             "--respawn-backoff", "0.5", "--platform", "cpu",
             "--journal-dir", journal_dir, "--",
             "--server", f"localhost:{port}",
             "--model", MODEL, "--synthetic", "--num-train", "1500",
             "--num-test", "96", "--epochs", "50", "--batch-size", "32",
             "--dtype", "float32", "--no-augment",
             "--heartbeat", "0.5", "--reconnect-timeout", "30"],
            sup_log_path)

        def _alive(view) -> int:
            rows = (view or {}).get("workers") or []
            return sum(1 for r in rows if r.get("alive"))

        def workers_alive():
            view = _get_json(cluster_url)
            return view if _alive(view) >= 2 else None

        view_a = _wait(workers_alive,
                       "both supervised workers to register", 240)
        checks.append(("A_worker_registered", True,
                       f"{len(view_a['workers'])} worker row(s)"))
        print(f"phase A: worker registered, journal -> {journal_dir}",
              flush=True)

        # -- phase B: seeded fault -> SLO burn -> automatic bundle -----------
        lg = _loadgen([f"localhost:{port}"], lg_s)
        view_b = _wait(
            lambda: (v := _get_json(cluster_url)) is not None
            and "slo_burn_fast" in _active_rules(v) and v,
            "the slo_burn_fast alert to fire", 90)
        live_breach = True  # observed: the live verdict cli query must match
        with open(os.path.join(OUT_DIR, "cluster_breach.json"), "w") as f:
            json.dump(view_b, f, indent=2)

        def slo_bundles():
            rows = _cli(["incident", "list", "--dir", incidents_dir,
                         "--json"])
            try:
                parsed = json.loads(rows[1])
            except ValueError:
                return []
            return [r for r in parsed
                    if (r.get("trigger") or {}).get("rule")
                    == "slo_burn_fast"]

        bundles_b = _wait(slo_bundles, "the automatic incident bundle", 60)
        checks += [
            ("B_loadgen_ok",
             lg is not None and lg["fetches_ok"] > 0,
             f"{(lg or {}).get('fetches_ok')} fetches"),
            ("B_slo_alert_fired", True,
             f"active rules: {sorted(_active_rules(view_b))}"),
            ("B_incident_autocaptured", len(bundles_b) == 1,
             f"{[b['id'] for b in bundles_b]}"),
        ]
        print(f"phase B: slo_burn_fast fired, bundle "
              f"{bundles_b[0]['id'] if bundles_b else '???'}", flush=True)

        # -- phase C: kill the worker -> respawn heals the alert -------------
        pid1 = _wait(lambda: _worker_pid(sup_log_path),
                     "the supervisor spawn line", 30)
        os.kill(pid1, signal.SIGKILL)
        _wait(lambda: "dead_worker"
              in _active_rules(_get_json(cluster_url)),
              "the dead_worker alert", 60)
        _wait(lambda: (v := _get_json(cluster_url)) is not None
              and "dead_worker" not in _active_rules(v)
              and _alive(v) >= 2,
              "the respawned worker to resolve the alert", 180)
        metrics_c = _get_json(f"http://127.0.0.1:{mport}/metrics.json")
        sup_text = open(sup_log_path).read()
        checks.append(
            ("C_respawn_heals_dead_worker",
             "SUPERVISOR_RESPAWN" in sup_text
             or "SUPERVISOR_SPAWN slot=0 attempt=2" in sup_text,
             "dead_worker fired -> respawn -> resolved"))
        print("phase C: dead_worker fired, respawn resolved it",
              flush=True)

        # -- phase D: second kill inside the cooldown -> storm dedupe --------
        pid2 = _wait(lambda: _worker_pid(sup_log_path, not_pid=pid1),
                     "the replacement worker pid", 30)
        os.kill(pid2, signal.SIGKILL)
        _wait(lambda: "dead_worker"
              in _active_rules(_get_json(cluster_url)),
              "the dead_worker refire", 60)

        def suppressed() -> float:
            m = _get_json(f"http://127.0.0.1:{mport}/metrics.json")
            return ((m or {}).get("counters") or {}).get(
                "dps_incidents_suppressed_total", 0)

        _wait(lambda: suppressed() >= 1,
              "the refire to be suppressed by the cooldown", 30)
        rows_rc, rows_out = _cli(["incident", "list", "--dir",
                                  incidents_dir, "--json"])
        all_rows = json.loads(rows_out)
        per_rule: dict = {}
        for r in all_rows:
            rule = (r.get("trigger") or {}).get("rule")
            per_rule[rule] = per_rule.get(rule, 0) + 1
        checks.append(
            ("D_storm_one_bundle_per_rule",
             per_rule.get("dead_worker") == 1
             and per_rule.get("slo_burn_fast") == 1
             and suppressed() >= 1,
             f"bundles per rule {per_rule}, "
             f"suppressed={suppressed()}"))
        print(f"phase D: bundles {per_rule}, refire suppressed",
              flush=True)

        # -- phase E: SIGKILL the coordinator (torn tail fair game) ----------
        final_metrics = _get_json(
            f"http://127.0.0.1:{mport}/metrics.json") or metrics_c or {}
        elapsed_live = time.time() - t0
        os.kill(primary.pid, signal.SIGKILL)
        primary.wait(timeout=30)
        _stop(sup, sup_log, grace=20.0)
        sup = sup_log = None
        _stop(obs, obs_log)
        procs.clear()
        print("phase E: coordinator SIGKILLed, all processes down",
              flush=True)

        # -- phase F: forensics from disk alone ------------------------------
        rep_rc, rep_out = _cli(
            ["incident", "report", bundles_b[0]["id"],
             "--dir", incidents_dir, "--json"])
        report = json.loads(rep_out)
        tl = report["timeline"]
        roles = {e.get("role") for e in tl["events"]}
        with open(os.path.join(OUT_DIR, "incident_report.json"),
                  "w") as f:
            json.dump(report, f, indent=2)
        human_rc, human_out = _cli(
            ["incident", "report", bundles_b[0]["id"],
             "--dir", incidents_dir])
        with open(os.path.join(OUT_DIR, "incident_report.txt"),
                  "w") as f:
            f.write(human_out)
        phase_order = ("fault", "alert", "remediation", "resolution")
        have_phases = [p for p in phase_order if p in tl["phases"]]
        checks.append(
            ("F_timeline_ordered_from_disk",
             rep_rc == 0 and tl["ordered"] is True
             and have_phases == list(phase_order) and len(roles) >= 2,
             f"phases={have_phases} roles={sorted(roles)} "
             f"events={len(tl['events'])}"))

        q_rc, q_out = _cli(["query", "--journal", journal_dir,
                            "--slo", "--json"])
        q_line = next(ln for ln in q_out.splitlines()
                      if ln.startswith("QUERY_JSON: "))
        q = json.loads(q_line[len("QUERY_JSON: "):])
        fast = ((q["slo"]["windows"].get("slo_burn_fast") or {})
                .get("objectives") or {}).get("fetch_latency") or {}
        retro_breach = bool(fast.get("breached"))
        with open(os.path.join(OUT_DIR, "retro_slo.json"), "w") as f:
            json.dump(q, f, indent=2)
        checks.append(
            ("F_retro_slo_agrees_with_live",
             retro_breach == live_breach and q_rc == 2,
             f"retro fast-window breached={retro_breach} "
             f"(max burn {fast.get('max_burn')}), live={live_breach}, "
             f"query rc={q_rc}"))

        p_rc, p_out = _cli(["query", "--journal", journal_dir,
                            "--percentiles", "--series",
                            "rpc_server_latency", "--json"])
        p_line = next((ln for ln in p_out.splitlines()
                       if ln.startswith("QUERY_JSON: ")), None)
        with open(os.path.join(OUT_DIR, "retro_percentiles.json"),
                  "w") as f:
            f.write((p_line or "QUERY_JSON: {}")[len("QUERY_JSON: "):])

        top_rc, top_out = _cli(["top", "--replay", journal_dir])
        with open(os.path.join(OUT_DIR, "top_replay.txt"), "w") as f:
            f.write(top_out)
        checks.append(
            ("F_top_replay_renders_final_frame",
             top_rc in (0, 2, 3) and bool(top_out.strip()),
             f"rc={top_rc}, {len(top_out.splitlines())} line(s)"))

        payload = {k: final_metrics.get(k) or {}
                   for k in ("counters", "gauges", "histograms")}
        oh = _journal_overhead(journal_dir, elapsed_live, payload)
        checks.append(
            ("F_journal_overhead_under_2pct",
             oh["overhead_frac"] < 0.02,
             f"{round(oh['overhead_frac'] * 100, 4)}% of one core "
             f"({oh['records_per_s']} rec/s x "
             f"{oh['per_write_us']}us/append; "
             f"stats={oh['journal_stats']})"))
        print(f"phase F: timeline {have_phases} over roles "
              f"{sorted(roles)}; retro breach={retro_breach} rc={q_rc}; "
              f"overhead {round(oh['overhead_frac'] * 100, 4)}%",
              flush=True)

        summary = {
            "demo": "incident forensics: durable journal, black-box "
                    "capture, postmortem timelines (ISSUE 18)",
            "quick": quick,
            "elapsed_seconds": round(time.time() - t0, 1),
            "environment": {"cpus": os.cpu_count()},
            "loadgen": {k: (lg or {}).get(k)
                        for k in ("fetches_ok", "fetches_err", "qps")},
            "bundles_per_rule": per_rule,
            "incidents_suppressed": suppressed(),
            "timeline_phases": have_phases,
            "timeline_roles": sorted(roles),
            "timeline_events": len(tl["events"]),
            "retro_fast_max_burn": fast.get("max_burn"),
            "journal": oh,
        }
    finally:
        _stop(sup, sup_log, grace=20.0)
        for proc, log in reversed(procs):
            _stop(proc, log)
        for name in ("primary.log", "observe.log", "supervise.log"):
            _trim_log(os.path.join(OUT_DIR, name))

    summary["checks"] = [{"name": n, "ok": bool(ok), "detail": d}
                         for n, ok, d in checks]
    summary["ok"] = all(ok for _, ok, _ in checks)
    with open(os.path.join(OUT_DIR, "incident_demo.json"), "w") as f:
        json.dump(summary, f, indent=2)
    n_pass = sum(1 for _, ok, _ in checks if ok)
    print(f"incident demo: {n_pass}/{len(checks)} checks PASS "
          f"({summary['elapsed_seconds']}s)")
    for name, ok, detail in checks:
        print(f"  [{'PASS' if ok else 'FAIL'}] {name} — {detail}")
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
