"""Recorded compression wire-matrix (ISSUE 6 acceptance evidence).

Two recorded cells, PR-2/PR-4 demo format (explicit PASS/FAIL checks, one
JSON artifact):

1. **Codec matrix** — the same 2-worker sync training run (tiny ResNet,
   synthetic CIFAR, fixed seed) under each push codec
   (fp32 control / fp16 / int8 / int4+EF / topk+EF / adaptive). Per cell:
   final accuracy, exact wire-payload bytes from the per-worker telemetry
   counters (precodec vs wire), effective bits/value, server-side
   compressed-domain engagement. Acceptance: **int4+EF moves ≥4× fewer
   push bytes than fp32 at final-accuracy parity within tolerance**.
2. **Server apply microbench, 8 workers sync** — the same int8 push
   stream against `compressed_domain=True` (homomorphic int32 accumulate,
   dequantize once per round) vs `False` (the legacy decode-per-push
   path). Acceptance: **measured per-push latency drop (the fp32 decode
   eliminated) and end-to-end round-wall speedup**.

Topology note: cells run in-process (worker threads against the python
store) — the byte counters count exactly the payload bytes the gRPC wire
would carry (the codec runs in `PSWorker._push` either way), and the
gRPC-specific negotiation/degradation matrix is pinned by tier-1 tests
(`tests/test_comms.py::TestCompressedDomainWire`).

Run:  python experiments/run_compression_matrix.py [--quick]
Artifact: experiments/results/compression/compression_matrix.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

OUT = os.path.join(REPO, "experiments", "results", "compression")

CODEC_CELLS = ["none", "fp16", "int8", "int4", "topk", "adaptive"]


def _counter_value(name, **labels):
    from distributed_parameter_server_for_ml_training_tpu.telemetry import (
        get_registry)
    return get_registry().counter(name, **labels).value


def run_codec_cell(codec: str, model, dataset, epochs: int,
                   workers: int = 2) -> dict:
    import numpy as np

    from distributed_parameter_server_for_ml_training_tpu.ps import (
        ParameterStore, StoreConfig, WorkerConfig, run_workers)
    from distributed_parameter_server_for_ml_training_tpu.utils import (
        flatten_params)
    import jax

    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 32, 32, 3), np.float32),
                           train=False)
    store = ParameterStore(
        flatten_params(variables["params"]),
        StoreConfig(mode="sync", total_workers=workers,
                    learning_rate=0.05, push_codec=codec))
    # Byte counters are process-cumulative (worker ids repeat across
    # cells) — snapshot before/after and diff.
    wids = [str(i) for i in range(workers)]
    before = {
        w: (_counter_value("dps_worker_push_bytes_total",
                           stage="precodec", worker=w),
            _counter_value("dps_worker_push_bytes_total",
                           stage="wire", worker=w))
        for w in wids}
    compressed_before = store._tm_compressed.value
    t0 = time.time()
    results = run_workers(store, model, dataset, n_workers=workers,
                          config=WorkerConfig(batch_size=32,
                                              num_epochs=epochs,
                                              augment=False, seed=0))
    wall = time.time() - t0
    pre = wire = 0
    for w in wids:
        b = before[w]
        pre += _counter_value("dps_worker_push_bytes_total",
                              stage="precodec", worker=w) - b[0]
        wire += _counter_value("dps_worker_push_bytes_total",
                               stage="wire", worker=w) - b[1]
    pushes = sum(r.pushes_accepted for r in results)
    accs = [r.test_accuracies[-1] for r in results if r.test_accuracies]
    return {
        "push_codec": codec,
        "workers": workers,
        "epochs": epochs,
        "wall_seconds": round(wall, 2),
        "global_step": store.global_step,
        "pushes_accepted": pushes,
        "final_accuracy": round(float(sum(accs) / max(len(accs), 1)), 4),
        "push_mb_precodec": round(pre / 1e6, 3),
        "push_mb_wire": round(wire / 1e6, 3),
        "byte_reduction_vs_fp32": round(pre / wire, 2) if wire else None,
        "effective_bits_per_value": round(wire * 32.0 / pre, 3)
        if pre else None,
        "server_compressed_accum_pushes": int(
            store._tm_compressed.value - compressed_before),
        "qscale_version": store.gradient_scales()[1],
    }


def run_apply_bench(workers: int = 8, rounds: int = 30,
                    n_tensors: int = 32, tensor_size: int = 32768) -> dict:
    """Server-side A/B at 8 workers sync: identical int8 push streams
    against the homomorphic path vs the legacy decode-per-push path.
    Reports per-push latency (non-round-final pushes: pure stash/decode,
    no apply) and total wall."""
    import numpy as np

    from distributed_parameter_server_for_ml_training_tpu.ops.compression \
        import compress_push
    from distributed_parameter_server_for_ml_training_tpu.ps import (
        ParameterStore, StoreConfig)

    def bench(compressed: bool):
        rng = np.random.default_rng(0)
        params = {f"p{i}": rng.normal(size=tensor_size).astype(np.float32)
                  for i in range(n_tensors)}
        store = ParameterStore(params, StoreConfig(
            mode="sync", total_workers=workers, learning_rate=0.01,
            push_codec="int8", compressed_domain=compressed))
        payloads = [compress_push(
            {k: rng.normal(size=v.shape).astype(np.float32)
             for k, v in params.items()}) for _ in range(workers)]
        push_s = []
        t0 = time.perf_counter()
        for r in range(rounds):
            for w in range(workers):
                t1 = time.perf_counter()
                store.push(w, payloads[w], r)
                push_s.append(time.perf_counter() - t1)
        wall = time.perf_counter() - t0
        per_round = np.array(push_s).reshape(rounds, workers)
        return {
            "wall_seconds": round(wall, 3),
            # Non-final pushes carry no apply: their latency IS the
            # per-push decode/stash cost the tentpole removes.
            "per_push_ms": round(float(per_round[:, :-1].mean()) * 1e3, 4),
            # The round-completing push runs the aggregation + apply.
            "round_apply_ms": round(float(per_round[:, -1].mean()) * 1e3,
                                    4),
            "compressed_accum_pushes": int(store._tm_compressed.value),
        }

    n_params = n_tensors * tensor_size
    legacy = bench(False)
    homomorphic = bench(True)
    return {
        "workers": workers,
        "rounds": rounds,
        "model_params": n_params,
        "payload": "int8 + per-tensor scales",
        "legacy_decode_per_push": legacy,
        "compressed_domain": homomorphic,
        "per_push_speedup": round(
            legacy["per_push_ms"] / homomorphic["per_push_ms"], 2),
        "round_wall_speedup": round(
            legacy["wall_seconds"] / homomorphic["wall_seconds"], 2),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="1 training epoch, fewer bench rounds")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--acc-tolerance", type=float, default=0.06,
                    help="final-accuracy parity band vs the fp32 control")
    args = ap.parse_args()
    epochs = 1 if args.quick else args.epochs
    bench_rounds = 10 if args.quick else 30

    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get("JAX_COMPILATION_CACHE_DIR",
                       os.path.join(REPO, ".jax_cache")))

    from distributed_parameter_server_for_ml_training_tpu.data import (
        synthetic_cifar100)
    from distributed_parameter_server_for_ml_training_tpu.models import (
        ResNet)

    dataset = synthetic_cifar100(n_train=640, n_test=128, num_classes=10,
                                 seed=1)
    model = ResNet(stage_sizes=(1, 1), num_filters=8, num_classes=10)

    cells = []
    for codec in CODEC_CELLS:
        cell = run_codec_cell(codec, model, dataset, epochs)
        cells.append(cell)
        print(f"cell {codec}: acc={cell['final_accuracy']} "
              f"wire={cell['push_mb_wire']}MB "
              f"({cell['byte_reduction_vs_fp32']}x under fp32, "
              f"{cell['effective_bits_per_value']} bits/value)", flush=True)

    bench = run_apply_bench(rounds=bench_rounds)
    print(f"apply bench (8w sync): per-push "
          f"{bench['legacy_decode_per_push']['per_push_ms']}ms -> "
          f"{bench['compressed_domain']['per_push_ms']}ms "
          f"({bench['per_push_speedup']}x), wall "
          f"{bench['round_wall_speedup']}x", flush=True)

    by_codec = {c["push_codec"]: c for c in cells}
    control = by_codec["none"]
    int4 = by_codec["int4"]
    checks = []

    def check(name, ok, detail):
        checks.append({"check": name, "pass": bool(ok), "detail": detail})
        print(f"[{'PASS' if ok else 'FAIL'}] {name}: {detail}", flush=True)

    check("int4_byte_reduction_ge_4x",
          int4["byte_reduction_vs_fp32"] is not None
          and int4["byte_reduction_vs_fp32"] >= 4.0,
          f"{int4['byte_reduction_vs_fp32']}x vs fp32 "
          f"({int4['push_mb_wire']} vs {control['push_mb_wire']} MB)")
    acc_gap = abs(int4["final_accuracy"] - control["final_accuracy"])
    check("int4_accuracy_parity",
          acc_gap <= args.acc_tolerance,
          f"|{int4['final_accuracy']} - {control['final_accuracy']}| = "
          f"{round(acc_gap, 4)} <= {args.acc_tolerance}")
    check("every_quantized_push_stayed_compressed",
          all(by_codec[c]["server_compressed_accum_pushes"]
              >= by_codec[c]["pushes_accepted"]
              for c in ("int8", "int4", "topk", "adaptive")),
          "dps_store_compressed_accum_total covered all accepted pushes "
          "in every quantized cell")
    check("shared_scales_published",
          all(by_codec[c]["qscale_version"] >= 1
              for c in ("int8", "int4", "topk", "adaptive")),
          "gradient_scales() versioned >= 1 after training in every "
          "quantized cell")
    check("apply_per_push_speedup_ge_3x",
          bench["per_push_speedup"] >= 3.0,
          f"{bench['per_push_speedup']}x (decode-per-push eliminated)")
    check("apply_round_wall_speedup",
          bench["round_wall_speedup"] >= 1.2,
          f"{bench['round_wall_speedup']}x end-to-end at 8 workers")

    os.makedirs(OUT, exist_ok=True)
    artifact = {
        "experiment": "compression_matrix",
        "topology": "in-process: N worker threads against the python "
                    "store; byte columns are exact codec-payload bytes "
                    "(the same bytes a gRPC push would carry)",
        "cells": cells,
        "apply_bench_8w_sync": bench,
        "checks": checks,
        "all_pass": all(c["pass"] for c in checks),
    }
    out_path = os.path.join(OUT, "compression_matrix.json")
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    print(f"\n{sum(c['pass'] for c in checks)}/{len(checks)} checks PASS "
          f"-> {out_path}", flush=True)
    return 0 if artifact["all_pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
