"""Recorded perf-observatory demo (ISSUE 12 acceptance evidence).

Three cells under ``experiments/results/perf_observatory/``, every check
exit-code-verified (the PR 4-11 recorded-demo format).

**Cell A — device-time attribution reconciles with the span wall.** A
small jitted training loop runs under BOTH instruments at once: the
flight recorder brackets each step (``worker.step``/``worker.compute``
spans) while ``telemetry.profiler.capture`` dumps the jax.profiler
trace. ``cli perf profile`` then joins the two into one artifact.
Checks: the capture parsed (>= 1 trace file, zero parse errors); the
attribution basis is a real one (device lanes, or the CPU backend's
host-op events — never presented as measured device time); the
attributed time reconciles against the span-level step wall with the
residual REPORTED; ``cost_analysis`` flops landed in the artifact while
MFU is null on CPU (no invented peak).

**Cell B — injected server-side latency burns the SLO budget.** A real
``cli serve`` process starts with compressed burn windows and a fault
schedule that delays the first N ``FetchParameters`` handlers past the
latency objective — INSIDE the handler instrumentation, so the breach
travels through the real histogram. A fetch load drives it. Checks:
``slo_burn_fast`` (critical) fires and lands in the active alerts AND
the ``GET /cluster`` ``"slo"`` block (breaching window, conservatively
snapped threshold); ``cli status`` renders the breach and exits 2
(critical, unremediated); once the fault schedule exhausts and the
windows slide past it, the alert RESOLVES and ``cli status`` exits 0.

**Cell C — benchwatch flags a synthetic regression, passes reality.**
``cli perf check`` against a synthetic ledger with a 20% throughput drop
(plus an rc=1 flake that must be skipped-with-reason, never compared)
exits 2 with the regression named; the same check against the repo's
real committed history exits 0; ``--validate-only`` (the lint gate)
exits 0.

Artifacts: ``perf_observatory.json`` (summary + PASS/FAIL checks),
the merged profile artifact + human table, breach/clear cluster
captures, ``cli status`` transcripts, and the benchwatch verdicts.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT_DIR = os.path.join(REPO, "experiments", "results", "perf_observatory")
PKG = "distributed_parameter_server_for_ml_training_tpu"
sys.path.insert(0, REPO)


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env(**extra) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _http(url: str, timeout: float = 5.0) -> str | None:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.read().decode()
    except Exception:
        return None


def _cluster(port: int) -> dict | None:
    raw = _http(f"http://127.0.0.1:{port}/cluster")
    if raw is None:
        return None
    try:
        return json.loads(raw)
    except ValueError:
        return None


def _run_cli(argv: list, timeout: float = 300) -> subprocess.CompletedProcess:
    return subprocess.run([sys.executable, "-m", f"{PKG}.cli"] + argv,
                          capture_output=True, text=True, env=_env(),
                          cwd=REPO, timeout=timeout)


# ---------------------------------------------------------------------------
# Cell A: profiled run -> cli perf profile -> reconciliation
# ---------------------------------------------------------------------------

def cell_a() -> tuple[dict, dict]:
    import jax
    import jax.numpy as jnp

    from distributed_parameter_server_for_ml_training_tpu import (
        telemetry as T)
    from distributed_parameter_server_for_ml_training_tpu.telemetry. \
        profiler import capture, compiled_cost

    import shutil
    prof_dir = os.path.join(OUT_DIR, "a_profile")
    dump_dir = os.path.join(OUT_DIR, "a_trace_dumps")
    for d in (prof_dir, dump_dir):  # stale captures would double-count
        shutil.rmtree(d, ignore_errors=True)
    os.makedirs(dump_dir, exist_ok=True)

    # A matmul-heavy jitted step: big enough that XLA thunk time
    # dominates the step wall on CPU.
    key = jax.random.PRNGKey(0)
    k1, k2, k3 = jax.random.split(key, 3)
    w1 = jax.random.normal(k1, (512, 512), jnp.float32) * 0.02
    w2 = jax.random.normal(k2, (512, 512), jnp.float32) * 0.02
    x = jax.random.normal(k3, (256, 512), jnp.float32)

    def loss_fn(params, batch):
        h = jnp.tanh(batch @ params["w1"])
        return jnp.mean((h @ params["w2"]) ** 2)

    @jax.jit
    def step(params, batch):
        loss, g = jax.value_and_grad(loss_fn)(params, batch)
        return ({k: v - 0.01 * g[k] for k, v in params.items()}, loss)

    params = {"w1": w1, "w2": w2}
    (params, _loss) = step(params, x)  # compile outside the capture
    jax.block_until_ready(params)

    n_steps = 5
    rec = T.enable_tracing(buffer=4096, role="perfdemo")
    rec.clear()
    try:
        with capture(prof_dir):
            for i in range(n_steps):
                with T.trace_span("worker.step", root=True, worker=0,
                                  step=i):
                    with T.trace_span("worker.compute"):
                        params, loss = step(params, x)
                        jax.block_until_ready(loss)
        dump_path = rec.dump_to_dir(dump_dir, "demo")
    finally:
        T.disable_tracing()

    cost = compiled_cost(step.lower(params, x).compile())

    out_json = os.path.join(OUT_DIR, "a_perf_profile.json")
    # --keep-traces: the cost join below re-reads the same capture; we
    # prune ourselves after the LAST consumer (uniform policy,
    # telemetry/profiler.prune_capture).
    p = _run_cli(["perf", "profile", "--profile-dir", prof_dir,
                  "--trace-dump-dir", dump_dir, "--out", out_json,
                  "--keep-traces"])
    with open(os.path.join(OUT_DIR, "a_table.txt"), "w") as f:
        f.write(p.stdout)
    report = {}
    if os.path.exists(out_json):
        with open(out_json) as f:
            report = json.load(f)

    # The CLI artifact has no model, so it carries no cost block; join
    # the compiled cost the way bench.py does, with MFU computed against
    # the REAL device kind — null on CPU (no invented peak).
    from distributed_parameter_server_for_ml_training_tpu.analysis \
        import attribute_profile, critical_path_report, load_trace_dumps
    from distributed_parameter_server_for_ml_training_tpu.analysis \
        import find_trace_dumps as _find_dumps
    from distributed_parameter_server_for_ml_training_tpu.telemetry. \
        profiler import mfu as mfu_of
    device_kind = str(jax.devices()[0].device_kind)
    critical = critical_path_report(
        load_trace_dumps(_find_dumps(dump_dir)))
    wall = critical.get("step_wall_total_s") or 0.0
    steps_per_s = (n_steps / wall) if wall else None
    costed = attribute_profile(
        prof_dir, critical=critical, cost=cost,
        mfu_value=mfu_of(cost.get("flops"), steps_per_s, device_kind),
        device_kind=device_kind)
    with open(os.path.join(OUT_DIR, "a_perf_profile_with_cost.json"),
              "w") as f:
        json.dump(costed, f, indent=2)
    # Both artifacts written — prune the raw capture if the attribution
    # actually succeeded (keep it on failure so the traces stay
    # debuggable; ISSUE 20 satellite f).
    if (costed.get("profile") or {}).get("basis") not in (None, "none") \
            and not costed.get("parse_errors"):
        from distributed_parameter_server_for_ml_training_tpu \
            .telemetry.profiler import prune_capture
        prune_capture(prof_dir)

    prof = report.get("profile") or {}
    rec_block = report.get("reconciliation") or {}
    critical = report.get("critical_path") or {}
    frac = (rec_block.get("attributed_s", 0.0)
            / rec_block["step_wall_s"]) if rec_block.get("step_wall_s") \
        else None
    record = {
        "perf_profile_rc": p.returncode,
        "trace_files": report.get("trace_files"),
        "parse_errors": report.get("parse_errors"),
        "basis": prof.get("basis"),
        "op_classes": {cls: row.get("fraction")
                       for cls, row in
                       (prof.get("op_classes") or {}).items()},
        "steps_attributed": critical.get("steps"),
        "reconciliation": rec_block,
        "attributed_fraction_of_wall": None if frac is None
        else round(frac, 4),
        "device_kind": device_kind,
        "cost": costed.get("cost"),
        "recorder_dump": os.path.basename(dump_path),
    }
    checks = {
        "A_capture_parsed_clean":
            p.returncode == 0 and len(report.get("trace_files") or []) >= 1
            and report.get("parse_errors") == [],
        "A_attribution_basis_real":
            prof.get("basis") in ("device_lanes", "host_ops",
                                  "host_execute_proxy")
            and prof.get("total_attributed_s", 0.0) > 0,
        "A_reconciles_with_span_step_wall":
            critical.get("steps") == n_steps
            and rec_block.get("step_wall_s", 0.0) > 0
            and frac is not None and 0.1 <= frac <= 1.5,
        "A_residual_reported_not_hidden":
            "residual_s" in rec_block
            and "residual_fraction" in rec_block
            and rec_block.get("residual_s", -1.0) >= 0.0,
        "A_mfu_honest_on_cpu":
            (costed.get("cost") or {}).get("flops") is not None
            and ((costed.get("cost") or {}).get("mfu") is None
                 if device_kind not in
                 ("TPU v4", "TPU v5 lite", "TPU v5e", "TPU v5p")
                 else (costed.get("cost") or {}).get("mfu") is not None),
    }
    return record, checks


# ---------------------------------------------------------------------------
# Cell B: injected latency -> slo_burn_fast fires, then resolves
# ---------------------------------------------------------------------------

FETCH_P99_MS = 50.0
FAST_WINDOW_S = 4.0
SLOW_WINDOW_S = 8.0
DELAYED_CALLS = 80          # fault schedule length (then it exhausts)
DELAY_S = 0.15              # 3x the latency objective


def cell_b() -> tuple[dict, dict]:
    port, mport = _free_port(), _free_port()
    fault_spec = (f"fetch.delay={DELAY_S}@n="
                  + ",".join(str(i) for i in range(1, DELAYED_CALLS + 1)))
    log = open(os.path.join(OUT_DIR, "b_server.log"), "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", f"{PKG}.cli", "serve",
         "--mode", "async", "--workers", "1",
         "--port", str(port), "--model", "vit_tiny",
         "--num-classes", "100", "--image-size", "32",
         "--platform", "cpu", "--metrics-port", str(mport),
         "--health-interval", "0.5",
         "--slo-fetch-p99-ms", str(FETCH_P99_MS),
         "--slo-fast-window", str(FAST_WINDOW_S),
         "--slo-slow-window", str(SLOW_WINDOW_S),
         "--faults", fault_spec],
        stdout=log, stderr=subprocess.STDOUT, env=_env(), cwd=REPO)
    try:
        deadline = time.time() + 180
        while _cluster(mport) is None:
            if time.time() > deadline or proc.poll() is not None:
                raise RuntimeError(
                    f"cell B server never came up (rc={proc.poll()})")
            time.sleep(0.25)

        # Drive fetches through the delayed handlers. The load run
        # outlasts the fault schedule, so good traffic follows the bad.
        lg = subprocess.Popen(
            [sys.executable, "-m", f"{PKG}.cli", "loadgen",
             "--targets", f"localhost:{port}", "--duration", "20",
             "--concurrency", "4", "--fetch-mode", "full"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=_env(), cwd=REPO)

        def slo_alerts(view: dict) -> list:
            return [a for a in view.get("alerts", [])
                    if str(a.get("rule", "")).startswith("slo_burn")]

        # Phase 1: wait for the fast burn to fire.
        breach_view = None
        deadline = time.time() + 60
        while time.time() < deadline:
            view = _cluster(mport) or {}
            if any(a.get("rule") == "slo_burn_fast"
                   for a in slo_alerts(view)):
                breach_view = view
                break
            time.sleep(0.3)
        with open(os.path.join(OUT_DIR, "b_cluster_breach.json"),
                  "w") as f:
            json.dump(breach_view, f, indent=2)

        st_breach = _run_cli(["status", "--metrics-port", str(mport)])
        with open(os.path.join(OUT_DIR, "b_status_breach.txt"), "w") as f:
            f.write(f"exit code: {st_breach.returncode}\n\n"
                    + st_breach.stdout + st_breach.stderr)

        lg_out, _ = lg.communicate(timeout=120)
        # Phase 2: the fault schedule has exhausted; the windows must
        # slide past the bad deltas and the alert must RESOLVE.
        clear_view = None
        deadline = time.time() + 90
        while time.time() < deadline:
            view = _cluster(mport) or {}
            if view and not slo_alerts(view):
                clear_view = view
                break
            time.sleep(0.5)
        with open(os.path.join(OUT_DIR, "b_cluster_clear.json"),
                  "w") as f:
            json.dump(clear_view, f, indent=2)

        st_clear = _run_cli(["status", "--metrics-port", str(mport)])
        with open(os.path.join(OUT_DIR, "b_status_clear.txt"), "w") as f:
            f.write(f"exit code: {st_clear.returncode}\n\n"
                    + st_clear.stdout + st_clear.stderr)

        metrics_text = _http(f"http://127.0.0.1:{mport}/metrics") or ""

        bv = breach_view or {}
        slo_block = bv.get("slo") or {}
        fetch_obj = next((o for o in slo_block.get("objectives", [])
                          if o.get("name") == "fetch_latency"), {})
        fast_win = (fetch_obj.get("windows") or {}) \
            .get("slo_burn_fast") or {}
        breach_alerts = {a.get("rule"): a for a in slo_alerts(bv)}
        cv = clear_view or {}
        clear_slo = cv.get("slo") or {}

        record = {
            "fault_spec": f"fetch.delay={DELAY_S}@n=1..{DELAYED_CALLS}",
            "objective_p99_ms": FETCH_P99_MS,
            "windows_s": [FAST_WINDOW_S, SLOW_WINDOW_S],
            "breach_alerts": {r: {k: a.get(k) for k in
                                  ("severity", "message")}
                              for r, a in breach_alerts.items()},
            "breach_fetch_objective": {
                k: fetch_obj.get(k)
                for k in ("threshold_ms", "snapped_threshold_ms",
                          "p99_ms", "total")},
            "breach_fast_window": fast_win,
            "breach_slo_breaches": slo_block.get("breaches"),
            "status_breach_rc": st_breach.returncode,
            "status_clear_rc": st_clear.returncode,
            "clear_breaches": clear_slo.get("breaches"),
            "clear_alerts": slo_alerts(cv),
        }
        checks = {
            "B_fast_burn_fired_as_critical_alert":
                breach_view is not None
                and breach_alerts.get("slo_burn_fast", {})
                .get("severity") == "critical"
                and bv.get("alerts_total", {}).get("critical", 0) >= 1,
            "B_slo_block_shows_breaching_window":
                bool(fast_win.get("breaching"))
                and any(b.get("rule") == "slo_burn_fast"
                        and b.get("objective") == "fetch_latency"
                        for b in slo_block.get("breaches") or []),
            "B_threshold_snapped_conservatively":
                fetch_obj.get("threshold_ms") == FETCH_P99_MS
                and fetch_obj.get("snapped_threshold_ms") == FETCH_P99_MS,
            "B_status_renders_breach_and_exits_critical":
                st_breach.returncode == 2
                and "slo_burn_fast" in st_breach.stdout
                and "BREACH" in st_breach.stdout,
            "B_server_histogram_on_metrics_surface":
                "dps_rpc_server_latency_seconds_bucket" in metrics_text
                and 'method="FetchParameters"' in metrics_text,
            "B_breach_resolves_when_fault_clears":
                clear_view is not None
                and not slo_alerts(cv)
                and (clear_slo.get("breaches") == []),
            "B_status_exits_zero_after_resolve":
                st_clear.returncode == 0,
        }
        return record, checks
    finally:
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=15)
        log.close()


# ---------------------------------------------------------------------------
# Cell C: benchwatch — synthetic regression flagged, real history green
# ---------------------------------------------------------------------------

def cell_c() -> tuple[dict, dict]:
    synth = os.path.join(OUT_DIR, "c_synth_ledger")
    os.makedirs(synth, exist_ok=True)

    def rec(value, rc=0):
        parsed = None if rc else {
            "metric": "cifar100_resnet18_train_images_per_sec_per_chip",
            "value": value, "unit": "images/sec/chip", "vs_baseline": 0.0}
        return {"n": 1, "cmd": "python bench.py", "rc": rc,
                "tail": "synthetic", "parsed": parsed}

    # Three healthy runs, one rc!=0 flake (skip with reason, never
    # compare), then a 20% drop.
    for i, r in enumerate([rec(100.0), rec(101.0), rec(99.0),
                           rec(0.0, rc=1), rec(80.0)]):
        with open(os.path.join(synth, f"BENCH_r{i:02d}.json"), "w") as f:
            json.dump(r, f, indent=2)

    p_synth = _run_cli(["perf", "check", "--root", synth,
                        "--format", "json"])
    with open(os.path.join(OUT_DIR, "c_check_synthetic.json"), "w") as f:
        f.write(p_synth.stdout)
    try:
        synth_verdict = json.loads(p_synth.stdout)
    except ValueError:
        synth_verdict = {}

    p_real = _run_cli(["perf", "check", "--format", "json"])
    with open(os.path.join(OUT_DIR, "c_check_real.json"), "w") as f:
        f.write(p_real.stdout)
    try:
        real_verdict = json.loads(p_real.stdout)
    except ValueError:
        real_verdict = {}

    p_validate = _run_cli(["perf", "check", "--validate-only"])

    skipped = {s.get("file"): s.get("reason")
               for s in synth_verdict.get("skipped", [])}
    record = {
        "synthetic_rc": p_synth.returncode,
        "synthetic_status": synth_verdict.get("status"),
        "synthetic_regressions": synth_verdict.get("regressions"),
        "synthetic_skipped": skipped,
        "real_rc": p_real.returncode,
        "real_status": real_verdict.get("status"),
        "real_metrics": {m: row.get("status") for m, row in
                         (real_verdict.get("metrics") or {}).items()},
        "validate_only_rc": p_validate.returncode,
        "validate_only_out": p_validate.stdout.strip(),
    }
    checks = {
        "C_synthetic_20pct_drop_flagged":
            p_synth.returncode == 2
            and synth_verdict.get("status") == "regression"
            and synth_verdict.get("regressions")
            == ["cifar100_resnet18_train_images_per_sec_per_chip"],
        "C_flake_skipped_with_reason_not_compared":
            "BENCH_r03.json" in skipped
            and str(skipped["BENCH_r03.json"]).startswith("rc=1"),
        "C_real_history_green":
            p_real.returncode == 0
            and real_verdict.get("status") == "pass",
        "C_validate_only_green": p_validate.returncode == 0,
    }
    return record, checks


def main(argv=None) -> int:
    import argparse
    global OUT_DIR
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out-dir", default=OUT_DIR,
                    help="artifact directory (default: the recorded "
                         "experiments/results/perf_observatory)")
    args = ap.parse_args(argv)
    OUT_DIR = args.out_dir
    os.makedirs(OUT_DIR, exist_ok=True)
    t0 = time.time()
    checks: dict = {}

    a_rec, a_checks = cell_a()
    checks.update(a_checks)
    print(f"cell A: basis={a_rec['basis']}, attributed "
          f"{a_rec['attributed_fraction_of_wall']} of step wall over "
          f"{a_rec['steps_attributed']} steps, residual "
          f"{(a_rec['reconciliation'] or {}).get('residual_s')}s",
          flush=True)

    b_rec, b_checks = cell_b()
    checks.update(b_checks)
    print(f"cell B: slo_burn_fast fired "
          f"(status rc={b_rec['status_breach_rc']}), resolved "
          f"(status rc={b_rec['status_clear_rc']})", flush=True)

    c_rec, c_checks = cell_c()
    checks.update(c_checks)
    print(f"cell C: synthetic ledger -> {c_rec['synthetic_status']} "
          f"(rc={c_rec['synthetic_rc']}), real ledger -> "
          f"{c_rec['real_status']} (rc={c_rec['real_rc']})", flush=True)

    record = {
        "demo": "perf observatory: device-time attribution, serve-tier "
                "SLOs, bench regression watch (ISSUE 12)",
        "elapsed_seconds": round(time.time() - t0, 1),
        "environment": {"cpus": os.cpu_count()},
        "checks": checks,
        "all_pass": all(checks.values()),
        "cell_a": a_rec,
        "cell_b": b_rec,
        "cell_c": c_rec,
    }
    with open(os.path.join(OUT_DIR, "perf_observatory.json"), "w") as f:
        json.dump(record, f, indent=2)
    n_pass = sum(bool(v) for v in checks.values())
    print(f"perf observatory demo: {n_pass}/{len(checks)} checks PASS "
          f"({record['elapsed_seconds']}s)")
    for cname, ok in checks.items():
        print(f"  [{'PASS' if ok else 'FAIL'}] {cname}")
    return 0 if record["all_pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
