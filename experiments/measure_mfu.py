"""Per-model-family MFU measurement on the attached TPU chip.

Round-2 VERDICT item 10 (+ item 5's MFU requirement): PERF.md's model table
listed img/s only; this script measures model-FLOPs utilization for each
BASELINE.json config family the same way the ResNet-18 headline number was
produced — XLA-counted FLOPs from ``compile().cost_analysis()`` over a
timed ``lax.scan`` window of real train steps (normalize + augment + fwd +
bwd + SGD) — and, for ViT-B/16, with the dense einsum attention core vs the
Pallas flash kernel (ops/pallas/flash_attention.py) at a long-sequence
resolution where the fused kernel matters.

Writes experiments/results/mfu.json and prints a markdown table for PERF.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from functools import partial

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                 os.path.join(REPO, ".jax_cache")))

V5E_BF16_PEAK_TFLOPS = 197.0  # per-chip bf16 peak (public v5e spec)


def measure(name: str, model, image_size: int, batch: int, steps: int,
            trials: int = 3, num_classes: int = 100,
            flops_rec: dict | None = None) -> dict:
    """``flops_rec``: reuse another row's per-step FLOPs instead of XLA
    cost_analysis — Pallas kernels are opaque custom calls the analysis
    cannot count, so a flash row borrows its DENSE twin's count (same
    logical model, so model-FLOPs/s stays apples-to-apples)."""
    import jax.numpy as jnp
    import numpy as np

    from distributed_parameter_server_for_ml_training_tpu.train import (
        create_train_state, make_train_step, server_sgd)

    state = create_train_state(model, jax.random.PRNGKey(0), server_sgd(0.1),
                               input_shape=(1, image_size, image_size, 3))
    train_step = make_train_step(augment=True)

    def window(state, images, labels, key):
        def body(carry, batch_):
            st, k = carry
            st, metrics = train_step(st, batch_[0], batch_[1], k)
            return (st, k), metrics["loss"]
        (state, _), losses = jax.lax.scan(body, (state, key),
                                          (images, labels))
        return state, losses[-1]

    jitted = jax.jit(window, donate_argnums=0)
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.integers(
        0, 255, (steps, batch, image_size, image_size, 3), dtype=np.uint8))
    labels = jnp.asarray(np.tile(np.arange(batch) % num_classes,
                                 (steps, 1)).astype(np.int32))
    key = jax.random.PRNGKey(1)

    # FLOPs come from a SINGLE-step compile: XLA's cost analysis counts a
    # lax.scan body once, not steps-times, so the windowed executable
    # under-reports by the window length.
    if flops_rec is not None:
        step_flops = (flops_rec["window_tflops"] * 1e12
                      / flops_rec["steps_per_window"])
    else:
        single = jax.jit(train_step).lower(
            state, images[0], labels[0], key).compile()
        step_flops = float(single.cost_analysis().get("flops", 0.0))
    window_flops = step_flops * steps

    state, loss = jitted(state, images, labels, key)
    _ = float(loss)
    best = float("inf")
    for _t in range(trials):
        t0 = time.perf_counter()
        state, loss = jitted(state, images, labels, key)
        _ = float(loss)
        best = min(best, time.perf_counter() - t0)

    tflops_rate = window_flops / best / 1e12
    rec = {
        "name": name,
        "batch": batch,
        "image_size": image_size,
        "steps_per_window": steps,
        "window_seconds": round(best, 4),
        "images_per_sec": round(steps * batch / best, 1),
        "ms_per_step": round(best / steps * 1e3, 2),
        "window_tflops": round(window_flops / 1e12, 2),
        "model_tflops_per_sec": round(tflops_rate, 1),
        "mfu_pct_vs_v5e_bf16_peak": round(
            100.0 * tflops_rate / V5E_BF16_PEAK_TFLOPS, 1),
    }
    print(f"{name}: {rec['images_per_sec']} img/s, "
          f"{rec['model_tflops_per_sec']} TF/s = "
          f"{rec['mfu_pct_vs_v5e_bf16_peak']}% MFU", flush=True)
    return rec


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trials", type=int, default=3)
    ap.add_argument("--attn-only", action="store_true",
                    help="skip the train-step MFU rows (keep mfu.json's)")
    ap.add_argument("--long-context", action="store_true",
                    help="measure ONLY the 1024px (4097-token) dense-vs-"
                         "flash train-step rows; keep every other recorded "
                         "row and the attention microbench as-is")
    args = ap.parse_args()

    import jax.numpy as jnp

    from distributed_parameter_server_for_ml_training_tpu.models import (
        ResNet18, ResNet50)
    from distributed_parameter_server_for_ml_training_tpu.models.vit import ViT
    from distributed_parameter_server_for_ml_training_tpu.ops.pallas.flash_attention import (
        flash_attention)

    print(f"device: {jax.devices()}", file=sys.stderr)
    out = os.path.join(REPO, "experiments", "results", "mfu.json")
    bf16 = jnp.bfloat16
    vit_b16 = dict(patch_size=16, hidden_dim=768, depth=12, num_heads=12,
                   num_classes=100, dtype=bf16)
    prior = {}
    if os.path.exists(out):
        with open(out) as f:
            prior = json.load(f)
    if args.long_context:
        # Round-4 VERDICT item 7: an END-TO-END train step in the regime
        # the flash kernel is FOR — 1024px -> 64^2 patches + CLS = 4097
        # tokens, where the microbench measured a 1.7x bwd kernel win.
        # The dense row materializes [B, H, T, T] logits (the O(T^2) HBM
        # cost flash exists to avoid), so the batch is what dense FITS;
        # flash's MFU uses the dense row's FLOP count (Pallas calls are
        # opaque to cost_analysis; same logical model either way).
        rows = [r for r in prior.get("train_step_mfu", [])
                if not r["name"].startswith("vit_b16_1024px")]
        dense_lc = measure("vit_b16_1024px_dense", ViT(**vit_b16),
                           1024, 4, 4, args.trials)
        flash_lc = measure("vit_b16_1024px_flash_auto",
                           ViT(**vit_b16, attention_fn=flash_attention),
                           1024, 4, 4, args.trials, flops_rec=dense_lc)
        flash_lc["flops_from"] = "vit_b16_1024px_dense"
        flash_lc["note"] = ("T=4097 >> measured crossover: the dispatch "
                            "selects the Pallas kernel; same model, same "
                            "batch, same data as the dense row")
        flash_lc["end_to_end_speedup_vs_dense"] = round(
            dense_lc["ms_per_step"] / flash_lc["ms_per_step"], 2)
        rows += [dense_lc, flash_lc]
        with open(out, "w") as f:
            json.dump({"train_step_mfu": rows,
                       "attention_core_bench": prior.get(
                           "attention_core_bench", [])}, f, indent=2)
            f.write("\n")
        print(f"wrote {out} (long-context rows only)", flush=True)
        return 0

    rows = prior.get("train_step_mfu", []) if args.attn_only else [
        measure("resnet18_32px", ResNet18(num_classes=100, dtype=bf16),
                32, 3072, 40, args.trials),
        measure("vit_b16_32px", ViT(**vit_b16), 32, 1024, 20, args.trials),
        # Long-sequence ViT-B/16 (224px -> 197 tokens): dense einsum
        # attention vs the Pallas flash kernel, same model otherwise.
        # "flash_auto" is what a user selecting flash_attention actually
        # gets — the measured-crossover dispatch (dense below, Pallas
        # above); "flash_forced" pins the Pallas path to document WHY
        # dispatch picks dense at 197 tokens.
        measure("vit_b16_224px_dense", ViT(**vit_b16), 224, 64, 10,
                args.trials),
        measure("vit_b16_224px_flash_auto",
                ViT(**vit_b16, attention_fn=flash_attention),
                224, 64, 10, args.trials),
        measure("vit_b16_224px_flash_forced",
                ViT(**vit_b16, attention_fn=partial(flash_attention,
                                                    use_pallas=True)),
                224, 64, 10, args.trials),
        measure("resnet50_224px_imagenet",
                ResNet50(num_classes=1000, dtype=bf16, imagenet_stem=True),
                224, 256, 10, args.trials, num_classes=1000),
        # Round-4 MFU push: the space-to-depth stem (4x4/1 conv over
        # 2x2-s2d input, exact-equivalent function — models/resnet.py
        # s2d_stem_kernel) replaces the MXU-hostile 3-channel 7x7/2 conv.
        measure("resnet50_224px_imagenet_s2d",
                ResNet50(num_classes=1000, dtype=bf16, imagenet_stem=True,
                         s2d_stem=True),
                224, 256, 10, args.trials, num_classes=1000),
        measure("resnet50_224px_imagenet_s2d_b512",
                ResNet50(num_classes=1000, dtype=bf16, imagenet_stem=True,
                         s2d_stem=True),
                224, 512, 10, args.trials, num_classes=1000),
    ]
    # The dense and flash_auto rows must be the SAME program below the
    # crossover (the dispatch routes through the shared dense core);
    # verify at the artifact level so the recorded img/s delta between
    # them is provably tunnel variance, not a real regression.
    if not args.attn_only:
        import hashlib

        from distributed_parameter_server_for_ml_training_tpu.train import (
            create_train_state, make_train_step, server_sgd)

        hashes = {}
        for tag, model in (("dense", ViT(**vit_b16)),
                           ("auto", ViT(**vit_b16,
                                        attention_fn=flash_attention))):
            st = create_train_state(model, jax.random.PRNGKey(0),
                                    server_sgd(0.1),
                                    input_shape=(1, 224, 224, 3))
            txt = jax.jit(make_train_step(augment=True)).lower(
                st, jnp.zeros((64, 224, 224, 3), jnp.uint8),
                jnp.zeros((64,), jnp.int32),
                jax.random.PRNGKey(1)).as_text()
            hashes[tag] = hashlib.sha256(txt.encode()).hexdigest()
        if hashes["dense"] == hashes["auto"]:
            for r in rows:
                if r["name"] == "vit_b16_224px_flash_auto":
                    r["hlo_identical_to"] = "vit_b16_224px_dense"
                    r["note"] = (
                        "lowered StableHLO is byte-identical to the dense "
                        "row (crossover dispatch routes through the shared "
                        "dense core at 197 tokens); the img/s delta between "
                        "the two rows is axon-tunnel run-to-run variance")
        print(f"dense-vs-auto HLO identical: "
              f"{hashes['dense'] == hashes['auto']}", flush=True)

    # Attention-core microbench: dense einsum vs the Pallas flash kernel,
    # fwd+bwd, across sequence lengths — the regime the fused kernel is
    # FOR (at CIFAR/224px token counts the whole attention is a rounding
    # error and XLA's fused dense path wins; the crossover matters for the
    # long-context/SP configs).
    import time as _time

    import jax.numpy as jnp
    import numpy as np

    # The dense arm must be the core the dispatch ACTUALLY falls back to
    # (input-dtype logits) — benchmarking against the fp32-upcast test
    # reference (parallel/ring_attention.dense_attention) overstated the
    # flash speedups by the 7-10% upcast tax and biased the crossover.
    from distributed_parameter_server_for_ml_training_tpu.ops.attention import (
        dense_core)
    from distributed_parameter_server_for_ml_training_tpu.ops.pallas.flash_attention import (
        FLASH_TIE_THRESHOLD)

    # Per-dispatch tunnel latency (~60-100 ms) would swamp a single
    # attention call, so each timing chains REPS dependent iterations
    # inside one lax.scan dispatch and divides. MEDIAN of ATTN_TRIALS
    # (not best-of-3): the axon tunnel's latency excursions flipped the
    # computed crossover between runs (512/2048/4096) when a single fast
    # or slow outlier decided a point.
    REPS = 20
    ATTN_TRIALS = max(5, args.trials)
    attn_rows = []
    for t in (512, 1024, 2048, 4096):
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(kk, (4, t, 8, 64), jnp.bfloat16)
                   for kk in ks)
        res = {"seq_len": t, "reps_per_dispatch": REPS}
        for label, fn in (("dense", dense_core),
                          ("flash", partial(flash_attention,
                                            use_pallas=True))):
            def fwd_chain(q, k, v, fn=fn):
                def body(qc, _):
                    return fn(qc, k, v), ()
                out, _ = jax.lax.scan(body, q, None, length=REPS)
                return jnp.sum(out.astype(jnp.float32))

            def grad_chain(q, k, v, fn=fn):
                g = jax.grad(lambda a: jnp.sum(
                    fn(a, k, v).astype(jnp.float32)))

                def body(qc, _):
                    return qc - 1e-3 * g(qc).astype(qc.dtype), ()
                out, _ = jax.lax.scan(body, q, None, length=REPS)
                return jnp.sum(out.astype(jnp.float32))

            for tag, chain in (("fwd", jax.jit(fwd_chain)),
                               ("fwd_bwd", jax.jit(grad_chain))):
                _ = float(chain(q, k, v))  # compile + warm
                times = []
                for _i in range(ATTN_TRIALS):
                    t0 = _time.perf_counter()
                    _ = float(chain(q, k, v))
                    times.append(_time.perf_counter() - t0)
                med = float(np.median(times))
                res[f"{label}_{tag}_ms"] = round(med / REPS * 1e3, 2)
        res["flash_fwd_speedup"] = round(
            res["dense_fwd_ms"] / res["flash_fwd_ms"], 2)
        res["flash_fwd_bwd_speedup"] = round(
            res["dense_fwd_bwd_ms"] / res["flash_fwd_bwd_ms"], 2)
        print(f"attn T={t}: dense fwd {res['dense_fwd_ms']}ms / "
              f"flash {res['flash_fwd_ms']}ms ({res['flash_fwd_speedup']}x); "
              f"fwd+bwd {res['dense_fwd_bwd_ms']} / "
              f"{res['flash_fwd_bwd_ms']}ms "
              f"({res['flash_fwd_bwd_speedup']}x)", flush=True)
        attn_rows.append(res)

    with open(out, "w") as f:
        json.dump({"train_step_mfu": rows,
                   "attention_core_bench": attn_rows}, f, indent=2)

    # Encode the measured crossover where flash_attention's auto dispatch
    # reads it (ops/pallas/attn_crossover.json): the smallest tabulated T
    # from which flash fwd+bwd SUSTAINS >= 0.95x dense. The 0.95 margin
    # treats statistical ties as flash wins — at a wall-clock tie the
    # fused kernel is strictly better on memory (no [T, T] score
    # materialization), and tunnel noise otherwise flips the boundary
    # point between runs (observed 512 <-> 1024 on a 0.97-vs-1.07 tie).
    xover = None
    for i, r in enumerate(attn_rows):
        if all(rr["flash_fwd_bwd_speedup"] >= FLASH_TIE_THRESHOLD
               for rr in attn_rows[i:]):
            xover = r["seq_len"]
            break
    if xover is None:
        # Flash never sustained a win: dispatch must NEVER auto-select it
        # (not even beyond the tabulated range — extrapolating a win from
        # an all-loss table would recreate the round-3 regression).
        xover = 2 ** 31
    from distributed_parameter_server_for_ml_training_tpu.ops.pallas import (
        flash_attention as fa_mod)
    try:
        with open(fa_mod._CROSSOVER_FILE, "w") as f:
            json.dump({
                "crossover_t": xover,
                "source": "experiments/measure_mfu.py attention_core_bench "
                          "(regenerated by every measure_mfu.py run)",
                "rule": "smallest tabulated T from which flash fwd+bwd "
                        "sustains >= 0.95x dense (ties break to flash: "
                        "O(T) memory); 2**31 = never wins",
                "measured_speedups_fwd_bwd": {
                    str(r["seq_len"]): r["flash_fwd_bwd_speedup"]
                    for r in attn_rows},
            }, f, indent=2)
            f.write("\n")
        print(f"crossover_t = {xover} -> {fa_mod._CROSSOVER_FILE}",
              flush=True)
    except OSError as e:    # read-only install: keep the results, warn
        print(f"WARNING: could not write {fa_mod._CROSSOVER_FILE}: {e}",
              file=sys.stderr, flush=True)

    print("\n| model / shape | batch | images/s/chip | ms/step | TF/s | MFU |")
    print("|---|---|---|---|---|---|")
    for r in rows:
        print(f"| {r['name']} | {r['batch']} | {r['images_per_sec']:,} | "
              f"{r['ms_per_step']} | {r['model_tflops_per_sec']} | "
              f"{r['mfu_pct_vs_v5e_bf16_peak']}% |")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
