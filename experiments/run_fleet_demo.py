"""Recorded fleet-observatory demo (ISSUE 16 acceptance evidence).

One live cluster — two shard primaries, two ``cli replica`` processes,
one supervised training worker — under ``cli loadgen``, watched by a
standalone ``cli observe`` aggregation process. Every check is
exit-code-verified (the PR 4-15 recorded-demo format); all long-lived
processes are real ``cli`` subprocesses and the driver talks to them
only over HTTP/gRPC.

**Phase A — honest rollups.** A clean loadgen window against both
primaries, then quiesce: the ``/fleet`` merged
``dps_rpc_server_latency_seconds{method=FetchParameters}`` histogram
must equal the element-wise union of the per-target ``/metrics.json``
snapshots BUCKET-EXACTLY, and the fleet p50/p95/p99 must equal the
percentiles computed from that union — no averaged percentiles.

**Phase B — discovery tiers.** Replicas announce their metrics ports
through the primaries' sharding views; the collector must adopt them
as non-explicit targets (``discovered_from`` set), the replica tier
must render, and the supervised worker must appear in the worker tier
via its primary's ``/cluster``.

**Phase C — partial-fleet tolerance.** One replica is SIGKILLed: the
next tick must stay uninterrupted (other targets fresh), mark the dead
target stale, and mint ``dps_fleet_scrape_errors_total{target=...}``
while ``/fleet`` keeps serving.

**Phase D — exemplar-linked fault.** Primary 0 is restarted with
``fetch.delay=0.12@p=0.8`` injected: the fleet p99 spikes over the
100 ms objective, the fleet-scope ``slo_burn_fast`` breach fires, and
``cli top`` exits 2. The spiked buckets carry sampled trace exemplars
that must resolve (``analysis.fleet_series.resolve_exemplars``) to at
least one assembled trace in the primaries' flight-recorder dumps.

**Phase E — recovery.** Primary 0 restarts clean; once the fast burn
window drains, ``cli top`` exits 0 again. ``cli status --via-fleet``
output is recorded alongside.

**Phase F — overhead.** The serving primary's CPU cost per scrape is
measured from ``/proc/<pid>/stat`` across an idle window with a 10 Hz
probe collector vs. without: at the default 2 s cadence the scrape
overhead must stay under 2% of one core.

Artifacts: ``fleet_demo.json`` (summary + PASS/FAIL checks), clean and
fault ``/fleet`` snapshots, flight-recorder dumps, ``cli top`` /
``cli status --via-fleet`` captures, and process logs.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT_DIR = os.path.join(REPO, "experiments", "results", "fleet")
PKG = "distributed_parameter_server_for_ml_training_tpu"
sys.path.insert(0, REPO)

MODEL = "vit_tiny"
FAULT_SPEC = "fetch.delay=0.12@p=0.8"


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env(**extra) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONUNBUFFERED"] = "1"
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _http(url: str, timeout: float = 5.0) -> str | None:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.read().decode()
    except Exception:
        return None


def _get_json(url: str, timeout: float = 5.0) -> dict | None:
    raw = _http(url, timeout)
    if raw is None:
        return None
    try:
        return json.loads(raw)
    except ValueError:
        return None


def _spawn(argv: list, log_path: str, **env_extra):
    log = open(log_path, "a")
    proc = subprocess.Popen(argv, stdout=log, stderr=subprocess.STDOUT,
                            env=_env(**env_extra), cwd=REPO)
    return proc, log


def _stop(proc, log, grace: float = 15.0) -> int | None:
    if proc is not None and proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=grace)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=grace)
    if log is not None:
        log.close()
    return None if proc is None else proc.returncode


def _serve_argv(*, index: int, port: int, metrics_port: int,
                peers: str, faults: str | None = None) -> list:
    argv = [sys.executable, "-m", f"{PKG}.cli", "serve",
            "--mode", "async", "--workers", "1",
            "--port", str(port), "--model", MODEL,
            "--num-classes", "100", "--image-size", "32",
            "--platform", "cpu", "--metrics-port", str(metrics_port),
            "--health-interval", "0.5", "--elastic",
            "--worker-timeout", "5",
            "--shard-index", str(index), "--shard-count", "2",
            "--shard-peers", peers,
            "--trace", "--trace-buffer", "8192"]
    if faults:
        argv += ["--faults", faults]
    return argv


def _wait(pred, what: str, timeout: float = 120.0, poll: float = 0.25):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(poll)
    raise RuntimeError(f"timed out waiting for {what}")


def _proc_cpu_s(pid: int) -> float:
    with open(f"/proc/{pid}/stat") as f:
        parts = f.read().rsplit(")", 1)[1].split()
    # fields 14/15 (utime/stime) are parts[11]/parts[12] after comm
    ticks = int(parts[11]) + int(parts[12])
    return ticks / os.sysconf("SC_CLK_TCK")


def _loadgen(targets: list[str], duration: float,
             concurrency: int = 2) -> dict | None:
    cp = subprocess.run(
        [sys.executable, "-m", f"{PKG}.cli", "loadgen",
         "--targets", ",".join(targets), "--duration", str(duration),
         "--concurrency", str(concurrency), "--fetch-mode", "full"],
        capture_output=True, text=True, env=_env(), cwd=REPO,
        timeout=duration + 120)
    for line in cp.stdout.splitlines():
        if line.startswith("LOADGEN_JSON "):
            return json.loads(line[len("LOADGEN_JSON "):])
    return None


def _top(fleet_port: int, json_out: bool = False):
    argv = [sys.executable, "-m", f"{PKG}.cli", "top",
            "--url", f"http://127.0.0.1:{fleet_port}"]
    if json_out:
        argv.append("--json")
    cp = subprocess.run(argv, capture_output=True, text=True,
                        env=_env(), cwd=REPO, timeout=60)
    return cp.returncode, cp.stdout


def main(argv=None) -> int:
    import argparse
    global OUT_DIR

    from distributed_parameter_server_for_ml_training_tpu.analysis. \
        fleet_series import resolve_exemplars
    from distributed_parameter_server_for_ml_training_tpu.telemetry. \
        fleet import FleetCollector
    from distributed_parameter_server_for_ml_training_tpu.telemetry. \
        registry import MetricsRegistry
    from distributed_parameter_server_for_ml_training_tpu.telemetry. \
        stats import histogram_quantile, merge_histograms

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out-dir", default=OUT_DIR)
    args = ap.parse_args(argv)
    OUT_DIR = args.out_dir
    os.makedirs(OUT_DIR, exist_ok=True)
    quick = args.quick
    lg_a_s = 5.0 if quick else 10.0
    lg_b_s = 6.0 if quick else 12.0
    fast_window = 10.0 if quick else 20.0
    idle_w = 5.0 if quick else 8.0

    t0 = time.time()
    checks: list[tuple[str, bool, str]] = []
    procs: list[tuple] = []
    sup = sup_log = None
    fetch_key = "dps_rpc_server_latency_seconds{method=FetchParameters}"

    try:
        # -- boot: 2 shard primaries + the observe process ------------------
        ports = [_free_port(), _free_port()]
        mports = [_free_port(), _free_port()]
        peers = ",".join(f"localhost:{p}" for p in ports)
        plogs = [os.path.join(OUT_DIR, f"primary{i}.log")
                 for i in range(2)]
        primaries: list = [None, None]
        for i in range(2):
            p, lg = _spawn(_serve_argv(index=i, port=ports[i],
                                       metrics_port=mports[i],
                                       peers=peers), plogs[i])
            primaries[i] = (p, lg)
            procs.append((p, lg))
        for i in range(2):
            _wait(lambda i=i: _get_json(
                f"http://127.0.0.1:{mports[i]}/cluster"),
                f"primary {i} admin plane")

        fleet_port = _free_port()
        obs, obs_log = _spawn(
            [sys.executable, "-m", f"{PKG}.cli", "observe",
             "--targets", ",".join(f"127.0.0.1:{m}" for m in mports),
             "--port", str(fleet_port),
             "--interval", "0.4", "--timeout", "1.0",
             "--slo-fast-window", str(fast_window),
             "--slo-slow-window", str(fast_window * 3)],
            os.path.join(OUT_DIR, "observe.log"))
        procs.append((obs, obs_log))
        fleet_url = f"http://127.0.0.1:{fleet_port}/fleet"
        _wait(lambda: _get_json(fleet_url), "the /fleet endpoint")

        def fleet_view() -> dict:
            return _get_json(fleet_url) or {}

        def wait_ticks(n: int, timeout: float = 30.0) -> dict:
            start = int(fleet_view().get("ticks") or 0)
            _wait(lambda: int(fleet_view().get("ticks") or 0)
                  >= start + n, f"{n} collector ticks", timeout)
            return fleet_view()

        # -- phase A: clean load, then bucket-exact rollup parity -----------
        lg_a = _loadgen([f"localhost:{p}" for p in ports], lg_a_s)
        wait_ticks(3)            # quiesced: nothing touches the serve path
        snaps = [_get_json(f"http://127.0.0.1:{m}/metrics.json")
                 for m in mports]
        clean = fleet_view()
        with open(os.path.join(OUT_DIR, "fleet_snapshot_clean.json"),
                  "w") as f:
            json.dump(clean, f, indent=2)
        union = merge_histograms(
            [s["histograms"][fetch_key] for s in snaps])
        merged = clean["rollups"]["histograms"].get(fetch_key) or {}
        pcts_ok = True
        for pct, pkey in ((50, "p50_ms"), (95, "p95_ms"), (99, "p99_ms")):
            q = histogram_quantile(union["le"], union["counts"], pct)
            want = None if q is None else round(q * 1e3, 3)
            pcts_ok &= merged.get(pkey) == want
        checks += [
            ("A_loadgen_clean",
             lg_a is not None and lg_a["fetches_ok"] > 0
             and lg_a["fetches_err"] == 0,
             f"{(lg_a or {}).get('fetches_ok')} fetches"),
            ("A_merged_histogram_bucket_exact",
             merged.get("counts") == union["counts"]
             and merged.get("count") == union["count"]
             and merged.get("targets") == 2,
             f"union count={union['count']} over 2 primaries"),
            ("A_fleet_percentiles_equal_union_percentiles", bool(pcts_ok),
             f"p99={merged.get('p99_ms')}ms"),
        ]
        print(f"phase A: merged count={merged.get('count')} "
              f"p99={merged.get('p99_ms')}ms bucket-exact="
              f"{merged.get('counts') == union['counts']}", flush=True)

        # -- phase B: replicas + supervised worker are discovered ------------
        rep_mports = [_free_port(), _free_port()]
        rep_procs = []
        for i in range(2):
            rp, rl = _spawn(
                [sys.executable, "-m", f"{PKG}.cli", "replica",
                 "--primary", f"localhost:{ports[i]}",
                 "--port", str(_free_port()), "--shard-id", str(i),
                 "--metrics-port", str(rep_mports[i]),
                 "--metrics-advertise", f"127.0.0.1:{rep_mports[i]}",
                 "--poll-interval", "0.2"],
                os.path.join(OUT_DIR, f"replica{i}.log"))
            procs.append((rp, rl))
            rep_procs.append(rp)
        sup, sup_log = _spawn(
            [sys.executable, "-m", f"{PKG}.cli", "supervise",
             "--workers", "1", "--healthy-after", "2",
             "--platform", "cpu", "--",
             "--server", f"localhost:{ports[1]}",
             "--model", MODEL, "--synthetic", "--num-train", "1500",
             "--num-test", "96", "--epochs", "3", "--batch-size", "32",
             "--dtype", "float32", "--no-augment",
             "--heartbeat", "0.5", "--reconnect-timeout", "30"],
            os.path.join(OUT_DIR, "supervise.log"))

        def discovered() -> list:
            return [t for t in fleet_view().get("targets", [])
                    if not t.get("explicit")]

        _wait(lambda: len(discovered()) >= 2,
              "both replicas discovered", 60)
        _wait(lambda: (fleet_view().get("tiers") or {}).get("workers"),
              "the supervised worker tier", 120)
        view_b = fleet_view()
        reps = [t for t in view_b["targets"] if not t["explicit"]]
        serve_roll = view_b["rollups"]["histograms"].get(
            "dps_replica_serve_seconds") or {}
        checks += [
            ("B_replicas_adopted_from_sharding_views",
             len(reps) == 2 and all(t["discovered_from"] for t in reps)
             and all(t["ok"] for t in reps),
             f"{[t['target'] for t in reps]}"),
            ("B_tiers_render_all_three",
             len(view_b["tiers"]["primaries"]) == 2
             and len(view_b["tiers"]["replicas"]) == 2
             and len(view_b["tiers"]["workers"]) >= 1,
             f"workers={len(view_b['tiers']['workers'])}"),
            ("B_replica_serve_series_rolled_up",
             serve_roll.get("targets") == 2,
             f"replica serve targets={serve_roll.get('targets')}"),
        ]
        print(f"phase B: {len(reps)} replicas discovered, "
              f"{len(view_b['tiers']['workers'])} supervised worker(s)",
              flush=True)

        # -- phase C: SIGKILL one replica — stale series, tick uninterrupted -
        victim = f"http://127.0.0.1:{rep_mports[1]}"
        os.kill(rep_procs[1].pid, signal.SIGKILL)
        rep_procs[1].wait(timeout=30)

        def victim_stale():
            v = fleet_view()
            rows = {t["target"]: t for t in v.get("targets", [])}
            row = rows.get(victim)
            return v if row is not None and row.get("stale") else None

        view_c = _wait(victim_stale, "the killed replica to go stale", 30)
        rows = {t["target"]: t for t in view_c["targets"]}
        others_fresh = all(not t["stale"] for t in view_c["targets"]
                           if t["target"] != victim)
        err_metrics = _http(
            f"http://127.0.0.1:{fleet_port}/metrics") or ""
        err_line = (f'dps_fleet_scrape_errors_total{{target="{victim}"}}')
        ticks_before = int(view_c["ticks"])
        time.sleep(1.5)
        ticks_after = int(fleet_view().get("ticks") or 0)
        checks += [
            ("C_dead_target_marked_stale",
             rows[victim]["stale"]
             and rows[victim]["consecutive_failures"] >= 1,
             f"failures={rows[victim]['consecutive_failures']}"),
            ("C_tick_uninterrupted_others_fresh",
             others_fresh and ticks_after > ticks_before,
             f"ticks {ticks_before}->{ticks_after}"),
            ("C_scrape_error_series_minted", err_line in err_metrics,
             err_line),
        ]
        print(f"phase C: victim stale, ticks {ticks_before}->"
              f"{ticks_after} with {len(view_c['targets'])} targets",
              flush=True)

        # -- phase D: latency fault on primary 0 -> spike, breach, exemplar --
        p0, p0log = primaries[0]
        _stop(p0, None)          # keep the log handle for the restart
        p0, _ = _spawn(_serve_argv(index=0, port=ports[0],
                                   metrics_port=mports[0], peers=peers,
                                   faults=FAULT_SPEC), plogs[0])
        primaries[0] = (p0, p0log)
        procs.append((p0, None))
        _wait(lambda: _get_json(f"http://127.0.0.1:{mports[0]}/cluster"),
              "primary 0 back with the fault injected")
        lg_b = _loadgen([f"localhost:{p}" for p in ports], lg_b_s)
        wait_ticks(2)
        fault = fleet_view()
        with open(os.path.join(OUT_DIR, "fleet_snapshot_fault.json"),
                  "w") as f:
            json.dump(fault, f, indent=2)
        dump_paths = []
        for i in range(2):
            dump = _get_json(
                f"http://127.0.0.1:{mports[i]}/debug/trace?n=8000")
            if dump:
                path = os.path.join(OUT_DIR, f"trace-primary{i}.json")
                with open(path, "w") as f:
                    json.dump(dump, f)
                dump_paths.append(path)
        fp99 = (fault["rollups"]["histograms"].get(fetch_key)
                or {}).get("p99_ms")
        breaches = {(b["rule"], b.get("scope"))
                    for b in fault.get("slo", {}).get("breaches", [])}
        resolved = resolve_exemplars(fault, dump_paths=dump_paths,
                                     min_value_s=0.1)
        with open(os.path.join(OUT_DIR, "exemplar_resolution.json"),
                  "w") as f:
            json.dump({k: resolved[k] for k in
                       ("exemplars", "resolved", "unresolved")},
                      f, indent=2)
        top_rc_fault, top_text = _top(fleet_port)
        with open(os.path.join(OUT_DIR, "top_fault.txt"), "w") as f:
            f.write(top_text)
        checks += [
            ("D_fleet_p99_spikes_over_objective",
             fp99 is not None and fp99 > 100.0, f"fleet p99={fp99}ms"),
            ("D_fleet_scope_burn_breach_fires",
             ("slo_burn_fast", "fleet") in breaches, f"{breaches}"),
            ("D_exemplar_resolves_to_flight_recorder_trace",
             resolved["resolved"] >= 1,
             f"{resolved['resolved']} resolved / "
             f"{resolved['unresolved']} unresolved"),
            ("D_cli_top_exits_2_during_fault", top_rc_fault == 2,
             f"rc={top_rc_fault}"),
            ("D_loadgen_survives_fault",
             lg_b is not None and lg_b["fetches_ok"] > 0,
             f"{(lg_b or {}).get('fetches_ok')} fetches"),
        ]
        print(f"phase D: p99={fp99}ms, breaches={breaches}, "
              f"{resolved['resolved']} exemplar trace(s) resolved, "
              f"top rc={top_rc_fault}", flush=True)

        # -- phase E: clean restart -> burn window drains -> top exits 0 -----
        p0, _ = primaries[0]
        _stop(p0, None)
        p0, _ = _spawn(_serve_argv(index=0, port=ports[0],
                                   metrics_port=mports[0], peers=peers),
                       plogs[0])
        primaries[0] = (p0, p0log)
        procs.append((p0, None))
        _wait(lambda: _get_json(f"http://127.0.0.1:{mports[0]}/cluster"),
              "primary 0 back clean")
        _loadgen([f"localhost:{ports[0]}"], 2.0, concurrency=1)

        def top_clear():
            rc, text = _top(fleet_port)
            return (rc, text) if rc == 0 else None

        rc_text = _wait(top_clear, "cli top to exit 0 again",
                        fast_window * 3 + 60, poll=1.0)
        with open(os.path.join(OUT_DIR, "top_recovered.txt"), "w") as f:
            f.write(rc_text[1])
        st = subprocess.run(
            [sys.executable, "-m", f"{PKG}.cli", "status",
             "--via-fleet", f"http://127.0.0.1:{fleet_port}"],
            capture_output=True, text=True, env=_env(), cwd=REPO,
            timeout=60)
        with open(os.path.join(OUT_DIR, "status_via_fleet.txt"),
                  "w") as f:
            f.write(st.stdout)
        checks += [
            ("E_cli_top_exits_0_after_recovery", rc_text[0] == 0,
             f"cleared {round(time.time() - t0, 1)}s into the demo"),
            ("E_status_via_fleet_renders",
             st.returncode == 0 and "cluster:" in st.stdout
             and "workers=" in st.stdout,
             f"rc={st.returncode}"),
        ]
        print(f"phase E: top rc=0, status --via-fleet rc="
              f"{st.returncode}", flush=True)

        # -- phase F: scrape overhead on the serving primary -----------------
        pid1 = primaries[1][0].pid
        cpu_a0 = _proc_cpu_s(pid1)
        time.sleep(idle_w)
        base_cpu = _proc_cpu_s(pid1) - cpu_a0
        probe = FleetCollector([f"127.0.0.1:{mports[1]}"],
                               interval_s=0.1, timeout_s=2.0,
                               registry=MetricsRegistry())
        cpu_b0 = _proc_cpu_s(pid1)
        t_probe = time.time()
        n_scrapes = 0
        while time.time() - t_probe < idle_w:
            probe.tick()
            n_scrapes += 1
            time.sleep(0.1)
        probe_cpu = _proc_cpu_s(pid1) - cpu_b0
        per_scrape_s = max(0.0, probe_cpu - base_cpu) / max(1, n_scrapes)
        overhead_frac = per_scrape_s / 2.0   # default observe cadence
        checks += [
            ("F_scrape_overhead_under_2pct", overhead_frac < 0.02,
             f"{round(overhead_frac * 100, 3)}% of one core at 2s "
             f"cadence ({n_scrapes} probe scrapes, "
             f"per-scrape {round(per_scrape_s * 1e3, 2)}ms cpu)"),
        ]
        print(f"phase F: per-scrape {round(per_scrape_s * 1e3, 2)}ms "
              f"primary cpu -> {round(overhead_frac * 100, 3)}% of one "
              f"core at the default cadence", flush=True)

        final_view = fleet_view()
        summary = {
            "demo": "fleet observatory: merged rollups, discovery, "
                    "exemplar-linked faults, live top (ISSUE 16)",
            "quick": quick,
            "elapsed_seconds": round(time.time() - t0, 1),
            "environment": {"cpus": os.cpu_count()},
            "loadgen_clean": {k: (lg_a or {}).get(k)
                              for k in ("fetches_ok", "fetches_err",
                                        "qps")},
            "clean_p99_ms": merged.get("p99_ms"),
            "fault_p99_ms": fp99,
            "exemplars_resolved": resolved["resolved"],
            "scrape_overhead_pct": round(overhead_frac * 100, 4),
            "overhead_windows": {
                "window_s": idle_w, "probe_scrapes": n_scrapes,
                "idle_cpu_s": round(base_cpu, 4),
                "probed_cpu_s": round(probe_cpu, 4),
                "per_scrape_cpu_ms": round(per_scrape_s * 1e3, 4)},
            "final_ticks": final_view.get("ticks"),
            "final_series_count": final_view.get("series_count"),
        }
    finally:
        _stop(sup, sup_log, grace=20.0)
        for proc, log in reversed(procs):
            _stop(proc, log)

    summary["checks"] = [{"name": n, "ok": bool(ok), "detail": d}
                         for n, ok, d in checks]
    summary["ok"] = all(ok for _, ok, _ in checks)
    with open(os.path.join(OUT_DIR, "fleet_demo.json"), "w") as f:
        json.dump(summary, f, indent=2)
    n_pass = sum(1 for _, ok, _ in checks if ok)
    print(f"fleet demo: {n_pass}/{len(checks)} checks PASS "
          f"({summary['elapsed_seconds']}s)")
    for name, ok, detail in checks:
        print(f"  [{'PASS' if ok else 'FAIL'}] {name} — {detail}")
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
