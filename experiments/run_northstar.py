"""North-star convergence runs on the attached TPU chip.

Produces the recorded experiment artifacts the reference ships
(/root/reference/experiment_results/{sync_4workers,async_4workers,
async_8workers}.json + charts) for THIS framework, plus runs the reference's
own comparison points to plateau:

1. the sync/async x {4,8} worker matrix at the reference's 3-epoch config
   (EXPERIMENT_GUIDE.md:95-111) -> experiments/results/<cell>.json + plots,
2. the single-machine baseline recipe (baseline_training.py:201-260) to
   plateau (past both MultiStepLR milestones) -> baseline_convergence.json,
3. a long sync run to plateau -> sync_4workers_long.json.

Real CIFAR-100 is NOT available in this environment (no network egress);
every run uses the deterministic class-structured synthetic stand-in
(data/cifar.py:synthetic_cifar100) and every artifact records that
provenance. The comparison against the reference's recorded curves is
therefore about *relative shapes* (sync vs async vs baseline, staleness
rejection behavior), written up in experiments/results/ACCURACY.md.

Run:  python experiments/run_northstar.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from functools import partial

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# XLA compiles on the HOST CPU (single core here, ~1-2 min per executable);
# the persistent cache makes every re-run and every identical cell free.
# Set via jax.config (the env-var route is swallowed by the axon site hook).
import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                 os.path.join(REPO, ".jax_cache")))

OUT = os.path.join(REPO, "experiments", "results")


def run_baseline_convergence(ds, epochs: int, out_dir: str) -> dict:
    from distributed_parameter_server_for_ml_training_tpu.train.baseline import (
        BaselineConfig, BaselineTrainer)

    # device_loop: one compiled program per epoch over the device-resident
    # dataset — the only way the remote-attached chip trains at compute speed.
    cfg = BaselineConfig(num_epochs=epochs, device_loop=True)
    trainer = BaselineTrainer(ds, cfg)
    t0 = time.time()
    metrics = trainer.train(
        plot_path=os.path.join(out_dir, "baseline_convergence.png"))
    total = time.time() - t0
    record = {
        "experiment_name": "baseline_convergence",
        "dataset": {
            "synthetic": bool(ds.synthetic),
            "num_classes": int(ds.num_classes),
            "n_train": int(len(ds.x_train)),
            "n_test": int(len(ds.x_test)),
        },
        "device": str(jax.devices()[0]),
        "config": {
            "batch_size": cfg.batch_size,
            "num_epochs": cfg.num_epochs,
            "learning_rate": cfg.learning_rate,
            "momentum": cfg.momentum,
            "weight_decay": cfg.weight_decay,
            "milestones": list(cfg.milestones),
            "gamma": cfg.gamma,
            "dtype": cfg.dtype,
        },
        "total_training_time_seconds": round(total, 2),
        "epochs": metrics.epochs,
        "train_losses": metrics.train_losses,
        "train_accuracies_pct": metrics.train_accuracies,
        "test_accuracies_pct": metrics.test_accuracies,
        "epoch_times_seconds": [round(t, 3) for t in metrics.epoch_times],
    }
    with open(os.path.join(out_dir, "baseline_convergence.json"), "w") as f:
        json.dump(record, f, indent=2)
    return record


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true",
                        help="tiny shapes for a smoke test of this script")
    parser.add_argument("--variant", choices=["easy", "hard", "calibrated"],
                        default="calibrated",
                        help="synthetic difficulty: 'calibrated' (default; "
                             "compositional dataset matched to the "
                             "reference's learning curve — ep1 ~8%%, 65%% "
                             "crossed at epoch 11, plateau ~70%%), 'easy' "
                             "(class templates; ~100%% in 1-2 epochs — "
                             "fast convergence checks) or 'hard' "
                             "(low-amplitude templates + heavy noise; "
                             "superseded by 'calibrated')")
    args = parser.parse_args()

    global OUT
    if args.variant != "easy":
        OUT = os.path.join(OUT, args.variant)
    os.makedirs(OUT, exist_ok=True)

    from distributed_parameter_server_for_ml_training_tpu.analysis import (
        run_matrix)
    from distributed_parameter_server_for_ml_training_tpu.analysis.runner import (
        run_cell)
    from distributed_parameter_server_for_ml_training_tpu.data import (
        compositional_cifar100, synthetic_cifar100)

    # 'hard' difficulty tuned so ResNet-18 shows a gradual CIFAR-like curve
    # instead of instant 100%; 'calibrated' goes further — the compositional
    # generator whose knobs were swept (experiments/calibrate_dataset.py)
    # until the baseline recipe reproduces the reference's curve SHAPE.
    make_ds = (compositional_cifar100 if args.variant == "calibrated"
               else partial(synthetic_cifar100,
                            **(dict(template_amp=0.06, noise=0.45)
                               if args.variant == "hard" else {})))
    if args.quick:
        ds = make_ds(n_train=2048, n_test=512)
        matrix_epochs, base_epochs, long_epochs = 1, 2, 1
        counts = (2,)
    else:
        ds = make_ds()   # 50k/10k, the reference's sizes
        matrix_epochs, base_epochs, long_epochs = 3, 20, 12
        counts = (4, 8)

    with open(os.path.join(OUT, "MANIFEST.json"), "w") as f:
        json.dump({
            "variant": args.variant,
            "dataset": {"generator": make_ds.func.__name__
                          if isinstance(make_ds, partial)
                          else make_ds.__name__,
                        "synthetic": True,
                        "n_train": len(ds.x_train),
                        "n_test": len(ds.x_test)},
            "note": "Real CIFAR-100 is unavailable in this environment "
                    "(no network egress); runs use the deterministic "
                    "synthetic stand-in (data/cifar.py).",
        }, f, indent=2)

    t0 = time.time()

    # 1) The reference's experiment matrix (3 epochs, per its recorded runs).
    #    backend='device': store tensors stay in HBM; the host-numpy store
    #    would move ~90 MB per worker step through the ~3 MB/s tunnel.
    print(f"== matrix: sync/async x {counts} ({matrix_epochs} epochs) ==",
          flush=True)
    run_matrix(ds, OUT, modes=("sync", "async"), worker_counts=counts,
               epochs=matrix_epochs, backend="device")

    # 2) Baseline recipe to plateau (README.md:138 trained 20 epochs).
    print(f"== baseline convergence ({base_epochs} epochs) ==", flush=True)
    rec = run_baseline_convergence(ds, base_epochs, OUT)
    print(f"   final test acc {rec['test_accuracies_pct'][-1]:.2f}% "
          f"in {rec['total_training_time_seconds']:.0f}s", flush=True)

    # 3) Long sync run to plateau.
    print(f"== long sync x {counts[0]} ({long_epochs} epochs) ==", flush=True)
    cell = run_cell(ds, "sync", counts[0], epochs=long_epochs,
                    backend="device")
    cell["experiment_name"] = f"sync_{counts[0]}workers_long"
    with open(os.path.join(OUT, cell["experiment_name"] + ".json"), "w") as f:
        json.dump(cell, f, indent=2)
    agg = cell["worker_metrics_aggregated"]
    print(f"   total {agg['total_training_time_seconds']:.1f}s, "
          f"final acc {agg['average_final_accuracy']:.4f}", flush=True)

    print(f"all north-star runs done in {time.time() - t0:.0f}s", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
