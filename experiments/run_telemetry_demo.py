"""Recorded end-to-end telemetry demo (ISSUE acceptance artifact).

Runs one SYNC and one ASYNC training run with ``--telemetry`` enabled as
real CLI subprocesses, captures their stdout (snapshot stream + classic
exit lines), and parses both through the extended ETL into per-worker
throughput and staleness time-series. Also records ``bench.py``'s
diagnostic JSON under an injected backend-init failure.

Outputs (checked into experiments/results/telemetry/):

- ``sync_demo.log`` / ``async_demo.log`` — raw captured stdout (the
  evidence the parses are real, and a fixture for re-running the ETL),
- ``sync_demo.json`` / ``async_demo.json`` — experiment record (reference
  schema, snapshots filtered) + built time-series + derived
  throughput/staleness series,
- ``telemetry_timeseries.png`` — 4-panel plot from the async stream,
- ``bench_diag_demo.json`` — bench.py stdout + rc under
  ``DPS_BENCH_FAIL_INJECT=99`` (proves the flake-proofing artifact).

Usage::

    python experiments/run_telemetry_demo.py [--out-dir experiments/results/telemetry]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # runnable from any cwd
    sys.path.insert(0, REPO)
CLI = [sys.executable, "-m",
       "distributed_parameter_server_for_ml_training_tpu.cli"]


def _env(n_devices: int = 1) -> dict:
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               PYTHONUNBUFFERED="1",
               JAX_COMPILATION_CACHE_DIR=os.path.join(REPO, ".jax_cache"))
    if n_devices > 1:
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            f" --xla_force_host_platform_device_count="
                            f"{n_devices}")
    return env


def run_mode(mode: str, out_dir: str, epochs: int = 2,
             workers: int = 2) -> dict:
    cmd = CLI + ["train", "--mode", mode, "--workers", str(workers),
                 "--model", "vit_tiny", "--synthetic",
                 "--num-train", "256", "--num-test", "64",
                 "--epochs", str(epochs), "--batch-size", "32",
                 "--platform", "cpu", "--dtype", "float32", "--no-augment",
                 "--emit-metrics", "--telemetry",
                 "--telemetry-interval", "1.0"]
    print(f"[{mode}] {' '.join(cmd)}", file=sys.stderr)
    proc = subprocess.run(cmd, cwd=REPO, env=_env(workers),
                          capture_output=True, timeout=900)
    log = proc.stdout.decode(errors="replace")
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr.decode(errors="replace")[-3000:])
        raise SystemExit(f"{mode} demo run failed rc={proc.returncode}")

    with open(os.path.join(out_dir, f"{mode}_demo.log"), "w") as f:
        f.write(log)

    from distributed_parameter_server_for_ml_training_tpu.analysis import (
        build_telemetry_timeseries, parse_experiment, staleness_series,
        worker_throughput_series)
    ts = build_telemetry_timeseries(log)
    record = {
        "experiment": parse_experiment(log, f"telemetry_{mode}_demo"),
        "timeseries": ts,
        "worker_throughput": worker_throughput_series(ts),
        "staleness": staleness_series(ts),
        "command": cmd[2:],
    }
    with open(os.path.join(out_dir, f"{mode}_demo.json"), "w") as f:
        json.dump(record, f, indent=2)
    n_snaps = sum(len(v["t"]) for v in ts["procs"].values())
    print(f"[{mode}] ok: {n_snaps} snapshots, "
          f"throughput series: {sorted(record['worker_throughput'])}",
          file=sys.stderr)
    return record


def run_bench_diag(out_dir: str) -> None:
    cmd = [sys.executable, "bench.py", "--init-backoff", "0.2",
           "--trials", "1"]
    proc = subprocess.run(cmd, cwd=REPO,
                          env=dict(os.environ, JAX_PLATFORMS="cpu",
                                   DPS_BENCH_FAIL_INJECT="99"),
                          capture_output=True, timeout=300)
    line = proc.stdout.decode(errors="replace").strip().splitlines()[-1]
    diag = json.loads(line)
    assert diag["ok"] is False and diag["stage"] == "backend_init", diag
    with open(os.path.join(out_dir, "bench_diag_demo.json"), "w") as f:
        json.dump({"rc": proc.returncode, "stdout_last_line": diag,
                   "command": cmd,
                   "env": {"DPS_BENCH_FAIL_INJECT": "99"}}, f, indent=2)
    print(f"[bench-diag] ok: rc={proc.returncode}, stage="
          f"{diag['stage']}, attempts={diag['attempts']}", file=sys.stderr)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir",
                    default=os.path.join(REPO, "experiments", "results",
                                         "telemetry"))
    ap.add_argument("--epochs", type=int, default=2)
    args = ap.parse_args()
    os.makedirs(args.out_dir, exist_ok=True)

    async_rec = run_mode("async", args.out_dir, epochs=args.epochs)
    run_mode("sync", args.out_dir, epochs=args.epochs)
    run_bench_diag(args.out_dir)

    from distributed_parameter_server_for_ml_training_tpu.analysis import (
        ExperimentVisualizer)
    ExperimentVisualizer.plot_telemetry(
        async_rec["timeseries"],
        os.path.join(args.out_dir, "telemetry_timeseries.png"))
    print(f"artifacts in {args.out_dir}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
