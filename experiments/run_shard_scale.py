"""Recorded sharded-serve-path demo (ISSUE 9 acceptance evidence).

Four cells under ``experiments/results/sharding/``, every check
exit-code-verified (the PR 4-7 recorded-demo format). Environment note
recorded in the artifact: this container exposes ONE cpu, so
process-parallel scale-out is not measurable here — the serve-path QPS
lever this demo pins is the PER-REQUEST cost collapse of the read tier
(cached-bytes replicas + delta polls) against the reference fetch path
(the source paper's server ships the full model on every fetch,
server.py:213-237).

**Cell A — serve-path QPS, 1 shard + 4 replicas vs single-server
control.** A control ``cli serve`` process takes ``cli loadgen`` full
fetches (the reference fetch path). The scale topology — one primary
with ``--shard-peers`` + four ``cli replica`` processes — takes the same
loadgen in both modes against the replica tier. Headline check: the
production read path (delta polls against the tier) sustains >= 10x the
aggregate fetch QPS of the reference path against the control, while
the primary's own fetch handler sees almost none of the consumer
traffic (offload check: its call counter moves by replica polls, not by
consumer fetches). Replica membership + zero lag are read live from
``GET /cluster``.

**Cell B/C — replica lag + exact training parity, real processes.**
Control: single server + 1 sync worker. Sharded: two shard primaries
(``--shard-count 2``) + a delta-fed replica behind shard 0 + the same
worker driving ``--shards``. While training runs, shard 0's
``GET /cluster`` sharding block is polled continuously: every observed
replica lag must stay within the bound, and ``cli status`` during the
run must render the shard/replica rows (exit code recorded). Parity
check: the sharded run's per-epoch accuracy curve and local step count
equal the control's EXACTLY — consistent-hash partitioning changes
where tensors live, not one bit of the math.

**Cell D — shard-primary kill+restart, journal-verified.** One shard
primary (``--shard-index 1 --shard-count 2``) with periodic
checkpoints: apply a tokened push, wait for the covering snapshot
(stamped with its shard identity), SIGKILL, restart with ``--restore``,
and replay the IDENTICAL push bytes — the restarted shard must answer
``duplicate`` from its restored journal with the step unmoved (zero
double-applies), then accept a genuinely new push.

Artifacts: ``shard_scale.json`` (summary + PASS/FAIL checks), per-cell
loadgen JSON, cluster/status captures, and server logs.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT_DIR = os.path.join(REPO, "experiments", "results", "sharding")
PKG = "distributed_parameter_server_for_ml_training_tpu"
sys.path.insert(0, REPO)

MODEL = "vit_tiny"
LOADGEN_SECS = 5.0
REPLICAS = 4
LAG_BOUND_STEPS = 5          # cell B: every observed replica lag <= this
STALENESS_BOUND_S = 5.0


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env(**extra) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _http(url: str, timeout: float = 5.0) -> str | None:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.read().decode()
    except Exception:
        return None


def _cluster(port: int) -> dict | None:
    raw = _http(f"http://127.0.0.1:{port}/cluster")
    if raw is None:
        return None
    try:
        return json.loads(raw)
    except ValueError:
        return None


def _metric_value(metrics_text: str | None, name: str,
                  labels: str = "") -> float | None:
    import re
    if not metrics_text:
        return None
    pat = re.compile(rf"^{re.escape(name + labels)} ([0-9.e+-]+)$", re.M)
    m = pat.search(metrics_text)
    return float(m.group(1)) if m else None


def _spawn(argv: list[str], log_path: str, **env_extra) -> tuple:
    log = open(log_path, "w")
    proc = subprocess.Popen(argv, stdout=log, stderr=subprocess.STDOUT,
                            env=_env(**env_extra), cwd=REPO)
    return proc, log


def _stop(proc, log, grace: float = 15.0) -> int | None:
    if proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=grace)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=grace)
    log.close()
    return proc.returncode


def _serve_argv(*, port: int, metrics_port: int, mode: str = "async",
                workers: int = 1, extra: list[str] | None = None):
    return [sys.executable, "-m", f"{PKG}.cli", "serve",
            "--mode", mode, "--workers", str(workers),
            "--port", str(port), "--model", MODEL, "--num-classes", "100",
            "--image-size", "32", "--platform", "cpu",
            "--metrics-port", str(metrics_port)] + (extra or [])


def _wait_up(metrics_port: int, proc, what: str, timeout: float = 180.0):
    deadline = time.time() + timeout
    while _cluster(metrics_port) is None:
        if time.time() > deadline or proc.poll() is not None:
            raise RuntimeError(f"{what} never came up "
                               f"(rc={proc.poll()})")
        time.sleep(0.25)


def _grpc_up(addr: str, timeout: float = 60.0) -> None:
    """Block until a PS answers FetchParameters at ``addr``."""
    from distributed_parameter_server_for_ml_training_tpu.comms.loadgen \
        import run_loadgen
    deadline = time.time() + timeout
    while time.time() < deadline:
        r = run_loadgen([addr], duration_s=0.2, concurrency=1,
                        rpc_timeout=2.0)
        if r["fetches_ok"] > 0:
            return
        time.sleep(0.5)
    raise RuntimeError(f"no PS answering at {addr}")


def _loadgen(targets: list[str], mode: str, name: str,
             concurrency: int = 4) -> tuple[int, dict | None]:
    """Run ``cli loadgen`` as a subprocess; returns (rc, LOADGEN_JSON)."""
    p = subprocess.run(
        [sys.executable, "-m", f"{PKG}.cli", "loadgen",
         "--targets", ",".join(targets),
         "--duration", str(LOADGEN_SECS),
         "--concurrency", str(concurrency), "--fetch-mode", mode],
        capture_output=True, text=True, env=_env(), cwd=REPO, timeout=300)
    result = None
    for line in p.stdout.splitlines():
        if line.startswith("LOADGEN_JSON "):
            result = json.loads(line[len("LOADGEN_JSON "):])
    with open(os.path.join(OUT_DIR, f"loadgen_{name}.json"), "w") as f:
        json.dump({"rc": p.returncode, "result": result}, f, indent=2)
    return p.returncode, result


def _run_status(metrics_port: int) -> tuple[int | None, str]:
    try:
        p = subprocess.run(
            [sys.executable, "-m", f"{PKG}.cli", "status",
             "--metrics-port", str(metrics_port)],
            capture_output=True, text=True, env=_env(), cwd=REPO,
            timeout=60)
        return p.returncode, p.stdout + p.stderr
    except subprocess.TimeoutExpired:
        return None, "status timed out"


# ---------------------------------------------------------------------------
# Cell A: 1 shard + 4 replicas vs single-server control
# ---------------------------------------------------------------------------

def cell_a() -> tuple[dict, dict]:
    procs = []
    try:
        # Control: one server, the reference fetch path.
        c_port, c_metrics = _free_port(), _free_port()
        control, c_log = _spawn(
            _serve_argv(port=c_port, metrics_port=c_metrics),
            os.path.join(OUT_DIR, "a_control_server.log"))
        procs.append((control, c_log))
        _wait_up(c_metrics, control, "cell A control server")
        control_rc, control_full = _loadgen([f"localhost:{c_port}"],
                                            "full", "control_full")
        _, control_delta = _loadgen([f"localhost:{c_port}"], "delta",
                                    "control_delta")
        _stop(control, c_log)
        procs.pop()

        # Scale tier: 1 shard primary + 4 replicas.
        p_port, p_metrics = _free_port(), _free_port()
        primary, p_log = _spawn(
            _serve_argv(port=p_port, metrics_port=p_metrics,
                        extra=["--shard-count", "1",
                               "--shard-peers", f"localhost:{p_port}"]),
            os.path.join(OUT_DIR, "a_primary_server.log"))
        procs.append((primary, p_log))
        _wait_up(p_metrics, primary, "cell A shard primary")

        rep_ports = [_free_port() for _ in range(REPLICAS)]
        for i, rport in enumerate(rep_ports):
            rep, r_log = _spawn(
                [sys.executable, "-m", f"{PKG}.cli", "replica",
                 "--primary", f"localhost:{p_port}", "--port", str(rport),
                 "--poll-interval", "0.05",
                 "--staleness-bound", str(STALENESS_BOUND_S)],
                os.path.join(OUT_DIR, f"a_replica{i}.log"))
            procs.append((rep, r_log))
        targets = [f"localhost:{p}" for p in rep_ports]
        for t in targets:
            _grpc_up(t)

        # Offload accounting: the primary's fetch handler should see the
        # replicas' polls — a CONSTANT-rate cost (4 pollers at 20 Hz,
        # independent of consumer load) — not the consumer traffic.
        t_window = time.time()
        before = _metric_value(
            _http(f"http://127.0.0.1:{p_metrics}/metrics"),
            "dps_rpc_handler_calls_total", '{rpc="FetchParameters"}') or 0
        tier_rc, tier_delta = _loadgen(targets, "delta", "tier_delta",
                                       concurrency=4)
        _, tier_full = _loadgen(targets, "full", "tier_full",
                                concurrency=4)
        after = _metric_value(
            _http(f"http://127.0.0.1:{p_metrics}/metrics"),
            "dps_rpc_handler_calls_total", '{rpc="FetchParameters"}') or 0
        t_window = time.time() - t_window
        poll_budget = REPLICAS * t_window / 0.05 * 1.5 + 50
        view = _cluster(p_metrics) or {}
        with open(os.path.join(OUT_DIR, "a_cluster.json"), "w") as f:
            json.dump(view, f, indent=2)
        sharding = view.get("sharding") or {}

        consumer_fetches = ((tier_delta or {}).get("fetches_ok", 0)
                            + (tier_full or {}).get("fetches_ok", 0))
        primary_fetch_delta = after - before
        record = {
            "model": MODEL,
            "replicas": REPLICAS,
            "loadgen_seconds": LOADGEN_SECS,
            "control_full_qps": (control_full or {}).get("qps", 0.0),
            "control_delta_qps": (control_delta or {}).get("qps", 0.0),
            "tier_delta_qps": (tier_delta or {}).get("qps", 0.0),
            "tier_full_qps": (tier_full or {}).get("qps", 0.0),
            "headline_ratio": round(
                (tier_delta or {}).get("qps", 0.0)
                / max(1e-9, (control_full or {}).get("qps", 0.0)), 1),
            "consumer_fetches_to_tier": consumer_fetches,
            "primary_fetches_during_tier_load": primary_fetch_delta,
            "offload_window_seconds": round(t_window, 1),
            "replica_poll_budget": int(poll_budget),
            "replica_membership": sharding.get("replicas", []),
            "note": "single-cpu container: the lever measured here is "
                    "per-request serve cost (cached-bytes replicas + "
                    "delta polls) vs the reference full-fetch path, not "
                    "process parallelism",
        }
        checks = {
            "A_loadgen_exit_codes_zero":
                control_rc == 0 and tier_rc == 0,
            # The headline: production read path vs the reference fetch
            # path, >= 10x aggregate QPS.
            "A_read_tier_10x_vs_reference_fetch_path":
                record["tier_delta_qps"]
                >= 10.0 * record["control_full_qps"] > 0,
            # Same-mode sanity: raw full-payload serving from the tier is
            # no slower than the control's.
            "A_tier_full_not_slower":
                record["tier_full_qps"]
                >= 0.9 * record["control_full_qps"],
            # Offload: consumer traffic lands on replicas; the primary's
            # fetch handler moved only by the (cheap, header-only,
            # rate-bounded) replica polls — within the 4x20Hz poll
            # budget for the window, and well under the consumer volume.
            "A_primary_offloaded":
                0 < primary_fetch_delta <= poll_budget
                and primary_fetch_delta < 0.2 * max(1, consumer_fetches),
            # Membership + lag live in GET /cluster: all 4 replicas
            # announced, all fully caught up on the idle primary.
            "A_replica_membership_live":
                len(record["replica_membership"]) == REPLICAS
                and all(r["lag_steps"] == 0
                        for r in record["replica_membership"]),
        }
        return record, checks
    finally:
        for proc, log in procs:
            _stop(proc, log)


# ---------------------------------------------------------------------------
# Cells B + C: replica lag under live training, exact sharded parity
# ---------------------------------------------------------------------------

def _worker_argv(server_args: list[str], name: str) -> list[str]:
    return [sys.executable, "-m", f"{PKG}.cli", "worker",
            *server_args, "--worker-name", name,
            "--model", MODEL, "--synthetic",
            "--num-train", "256", "--num-test", "96",
            "--epochs", "2", "--batch-size", "32",
            "--dtype", "float32", "--no-augment",
            "--seed", "0", "--platform", "cpu", "--emit-metrics"]


def _worker_metrics(log_path: str) -> dict | None:
    from distributed_parameter_server_for_ml_training_tpu.utils.metrics \
        import parse_metrics_lines
    recs = [r for r in parse_metrics_lines(open(log_path).read())
            if "final_test_accuracy" in r]
    return recs[-1] if recs else None


def cell_bc() -> tuple[dict, dict]:
    procs = []
    try:
        # Control: single server, one sync worker.
        c_port, c_metrics = _free_port(), _free_port()
        control, c_log = _spawn(
            _serve_argv(port=c_port, metrics_port=c_metrics, mode="sync"),
            os.path.join(OUT_DIR, "c_control_server.log"))
        procs.append((control, c_log))
        _wait_up(c_metrics, control, "cell C control server")
        wlog = os.path.join(OUT_DIR, "c_control_worker.log")
        w = subprocess.run(
            _worker_argv(["--server", f"localhost:{c_port}"], "ctl-0"),
            stdout=open(wlog, "w"), stderr=subprocess.STDOUT,
            env=_env(), cwd=REPO, timeout=1200)
        control_worker_rc = w.returncode
        control_metrics = _worker_metrics(wlog)
        _stop(control, c_log)
        procs.pop()

        # Sharded: 2 primaries + a delta-fed replica behind shard 0.
        ports = [_free_port(), _free_port()]
        metrics_ports = [_free_port(), _free_port()]
        peers = ",".join(f"localhost:{p}" for p in ports)
        shards = []
        for i in range(2):
            sp, s_log = _spawn(
                _serve_argv(port=ports[i], metrics_port=metrics_ports[i],
                            mode="sync",
                            extra=["--shard-index", str(i),
                                   "--shard-count", "2",
                                   "--shard-peers", peers]),
                os.path.join(OUT_DIR, f"c_shard{i}_server.log"))
            procs.append((sp, s_log))
            shards.append(sp)
        for i in range(2):
            _wait_up(metrics_ports[i], shards[i], f"cell C shard {i}")
        rep_port = _free_port()
        rep, r_log = _spawn(
            [sys.executable, "-m", f"{PKG}.cli", "replica",
             "--primary", f"localhost:{ports[0]}",
             "--port", str(rep_port), "--poll-interval", "0.05",
             "--staleness-bound", str(STALENESS_BOUND_S)],
            os.path.join(OUT_DIR, "c_replica.log"))
        procs.append((rep, r_log))
        _grpc_up(f"localhost:{rep_port}")

        swlog = os.path.join(OUT_DIR, "c_sharded_worker.log")
        worker = subprocess.Popen(
            _worker_argv(["--server", f"localhost:{ports[0]}",
                          "--shards", peers], "shard-0"),
            stdout=open(swlog, "w"), stderr=subprocess.STDOUT,
            env=_env(), cwd=REPO)

        # Cell B evidence, captured MID-RUN: poll shard 0's sharding
        # block for replica lag; grab cli status once the replica has
        # announced. The shard primaries exit on their own once the
        # worker reports JobFinished, so catch-up evidence is the LAST
        # live sample, not a post-mortem read.
        lags, max_age = [], 0.0
        last_sharding: dict | None = None
        lag_gauge_mid: float | None = None
        status_cap: tuple[int | None, str] | None = None
        deadline = time.time() + 1200

        def _sample() -> bool:
            nonlocal max_age, last_sharding
            view = _cluster(metrics_ports[0])
            if not view:
                return False
            sh = view.get("sharding")
            if sh and sh["replicas"]:
                last_sharding = sh
                for r in sh["replicas"]:
                    lags.append(r["lag_steps"])
                    max_age = max(max_age, r["announce_age_s"])
            return True

        while worker.poll() is None and time.time() < deadline:
            if _sample() and status_cap is None and lags:
                status_cap = _run_status(metrics_ports[0])
                lag_gauge_mid = _metric_value(
                    _http(f"http://127.0.0.1:{metrics_ports[0]}"
                          "/metrics"),
                    "dps_replica_lag_steps",
                    f'{{replica="localhost:{rep_port}"}}')
            time.sleep(0.25)
        worker.wait(timeout=60)
        sharded_worker_rc = worker.returncode
        sharded_metrics = _worker_metrics(swlog)

        # Keep sampling until the primary leaves: the final samples show
        # the replica converged to the shard's last step.
        grace = time.time() + 15
        while time.time() < grace and _sample():
            time.sleep(0.1)
        with open(os.path.join(OUT_DIR, "c_cluster.json"), "w") as f:
            json.dump(last_sharding, f, indent=2)
        if status_cap is not None:
            with open(os.path.join(OUT_DIR, "c_status.txt"), "w") as f:
                f.write(f"# cli status exit code: {status_cap[0]}\n\n"
                        f"{status_cap[1]}")
        final_reps = (last_sharding or {}).get("replicas", [])
        final_step = (sharded_metrics or {}).get("local_steps_completed")

        record = {
            "control_worker_rc": control_worker_rc,
            "sharded_worker_rc": sharded_worker_rc,
            "control": {k: control_metrics.get(k) for k in
                        ("all_test_accuracies", "local_steps_completed",
                         "final_test_accuracy")} if control_metrics
                       else None,
            "sharded": {k: sharded_metrics.get(k) for k in
                        ("all_test_accuracies", "local_steps_completed",
                         "final_test_accuracy")} if sharded_metrics
                       else None,
            "lag_samples": len(lags),
            "max_lag_steps_observed": max(lags) if lags else None,
            "max_announce_age_s_observed": round(max_age, 3),
            "mid_run_replica_lag_steps_gauge": lag_gauge_mid,
            "final_replicas": final_reps,
            "final_local_steps": final_step,
            "status_rc": (status_cap or (None, ""))[0],
            "status_has_shard_rows": bool(
                status_cap and "shard:" in status_cap[1]
                and "replica " in status_cap[1]),
        }
        checks = {
            "B_workers_clean_exit":
                control_worker_rc == 0 and sharded_worker_rc == 0,
            "B_replica_lag_within_bound":
                bool(lags) and max(lags) <= LAG_BOUND_STEPS,
            "B_replica_announces_fresh":
                bool(lags) and max_age <= STALENESS_BOUND_S,
            "B_replica_caught_up_to_final_step":
                bool(final_reps) and final_reps[0]["lag_steps"] == 0
                and final_reps[0]["step"] == final_step,
            "B_status_renders_shard_rows":
                record["status_has_shard_rows"]
                and record["status_rc"] == 0,
            # Cell C: EXACT parity — accuracy-vs-step curve and step
            # count identical between sharded and single-server runs.
            "C_accuracy_curve_exactly_equal":
                control_metrics is not None
                and sharded_metrics is not None
                and control_metrics["all_test_accuracies"]
                == sharded_metrics["all_test_accuracies"]
                and len(control_metrics["all_test_accuracies"]) == 2,
            "C_step_count_equal":
                control_metrics is not None
                and sharded_metrics is not None
                and control_metrics["local_steps_completed"]
                == sharded_metrics["local_steps_completed"] > 0,
        }
        return record, checks
    finally:
        for proc, log in procs:
            _stop(proc, log)


# ---------------------------------------------------------------------------
# Cell D: shard-primary kill+restart, journal-verified exactly-once
# ---------------------------------------------------------------------------

def cell_d() -> tuple[dict, dict]:
    import glob

    import grpc as grpc_mod
    import numpy as np

    from distributed_parameter_server_for_ml_training_tpu.comms.service \
        import GRPC_OPTIONS, SERVICE_NAME, pack_msg, unpack_msg
    from distributed_parameter_server_for_ml_training_tpu.comms.wire \
        import decode_tensor_dict, encode_tensor_dict

    ckpt_dir = os.path.join(OUT_DIR, "d_ckpt")
    os.makedirs(ckpt_dir, exist_ok=True)
    for f in glob.glob(os.path.join(ckpt_dir, "*")):
        os.remove(f)
    port = _free_port()
    argv = _serve_argv(
        port=port, metrics_port=_free_port(), mode="sync",
        extra=["--shard-index", "1", "--shard-count", "2",
               "--shard-peers", f"localhost:1,localhost:{port}",
               "--checkpoint-dir", ckpt_dir,
               "--checkpoint-interval", "0.5"])

    def stub(name):
        ch = grpc_mod.insecure_channel(f"localhost:{port}",
                                       options=GRPC_OPTIONS)
        return ch, ch.unary_unary(f"/{SERVICE_NAME}/{name}",
                                  request_serializer=lambda b: b,
                                  response_deserializer=lambda b: b)

    def rpc(name, req, timeout=20.0):
        ch, s = stub(name)
        try:
            return unpack_msg(s(req, timeout=timeout))
        finally:
            ch.close()

    server, log = _spawn(argv, os.path.join(OUT_DIR, "d_shard1.log"))
    record: dict = {"checkpoint_dir": os.path.relpath(ckpt_dir, REPO)}
    try:
        _grpc_up(f"localhost:{port}", timeout=180.0)

        # This shard owns the shard-1 key subset of the model; build a
        # matching gradient from the served parameters themselves.
        meta, _ = rpc("RegisterWorker", pack_msg({"worker_name": "d"}))
        wid = meta["worker_id"]
        fmeta, payload = rpc("FetchParameters", pack_msg({}))
        params0 = {k: np.array(v) for k, v in
                   decode_tensor_dict(payload).items()}
        record["shard1_tensors"] = len(params0)
        grads = {k: np.full(v.shape, 0.01, np.float32)
                 for k, v in params0.items()}
        push1 = pack_msg({"worker_id": wid, "fetched_step": 0,
                          "push_token": "demo:1"},
                         encode_tensor_dict(grads))
        m1, _ = rpc("PushGradrients", push1)
        record["push1"] = {"accepted": m1["accepted"],
                           "duplicate": bool(m1.get("duplicate"))}
        fmeta, payload = rpc("FetchParameters", pack_msg({}))
        step_after_push = int(fmeta["global_step"])
        params1 = {k: np.array(v) for k, v in
                   decode_tensor_dict(payload).items()}

        # Wait for a snapshot covering the push, stamped with the shard
        # identity.
        covering = None
        deadline = time.time() + 60
        while covering is None and time.time() < deadline:
            for mf in glob.glob(os.path.join(ckpt_dir, "*.json")):
                try:
                    snap = json.load(open(mf))
                except ValueError:
                    continue
                if snap.get("global_step", -1) >= step_after_push:
                    covering = snap
            time.sleep(0.2)
        if covering is None:
            raise RuntimeError("no covering snapshot appeared")
        record["snapshot_shard_identity"] = covering.get("shard")
        record["snapshot_journal"] = [
            {"nonce": e["nonce"], "count": e["count"],
             "accepted": e["accepted"]}
            for e in covering.get("push_journal", [])]

        # Crash the shard primary (SIGKILL: no clean shutdown path).
        server.send_signal(signal.SIGKILL)
        server.wait(timeout=30)
        log.close()

        # Restart with --restore on the same port and identity.
        server, log = _spawn(argv + ["--restore"],
                             os.path.join(OUT_DIR, "d_shard1_restart.log"))
        _grpc_up(f"localhost:{port}", timeout=180.0)
        restart_log = open(os.path.join(OUT_DIR,
                                        "d_shard1_restart.log")).read()
        record["restore_line"] = next(
            (ln.strip() for ln in restart_log.splitlines()
             if "restored store at step" in ln), None)

        # Session resume, then the IDENTICAL push bytes: the journal
        # must replay, not re-apply.
        rpc("RegisterWorker", pack_msg({"worker_name": "d"}))
        m2, _ = rpc("PushGradrients", push1)
        record["replay"] = {"accepted": m2["accepted"],
                            "duplicate": bool(m2.get("duplicate"))}
        fmeta, payload = rpc("FetchParameters", pack_msg({}))
        record["step_after_replay"] = int(fmeta["global_step"])
        params2 = {k: np.array(v) for k, v in
                   decode_tensor_dict(payload).items()}
        params_equal = (sorted(params1) == sorted(params2)
                        and all(np.array_equal(params1[k], params2[k])
                                for k in params1))
        params_moved_once = any(not np.array_equal(params0[k], params1[k])
                                for k in params0)

        # A genuinely new push still applies on the recovered shard.
        m3, _ = rpc("PushGradrients",
                    pack_msg({"worker_id": wid, "fetched_step": 1,
                              "push_token": "demo:2"},
                             encode_tensor_dict(grads)))
        fmeta, _ = rpc("FetchParameters", pack_msg({}))
        record["step_after_new_push"] = int(fmeta["global_step"])

        checks = {
            "D_push_applied_before_crash":
                record["push1"]["accepted"]
                and not record["push1"]["duplicate"]
                and step_after_push == 1 and params_moved_once,
            "D_snapshot_stamped_with_shard_identity":
                record["snapshot_shard_identity"]
                == {"shard_index": 1, "shard_count": 2},
            "D_journal_in_snapshot":
                record["snapshot_journal"]
                == [{"nonce": "demo", "count": 1, "accepted": True}],
            "D_restore_reseeded_journal":
                record["restore_line"] is not None
                and "+1 journaled push tokens" in record["restore_line"],
            "D_replay_deduped_zero_double_applies":
                record["replay"]["duplicate"]
                and record["replay"]["accepted"]
                and record["step_after_replay"] == 1 and params_equal,
            "D_new_push_applies_after_recovery":
                m3["accepted"] and not m3.get("duplicate")
                and record["step_after_new_push"] == 2,
        }
        return record, checks
    finally:
        _stop(server, log)


def main(argv=None) -> int:
    import argparse
    global OUT_DIR
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out-dir", default=OUT_DIR,
                    help="artifact directory (default: the recorded "
                         "experiments/results/sharding)")
    args = ap.parse_args(argv)
    OUT_DIR = args.out_dir
    os.makedirs(OUT_DIR, exist_ok=True)
    t0 = time.time()
    checks: dict = {}

    a_rec, a_checks = cell_a()
    checks.update(a_checks)
    print(f"cell A: control_full={a_rec['control_full_qps']:.1f} qps, "
          f"tier_delta={a_rec['tier_delta_qps']:.1f} qps "
          f"(x{a_rec['headline_ratio']}), "
          f"{len(a_rec['replica_membership'])} replicas live", flush=True)

    bc_rec, bc_checks = cell_bc()
    checks.update(bc_checks)
    print(f"cell B/C: max lag {bc_rec['max_lag_steps_observed']} step(s) "
          f"over {bc_rec['lag_samples']} samples; parity "
          f"{'EXACT' if bc_checks['C_accuracy_curve_exactly_equal'] else 'BROKEN'}",
          flush=True)

    d_rec, d_checks = cell_d()
    checks.update(d_checks)
    print(f"cell D: replay duplicate={d_rec['replay']['duplicate']}, "
          f"step stayed {d_rec['step_after_replay']}", flush=True)

    record = {
        "demo": "sharded parameter server + delta-fed read replicas "
                "(ISSUE 9)",
        "elapsed_seconds": round(time.time() - t0, 1),
        "environment": {"cpus": os.cpu_count()},
        "checks": checks,
        "all_pass": all(checks.values()),
        "cell_a": a_rec,
        "cell_bc": bc_rec,
        "cell_d": d_rec,
    }
    with open(os.path.join(OUT_DIR, "shard_scale.json"), "w") as f:
        json.dump(record, f, indent=2)
    n_pass = sum(bool(v) for v in checks.values())
    print(f"shard scale demo: {n_pass}/{len(checks)} checks PASS "
          f"({record['elapsed_seconds']}s)")
    for name, ok in checks.items():
        print(f"  [{'PASS' if ok else 'FAIL'}] {name}")
    return 0 if record["all_pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
