"""Recorded chaos soak: training survives a parameter-server kill+restart.

The crash-recovery subsystem's acceptance artifact (ISSUE 4,
docs/ROBUSTNESS.md), written to ``experiments/results/chaos/``:

**Cell A — sync parity through a server restart.** One ``serve``-equivalent
server SUBPROCESS (tiny ResNet store, periodic checkpoints + push-token
journal, SIGTERM snapshot flush through the telemetry shutdown path) and
one PSWorker over gRPC. Mid-run — deterministically, just before the
worker's Nth push leaves — the server is SIGTERM'd (its handler flushes a
final durable snapshot and exits 143), a replacement starts on the same
port with ``--restore``, and the worker's reconnect state machine rides
through: re-register, re-fetch at the restored step, reconcile the
in-flight gradient with its ORIGINAL exactly-once token. The run must
reach the **same step count and accuracy curve** as a fault-free control,
with **zero double-applied pushes** (journal-verified: restored step +
post-restart applies == total accepted pushes).

**Cell B — async convergence under faults + restart.** Two workers against
an async server, with deterministic client-side fault injection
(``comms/faults.py``: seeded UNAVAILABLE blips + replies dropped AFTER the
server-side apply) and the same mid-run SIGTERM/restore restart. The run
must complete with final accuracy within tolerance of its fault-free
control and no double-applies (final step <= total accepted, bounded
apply loss at the kill edge).

Both cells capture worker-side telemetry snapshot streams; the recorded
``dps_worker_reconnect_total`` > 0 is part of the artifact.

Run: JAX_PLATFORMS=cpu python experiments/run_chaos_soak.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(REPO, ".jax_cache")))

import numpy as np  # noqa: E402

OUT_DIR = os.path.join(REPO, "experiments", "results", "chaos")


def _build_model_and_params():
    from distributed_parameter_server_for_ml_training_tpu.models import (
        ResNet)
    from distributed_parameter_server_for_ml_training_tpu.utils.pytree \
        import flatten_params
    model = ResNet(stage_sizes=(1, 1), num_filters=8, num_classes=10)
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 32, 32, 3), np.float32),
                           train=False)
    return model, flatten_params(variables["params"])


# -- server child -------------------------------------------------------------

def server_child(args) -> int:
    """The parameter-server process for one life: tiny-model store +
    service + periodic checkpointer + SIGTERM snapshot flush, telemetry
    snapshots on stdout. ``--restore`` resumes params/step/journal from
    the checkpoint dir (the second life after a kill)."""
    from distributed_parameter_server_for_ml_training_tpu.checkpoint import (
        PeriodicStoreCheckpointer, restore_server_state)
    from distributed_parameter_server_for_ml_training_tpu.comms import (
        ParameterService, serve)
    from distributed_parameter_server_for_ml_training_tpu.ps import (
        ParameterStore, StoreConfig)
    from distributed_parameter_server_for_ml_training_tpu.telemetry import (
        SnapshotEmitter, add_shutdown_flush, install_shutdown_hooks)

    _, flat = _build_model_and_params()
    store = ParameterStore(flat, StoreConfig(
        mode=args.mode, total_workers=args.workers, learning_rate=0.05,
        staleness_bound=10, elastic=True, worker_timeout=30.0,
        push_codec="none"))
    svc = ParameterService(store)
    if args.restore:
        step, journal_n = restore_server_state(store, svc, args.ckpt_dir)
        print(f"CHAOS_SERVER_RESTORED step={step} journal={journal_n}",
              flush=True)
    ckpt = PeriodicStoreCheckpointer(store, args.ckpt_dir,
                                     interval=args.ckpt_interval,
                                     journal_fn=svc.journal_snapshot)
    ckpt.start()
    # SIGTERM drains the end state through the SAME shutdown path that
    # dumps the flight recorder (telemetry/trace.py) — the tentpole's
    # durable-kill semantics, exercised for real by the parent's kill.
    install_shutdown_hooks(role="server")
    add_shutdown_flush(ckpt.flush_now)
    emitter = SnapshotEmitter(interval=1.0, role="server").start()
    server, port = serve(store, port=args.port, service=svc)
    print(f"CHAOS_SERVER_READY port={port}", flush=True)
    lifetime_deadline = time.time() + args.max_lifetime
    while not store.wait_all_finished(timeout=0.5):
        store.expire_stale_workers()
        if time.time() > lifetime_deadline:
            print("CHAOS_SERVER_LIFETIME_EXCEEDED", flush=True)
            break
    time.sleep(0.3)
    server.stop(grace=1.0)
    ckpt.stop(final_snapshot=True)
    emitter.stop(final=True)
    print("CHAOS_SERVER_EXIT " + json.dumps({
        "global_step": store.global_step,
        "gradients_processed": store.stats.gradients_processed,
        "gradients_rejected": store.stats.gradients_rejected,
    }), flush=True)
    return 0


# -- parent-side orchestration ------------------------------------------------

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_server(out_dir, tag, port, ckpt_dir, mode, workers,
                  restore=False, ckpt_interval=2.0):
    log_path = os.path.join(out_dir, f"{tag}.log")
    log = open(log_path, "w")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--server-child",
         "--port", str(port), "--ckpt-dir", ckpt_dir, "--mode", mode,
         "--workers", str(workers), "--ckpt-interval", str(ckpt_interval)]
        + (["--restore"] if restore else []),
        stdout=log, stderr=subprocess.STDOUT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO)
    deadline = time.time() + 120
    while time.time() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(f"server {tag} died at startup; see "
                               f"{log_path}")
        with open(log_path) as f:
            if "CHAOS_SERVER_READY" in f.read():
                return proc, log_path
        time.sleep(0.1)
    raise RuntimeError(f"server {tag} never came up; see {log_path}")


def _server_exit_stats(log_path) -> dict:
    with open(log_path) as f:
        for line in f:
            if line.startswith("CHAOS_SERVER_EXIT "):
                return json.loads(line[len("CHAOS_SERVER_EXIT "):])
    return {}


class _KillSwitch:
    """Deterministic crash point: before the worker's Nth push leaves,
    SIGTERM the server (its handler flushes the durable snapshot), wait
    for it to die, and arm the delayed restart."""

    def __init__(self, client, at_push, kill_fn):
        self._inner = client._call["PushGradrients"]
        self._at = at_push
        self._kill_fn = kill_fn
        self.calls = 0
        self.fired = False
        client._call["PushGradrients"] = self

    def __call__(self, request, timeout=None):
        self.calls += 1
        if self.calls == self._at and not self.fired:
            self.fired = True
            self._kill_fn()
        return self._inner(request, timeout=timeout)


def _run_worker_cell(model, ds, *, port, n_workers, sync_steps, epochs,
                     batch, log_path, faults=None, reconnect_timeout=120.0,
                     kill_at_push=None, kill_fn=None, grad_step=None,
                     eval_step=None):
    """Run N PSWorkers against the (already-up) server at ``port``,
    telemetry snapshots to ``log_path``. Returns per-worker results."""
    from distributed_parameter_server_for_ml_training_tpu.comms import (
        RemoteStore)
    from distributed_parameter_server_for_ml_training_tpu.ps import (
        PSWorker, WorkerConfig)
    from distributed_parameter_server_for_ml_training_tpu.telemetry import (
        SnapshotEmitter)

    with open(log_path, "w") as stream:
        emitter = SnapshotEmitter(interval=0.5, role="worker",
                                  stream=stream).start()
        clients, workers = [], []
        try:
            for i in range(n_workers):
                c = RemoteStore(f"localhost:{port}", rpc_timeout=15.0,
                                rpc_retries=1, rpc_backoff=0.05,
                                faults=faults)
                if i == 0 and kill_at_push is not None:
                    _KillSwitch(c, kill_at_push, kill_fn)
                clients.append(c)
                cfg = WorkerConfig(batch_size=batch, num_epochs=epochs,
                                   sync_steps=sync_steps, augment=False,
                                   heartbeat_interval=2.0,
                                   reconnect_timeout=reconnect_timeout,
                                   reconnect_backoff=0.1)
                workers.append(PSWorker(c, model, ds, cfg,
                                        grad_step=grad_step,
                                        eval_step=eval_step,
                                        worker_name=f"worker-{i}"))
            for w in workers:
                w.start()
            for w in workers:
                w.join(timeout=900)
        finally:
            emitter.stop(final=True)
            for c in clients:
                c.close()
    for w in workers:
        if w.result.error is not None:
            raise RuntimeError(
                f"{w.worker_name} failed") from w.result.error
    return [w.result for w in workers]


def _reconnect_counter_from_snapshots(log_path) -> float:
    from distributed_parameter_server_for_ml_training_tpu.analysis. \
        parse_logs import parse_snapshot_series
    series = parse_snapshot_series(open(log_path).read())
    total = 0.0
    for payloads in series.values():
        last = payloads[-1].get("counters", {})
        total += sum(v for k, v in last.items()
                     if k.startswith("dps_worker_reconnect_total"))
    return total


def _load_final_snapshot(ckpt_dir):
    from distributed_parameter_server_for_ml_training_tpu.checkpoint import (
        load_store_record)
    return load_store_record(ckpt_dir)


def run_soak(args) -> int:
    from distributed_parameter_server_for_ml_training_tpu.data import (
        synthetic_cifar100)
    from distributed_parameter_server_for_ml_training_tpu.train.steps \
        import make_eval_step, make_grad_step

    out_dir = args.out_dir
    os.makedirs(out_dir, exist_ok=True)
    quick = args.quick
    epochs = 2 if quick else 3
    n_train = 128 if quick else 256
    batch = 32
    model, _flat = _build_model_and_params()
    ds = synthetic_cifar100(n_train=n_train, n_test=64, num_classes=10,
                            seed=1)
    grad_step = make_grad_step(model, augment=False)
    eval_step = jax.jit(make_eval_step())
    summary = {"quick": quick, "cells": {}}
    checks: list[tuple[str, bool, str]] = []

    # ---- Cell A: sync parity through a kill+restart ------------------------
    sync_steps = 2
    pushes_per_epoch = (n_train // batch) // sync_steps
    total_pushes = pushes_per_epoch * epochs
    kill_at = total_pushes // 2 + 1

    port = _free_port()
    ctl_ckpt = os.path.join(out_dir, "ckpt_sync_control")
    p, ctl_log = _spawn_server(out_dir, "sync_control_server", port,
                               ctl_ckpt, "sync", 1)
    control = _run_worker_cell(
        model, ds, port=port, n_workers=1, sync_steps=sync_steps,
        epochs=epochs, batch=batch,
        log_path=os.path.join(out_dir, "sync_control_worker.log"),
        grad_step=grad_step, eval_step=eval_step)[0]
    p.wait(timeout=120)
    ctl_stats = _server_exit_stats(ctl_log)

    port = _free_port()
    chaos_ckpt = os.path.join(out_dir, "ckpt_sync_chaos")
    p1, log1 = _spawn_server(out_dir, "sync_chaos_server1", port,
                             chaos_ckpt, "sync", 1)
    restart_ready = threading.Event()
    holder = {}

    def kill_and_schedule_restart():
        p1.send_signal(signal.SIGTERM)  # handler flushes the snapshot
        rc = p1.wait(timeout=60)
        print(f"server1 SIGTERM'd (rc={rc}); restarting shortly",
              flush=True)
        def _restart():
            time.sleep(0.5)  # let the worker hit SessionLost first
            holder["p2"], holder["log2"] = _spawn_server(
                out_dir, "sync_chaos_server2", port, chaos_ckpt, "sync",
                1, restore=True)
            restart_ready.set()
        threading.Thread(target=_restart, daemon=True).start()

    chaos = _run_worker_cell(
        model, ds, port=port, n_workers=1, sync_steps=sync_steps,
        epochs=epochs, batch=batch,
        log_path=os.path.join(out_dir, "sync_chaos_worker.log"),
        kill_at_push=kill_at, kill_fn=kill_and_schedule_restart,
        grad_step=grad_step, eval_step=eval_step)[0]
    assert restart_ready.wait(120)
    holder["p2"].wait(timeout=120)
    chaos_stats = _server_exit_stats(holder["log2"])
    _, final_meta = _load_final_snapshot(chaos_ckpt)
    reconnects_in_snapshots = _reconnect_counter_from_snapshots(
        os.path.join(out_dir, "sync_chaos_worker.log"))

    restored_step = None
    with open(holder["log2"]) as f:
        for line in f:
            if line.startswith("CHAOS_SERVER_RESTORED"):
                restored_step = int(line.split("step=")[1].split()[0])
    applies_life2 = chaos_stats.get("gradients_processed", -1)

    checks += [
        ("A.control_completed",
         control.local_steps_completed == epochs * n_train // batch
         and ctl_stats.get("global_step") == total_pushes,
         f"{control.local_steps_completed} steps, server "
         f"{ctl_stats.get('global_step')}"),
        ("A.worker_survived_restart", chaos.reconnects == 1,
         f"reconnects={chaos.reconnects}"),
        ("A.step_parity",
         chaos_stats.get("global_step") == ctl_stats.get("global_step"),
         f"chaos={chaos_stats.get('global_step')} "
         f"control={ctl_stats.get('global_step')}"),
        ("A.accuracy_curve_parity",
         np.allclose(control.test_accuracies, chaos.test_accuracies,
                     atol=1e-12),
         f"control={control.test_accuracies} "
         f"chaos={chaos.test_accuracies}"),
        ("A.zero_double_applies_journal_verified",
         restored_step is not None
         and restored_step + applies_life2 == chaos.pushes_accepted
         and chaos.pushes_accepted == total_pushes,
         f"restored={restored_step} + life2={applies_life2} vs "
         f"accepted={chaos.pushes_accepted} (expected {total_pushes})"),
        ("A.reconnect_counter_in_snapshots", reconnects_in_snapshots > 0,
         f"dps_worker_reconnect_total={reconnects_in_snapshots}"),
    ]
    summary["cells"]["sync_parity"] = {
        "epochs": epochs, "sync_steps": sync_steps,
        "total_pushes": total_pushes, "killed_before_push": kill_at,
        "control": {"server": ctl_stats,
                    "accuracy_curve": control.test_accuracies,
                    "pushes_accepted": control.pushes_accepted},
        "chaos": {"server_life2": chaos_stats,
                  "restored_step": restored_step,
                  "accuracy_curve": chaos.test_accuracies,
                  "pushes_accepted": chaos.pushes_accepted,
                  "reconnects": chaos.reconnects,
                  "reconnect_counter_in_snapshots":
                      reconnects_in_snapshots},
        "final_snapshot_meta": {
            "global_step": final_meta["global_step"],
            "push_journal": final_meta["push_journal"]},
    }

    # ---- Cell B: async convergence under injected faults + restart ---------
    n_workers = 2
    fault_spec = ("seed=5;push.unavailable@p=0.08;push.drop_reply@every=5;"
                  "fetch.unavailable@p=0.04")
    from distributed_parameter_server_for_ml_training_tpu.comms import (
        FaultInjector)
    schedule_preview = FaultInjector(fault_spec).schedule_preview(
        "PushGradrients", 24)

    port = _free_port()
    b_ctl_ckpt = os.path.join(out_dir, "ckpt_async_control")
    p, b_ctl_log = _spawn_server(out_dir, "async_control_server", port,
                                 b_ctl_ckpt, "async", n_workers)
    b_control = _run_worker_cell(
        model, ds, port=port, n_workers=n_workers, sync_steps=1,
        epochs=epochs, batch=batch,
        log_path=os.path.join(out_dir, "async_control_worker.log"),
        grad_step=grad_step, eval_step=eval_step)
    p.wait(timeout=120)
    b_ctl_stats = _server_exit_stats(b_ctl_log)

    port = _free_port()
    b_ckpt = os.path.join(out_dir, "ckpt_async_chaos")
    bp1, b_log1 = _spawn_server(out_dir, "async_chaos_server1", port,
                                b_ckpt, "async", n_workers)
    b_restart_ready = threading.Event()
    b_holder = {}

    def b_kill_and_restart():
        bp1.send_signal(signal.SIGTERM)
        bp1.wait(timeout=60)
        def _restart():
            time.sleep(0.5)
            b_holder["p2"], b_holder["log2"] = _spawn_server(
                out_dir, "async_chaos_server2", port, b_ckpt, "async",
                n_workers, restore=True)
            b_restart_ready.set()
        threading.Thread(target=_restart, daemon=True).start()

    b_chaos = _run_worker_cell(
        model, ds, port=port, n_workers=n_workers, sync_steps=1,
        epochs=epochs, batch=batch,
        log_path=os.path.join(out_dir, "async_chaos_worker.log"),
        faults=fault_spec, kill_at_push=max(3, epochs),
        kill_fn=b_kill_and_restart,
        grad_step=grad_step, eval_step=eval_step)
    assert b_restart_ready.wait(120)
    b_holder["p2"].wait(timeout=120)
    b_stats = _server_exit_stats(b_holder["log2"])
    b_restored = None
    with open(b_holder["log2"]) as f:
        for line in f:
            if line.startswith("CHAOS_SERVER_RESTORED"):
                b_restored = int(line.split("step=")[1].split()[0])

    accepted = sum(r.pushes_accepted for r in b_chaos)
    acc_ctl = float(np.mean([r.test_accuracies[-1] for r in b_control]))
    acc_chaos = float(np.mean([r.test_accuracies[-1] for r in b_chaos]))
    final_step = b_stats.get("global_step", -1)
    applied_total = (b_restored or 0) + b_stats.get("gradients_processed",
                                                    0)
    checks += [
        ("B.workers_survived",
         all(r.reconnects >= 1 for r in b_chaos[:1]),
         f"reconnects={[r.reconnects for r in b_chaos]}"),
        ("B.no_double_applies",
         applied_total <= accepted,
         f"applied={applied_total} accepted={accepted}"),
        ("B.bounded_apply_loss_at_kill_edge",
         applied_total >= accepted - n_workers,
         f"applied={applied_total} accepted={accepted}"),
        ("B.converges_within_tolerance",
         abs(acc_chaos - acc_ctl) <= 0.15,
         f"control={acc_ctl:.4f} chaos={acc_chaos:.4f}"),
    ]
    summary["cells"]["async_faults"] = {
        "workers": n_workers, "epochs": epochs,
        "fault_spec": fault_spec,
        "fault_schedule_preview_push": schedule_preview,
        "control": {"server": b_ctl_stats, "final_accuracy": acc_ctl},
        "chaos": {"server_life2": b_stats, "restored_step": b_restored,
                  "final_accuracy": acc_chaos,
                  "pushes_accepted_total": accepted,
                  "reconnects": [r.reconnects for r in b_chaos]},
    }

    summary["checks"] = [
        {"name": n, "ok": bool(ok), "detail": d} for n, ok, d in checks]
    summary["ok"] = all(ok for _, ok, _ in checks)
    out_path = os.path.join(out_dir, "chaos_soak.json")
    with open(out_path, "w") as f:
        json.dump(summary, f, indent=2)
    for n, ok, d in checks:
        print(f"{'PASS' if ok else 'FAIL'} {n}: {d}")
    print(f"wrote {out_path}")
    return 0 if summary["ok"] else 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out-dir", default=OUT_DIR)
    # internal: server-child mode
    ap.add_argument("--server-child", action="store_true")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--ckpt-interval", type=float, default=2.0)
    ap.add_argument("--mode", default="sync")
    ap.add_argument("--workers", type=int, default=1)
    ap.add_argument("--restore", action="store_true")
    ap.add_argument("--max-lifetime", type=float, default=600.0,
                    help="server-child self-destruct (orphan guard)")
    args = ap.parse_args()
    if args.server_child:
        return server_child(args)
    return run_soak(args)


if __name__ == "__main__":
    raise SystemExit(main())
