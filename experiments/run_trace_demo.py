"""Recorded distributed-tracing demo (ISSUE 3 acceptance artifacts).

Three recorded scenarios, artifacts under ``experiments/results/trace/``:

(a) **Multi-process sync trace tree** — a real ``cli serve`` + two
    ``cli worker`` processes with ``--trace --trace-dump-dir``; their
    flight-recorder dumps are assembled by ``trace_id`` and the demo
    verifies a server-side ``store.apply`` span is parented — through the
    RPC chain — by the originating worker's ``worker.step`` span.
    Artifacts: ``sync_trace_tree.json``, ``sync_trace.perfetto.json``
    (validated Perfetto-loadable by ``tests/test_trace.py``), raw dumps
    under ``raw/``.

(b) **Async staleness-attributed straggler** — an in-process async run
    where one worker's fetches are delayed (the injected-latency
    technique of run_overlap_probe.py): the critical-path report must
    attribute >=95% of the straggler step's wall time across
    compute/fetch-wait/push-wait/server-apply/codec and carry the
    staleness its pushes incurred. Artifacts:
    ``async_straggler_report.json``, ``async_trace.perfetto.json``.

(c) **SIGTERM post-mortem** — a ``cli train`` process is TERM'd mid-run
    after scraping its live ``/debug/trace``; the dump the signal handler
    writes must contain the live trace's spans. Artifact:
    ``sigterm_postmortem.json``.

Usage::

    python experiments/run_trace_demo.py [--out-dir experiments/results/trace]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import time
from urllib.request import urlopen

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # runnable from any cwd
    sys.path.insert(0, REPO)
CLI = [sys.executable, "-m",
       "distributed_parameter_server_for_ml_training_tpu.cli"]


def _env() -> dict:
    return dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1",
                JAX_COMPILATION_CACHE_DIR=os.path.join(REPO, ".jax_cache"))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_port(port: int, deadline_s: float = 120.0) -> None:
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=1):
                return
        except OSError:
            time.sleep(0.25)
    raise TimeoutError(f"port {port} never opened")


def _ancestor_chain(span: dict, by_id: dict) -> list[str]:
    chain, node = [], span
    while node is not None:
        chain.append(node["name"])
        node = by_id.get(node.get("parent_id"))
    return chain


# -- (a) multi-process sync run -> assembled trace tree ----------------------

def run_sync_tree(out_dir: str) -> None:
    raw_dir = os.path.join(out_dir, "raw")
    os.makedirs(raw_dir, exist_ok=True)
    port = _free_port()
    serve_cmd = CLI + [
        "serve", "--mode", "sync", "--workers", "2", "--port", str(port),
        "--model", "vit_tiny", "--image-size", "32", "--platform", "cpu",
        "--trace", "--trace-buffer", "2048", "--trace-dump-dir", raw_dir]
    print(f"[sync] {' '.join(serve_cmd)}", file=sys.stderr)
    server = subprocess.Popen(serve_cmd, cwd=REPO, env=_env(),
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE)
    try:
        _wait_port(port)
        workers = []
        for i in range(2):
            cmd = CLI + [
                "worker", "--server", f"localhost:{port}",
                "--worker-name", f"trace-w{i}", "--model", "vit_tiny",
                "--synthetic", "--num-train", "256", "--num-test", "32",
                "--epochs", "1", "--batch-size", "32", "--sync-steps", "2",
                "--platform", "cpu", "--dtype", "float32", "--no-augment",
                "--trace", "--trace-buffer", "2048",
                "--trace-dump-dir", raw_dir]
            workers.append(subprocess.Popen(
                cmd, cwd=REPO, env=_env(), stdout=subprocess.PIPE,
                stderr=subprocess.PIPE))
        for w in workers:
            out, err = w.communicate(timeout=900)
            if w.returncode != 0:
                sys.stderr.write(err.decode(errors="replace")[-3000:])
                raise SystemExit(f"sync demo worker failed rc={w.returncode}")
        sout, serr = server.communicate(timeout=120)
        if server.returncode != 0:
            sys.stderr.write(serr.decode(errors="replace")[-3000:])
            raise SystemExit(f"sync demo server failed "
                             f"rc={server.returncode}")
    finally:
        if server.poll() is None:
            server.kill()

    from distributed_parameter_server_for_ml_training_tpu.analysis import (
        assemble_traces, find_trace_dumps, load_trace_dumps,
        save_chrome_trace)
    dumps = find_trace_dumps(raw_dir)
    spans = load_trace_dumps(dumps)
    roles = {s.get("role") for s in spans}
    assert {"server", "worker"} <= roles, roles
    by_id = {s["span_id"]: s for s in spans}

    # The acceptance join: a server apply span whose ancestor chain (via
    # the wire-propagated context) reaches the originating worker's step.
    joined = []
    for s in spans:
        if s["name"] == "store.apply" and s.get("role") == "server":
            chain = _ancestor_chain(s, by_id)
            if chain[-1] == "worker.step":
                joined.append({
                    "apply_span_id": s["span_id"],
                    "trace_id": s["trace_id"],
                    "ancestor_chain": chain,
                    "originating_step": by_id[
                        _root_of(s, by_id)]["attrs"],
                })
    assert joined, "no server apply span joined a worker step"

    assembled = assemble_traces(spans)
    save_chrome_trace(spans, os.path.join(out_dir,
                                          "sync_trace.perfetto.json"))
    record = {
        "scenario": "multi-process sync serve + 2 workers, traced",
        "processes": sorted(
            {f"{s.get('role')}:{s.get('pid')}" for s in spans}),
        "dump_files": [os.path.basename(p) for p in dumps],
        "span_count": len(spans),
        "trace_count": len(assembled["traces"]),
        "orphan_spans": assembled["orphan_spans"],
        "server_apply_joined_to_worker_step": joined[:5],
        "example_trace_tree": _tree_summary(next(
            t for t in assembled["traces"]
            if t["trace_id"] == joined[0]["trace_id"])),
    }
    with open(os.path.join(out_dir, "sync_trace_tree.json"), "w") as f:
        json.dump(record, f, indent=2)
    print(f"[sync] ok: {len(spans)} spans from {len(dumps)} dumps, "
          f"{len(joined)} server-apply spans parented to worker steps",
          file=sys.stderr)


def _root_of(span: dict, by_id: dict) -> str:
    node = span
    while by_id.get(node.get("parent_id")) is not None:
        node = by_id[node["parent_id"]]
    return node["span_id"]


def _tree_summary(trace: dict) -> dict:
    def node(n):
        out = {"name": n["name"], "role": n.get("role"),
               "dur_ms": round(n.get("dur", 0.0) * 1e3, 3)}
        if n.get("attrs"):
            out["attrs"] = n["attrs"]
        if n.get("children"):
            out["children"] = [node(c) for c in n["children"]]
        return out

    return {"trace_id": trace["trace_id"],
            "span_count": trace["span_count"],
            "roots": [node(r) for r in trace["roots"]]}


# -- (b) async straggler: injected slow fetch + critical-path report ---------

class _SlowFetchStore:
    """Per-worker store wrapper injecting one-way fetch latency — the
    straggler-injection technique of run_overlap_probe.py (sleeps release
    the GIL exactly like a blocking socket read would)."""

    def __init__(self, inner, delay_s: float):
        self._inner = inner
        self._delay_s = delay_s

    def fetch(self, *a, **kw):
        time.sleep(self._delay_s)
        return self._inner.fetch(*a, **kw)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def run_async_straggler(out_dir: str, delay_s: float = 3.0) -> None:
    import jax
    import numpy as np

    from distributed_parameter_server_for_ml_training_tpu import (
        telemetry as T)
    from distributed_parameter_server_for_ml_training_tpu.analysis import (
        critical_path_report, save_chrome_trace)
    from distributed_parameter_server_for_ml_training_tpu.data import (
        synthetic_cifar100)
    from distributed_parameter_server_for_ml_training_tpu.models import (
        get_model)
    from distributed_parameter_server_for_ml_training_tpu.ps.store import (
        ParameterStore, StoreConfig)
    from distributed_parameter_server_for_ml_training_tpu.ps.worker import (
        PSWorker, WorkerConfig)
    from distributed_parameter_server_for_ml_training_tpu.train.steps \
        import make_eval_step, make_grad_step
    from distributed_parameter_server_for_ml_training_tpu.utils.pytree \
        import flatten_params

    rec = T.enable_tracing(buffer=8192, role="trainer")
    rec.clear()

    ds = synthetic_cifar100()
    ds.x_train, ds.y_train = ds.x_train[:256], ds.y_train[:256]
    ds.x_test, ds.y_test = ds.x_test[:64], ds.y_test[:64]
    model = get_model("vit_tiny", num_classes=ds.num_classes,
                      image_size=32)
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 32, 32, 3), np.float32),
                           train=False)
    store = ParameterStore(
        flatten_params(variables["params"]),
        StoreConfig(mode="async", total_workers=2, staleness_bound=32))
    cfg = WorkerConfig(batch_size=32, num_epochs=2, augment=False,
                       eval_each_epoch=False)
    grad_step = make_grad_step(model, augment=False)
    eval_step = jax.jit(make_eval_step())
    slow = PSWorker(_SlowFetchStore(store, delay_s), model, ds, cfg,
                    grad_step=grad_step, eval_step=eval_step,
                    worker_name="slow-w0")
    fast = PSWorker(store, model, ds, cfg, grad_step=grad_step,
                    eval_step=eval_step, worker_name="fast-w1")
    slow.start()
    time.sleep(0.1)  # deterministic id order: slow registers first
    fast.start()
    slow.join(600)
    fast.join(600)
    T.disable_tracing()
    for w in (slow, fast):
        if w.result.error is not None:
            raise w.result.error

    spans = rec.tail()
    report = critical_path_report(spans, top=10_000)
    # The straggler we injected: slowest fetch-wait-dominant step.
    fetch_bound = [e for e in report["stragglers"]
                   if e["dominant_phase"] == "fetch_wait"]
    assert fetch_bound, report["by_dominant_phase"]
    straggler = fetch_bound[0]
    assert straggler["coverage"] >= 0.95, straggler
    assert straggler["phases_s"]["fetch_wait"] >= delay_s * 0.9, straggler
    staleness_steps = [e for e in report["stragglers"]
                       if e.get("staleness") is not None]

    save_chrome_trace(spans, os.path.join(out_dir,
                                          "async_trace.perfetto.json"))
    record = {
        "scenario": f"in-process async, 2 workers, worker 0 fetches "
                    f"delayed {delay_s * 1e3:.0f} ms (injected straggler)",
        "injected_fetch_delay_s": delay_s,
        "steps_attributed": report["steps"],
        "by_dominant_phase": report["by_dominant_phase"],
        "phase_totals_s": report["phase_totals_s"],
        "straggler": straggler,
        "straggler_note": "coverage = attributed phase time / step wall "
                          "time; the acceptance bar is >= 0.95",
        "staleness_attributed_examples": staleness_steps[:3],
        "stragglers_top": report["stragglers"][:12],
        "worker_results": {
            "slow-w0": {"steps": slow.result.local_steps_completed,
                        "accepted": slow.result.pushes_accepted,
                        "rejected": slow.result.pushes_rejected},
            "fast-w1": {"steps": fast.result.local_steps_completed,
                        "accepted": fast.result.pushes_accepted,
                        "rejected": fast.result.pushes_rejected},
        },
    }
    with open(os.path.join(out_dir, "async_straggler_report.json"),
              "w") as f:
        json.dump(record, f, indent=2)
    print(f"[async] ok: straggler coverage={straggler['coverage']}, "
          f"dominant={straggler['dominant_phase']}, "
          f"fetch_wait={straggler['phases_s']['fetch_wait']:.3f}s of "
          f"wall={straggler['wall_s']:.3f}s", file=sys.stderr)


# -- (c) SIGTERM post-mortem --------------------------------------------------

def run_sigterm_postmortem(out_dir: str) -> None:
    raw_dir = os.path.join(out_dir, "raw_sigterm")
    os.makedirs(raw_dir, exist_ok=True)
    mport = _free_port()
    cmd = CLI + [
        "train", "--mode", "async", "--workers", "2", "--model",
        "vit_tiny", "--synthetic", "--num-train", "4096", "--num-test",
        "64", "--epochs", "50", "--batch-size", "32", "--platform", "cpu",
        "--dtype", "float32", "--no-augment",
        "--trace", "--trace-buffer", "4096", "--trace-dump-dir", raw_dir,
        "--metrics-port", str(mport),
        "--telemetry", "--telemetry-interval", "2.0"]
    print(f"[sigterm] {' '.join(cmd)}", file=sys.stderr)
    proc = subprocess.Popen(cmd, cwd=REPO, env=_env(),
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE)
    live = None
    try:
        deadline = time.time() + 600
        while time.time() < deadline:
            if proc.poll() is not None:
                out, err = proc.communicate()
                sys.stderr.write(err.decode(errors="replace")[-3000:])
                raise SystemExit("sigterm demo run exited early")
            try:
                body = json.loads(urlopen(
                    f"http://127.0.0.1:{mport}/debug/trace",
                    timeout=2).read())
                if sum(1 for s in body.get("spans", [])
                       if s["name"] == "worker.step") >= 8:
                    live = body
                    break
            except OSError:
                pass
            time.sleep(0.5)
        assert live is not None, "never scraped a live trace with steps"
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()

    dump_path = os.path.join(raw_dir,
                             f"trace-trainer-{proc.pid}-sigterm.json")
    assert os.path.exists(dump_path), os.listdir(raw_dir)
    with open(dump_path) as f:
        dump = json.load(f)
    live_ids = {s["span_id"] for s in live["spans"]}
    dump_ids = {s["span_id"] for s in dump["spans"]}
    overlap = live_ids & dump_ids
    # The post-mortem's tail must contain the trace that was live just
    # before the kill (the buffer is far larger than the run's span
    # count, so nothing was evicted in between).
    assert len(overlap) >= 0.9 * len(live_ids), (len(overlap),
                                                 len(live_ids))
    final_snaps = [ln for ln in out.decode(errors="replace").splitlines()
                   if "METRICS_JSON" in ln and '"kind": "snapshot"' in ln]
    record = {
        "scenario": "cli train --mode async TERM'd mid-run",
        "rc": proc.returncode,
        "rc_note": "143 = 128 + SIGTERM via the shutdown handler "
                   "(dump + final snapshot ran instead of a silent kill)",
        "live_scrape_spans": len(live_ids),
        "sigterm_dump_spans": len(dump_ids),
        "live_spans_found_in_dump": len(overlap),
        "dump_reason": dump["reason"],
        "dump_file": os.path.basename(dump_path),
        "final_snapshot_flushed_on_sigterm": bool(final_snaps),
        "dump_tail_example": dump["spans"][-6:],
    }
    assert proc.returncode == 143, proc.returncode
    assert dump["reason"] == "sigterm"
    assert final_snaps, "snapshot emitter tail was dropped"
    with open(os.path.join(out_dir, "sigterm_postmortem.json"), "w") as f:
        json.dump(record, f, indent=2)
    print(f"[sigterm] ok: rc=143, {len(overlap)}/{len(live_ids)} live "
          f"spans present in the post-mortem dump", file=sys.stderr)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir",
                    default=os.path.join(REPO, "experiments", "results",
                                         "trace"))
    ap.add_argument("--skip-sync", action="store_true")
    ap.add_argument("--skip-async", action="store_true")
    ap.add_argument("--skip-sigterm", action="store_true")
    args = ap.parse_args()
    os.makedirs(args.out_dir, exist_ok=True)

    if not args.skip_sync:
        run_sync_tree(args.out_dir)
    if not args.skip_async:
        run_async_straggler(args.out_dir)
    if not args.skip_sigterm:
        run_sigterm_postmortem(args.out_dir)
    print(f"artifacts in {args.out_dir}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
