"""Pipeline schedule efficiency: GPipe vs 1F1B, quantified (round-4 VERDICT
weak 5).

Three measurements per (schedule, M) at S=4 stages, each from an exact
artifact rather than a wall clock this 1-chip host cannot produce (a real
stage mesh needs S chips; CPU "timing" of a virtual mesh on one core would
measure nothing but the host):

- **tick-table occupancy** — useful units / (ticks x stages), computed from
  the actual schedule table the SPMD program unrolls (build_1f1b_schedule
  verifies its own tables; GPipe's occupancy is closed-form M/(S+M-1) per
  phase). This IS the bubble: 1 - occupancy = idle tick fraction.
- **XLA memory_analysis** — per-device peak allocation of the AOT-compiled
  train step (the number that decides an OOM; same method as
  measure_pp_memory.py).
- **XLA cost_analysis FLOPs** — total program FLOPs, exposing each
  schedule's recompute overhead (GPipe remat vs 1F1B's vjp-per-unit).

Key facts the recorded table shows (see the JSON's "conclusions"):
- at EQUAL (S, M), non-interleaved 1F1B and GPipe have the SAME tick count
  2(S+M-1) and bubble (S-1)/(S+M-1) — 1F1B's schedule-level win is its
  O(S) in-flight activation cap (vs GPipe's O(M) stash; stash_gb column);
- the MEASURED program peak goes the other way: the 1F1B body's per-tick
  lax.cond units and dynamically indexed buffers defeat XLA's aliasing,
  costing more than the stash cap saves — a real, recorded negative
  result for single-program 1F1B on TPU;
- the bubble reduction itself comes from raising M: the GPipe M=32 row
  fits v5e at an 8.6% bubble (vs 27.3% at M=8) thanks to remat+sharded
  IO — the TPU-idiomatic route the trainers take. Megatron-style 1F1B
  pays off under per-stage asynchronous controllers, not inside one
  lockstep XLA program (the per-tick ring collectives synchronize
  stages, so a mixed fwd/bwd tick costs max(t_fwd, t_bwd) for all).

Run:  python experiments/measure_pp_schedule.py [--batch 512]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                 os.path.join(REPO, ".jax_cache")))

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

V5E_HBM_GB = 16.0
STAGES = 4
TOKENS = 197
HIDDEN = 768


def schedule_occupancy(schedule: str, s: int, m: int) -> dict:
    from distributed_parameter_server_for_ml_training_tpu.parallel.pipeline \
        import build_1f1b_schedule

    if schedule == "1f1b":
        t = build_1f1b_schedule(s, m)
        ticks = int(t["ticks"])
        useful = int((t["act"] != 0).sum())
        # max in-flight fwd-done-not-bwd-done units (stashed activations)
        stash = 0
        for stage in range(s):
            run = np.cumsum((t["act"][:, stage] == 1).astype(int)
                            - (t["act"][:, stage] == 2).astype(int))
            stash = max(stash, int(run.max()))
    else:
        ticks = 2 * (s + m - 1)      # fwd unroll + autodiff replay
        useful = 2 * m * s
        stash = m                    # one stashed input per microbatch
    return {
        "ticks": ticks,
        "useful_units": useful,
        "occupancy": round(useful / (ticks * s), 4),
        "bubble_fraction": round(1 - useful / (ticks * s), 4),
        "max_inflight_activations_per_stage": stash,
    }


def build_step(schedule: str, m: int, batch: int):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from distributed_parameter_server_for_ml_training_tpu.models.vit import (
        EncoderStage)
    from distributed_parameter_server_for_ml_training_tpu.parallel.pipeline \
        import make_pipeline_train_step, stack_stage_params

    mesh = Mesh(np.array(jax.devices()[:STAGES]), ("stage",))
    stage = EncoderStage(num_blocks=12 // STAGES, num_heads=12,
                         dtype=jnp.bfloat16)
    rng = jax.random.PRNGKey(0)
    tok = jnp.zeros((1, TOKENS, HIDDEN), jnp.float32)
    stage_ps = [stage.init(jax.random.fold_in(rng, 100 + s), tok)["params"]
                for s in range(STAGES)]
    stacked = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P("stage"))),
        stack_stage_params(stage_ps))

    def loss_fn(y_mb, t_mb):
        # l2 head stand-in: the cotangent entering the ring backward has
        # the real [mb, T, D] shape; identical across both schedules.
        return jnp.mean((y_mb.astype(jnp.float32) - t_mb) ** 2)

    step = make_pipeline_train_step(
        mesh, lambda p, x: stage.apply({"params": p}, x), loss_fn, m,
        schedule=schedule)
    x = jax.ShapeDtypeStruct((batch, TOKENS, HIDDEN), jnp.float32,
                             sharding=NamedSharding(mesh, P()))
    y = jax.ShapeDtypeStruct((batch, TOKENS, HIDDEN), jnp.float32,
                             sharding=NamedSharding(mesh, P()))
    return step, stacked, x, y


def measure(schedule: str, m: int, batch: int) -> dict:
    occ = schedule_occupancy(schedule, STAGES, m)
    step, stacked, x, y = build_step(schedule, m, batch)
    compiled = step.lower(stacked, x, y).compile()
    ma = compiled.memory_analysis()
    ca = compiled.cost_analysis()
    flops = (ca or {}).get("flops", 0.0)
    rec = {
        "schedule": schedule, "stages": STAGES, "microbatches": m,
        **occ,
        # schedule-level stash: max in-flight microbatch inputs x bytes
        "stash_gb": round(occ["max_inflight_activations_per_stage"]
                          * (batch // m) * TOKENS * HIDDEN * 4 / 1e9, 3),
        "temp_gb": round(ma.temp_size_in_bytes / 1e9, 3),
        "argument_gb": round(ma.argument_size_in_bytes / 1e9, 3),
        "peak_estimate_gb": round(
            (ma.temp_size_in_bytes + ma.argument_size_in_bytes
             + ma.output_size_in_bytes) / 1e9, 3),
        # NOTE: XLA cost_analysis sums BOTH lax.cond branches (static
        # accounting); the EXECUTED flops follow the tick tables and are
        # equal for both schedules up to the loss head. Recorded anyway —
        # it bounds program size, not runtime.
        "program_tflops_static": round(flops / 1e12, 3),
    }
    rec["fits_v5e"] = rec["peak_estimate_gb"] < V5E_HBM_GB
    print(rec, flush=True)
    return rec


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--microbatches", default="8,32")
    args = ap.parse_args()
    ms = [int(v) for v in args.microbatches.split(",")]

    rows = []
    for m in ms:
        for schedule in ("gpipe", "1f1b"):
            rows.append(measure(schedule, m, args.batch))
        _write(rows, args)  # incremental
    return 0


def _write(rows, args) -> None:
    out = os.path.join(REPO, "experiments", "results", "pp_schedule.json")
    with open(out, "w") as f:
        json.dump({
            "config": {"model": "vit_b16 encoder pipeline (3 blocks/stage)",
                       "tokens": TOKENS, "hidden": HIDDEN,
                       "batch": args.batch, "stages": STAGES,
                       "dtype": "bfloat16 params, fp32 boundaries",
                       "method": "tick-table occupancy (exact) + AOT "
                                 "memory_analysis + cost_analysis, "
                                 "4-stage virtual mesh; equal-numerics "
                                 "asserted in tests/test_pipeline.py"},
            "lockstep_caveat": "single-program SPMD: per-tick ring "
                               "collectives synchronize stages, so a "
                               "mixed fwd/bwd tick costs max(t_fwd, "
                               "t_bwd) for every stage; tick counts "
                               "price both schedules in the same units",
            "conclusions": [
                "At equal (S, M) both schedules have the same tick count "
                "2(S+M-1) and bubble (S-1)/(S+M-1); 1F1B's schedule-level "
                "win is the O(S) in-flight stash (stash_gb column: capped "
                "at S microbatches vs GPipe's M).",
                "MEASURED program peak goes the OTHER way: the 1F1B "
                "body's per-tick lax.cond units and dynamically indexed "
                "buffers defeat XLA's liveness/aliasing analysis, costing "
                "more than the stash cap saves — GPipe+remat lets XLA "
                "free each microbatch's residuals optimally.",
                "TPU-idiomatic conclusion, adopted by the trainers: keep "
                "GPipe+remat+sharded-IO and raise M — the M=32 GPipe row "
                "fits v5e with an 8.6% bubble (vs 27.3% at M=8), which is "
                "the bubble reduction 1F1B's memory headroom is FOR, "
                "without fighting the compiler. Megatron-style 1F1B "
                "pays off under per-stage asynchronous controllers, not "
                "inside one lockstep XLA program (pipeline.py module "
                "comment).",
            ],
            "v5e_hbm_gb": V5E_HBM_GB,
            "rows": rows}, f, indent=2)
        f.write("\n")
    print(f"wrote {out}", flush=True)
    print("\n| schedule | M | ticks | bubble | max stash/stage | "
          "peak GB | TFLOPs |")
    print("|---|---|---|---|---|---|---|")
    for r in rows:
        print(f"| {r['schedule']} | {r['microbatches']} | {r['ticks']} | "
              f"{r['bubble_fraction']} | "
              f"{r['max_inflight_activations_per_stage']} | "
              f"{r['peak_estimate_gb']} | {r['program_tflops_static']} |")


if __name__ == "__main__":
    raise SystemExit(main())
