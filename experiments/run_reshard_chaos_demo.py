"""Recorded reshard-chaos demo (ISSUE 13 acceptance evidence).

Three cells under ``experiments/results/reshard_chaos/``, every check
exit-code-verified (the PR 4-12 recorded-demo format). All long-lived
processes are real ``cli`` subprocesses; the driver talks to them only
over the wire.

**Cell A — crash-safe resharding: the coordinator dies at every phase
boundary.** Two shard primaries take a continuous ``cli loadgen``
full-fetch stream while ``cli reshard --crash-after`` hard-kills the
coordinator (exit 21) at each of the four boundaries in turn — after
``export``, ``import``, the first ``apply_ranges``, and the last
``apply_ranges`` — ping-ponging the SAME slot range [16,32) between the
primaries so each crash starts from a clean map. After every kill,
``cli reshard --resume`` reads the primaries' durable migration ledger
and deterministically rolls forward (``from_phase`` export for the
pre-publish crashes, ``apply_ranges`` for the post-publish ones). A
push token applied ONCE before any migration is replayed byte-identical
against the range's current owner after every recovery: each replay
must answer ``duplicate`` with params and step unmoved — journal-
verified parity, zero double-applies across four crash/resume cycles.
While the donor sits frozen mid-crash, its migration ledger is visible
in ``GET /cluster``'s sharding block and the ``cli status`` table. A
final cycle crashes with ``--lease-ttl 1.5`` and never resumes in time:
the donor's freeze lease expires (counter + RESHARD_LEASE_EXPIRED log),
``--resume`` rolls the recipient back, and the map is untouched.

**Cell B — corrupt frames refused end to end, faulted vs clean
control.** One primary (fast health tick). A client with
``push.corrupt@every=2`` injected (comms/faults.py) sends 8 pushes:
the 4 corrupted frames must be REFUSED server-side by the wire-CRC
gate (``dps_wire_corrupt_total`` == 4, WIRE_CORRUPT log lines, the
``wire_corrupt`` health rule fires) while the 4 clean ones apply — the
store's step and params advance by exactly the clean pushes (zero
corrupt applies). A clean control client then pushes 8/8 with the
corrupt counter unmoved, and a loadgen window spanning the corruption
records zero failed fetches.

**Cell C — partitioned replica refuses or serves within its staleness
bound.** One primary + one ``cli replica`` whose refresh subscription
carries ``refresh.partition=3@n=80``: a 3 s partition window against a
2 s staleness bound. Inside the window the replica first keeps serving
its last-synced step (within bound), then REFUSES with UNAVAILABLE
(``dps_replica_stale_rejects_total``); its poll loop backs off
(capped exponential, ``dps_replica_refresh_errors_total``) logging the
failing/recovered transition exactly once each, and after the window
it catches back up to the primary's advanced step. Primary-side
loadgen across the partition records zero failed fetches.

Artifacts: ``reshard_chaos.json`` (summary + PASS/FAIL checks),
per-cycle reshard/resume JSON, cluster captures, and process logs.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT_DIR = os.path.join(REPO, "experiments", "results", "reshard_chaos")
PKG = "distributed_parameter_server_for_ml_training_tpu"
sys.path.insert(0, REPO)

MODEL = "vit_tiny"
LR = 0.1                     # serve default (StoreConfig.learning_rate)


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env(**extra) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # Log-line checks (RESHARD_LEASE_EXPIRED, WIRE_CORRUPT,
    # REPLICA_REFRESH_FAILING) read child logs while the child is still
    # alive — don't let block buffering hide them.
    env["PYTHONUNBUFFERED"] = "1"
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _http(url: str, timeout: float = 5.0) -> str | None:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.read().decode()
    except Exception:
        return None


def _cluster(port: int) -> dict | None:
    raw = _http(f"http://127.0.0.1:{port}/cluster")
    if raw is None:
        return None
    try:
        return json.loads(raw)
    except ValueError:
        return None


def _metric_value(metrics_text: str | None, name: str,
                  labels: str = "") -> float | None:
    if not metrics_text:
        return None
    import re
    pat = re.compile(rf"^{re.escape(name)}{re.escape(labels)} (\S+)$",
                     re.M)
    m = pat.search(metrics_text)
    return float(m.group(1)) if m else None


def _spawn(argv: list, log_path: str, **env_extra):
    log = open(log_path, "w")
    proc = subprocess.Popen(argv, stdout=log, stderr=subprocess.STDOUT,
                            env=_env(**env_extra), cwd=REPO)
    return proc, log


def _stop(proc, log, grace: float = 15.0) -> int | None:
    if proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=grace)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=grace)
    log.close()
    return proc.returncode


def _serve_argv(*, port: int, metrics_port: int, mode: str = "async",
                extra: list[str] | None = None) -> list:
    return [sys.executable, "-m", f"{PKG}.cli", "serve",
            "--mode", mode, "--workers", "1",
            "--port", str(port), "--model", MODEL, "--num-classes", "100",
            "--image-size", "32", "--platform", "cpu",
            "--metrics-port", str(metrics_port)] + (extra or [])


def _wait_up(metrics_port: int, proc, what: str,
             timeout: float = 180.0) -> None:
    deadline = time.time() + timeout
    while _cluster(metrics_port) is None:
        if time.time() > deadline or proc.poll() is not None:
            raise RuntimeError(f"{what} never came up (rc={proc.poll()})")
        time.sleep(0.25)


def _grpc_up(addr: str, timeout: float = 60.0) -> None:
    from distributed_parameter_server_for_ml_training_tpu.comms.loadgen \
        import run_loadgen
    deadline = time.time() + timeout
    while time.time() < deadline:
        r = run_loadgen([addr], duration_s=0.2, concurrency=1,
                        rpc_timeout=2.0)
        if r["fetches_ok"] > 0:
            return
        time.sleep(0.5)
    raise RuntimeError(f"no PS answering at {addr}")


def _loadgen_proc(targets: list[str], mode: str, duration: float,
                  concurrency: int = 4) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, "-m", f"{PKG}.cli", "loadgen",
         "--targets", ",".join(targets), "--duration", str(duration),
         "--concurrency", str(concurrency), "--fetch-mode", mode],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=_env(), cwd=REPO)


def _json_line(text: str, prefix: str) -> dict | None:
    out = None
    for line in (text or "").splitlines():
        if line.startswith(prefix):
            out = json.loads(line[len(prefix):])
    return out


def _raw_stub(addr: str, method: str):
    import grpc
    from distributed_parameter_server_for_ml_training_tpu.comms.service \
        import GRPC_OPTIONS, SERVICE_NAME
    ident = lambda b: b  # noqa: E731
    channel = grpc.insecure_channel(addr, options=GRPC_OPTIONS)
    return channel, channel.unary_unary(
        f"/{SERVICE_NAME}/{method}",
        request_serializer=ident, response_deserializer=ident)


def _read_log(path: str) -> str:
    try:
        with open(path) as f:
            return f.read()
    except OSError:
        return ""


# ---------------------------------------------------------------------------
# Cell A: coordinator killed at every phase boundary, then --resume
# ---------------------------------------------------------------------------

def cell_a() -> tuple[dict, dict]:
    import numpy as np

    from distributed_parameter_server_for_ml_training_tpu.comms.client \
        import RemoteStore
    from distributed_parameter_server_for_ml_training_tpu.comms.service \
        import pack_msg, unpack_msg
    from distributed_parameter_server_for_ml_training_tpu.comms.wire \
        import encode_tensor_dict
    from distributed_parameter_server_for_ml_training_tpu.ps.sharding \
        import key_slot

    procs = []
    chans: dict[int, tuple] = {}
    try:
        ports = [_free_port(), _free_port()]
        mports = [_free_port(), _free_port()]
        peers = ",".join(f"localhost:{p}" for p in ports)
        for i in range(2):
            sp, slog = _spawn(
                _serve_argv(port=ports[i], metrics_port=mports[i],
                            extra=["--shard-index", str(i),
                                   "--shard-count", "2",
                                   "--shard-peers", peers]),
                os.path.join(OUT_DIR, f"a_shard{i}_server.log"))
            procs.append((sp, slog))
        for i in range(2):
            _wait_up(mports[i], procs[i][0], f"cell A shard {i}")
        v0 = int(((_cluster(mports[0]) or {}).get("sharding") or {})
                 .get("map_version") or 0)

        rs = [RemoteStore(f"localhost:{p}") for p in ports]
        wid, _ = rs[0].register_worker("chaos-parity")
        rs[1].register_worker("chaos-parity")
        params0, pstep = rs[0].fetch(wid)
        moved = sorted(k for k in params0 if 16 <= key_slot(k) < 32)
        k_parity = moved[0]
        w0 = params0[k_parity].copy()
        # The one-and-only application of this token, BEFORE any
        # migration. Every later byte-identical replay must dedupe.
        parity_req = pack_msg(
            {"worker_id": wid, "fetched_step": pstep,
             "push_token": "chaos-parity:1"},
            encode_tensor_dict({k_parity: np.full_like(w0, 0.25)}))

        def push_raw(shard: int) -> dict:
            if shard not in chans:
                chans[shard] = _raw_stub(f"localhost:{ports[shard]}",
                                         "PushGradrients")
            meta, _ = unpack_msg(chans[shard][1](parity_req,
                                                 timeout=10.0))
            return meta

        first = push_raw(0)
        expected = w0 - LR * 0.25

        def reshard(extra: list[str]):
            return subprocess.run(
                [sys.executable, "-m", f"{PKG}.cli", "reshard",
                 "--primaries", peers, "--slots", "16:32", "--json"]
                + extra,
                capture_output=True, text=True, env=_env(), cwd=REPO,
                timeout=120)

        # Client load spanning every crash/resume cycle below.
        lg = _loadgen_proc([f"localhost:{p}" for p in ports], "full",
                           duration=60.0, concurrency=2)
        time.sleep(1.0)

        # Ping-pong [16,32) between the primaries so every crash point
        # starts from a clean, converged map.
        cycles = [("export", 0, 1), ("import", 1, 0),
                  ("apply_first", 0, 1), ("apply_all", 1, 0)]
        cycle_recs = []
        frozen_view = None
        frozen_status = ""
        all_crashed = all_resumed = all_deduped = all_owned = True
        for point, d, r in cycles:
            cp = reshard(["--donor", str(d), "--recipient", str(r),
                          "--migration-id", f"mig-{point}",
                          "--crash-after", point])
            crashed = (cp.returncode == 21
                       and f"RESHARD_CRASH_POINT {point}" in cp.stdout)
            if point == "export":
                # Satellite evidence: the frozen donor's ledger is
                # visible over the admin plane while the coordinator
                # is dead.
                frozen_view = ((_cluster(mports[d]) or {})
                               .get("sharding") or {}).get("migration")
                st = subprocess.run(
                    [sys.executable, "-m", f"{PKG}.cli", "status",
                     "--url", f"http://127.0.0.1:{mports[d]}"],
                    capture_output=True, text=True, env=_env(),
                    cwd=REPO, timeout=60)
                frozen_status = st.stdout
            rp = reshard(["--donor", str(d), "--recipient", str(r),
                          "--resume"])
            resume = _json_line(rp.stdout, "RESHARD_RESUME_JSON ")
            want_from = ("export" if point in ("export", "import")
                         else "apply_ranges")
            resumed = (rp.returncode == 0 and resume is not None
                       and resume.get("outcome") == "rolled_forward"
                       and resume.get("from_phase") == want_from)
            # Parity replay against the range's NEW owner: duplicate,
            # nothing applied, step unmoved.
            s_before = rs[r].fetch(None)[1]
            replay = push_raw(r)
            p_new, s_after = rs[r].fetch(None)
            p_old, _ = rs[d].fetch(None)
            deduped = (bool(replay.get("accepted"))
                       and bool(replay.get("duplicate"))
                       and s_before == s_after)
            owned = (all(k in p_new and k not in p_old for k in moved)
                     and bool(np.allclose(p_new[k_parity], expected,
                                          atol=1e-6)))
            all_crashed &= crashed
            all_resumed &= resumed
            all_deduped &= deduped
            all_owned &= owned
            cycle_recs.append({
                "point": point, "donor": d, "recipient": r,
                "crash_rc": cp.returncode, "crashed": crashed,
                "resume_rc": rp.returncode, "resume": resume,
                "replay": {k: replay.get(k)
                           for k in ("accepted", "duplicate")},
                "owner_step_around_replay": [s_before, s_after],
                "ownership_ok": owned,
            })

        views = [(_cluster(mp) or {}).get("sharding") or {}
                 for mp in mports]
        converged = (
            [v.get("slot_range") for v in views]
            == [[0, 32], [32, 64]]
            and all(int(v.get("map_version") or 0) == v0 + 4
                    for v in views))

        # Lease sub-cell: crash pre-publish with a short TTL and DON'T
        # resume in time — the donor must self-heal (auto-unfreeze +
        # drop its record) and --resume must roll the recipient back.
        lp = reshard(["--donor", "0", "--recipient", "1",
                      "--migration-id", "mig-lease",
                      "--lease-ttl", "1.5", "--crash-after", "import"])
        lease_crashed = (lp.returncode == 21
                         and "RESHARD_CRASH_POINT import" in lp.stdout)
        time.sleep(2.6)
        lr = reshard(["--donor", "0", "--recipient", "1", "--resume"])
        lease_resume = _json_line(lr.stdout, "RESHARD_RESUME_JSON ")
        lease_metric = _metric_value(
            _http(f"http://127.0.0.1:{mports[0]}/metrics"),
            "dps_reshard_lease_expired_total")
        donor_log = _read_log(
            os.path.join(OUT_DIR, "a_shard0_server.log"))
        p0_final, _ = rs[0].fetch(None)
        p1_final, _ = rs[1].fetch(None)
        views_after = [(_cluster(mp) or {}).get("sharding") or {}
                       for mp in mports]
        lease_rolled_back = (
            lr.returncode == 0 and lease_resume is not None
            and lease_resume.get("outcome") == "rolled_back"
            and int(lease_resume.get("dropped") or 0) >= 1
            and (lease_metric or 0) >= 1
            and "RESHARD_LEASE_EXPIRED" in donor_log
            # Map untouched, donor still owns and serves the range with
            # the pre-crash values.
            and [v.get("map_version") for v in views_after]
            == [v0 + 4] * 2
            and all(k in p0_final and k not in p1_final for k in moved)
            and bool(np.allclose(p0_final[k_parity], expected,
                                 atol=1e-6)))

        lg_out, _ = lg.communicate(timeout=180)
        loadgen = _json_line(lg_out, "LOADGEN_JSON ")
        with open(os.path.join(OUT_DIR, "a_cycles.json"), "w") as f:
            json.dump({"map_version_start": v0, "cycles": cycle_recs,
                       "frozen_cluster_migration": frozen_view,
                       "lease": {"crash_rc": lp.returncode,
                                 "resume_rc": lr.returncode,
                                 "resume": lease_resume,
                                 "lease_expired_total": lease_metric},
                       "final_sharding": views_after,
                       "loadgen": loadgen}, f, indent=2)
        with open(os.path.join(OUT_DIR, "a_status_frozen.txt"),
                  "w") as f:
            f.write(frozen_status)

        for s in rs:
            s.close()

        record = {
            "parity_key": k_parity, "moved_params": len(moved),
            "parity_first": {k: first.get(k)
                             for k in ("accepted", "duplicate")},
            "cycles": [{k: c[k] for k in ("point", "crash_rc",
                                          "resume_rc", "resume")}
                       for c in cycle_recs],
            "map_versions_final": [v.get("map_version")
                                   for v in views_after],
            "lease_resume": lease_resume,
            "lease_expired_total": lease_metric,
            "loadgen": {k: (loadgen or {}).get(k)
                        for k in ("fetches_ok", "fetches_err", "qps")},
        }
        checks = {
            "A_coordinator_killed_at_all_four_boundaries":
                all_crashed and lease_crashed,
            "A_resume_rolls_forward_from_any_crash_point":
                all_resumed,
            "A_journal_parity_zero_double_applies":
                bool(first.get("accepted"))
                and not first.get("duplicate") and all_deduped,
            "A_ownership_and_map_converge_after_chaos":
                all_owned and converged,
            "A_migration_ledger_visible_while_frozen":
                isinstance(frozen_view, dict)
                and frozen_view.get("id") == "mig-export"
                and frozen_view.get("role") == "donor"
                and frozen_view.get("phase") == "export"
                and "migration mig-export: donor phase=export"
                in frozen_status,
            "A_lease_expiry_rolls_back_map_untouched":
                lease_rolled_back,
            "A_zero_failed_fetches_under_chaos":
                lg.returncode == 0 and loadgen is not None
                and loadgen["fetches_ok"] > 0
                and loadgen["fetches_err"] == 0,
        }
        return record, checks
    finally:
        for ch, _call in chans.values():
            ch.close()
        for proc, log in procs:
            _stop(proc, log)


# ---------------------------------------------------------------------------
# Cell B: corrupt pushes refused end to end, faulted vs clean control
# ---------------------------------------------------------------------------

def cell_b() -> tuple[dict, dict]:
    import numpy as np

    from distributed_parameter_server_for_ml_training_tpu.comms.client \
        import RemoteStore

    port, mport = _free_port(), _free_port()
    log_path = os.path.join(OUT_DIR, "b_primary.log")
    proc, log = _spawn(
        _serve_argv(port=port, metrics_port=mport,
                    extra=["--shard-count", "1",
                           "--shard-peers", f"localhost:{port}",
                           "--health-interval", "0.5"]),
        log_path)
    stores = []
    try:
        _wait_up(mport, proc, "cell B primary")
        addr = f"localhost:{port}"

        def metric(name: str, labels: str = "") -> float | None:
            return _metric_value(
                _http(f"http://127.0.0.1:{mport}/metrics"), name, labels)

        # Serve traffic spanning the whole corruption episode.
        lg = _loadgen_proc([addr], "full", duration=12.0, concurrency=2)

        faulted = RemoteStore(addr, faults="push.corrupt@every=2")
        stores.append(faulted)
        wid, _ = faulted.register_worker("chaos-faulted")
        advertises = faulted.supports_checksum is True
        params, _ = faulted.fetch(wid)
        name = sorted(params)[0]
        g = np.full_like(params[name], 0.01)
        w0 = params[name].copy()

        def push_n(store, worker, n) -> list[bool]:
            out = []
            for _ in range(n):
                _, step = store.fetch(worker)
                out.append(bool(store.push(worker, {name: g}, step)))
            return out

        faulted_results = push_n(faulted, wid, 8)
        w_mid, step_mid = faulted.fetch(wid)
        corrupt_total = metric("dps_wire_corrupt_total")

        # The health engine runs on a 0.5 s tick: the corrupt-frame
        # window delta must surface as a fired wire_corrupt alert.
        alerts = None
        deadline = time.time() + 10
        while time.time() < deadline:
            alerts = metric("dps_alerts_total",
                            '{rule="wire_corrupt",severity="warning"}')
            if alerts:
                break
            time.sleep(0.3)

        # Clean control: same workload, no injector — every push lands
        # and the corrupt counter does not move.
        clean = RemoteStore(addr)
        stores.append(clean)
        cwid, _ = clean.register_worker("chaos-clean")
        clean_results = push_n(clean, cwid, 8)
        w_end, step_end = clean.fetch(cwid)
        corrupt_after_clean = metric("dps_wire_corrupt_total")

        lg_out, _ = lg.communicate(timeout=60)
        loadgen = _json_line(lg_out, "LOADGEN_JSON ")
        refusal_lines = _read_log(log_path).count("WIRE_CORRUPT")

        with open(os.path.join(OUT_DIR, "b_integrity.json"), "w") as f:
            json.dump({"faulted_results": faulted_results,
                       "clean_results": clean_results,
                       "wire_corrupt_total": corrupt_total,
                       "wire_corrupt_after_clean": corrupt_after_clean,
                       "alerts_fired": alerts,
                       "refusal_log_lines": refusal_lines,
                       "loadgen": loadgen}, f, indent=2)

        record = {
            "advertises_checksum": advertises,
            "faulted_accepted": sum(faulted_results),
            "faulted_refused": 8 - sum(faulted_results),
            "clean_accepted": sum(clean_results),
            "wire_corrupt_total": corrupt_total,
            "alerts_fired": alerts,
            "step_after_faulted": step_mid,
            "step_after_clean": step_end,
            "loadgen": {k: (loadgen or {}).get(k)
                        for k in ("fetches_ok", "fetches_err", "qps")},
        }
        checks = {
            "B_register_advertises_checksum": advertises,
            "B_corrupt_pushes_refused_server_side":
                faulted_results == [True, False] * 4
                and corrupt_total == 4.0 and refusal_lines >= 4,
            "B_zero_corrupt_applies":
                step_mid == 4
                and bool(np.allclose(w_mid[name], w0 - 4 * LR * 0.01,
                                     atol=1e-5)),
            "B_wire_corrupt_health_alert_fired": (alerts or 0) >= 1,
            "B_clean_control_unaffected":
                clean_results == [True] * 8
                and corrupt_after_clean == corrupt_total
                and step_end == 12,
            "B_zero_failed_fetches_under_corruption":
                lg.returncode == 0 and loadgen is not None
                and loadgen["fetches_ok"] > 0
                and loadgen["fetches_err"] == 0,
        }
        return record, checks
    finally:
        for s in stores:
            s.close()
        _stop(proc, log)


# ---------------------------------------------------------------------------
# Cell C: partitioned replica — serve within bound, refuse past it
# ---------------------------------------------------------------------------

def cell_c() -> tuple[dict, dict]:
    import grpc
    import numpy as np

    from distributed_parameter_server_for_ml_training_tpu.comms.client \
        import RemoteStore
    from distributed_parameter_server_for_ml_training_tpu.comms.service \
        import pack_msg, unpack_msg

    procs = []
    rlog_path = os.path.join(OUT_DIR, "c_replica.log")
    try:
        port, mport = _free_port(), _free_port()
        primary, plog = _spawn(
            _serve_argv(port=port, metrics_port=mport,
                        extra=["--shard-count", "1",
                               "--shard-peers", f"localhost:{port}"]),
            os.path.join(OUT_DIR, "c_primary.log"))
        procs.append((primary, plog))
        _wait_up(mport, primary, "cell C primary")

        rs = RemoteStore(f"localhost:{port}")
        wid, _ = rs.register_worker("chaos-partition")
        params, step = rs.fetch(wid)
        name = sorted(params)[0]
        g = np.full_like(params[name], 0.01)

        def advance() -> int:
            nonlocal step
            rs.push(wid, {name: g}, step)
            step = rs.fetch(wid)[1]
            return step

        for _ in range(3):
            advance()            # primary at step 3 before the replica

        # refresh.partition=3@n=80: the ~80th subscription poll (~8 s at
        # 10 Hz — past boot and sync) opens a 3 s partition, longer than
        # the 2 s staleness bound, so the replica must cross from
        # serve-stale into refuse.
        rport, rmport = _free_port(), _free_port()
        rep, rlog = _spawn(
            [sys.executable, "-m", f"{PKG}.cli", "replica",
             "--primary", f"localhost:{port}", "--port", str(rport),
             "--poll-interval", "0.1", "--staleness-bound", "2.0",
             "--metrics-port", str(rmport),
             "--faults", "refresh.partition=3@n=80"],
            rlog_path)
        procs.append((rep, rlog))
        _grpc_up(f"localhost:{rport}")

        def rmetric(n: str, labels: str = "") -> float | None:
            return _metric_value(
                _http(f"http://127.0.0.1:{rmport}/metrics"), n, labels)

        synced = False
        deadline = time.time() + 30
        while time.time() < deadline:
            if (rmetric("dps_replica_step") or -1) >= 3:
                synced = True
                break
            time.sleep(0.1)

        # Primary-side serve traffic spanning the partition window.
        lg = _loadgen_proc([f"localhost:{port}"], "full",
                           duration=16.0, concurrency=2)

        base_errors = rmetric("dps_replica_refresh_errors_total") or 0
        t_open = None
        deadline = time.time() + 30
        while time.time() < deadline:
            if (rmetric("dps_replica_refresh_errors_total")
                    or 0) > base_errors:
                t_open = time.time()
                break
            time.sleep(0.1)
        partition_opened = t_open is not None

        advance()                # step 4 lands while the replica is cut

        ch, fetch_raw = _raw_stub(f"localhost:{rport}",
                                  "FetchParameters")
        samples = []
        end = (t_open or time.time()) + 4.5
        while time.time() < end:
            t = round(time.time() - (t_open or time.time()), 2)
            try:
                meta, _ = unpack_msg(fetch_raw(pack_msg({}, b""),
                                               timeout=2.0))
                samples.append({"t": t, "ok": True,
                                "step": int(meta["global_step"])})
            except grpc.RpcError as e:
                samples.append({"t": t, "ok": False,
                                "code": str(e.code())})
            time.sleep(0.25)
        served_in_bound = any(s["ok"] and s["step"] == 3
                              for s in samples)
        refused_stale = any(not s["ok"] and "UNAVAILABLE" in s["code"]
                            for s in samples)

        recovered = False
        recovered_step = None
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                meta, _ = unpack_msg(fetch_raw(pack_msg({}, b""),
                                               timeout=2.0))
                if int(meta["global_step"]) >= 4:
                    recovered = True
                    recovered_step = int(meta["global_step"])
                    break
            except grpc.RpcError:
                pass
            time.sleep(0.25)
        ch.close()

        refresh_errors = (rmetric("dps_replica_refresh_errors_total")
                          or 0) - base_errors
        stale_rejects = rmetric("dps_replica_stale_rejects_total")
        injected = rmetric(
            "dps_fault_injections_total",
            '{kind="partition",op="refresh",side="replica"}')
        rep_log = _read_log(rlog_path)

        lg_out, _ = lg.communicate(timeout=60)
        loadgen = _json_line(lg_out, "LOADGEN_JSON ")
        with open(os.path.join(OUT_DIR, "c_partition.json"), "w") as f:
            json.dump({"samples": samples,
                       "refresh_errors": refresh_errors,
                       "stale_rejects": stale_rejects,
                       "injections": injected,
                       "recovered_step": recovered_step,
                       "loadgen": loadgen}, f, indent=2)
        rs.close()

        record = {
            "partition_opened": partition_opened,
            "refresh_errors_during_window": refresh_errors,
            "stale_rejects_total": stale_rejects,
            "partition_injections": injected,
            "recovered_step": recovered_step,
            "fetch_samples": samples,
            "loadgen": {k: (loadgen or {}).get(k)
                        for k in ("fetches_ok", "fetches_err", "qps")},
        }
        checks = {
            "C_replica_synced_before_partition": synced,
            "C_partition_injected_and_counted":
                partition_opened and (injected or 0) >= 1
                and refresh_errors >= 2,
            "C_serves_within_bound_then_refuses":
                served_in_bound and refused_stale
                and (stale_rejects or 0) >= 1,
            "C_backoff_recovers_and_catches_up":
                recovered and (recovered_step or 0) >= 4
                and "REPLICA_REFRESH_RECOVERED" in rep_log,
            "C_transitions_logged_once":
                rep_log.count("REPLICA_REFRESH_FAILING") == 1
                and rep_log.count("REPLICA_REFRESH_RECOVERED") == 1,
            "C_primary_traffic_unaffected":
                lg.returncode == 0 and loadgen is not None
                and loadgen["fetches_ok"] > 0
                and loadgen["fetches_err"] == 0,
        }
        return record, checks
    finally:
        for proc, log in procs:
            _stop(proc, log)


def main(argv=None) -> int:
    import argparse
    global OUT_DIR
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out-dir", default=OUT_DIR,
                    help="artifact directory (default: the recorded "
                         "experiments/results/reshard_chaos)")
    args = ap.parse_args(argv)
    OUT_DIR = args.out_dir
    os.makedirs(OUT_DIR, exist_ok=True)
    t0 = time.time()
    checks: dict = {}

    a_rec, a_checks = cell_a()
    checks.update(a_checks)
    print(f"cell A: 4 crash points + lease expiry over "
          f"{a_rec['moved_params']}-tensor range, final map versions "
          f"{a_rec['map_versions_final']}, "
          f"{a_rec['loadgen']['fetches_ok']} live fetches "
          f"({a_rec['loadgen']['fetches_err']} failed)", flush=True)

    b_rec, b_checks = cell_b()
    checks.update(b_checks)
    print(f"cell B: {b_rec['faulted_refused']}/8 corrupt pushes "
          f"refused (counter={b_rec['wire_corrupt_total']}, "
          f"alerts={b_rec['alerts_fired']}), clean control "
          f"{b_rec['clean_accepted']}/8 applied", flush=True)

    c_rec, c_checks = cell_c()
    checks.update(c_checks)
    print(f"cell C: partition -> {c_rec['stale_rejects_total']} stale "
          f"rejects, {c_rec['refresh_errors_during_window']} refresh "
          f"errors, recovered at step {c_rec['recovered_step']}",
          flush=True)

    record = {
        "demo": "crash-safe resharding + serve-tier chaos hardening: "
                "migration leases, fault injection, payload integrity "
                "(ISSUE 13)",
        "elapsed_seconds": round(time.time() - t0, 1),
        "environment": {"cpus": os.cpu_count()},
        "checks": checks,
        "all_pass": all(checks.values()),
        "cell_a": a_rec,
        "cell_b": b_rec,
        "cell_c": c_rec,
    }
    with open(os.path.join(OUT_DIR, "reshard_chaos.json"), "w") as f:
        json.dump(record, f, indent=2)
    n_pass = sum(bool(v) for v in checks.values())
    print(f"reshard chaos demo: {n_pass}/{len(checks)} checks PASS "
          f"({record['elapsed_seconds']}s)")
    for cname, ok in checks.items():
        print(f"  [{'PASS' if ok else 'FAIL'}] {cname}")
    return 0 if record["all_pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
