"""Recorded two-job tenancy soak: isolation, QoS, and worker autoscaling.

The multi-job tenancy subsystem's acceptance artifact (ISSUE 15,
docs/TENANCY.md), written to ``experiments/results/tenancy/``:

**Phase 0 — solo control.** A pre-tenancy server subprocess plus one
PSWorker records the control accuracy curve for the tiny-ResNet run.

**Phase 1 — parity under neighbor chaos.** ONE tenancy server subprocess
(JobManager + weighted-fair QoS + ClusterMonitor + per-job checkpoint
lineages + the real ``/metrics`` HTTP endpoint serving ``GET /cluster``
— the same wiring ``cli serve --jobs`` assembles). Job B trains the same
model from the same seed as the control while job A takes concurrent
chaos: a push storm whose exactly-once tokens carry a leak-sentinel
string, a NaN gradient that poisons job A's params in place, and a
worker-child subprocess SIGKILLed mid-run (the reaper must expire it).
Job B's accuracy curve must match the control EXACTLY and its params
must stay finite — the poison landed, and stayed, in job A's namespace.

**Phase 2 — autoscale under load.** A real WorkerSupervisor spawns
fetch-loop worker children for job B; a real WorkerAutoscaler polls the
server's ``GET /cluster`` jobs block for admission-queue pressure while
the fetch load generator hammers job B with concurrency far above its
``max_inflight``. The scaler must grow (>= 1 ``worker_grow``, the grown
children visible as registered members in ``/cluster``) and, once the
storm ends, shrink back to the floor (>= 1 ``worker_shrink``).

**Leakage audit.** After SIGTERM (checkpoint flush through the shutdown
path), every byte of job B's and the default job's checkpoint lineage is
scanned for the sentinel: it must appear in job A's journal and NOWHERE
else — zero cross-job leakage, byte-verified.

Run: JAX_PLATFORMS=cpu python experiments/run_tenancy_demo.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(REPO, ".jax_cache")))

import numpy as np  # noqa: E402

OUT_DIR = os.path.join(REPO, "experiments", "results", "tenancy")
#: The cross-job leak marker: seeded as the chaos client's push-token
#: nonce, so every storm push's exactly-once token carries it into job
#: A's dedupe journal — and, at flush, into job A's checkpoint lineage
#: and nobody else's (the byte scan at the end is the proof).
SENTINEL = "LEAKSENTRYJOBA"
JOBS_SPEC = ("joba:weight=1,max_inflight=4;"
             "jobb:weight=2,max_inflight=2,min_workers=1,max_workers=3")


def _build_model_and_params():
    from distributed_parameter_server_for_ml_training_tpu.models import (
        ResNet)
    from distributed_parameter_server_for_ml_training_tpu.utils.pytree \
        import flatten_params
    model = ResNet(stage_sizes=(1, 1), num_filters=8, num_classes=10)
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 32, 32, 3), np.float32),
                           train=False)
    return model, flatten_params(variables["params"])


# -- server child -------------------------------------------------------------

def server_child(args) -> int:
    """One parameter-server life. With ``--jobs`` this is the tenancy
    stack ``cli serve --jobs`` wires: JobManager (per-job stores, strided
    worker ids), ParameterService with weighted-fair admission,
    ClusterMonitor feeding ``GET /cluster``, a real metrics HTTP
    endpoint, and one checkpoint lineage PER JOB (each journaling only
    its own tenant's push tokens). Without ``--jobs`` it is the plain
    pre-tenancy server (the control)."""
    import functools

    from distributed_parameter_server_for_ml_training_tpu.checkpoint \
        import PeriodicStoreCheckpointer
    from distributed_parameter_server_for_ml_training_tpu.comms import (
        ParameterService, serve)
    from distributed_parameter_server_for_ml_training_tpu.ps import (
        ParameterStore, StoreConfig)
    from distributed_parameter_server_for_ml_training_tpu.ps.tenancy \
        import DEFAULT_JOB, JobManager, parse_jobs_spec
    from distributed_parameter_server_for_ml_training_tpu.telemetry import (
        ClusterMonitor, HealthThresholds, add_shutdown_flush,
        install_shutdown_hooks, set_cluster_monitor, start_metrics_server)

    _, flat = _build_model_and_params()
    store = ParameterStore(flat, StoreConfig(
        mode="async", total_workers=1, learning_rate=0.05,
        staleness_bound=10, elastic=True,
        worker_timeout=args.worker_timeout, push_codec="none"))
    jobs = None
    if args.jobs:
        jobs = JobManager(store, parse_jobs_spec(args.jobs))
    monitor = ClusterMonitor(
        store,
        HealthThresholds(dead_after_s=max(2.0, args.worker_timeout),
                         straggler_lag_steps=100_000),
        interval=0.5)
    set_cluster_monitor(monitor)
    monitor.start()
    if jobs is not None:
        monitor.jobs = jobs
    svc = ParameterService(store, monitor=monitor, jobs=jobs)
    if args.serve_cost > 0:
        # Synthetic per-fetch serve cost, held INSIDE the admission slot
        # (the tiny demo model's encode path is near-free; a production
        # model's is not). This is what lets the weighted-fair queue
        # actually build under the phase-2 load storm — the admission
        # math under test is real, only the handler occupancy is
        # simulated.
        inner_fetch = svc._fetch_body

        def slow_fetch_body(meta, job, store_, lwid):
            time.sleep(args.serve_cost)
            return inner_fetch(meta, job, store_, lwid)

        svc._fetch_body = slow_fetch_body
    ckpts = []
    if args.ckpt_dir:
        primary_journal = (svc.journal_snapshot if jobs is None
                           else functools.partial(svc.journal_snapshot,
                                                  job=DEFAULT_JOB))
        ckpts.append(PeriodicStoreCheckpointer(
            store, args.ckpt_dir, interval=args.ckpt_interval,
            journal_fn=primary_journal))
        if jobs is not None:
            for jname in jobs.names():
                if jname == DEFAULT_JOB:
                    continue
                ckpts.append(PeriodicStoreCheckpointer(
                    jobs.store_for(jname),
                    os.path.join(args.ckpt_dir, f"job-{jname}"),
                    interval=args.ckpt_interval,
                    journal_fn=functools.partial(svc.journal_snapshot,
                                                 job=jname)))
        for c in ckpts:
            c.start()
    # SIGTERM drains every lineage's end state through the telemetry
    # shutdown path — the parent's kill at the end of the soak is what
    # makes the leakage byte-scan read FINAL journals, not stale ones.
    install_shutdown_hooks(role="server")
    for c in ckpts:
        add_shutdown_flush(c.flush_now)
    _http, mport = start_metrics_server(port=args.metrics_port)
    server, port = serve(store, port=args.port, service=svc)
    print(f"TENANCY_SERVER_READY port={port} metrics={mport}", flush=True)
    lifetime_deadline = time.time() + args.max_lifetime
    while not store.wait_all_finished(timeout=0.5):
        if jobs is not None:
            jobs.expire_stale_workers()
        else:
            store.expire_stale_workers()
        if time.time() > lifetime_deadline:
            print("TENANCY_SERVER_LIFETIME_EXCEEDED", flush=True)
            break
    time.sleep(0.3)
    server.stop(grace=1.0)
    for c in ckpts:
        c.stop(final_snapshot=True)
    monitor.stop()
    print("TENANCY_SERVER_EXIT " + json.dumps({
        "global_step": store.global_step,
        "gradients_processed": store.stats.gradients_processed,
    }), flush=True)
    return 0


# -- worker child (supervisor-spawned fetch loop / kill victim) ---------------

def worker_child(args) -> int:
    """A registered fetch-loop worker for one job: what the supervisor's
    elastic slots spawn in phase 2 (and what phase 1 SIGKILLs). Liveness
    comes from the fetches; it runs until its lifetime guard or a
    supervisor SIGTERM."""
    from distributed_parameter_server_for_ml_training_tpu.comms import (
        RemoteStore)
    rs = RemoteStore(f"localhost:{args.server_port}", rpc_timeout=10.0,
                     rpc_retries=2, rpc_backoff=0.1, job=args.job or None)
    wid, _total = rs.register_worker(args.worker_name)
    print(f"TENANCY_WORKER_REGISTERED wid={wid} job={rs.job}", flush=True)
    deadline = time.time() + args.max_lifetime
    while time.time() < deadline:
        try:
            rs.fetch(worker_id=wid)
        except Exception:  # throttled/expired past retries: keep looping
            pass
        time.sleep(0.25)
    rs.close()
    return 0


# -- parent-side orchestration ------------------------------------------------

def _spawn_server(out_dir, tag, *, jobs="", ckpt_dir="", worker_timeout,
                  ckpt_interval=1.0, serve_cost=0.0):
    """Start a server child, poll its log for READY, and return
    (proc, log_path, grpc_port, metrics_port) — both ports are
    OS-assigned and parsed back from the READY line."""
    log_path = os.path.join(out_dir, f"{tag}.log")
    log = open(log_path, "w")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--server-child",
         "--jobs", jobs, "--ckpt-dir", ckpt_dir,
         "--worker-timeout", str(worker_timeout),
         "--ckpt-interval", str(ckpt_interval),
         "--serve-cost", str(serve_cost)],
        stdout=log, stderr=subprocess.STDOUT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO)
    deadline = time.time() + 120
    while time.time() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(f"server {tag} died at startup; see "
                               f"{log_path}")
        with open(log_path) as f:
            for line in f:
                if line.startswith("TENANCY_SERVER_READY"):
                    fields = dict(p.split("=") for p in line.split()[1:])
                    return (proc, log_path, int(fields["port"]),
                            int(fields["metrics"]))
        time.sleep(0.1)
    raise RuntimeError(f"server {tag} never came up; see {log_path}")


def _server_exit_stats(log_path) -> dict:
    with open(log_path) as f:
        for line in f:
            if line.startswith("TENANCY_SERVER_EXIT "):
                return json.loads(line[len("TENANCY_SERVER_EXIT "):])
    return {}


def _cluster_view(mport) -> dict:
    from urllib.request import urlopen
    raw = urlopen(f"http://127.0.0.1:{mport}/cluster", timeout=5).read()
    return json.loads(raw)


def _metrics_text(mport) -> str:
    from urllib.request import urlopen
    return urlopen(f"http://127.0.0.1:{mport}/metrics",
                   timeout=5).read().decode()


def _run_training_worker(model, ds, *, port, job, epochs, batch, name,
                         grad_step, eval_step):
    """One PSWorker against the server at ``port``, optionally inside a
    job's namespace. Returns the worker result (accuracy curve etc.)."""
    from distributed_parameter_server_for_ml_training_tpu.comms import (
        RemoteStore)
    from distributed_parameter_server_for_ml_training_tpu.ps import (
        PSWorker, WorkerConfig)
    c = RemoteStore(f"localhost:{port}", rpc_timeout=15.0, rpc_retries=2,
                    rpc_backoff=0.1, job=job)
    try:
        cfg = WorkerConfig(batch_size=batch, num_epochs=epochs,
                           sync_steps=1, augment=False,
                           heartbeat_interval=1.0,
                           reconnect_timeout=60.0, reconnect_backoff=0.1)
        w = PSWorker(c, model, ds, cfg, grad_step=grad_step,
                     eval_step=eval_step, worker_name=name)
        w.start()
        w.join(timeout=600)
    finally:
        c.close()
    if w.result.error is not None:
        raise RuntimeError(f"{name} failed") from w.result.error
    return w.result


def _joba_chaos(port, *, pushes, nan_at):
    """Job A's bad day, driven from one registered chaos client: a push
    storm whose tokens all carry the leak sentinel, with one NaN
    gradient in the middle. Zero-valued gradients elsewhere keep job A's
    params constant until the poison turns them NaN — which must never
    show up in job B (the parity check runs concurrently)."""
    from distributed_parameter_server_for_ml_training_tpu.comms import (
        RemoteStore)
    rs = RemoteStore(f"localhost:{port}", rpc_timeout=10.0, rpc_retries=2,
                     rpc_backoff=0.1, job="joba")
    rs._push_nonce = SENTINEL  # every storm token now carries the marker
    out = {"sent": 0, "accepted": 0, "errors": []}
    wid, _ = rs.register_worker("storm-a")
    out["wid"] = wid
    params, step = rs.fetch(worker_id=wid)
    zero = {k: np.zeros_like(v) for k, v in params.items()}
    poison = {k: np.full_like(v, np.nan) for k, v in params.items()}
    for i in range(pushes):
        grads = poison if i == nan_at else zero
        out["sent"] += 1
        try:
            if rs.push(wid, grads, step):
                out["accepted"] += 1
        except Exception as e:
            out["errors"].append(repr(e))
        try:
            params, step = rs.fetch(worker_id=wid)
        except Exception as e:
            out["errors"].append(repr(e))
    out["params_nonfinite_after"] = bool(any(
        not np.all(np.isfinite(np.asarray(v, np.float32)))
        for v in params.values()))
    rs.close()
    return out


def _fetch_job_params(port, job):
    from distributed_parameter_server_for_ml_training_tpu.comms import (
        RemoteStore)
    rs = RemoteStore(f"localhost:{port}", rpc_timeout=10.0, rpc_retries=2,
                     rpc_backoff=0.1, job=job)
    try:
        # The job label is capability-gated on the registration
        # handshake — an unregistered probe would read the DEFAULT job.
        wid, _ = rs.register_worker(f"probe-{job}")
        params, step = rs.fetch(worker_id=wid)
        return params, step
    finally:
        rs.close()


def _spawn_kill_victim(out_dir, port):
    log_path = os.path.join(out_dir, "kill_victim.log")
    log = open(log_path, "w")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker-child",
         "--server-port", str(port), "--job", "joba",
         "--worker-name", "victim-a", "--max-lifetime", "120"],
        stdout=log, stderr=subprocess.STDOUT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO)
    deadline = time.time() + 60
    while time.time() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(f"kill victim died early; see {log_path}")
        with open(log_path) as f:
            for line in f:
                if line.startswith("TENANCY_WORKER_REGISTERED"):
                    fields = dict(p.split("=") for p in line.split()[1:])
                    return proc, int(fields["wid"])
        time.sleep(0.1)
    raise RuntimeError(f"kill victim never registered; see {log_path}")


def _wait_worker_gone(mport, job, wid, timeout=30.0) -> bool:
    deadline = time.time() + timeout
    while time.time() < deadline:
        row = (_cluster_view(mport).get("jobs") or {}).get(job) or {}
        if wid not in (row.get("workers") or []):
            return True
        time.sleep(0.5)
    return False


def _run_autoscale_phase(port, mport, out_dir, *, storm_s, settle_s):
    """Phase 2: a real WorkerSupervisor (elastic slots spawning
    ``--worker-child`` fetch loops for job B) actuated by a real
    WorkerAutoscaler whose pressure_fn polls the server's live
    ``GET /cluster`` jobs block, while the fetch load generator hammers
    job B with concurrency far above its max_inflight=2."""
    from distributed_parameter_server_for_ml_training_tpu.comms.loadgen \
        import run_loadgen
    from distributed_parameter_server_for_ml_training_tpu.ps.supervisor \
        import SupervisorConfig, WorkerSupervisor
    from distributed_parameter_server_for_ml_training_tpu.telemetry. \
        remediation import WorkerAutoscalePolicy, WorkerAutoscaler

    row0 = (_cluster_view(mport).get("jobs") or {}).get("jobb") or {}
    members_start = len(row0.get("workers") or [])

    def argv_for(slot: int, attempt: int):
        return [sys.executable, os.path.abspath(__file__),
                "--worker-child", "--server-port", str(port),
                "--job", "jobb", "--worker-name",
                f"scale-{slot}-{attempt}", "--max-lifetime", "120"]

    sup = WorkerSupervisor(argv_for, 1, SupervisorConfig(
        respawn=True, backoff_initial=0.2, backoff_max=1.0,
        healthy_after=1.0, crash_loop_after=5, graceful_timeout=3.0))
    sup.start()
    run_t = threading.Thread(target=sup.run, daemon=True,
                             name="demo-supervisor")
    run_t.start()

    def pressure() -> dict:
        row = (_cluster_view(mport).get("jobs") or {}).get("jobb") or {}
        return {"queue_depth": row.get("waiting") or 0,
                "stragglers": 0,
                "workers": len(row.get("workers") or [])}

    scaler = WorkerAutoscaler(
        "jobb", pressure, supervisor=sup,
        policy=WorkerAutoscalePolicy(depth_high=4.0, depth_low=1.0,
                                     sustain_ticks=2, min_workers=1,
                                     max_workers=3, cooldown_s=2.0))
    lg_result: dict = {}

    def _storm():
        lg_result.update(run_loadgen(
            [f"localhost:{port}"], duration_s=storm_s, concurrency=12,
            mode="full", rpc_timeout=10.0, job="jobb"))

    # Let the base slot's child come up and register before the storm —
    # the grown-members check below is measured against a settled floor.
    time.sleep(settle_s)
    storm_t = threading.Thread(target=_storm, daemon=True,
                               name="demo-loadgen")
    storm_t.start()
    samples = []
    max_members = 0
    max_slots = 0
    t0 = time.time()
    deadline = t0 + storm_s + 45.0
    while time.time() < deadline:
        event = scaler.tick()
        try:
            row = (_cluster_view(mport).get("jobs") or {}).get("jobb") or {}
        except Exception:
            row = {}
        members = len(row.get("workers") or [])
        max_members = max(max_members, members)
        max_slots = max(max_slots, sup.count())
        samples.append({"t": round(time.time() - t0, 2),
                        "waiting": row.get("waiting"),
                        "inflight": row.get("inflight"),
                        "slots": sup.count(), "members": members,
                        "event": event})
        if (not storm_t.is_alive() and sup.count() <= 1
                and scaler.actions["worker_shrink"] >= 1):
            break
        time.sleep(0.5)
    storm_t.join(timeout=60)
    while sup.remove_slot() is not None:  # retire the floor -> run() exits
        pass
    run_t.join(timeout=30)
    return {"members_start": members_start, "max_members": max_members,
            "max_slots": max_slots, "actions": dict(scaler.actions),
            "events": scaler.view()["events"], "samples": samples,
            "loadgen": lg_result}


def _scan_lineage_for_sentinel(ckpt_dir) -> dict:
    """Byte-scan every checkpoint file: which lineage dirs carry the
    sentinel? Keys are '<default>' for top-level files and the job-*
    subdir name otherwise."""
    marker = SENTINEL.encode()
    hits: dict[str, list[str]] = {}
    files_scanned = 0
    for root, _dirs, files in os.walk(ckpt_dir):
        rel_root = os.path.relpath(root, ckpt_dir)
        top = rel_root.split(os.sep)[0]
        lineage = "<default>" if top == "." else top
        for fname in files:
            files_scanned += 1
            path = os.path.join(root, fname)
            with open(path, "rb") as f:
                if marker in f.read():
                    hits.setdefault(lineage, []).append(
                        os.path.relpath(path, ckpt_dir))
    return {"files_scanned": files_scanned, "hits": hits}


def _metric_value(metrics_text, name, **labels) -> float | None:
    """Parse one sample out of the Prometheus text exposition."""
    want = None
    for line in metrics_text.splitlines():
        if not line.startswith(name):
            continue
        if labels:
            rendered = [f'{k}="{v}"' for k, v in labels.items()]
            if not all(r in line for r in rendered):
                continue
        try:
            want = float(line.rsplit(" ", 1)[1])
        except (ValueError, IndexError):
            continue
    return want


def run_demo(args) -> int:
    from distributed_parameter_server_for_ml_training_tpu.data import (
        synthetic_cifar100)
    from distributed_parameter_server_for_ml_training_tpu.train.steps \
        import make_eval_step, make_grad_step

    out_dir = args.out_dir
    os.makedirs(out_dir, exist_ok=True)
    quick = args.quick
    epochs = 1 if quick else 2
    n_train = 128 if quick else 256
    batch = 32
    storm_pushes = 16 if quick else 40
    loadgen_s = 8.0 if quick else 14.0
    worker_timeout = 6.0
    total_steps = epochs * (n_train // batch)

    model, _flat = _build_model_and_params()
    ds = synthetic_cifar100(n_train=n_train, n_test=64, num_classes=10,
                            seed=1)
    grad_step = make_grad_step(model, augment=False)
    eval_step = jax.jit(make_eval_step())
    summary: dict = {"quick": quick, "jobs_spec": JOBS_SPEC,
                     "sentinel": SENTINEL, "phases": {}}
    checks: list[tuple[str, bool, str]] = []

    # ---- Phase 0: solo control --------------------------------------------
    ctl_ckpt = os.path.join(out_dir, "ckpt_control")
    p_ctl, ctl_log, ctl_port, _ctl_mport = _spawn_server(
        out_dir, "control_server", ckpt_dir=ctl_ckpt,
        worker_timeout=worker_timeout)
    control = _run_training_worker(
        model, ds, port=ctl_port, job=None, epochs=epochs, batch=batch,
        name="control-0", grad_step=grad_step, eval_step=eval_step)
    p_ctl.wait(timeout=120)
    ctl_stats = _server_exit_stats(ctl_log)
    summary["phases"]["control"] = {
        "server": ctl_stats,
        "accuracy_curve": control.test_accuracies,
        "pushes_accepted": control.pushes_accepted}

    # ---- Phase 1: tenancy server, parity under neighbor chaos -------------
    ten_ckpt = os.path.join(out_dir, "ckpt_tenancy")
    p_ten, ten_log, port, mport = _spawn_server(
        out_dir, "tenancy_server", jobs=JOBS_SPEC, ckpt_dir=ten_ckpt,
        worker_timeout=worker_timeout, serve_cost=0.025)

    parity_holder: dict = {}

    def _parity():
        try:
            parity_holder["result"] = _run_training_worker(
                model, ds, port=port, job="jobb", epochs=epochs,
                batch=batch, name="parity-b", grad_step=grad_step,
                eval_step=eval_step)
        except Exception as e:
            parity_holder["error"] = repr(e)

    parity_t = threading.Thread(target=_parity, daemon=True,
                                name="demo-parity-b")
    parity_t.start()

    victim, victim_wid = _spawn_kill_victim(out_dir, port)
    storm = _joba_chaos(port, pushes=storm_pushes,
                        nan_at=storm_pushes // 3)
    victim.kill()  # SIGKILL: no goodbye — the reaper must notice
    victim.wait(timeout=30)
    victim_expired = _wait_worker_gone(mport, "joba", victim_wid,
                                       timeout=worker_timeout * 4)
    parity_t.join(timeout=600)
    if "result" not in parity_holder:
        raise RuntimeError(f"parity worker failed: "
                           f"{parity_holder.get('error', 'timeout')}")
    parity = parity_holder["result"]
    jobb_params, _ = _fetch_job_params(port, "jobb")
    jobb_finite = bool(all(
        np.all(np.isfinite(np.asarray(v, np.float32)))
        for v in jobb_params.values()))
    jobb_row = (_cluster_view(mport).get("jobs") or {}).get("jobb") or {}
    summary["phases"]["parity_under_chaos"] = {
        "accuracy_curve": parity.test_accuracies,
        "pushes_accepted": parity.pushes_accepted,
        "jobb_global_step": jobb_row.get("global_step"),
        "jobb_params_finite": jobb_finite,
        "storm": storm, "victim_wid": victim_wid,
        "victim_expired": victim_expired}

    checks += [
        ("control.completed",
         control.local_steps_completed == total_steps
         and ctl_stats.get("global_step") == control.pushes_accepted,
         f"{control.local_steps_completed}/{total_steps} steps, server "
         f"step {ctl_stats.get('global_step')}"),
        ("B.accuracy_parity_exact",
         np.allclose(control.test_accuracies, parity.test_accuracies,
                     atol=1e-12),
         f"control={control.test_accuracies} "
         f"jobb={parity.test_accuracies}"),
        ("B.step_parity",
         jobb_row.get("global_step") == ctl_stats.get("global_step"),
         f"jobb={jobb_row.get('global_step')} "
         f"control={ctl_stats.get('global_step')}"),
        ("B.params_finite_after_neighbor_nan", jobb_finite,
         "all job B tensors finite"),
        ("A.storm_applied_with_sentinel_tokens",
         storm["accepted"] == storm["sent"] and not storm["errors"],
         f"accepted={storm['accepted']}/{storm['sent']} "
         f"errors={len(storm['errors'])}"),
        ("A.nan_poison_landed_in_joba",
         storm["params_nonfinite_after"], "job A params went NaN"),
        ("A.killed_worker_expired", victim_expired,
         f"wid={victim_wid} reaped within {worker_timeout * 4:.0f}s"),
        ("server.survived_chaos", p_ten.poll() is None,
         "tenancy server still serving after phase 1"),
    ]

    # ---- Phase 2: autoscale under load ------------------------------------
    time.sleep(worker_timeout + 2.0)  # let phase-1 members expire out
    scale = _run_autoscale_phase(port, mport, out_dir,
                                 storm_s=loadgen_s, settle_s=6.0)
    summary["phases"]["autoscale"] = scale

    metrics_txt = _metrics_text(mport)
    with open(os.path.join(out_dir, "metrics_final.txt"), "w") as f:
        f.write(metrics_txt)
    final_view = _cluster_view(mport)
    with open(os.path.join(out_dir, "cluster_final.json"), "w") as f:
        json.dump(final_view, f, indent=2)
    admitted_a = _metric_value(metrics_txt, "dps_job_admitted_total",
                               job="joba")
    admitted_b = _metric_value(metrics_txt, "dps_job_admitted_total",
                               job="jobb")
    throttled_b = _metric_value(metrics_txt, "dps_job_throttled_total",
                                job="jobb")
    summary["qos"] = {
        "admitted_joba": admitted_a, "admitted_jobb": admitted_b,
        "throttled_jobb": throttled_b,
        "loadgen_jobs": scale["loadgen"].get("jobs")}
    peak_waiting = max((s["waiting"] or 0) for s in scale["samples"])
    lg_jobb = (scale["loadgen"].get("jobs") or {}).get("jobb") or {}

    checks += [
        ("qos.per_job_attribution",
         bool(admitted_a and admitted_a > 0
              and admitted_b and admitted_b > 0),
         f"admitted joba={admitted_a} jobb={admitted_b} "
         f"throttled_jobb={throttled_b}"),
        ("qos.pressure_observed_over_depth_high", peak_waiting > 4.0,
         f"peak jobb waiting={peak_waiting}"),
        ("qos.loadgen_per_job_latency_recorded",
         bool(lg_jobb.get("ok", 0) > 0
              and "p99" in (lg_jobb.get("latency_ms") or {})),
         f"jobb loadgen={lg_jobb}"),
        ("autoscale.grew", scale["actions"]["worker_grow"] >= 1,
         f"actions={scale['actions']}"),
        ("autoscale.shrank", scale["actions"]["worker_shrink"] >= 1,
         f"actions={scale['actions']}"),
        ("autoscale.grown_workers_in_cluster_view",
         scale["max_members"] >= scale["members_start"] + 2,
         f"members {scale['members_start']} -> max "
         f"{scale['max_members']} (slots max {scale['max_slots']})"),
    ]

    # ---- Teardown + leakage audit -----------------------------------------
    p_ten.send_signal(signal.SIGTERM)  # flush every lineage's journal
    p_ten.wait(timeout=60)
    scan = _scan_lineage_for_sentinel(ten_ckpt)
    summary["leakage_scan"] = scan
    leaked_into = sorted(k for k in scan["hits"] if k != "job-joba")
    checks += [
        ("leakage.sentinel_in_joba_lineage",
         bool(scan["hits"].get("job-joba")),
         f"hits={scan['hits'].get('job-joba')}"),
        ("leakage.zero_cross_job_bytes", not leaked_into,
         f"scanned {scan['files_scanned']} files; "
         f"foreign hits={leaked_into or 'none'}"),
    ]

    summary["checks"] = [
        {"name": n, "ok": bool(ok), "detail": d} for n, ok, d in checks]
    summary["ok"] = all(ok for _, ok, _ in checks)
    out_path = os.path.join(out_dir, "tenancy_demo.json")
    with open(out_path, "w") as f:
        json.dump(summary, f, indent=2)
    for n, ok, d in checks:
        print(f"{'PASS' if ok else 'FAIL'} {n}: {d}")
    print(f"wrote {out_path}")
    return 0 if summary["ok"] else 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out-dir", default=OUT_DIR)
    # internal: server-child mode
    ap.add_argument("--server-child", action="store_true")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--metrics-port", type=int, default=0)
    ap.add_argument("--jobs", default="")
    ap.add_argument("--ckpt-dir", default="")
    ap.add_argument("--ckpt-interval", type=float, default=1.0)
    ap.add_argument("--worker-timeout", type=float, default=6.0)
    ap.add_argument("--serve-cost", type=float, default=0.0,
                    help="synthetic seconds of per-fetch handler cost "
                         "held inside the admission slot")
    ap.add_argument("--max-lifetime", type=float, default=600.0,
                    help="child self-destruct (orphan guard)")
    # internal: worker-child mode (fetch loop)
    ap.add_argument("--worker-child", action="store_true")
    ap.add_argument("--server-port", type=int, default=0)
    ap.add_argument("--job", default="")
    ap.add_argument("--worker-name", default="child")
    args = ap.parse_args()
    if args.server_child:
        return server_child(args)
    if args.worker_child:
        return worker_child(args)
    return run_demo(args)


if __name__ == "__main__":
    raise SystemExit(main())
