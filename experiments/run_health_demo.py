"""Recorded cluster-health demo (ISSUE 5 acceptance evidence).

Two real serve + 2-worker runs over gRPC (separate processes, CPU backend),
plus a monitor-overhead A/B, recorded under ``experiments/results/health/``:

- **faulted**: worker-0 is killed mid-run by the PR 4 fault injector
  (client-side ``push.kill@n=2`` — ``os._exit`` mid-RPC, no goodbye), and
  worker-1 gets one batch's loss+gradients poisoned with NaN
  (``DPS_NAN_STEP``). The demo polls ``GET /cluster`` live and requires the
  ``dead_worker`` alert to fire for worker-0's id and a non-finite alert
  (``nonfinite_loss``/``nonfinite_grad``) for worker-1's id — correct
  attribution, not just "something fired". ``cli status`` must exit 2.
- **control**: the identical run with no faults; ZERO alerts may fire and
  ``cli status`` must exit 0.
- **overhead**: the same push/fetch byte-path through ``ParameterService``
  with the monitor attached (health report riding every envelope) vs
  without — the recorded form of the tier-1 <2% guard
  (``tests/test_health.py::TestMonitorOverheadGuard``).

Artifacts: ``health_demo.json`` (summary + PASS/FAIL checks),
``{faulted,control}_cluster.json`` (captured views),
``{faulted,control}_status.txt`` (rendered dashboards + exit codes),
``{faulted,control}_log.txt`` (raw stdout incl. ``"kind": "cluster"``
records), ``alert_timeline.json``, ``health_demo.png`` (alert-overlay
plot), ``overhead_bench.json``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT_DIR = os.path.join(REPO, "experiments", "results", "health")
PKG = "distributed_parameter_server_for_ml_training_tpu"
sys.path.insert(0, REPO)


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env(**extra) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _cluster(port: int) -> dict | None:
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/cluster", timeout=5) as r:
            return json.loads(r.read())
    except Exception:
        return None


def _run_status(port: int) -> tuple[int, str]:
    p = subprocess.run(
        [sys.executable, "-m", f"{PKG}.cli", "status",
         "--metrics-port", str(port)],
        capture_output=True, text=True, env=_env(), cwd=REPO, timeout=60)
    return p.returncode, p.stdout + p.stderr


def _scenario(name: str, faulted: bool) -> dict:
    """One serve + 2-worker run; returns the scenario record."""
    grpc_port, metrics_port = _free_port(), _free_port()
    log_path = os.path.join(OUT_DIR, f"{name}_log.txt")
    log = open(log_path, "w")
    server = subprocess.Popen(
        [sys.executable, "-m", f"{PKG}.cli", "serve",
         "--mode", "async", "--workers", "2", "--port", str(grpc_port),
         "--model", "vit_tiny", "--num-classes", "100",
         "--image-size", "32", "--platform", "cpu",
         "--worker-timeout", "3", "--dead-after", "5",
         "--health-interval", "1",
         "--telemetry", "--telemetry-interval", "1",
         "--metrics-port", str(metrics_port), "--emit-metrics"],
        stdout=log, stderr=subprocess.STDOUT, env=_env(), cwd=REPO)

    deadline = time.time() + 60
    while _cluster(metrics_port) is None:
        if time.time() > deadline or server.poll() is not None:
            raise RuntimeError(f"{name}: server never came up")
        time.sleep(0.25)

    def start_worker(wname: str, faults: str | None, nan_step: int | None):
        argv = [sys.executable, "-m", f"{PKG}.cli", "worker",
                "--server", f"localhost:{grpc_port}",
                "--worker-name", wname, "--model", "vit_tiny",
                "--synthetic", "--num-train", "256", "--num-test", "64",
                "--epochs", "2", "--batch-size", "32",
                "--platform", "cpu", "--dtype", "float32", "--no-augment",
                "--heartbeat", "0.5", "--emit-metrics"]
        if faults:
            argv += ["--faults", faults]
        env = _env(**({"DPS_NAN_STEP": nan_step}
                      if nan_step is not None else {}))
        return subprocess.Popen(argv, stdout=log,
                                stderr=subprocess.STDOUT, env=env,
                                cwd=REPO)

    # Deterministic id assignment: w0 registers (id 0) before w1 starts.
    w0 = start_worker(
        "demo-w0", "seed=7;push.kill@n=2" if faulted else None, None)
    deadline = time.time() + 180
    while True:
        view = _cluster(metrics_port)
        if view and len(view.get("workers", [])) >= 1:
            break
        if time.time() > deadline or w0.poll() not in (None, 137):
            raise RuntimeError(f"{name}: worker 0 never registered")
        time.sleep(0.5)
    w1 = start_worker("demo-w1", None, 4 if faulted else None)

    # Poll the live endpoint: the demo's evidence is captured MID-RUN.
    views: list[dict] = []
    best_view: dict | None = None
    status_rc: int | None = None
    status_out = ""
    want = {"dead_worker", "nonfinite"} if faulted else set()
    deadline = time.time() + 420
    while time.time() < deadline:
        view = _cluster(metrics_port)
        if view is not None:
            views.append(view)
            rules = {a["rule"] for a in view.get("alerts", [])}
            have = {"dead_worker"} & rules
            if any(r.startswith("nonfinite") for r in rules):
                have.add("nonfinite")
            if want and want <= have and status_rc is None:
                best_view = view
                status_rc, status_out = _run_status(metrics_port)
            if not want and status_rc is None \
                    and any(len(r.get("workers", [])) >= 2 for r in [view]) \
                    and any("step" in w for w in view["workers"]):
                best_view = view
                status_rc, status_out = _run_status(metrics_port)
        if w1.poll() is not None and (faulted or w0.poll() is not None):
            break
        time.sleep(0.5)

    # One last capture if we never got the mid-run one (server may still
    # be up briefly after the workers exit).
    if status_rc is None:
        view = _cluster(metrics_port)
        if view:
            best_view = view
            status_rc, status_out = _run_status(metrics_port)

    try:
        server.wait(timeout=120)
    except subprocess.TimeoutExpired:
        server.terminate()
        server.wait(timeout=30)
    for w in (w0, w1):
        try:
            w.wait(timeout=120)
        except subprocess.TimeoutExpired:
            w.kill()
    log.close()

    with open(os.path.join(OUT_DIR, f"{name}_status.txt"), "w") as f:
        f.write(f"# cli status exit code: {status_rc}\n\n{status_out}")
    final = best_view or (views[-1] if views else {})
    with open(os.path.join(OUT_DIR, f"{name}_cluster.json"), "w") as f:
        json.dump(final, f, indent=2)

    alerts = final.get("alerts", [])
    all_rules = {a["rule"]: a for v in views for a in v.get("alerts", [])}
    return {
        "name": name,
        "grpc_port": grpc_port,
        "metrics_port": metrics_port,
        "server_rc": server.returncode,
        "worker_rcs": [w0.returncode, w1.returncode],
        "views_captured": len(views),
        "alerts_final": alerts,
        "alert_rules_seen": sorted(all_rules),
        "alerts_seen": list(all_rules.values()),
        "status_rc": status_rc,
        "log": os.path.relpath(log_path, REPO),
    }


def _overhead_bench() -> dict:
    """Monitor on vs off through the real ParameterService byte path."""
    import numpy as np

    from distributed_parameter_server_for_ml_training_tpu.comms.service import (  # noqa: E501
        ParameterService, pack_msg)
    from distributed_parameter_server_for_ml_training_tpu.comms.wire import (
        encode_tensor_dict)
    from distributed_parameter_server_for_ml_training_tpu.ps.store import (
        ParameterStore, StoreConfig)
    from distributed_parameter_server_for_ml_training_tpu.telemetry import (
        ClusterMonitor)

    def run(monitored: bool) -> float:
        store = ParameterStore(
            {"w": np.zeros((1024, 1024), np.float32)},
            StoreConfig(mode="async", total_workers=1, push_codec="none"))
        mon = ClusterMonitor(store) if monitored else None
        svc = ParameterService(store, monitor=mon)
        wid, _ = store.register_worker()
        payload = encode_tensor_dict(
            {"w": np.ones((1024, 1024), np.float32)})
        health = {"step": 1, "loss": 2.0, "loss_finite": True,
                  "grad_norm": 1.0, "grad_finite": True,
                  "examples_per_s": 100.0}
        durations = []
        for i in range(40):
            meta = {"worker_id": wid, "fetched_step": store.global_step,
                    "push_token": f"bench:{('on' if monitored else 'off')}"
                                  f"{i}:1"}
            fmeta = {"worker_id": wid}
            if monitored:
                meta["health"] = dict(health, step=i)
                fmeta["health"] = dict(health, step=i)
            t0 = time.perf_counter()
            svc.push_gradrients(pack_msg(meta, payload), None)
            svc.fetch_parameters(pack_msg(fmeta), None)
            durations.append(time.perf_counter() - t0)
        durations.sort()
        return durations[len(durations) // 2]

    run(False)  # warm caches
    off = run(False)
    on = run(True)
    overhead = (on - off) / off
    return {
        "payload": "1M fp32 params, push+fetch pair via ParameterService",
        "pairs_per_side": 40,
        "median_pair_seconds_monitor_off": round(off, 6),
        "median_pair_seconds_monitor_on": round(on, 6),
        "overhead_fraction": round(overhead, 4),
        "guard": "tests/test_health.py::TestMonitorOverheadGuard (<2%)",
    }


def main() -> int:
    os.makedirs(OUT_DIR, exist_ok=True)
    t0 = time.time()

    faulted = _scenario("faulted", faulted=True)
    control = _scenario("control", faulted=False)
    overhead = _overhead_bench()

    # Attribution: w0 registered first -> id 0 (killed); w1 -> id 1 (NaN).
    f_alerts = {a["rule"]: a for a in faulted["alerts_seen"]}
    nonfinite = [a for r, a in f_alerts.items()
                 if r in ("nonfinite_loss", "nonfinite_grad")]
    checks = {
        "faulted_dead_worker_fired": "dead_worker" in f_alerts,
        "faulted_dead_worker_names_killed_worker":
            f_alerts.get("dead_worker", {}).get("worker") == 0,
        "faulted_nonfinite_fired": bool(nonfinite),
        "faulted_nonfinite_names_nan_worker":
            all(a.get("worker") == 1 for a in nonfinite),
        "faulted_status_exit_2": faulted["status_rc"] == 2,
        "faulted_killed_worker_rc_137": faulted["worker_rcs"][0] == 137,
        "control_zero_alerts": control["alert_rules_seen"] == [],
        "control_status_exit_0": control["status_rc"] == 0,
        "control_workers_clean_exit": control["worker_rcs"] == [0, 0],
        "overhead_under_2_percent": overhead["overhead_fraction"] < 0.02,
    }

    # Alert timeline + overlay plot from the faulted run's captured stdout.
    from distributed_parameter_server_for_ml_training_tpu.analysis import (
        ExperimentVisualizer, alert_timeline)
    flog = open(os.path.join(OUT_DIR, "faulted_log.txt")).read()
    timeline = alert_timeline(flog)
    with open(os.path.join(OUT_DIR, "alert_timeline.json"), "w") as f:
        json.dump(timeline, f, indent=2)
    plotted = ExperimentVisualizer.plot_cluster_health(
        flog, os.path.join(OUT_DIR, "health_demo.png"))
    checks["faulted_timeline_has_fired_edges"] = any(
        e["state"] == "fired" for e in timeline)
    checks["plot_rendered_both_workers"] = len(plotted["workers"]) >= 2

    record = {
        "demo": "cluster health monitor (ISSUE 5)",
        "elapsed_seconds": round(time.time() - t0, 1),
        "checks": checks,
        "all_pass": all(checks.values()),
        "faulted": faulted,
        "control": control,
        "overhead_bench": overhead,
    }
    with open(os.path.join(OUT_DIR, "overhead_bench.json"), "w") as f:
        json.dump(overhead, f, indent=2)
    with open(os.path.join(OUT_DIR, "health_demo.json"), "w") as f:
        json.dump(record, f, indent=2)
    n_pass = sum(checks.values())
    print(f"health demo: {n_pass}/{len(checks)} checks PASS "
          f"({record['elapsed_seconds']}s)")
    for name, ok in checks.items():
        print(f"  [{'PASS' if ok else 'FAIL'}] {name}")
    return 0 if record["all_pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
