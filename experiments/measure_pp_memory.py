"""Per-device pipeline memory: round-3 replicating schedule vs round-4
sharded-IO + remat.

Round-4 VERDICT item 5 'done' bar: a recorded peak-HBM table showing pp
fits where the replicating scheme OOMs. Compiles the FULL pp train step
(prologue -> pipeline over ViT-B/16 encoder stages at 224px tokens ->
epilogue -> CE loss -> grads) ahead-of-time on a 4-stage mesh for each
(shard_io, remat) combination and reads XLA's per-device
``memory_analysis`` — the compiler's own peak-allocation accounting, which
is what determines an OOM on a real chip (v5e: 16 GB HBM/chip).

No execution needed (and none would fit on the CPU host at batch 512);
the same SPMD program is what a TPU mesh would run.

Run:  python experiments/measure_pp_memory.py [--batch 512]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                 os.path.join(REPO, ".jax_cache")))

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

V5E_HBM_GB = 16.0
STAGES = 4
MICROBATCHES = 8


def build_and_measure(batch: int, image_size: int, shard_io: bool,
                      remat: bool) -> dict:
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from distributed_parameter_server_for_ml_training_tpu.models.vit import (
        EncoderStage, ViTEpilogue, ViTPrologue)
    from distributed_parameter_server_for_ml_training_tpu.parallel.pipeline import (
        make_pipeline_apply, stack_stage_params)
    from distributed_parameter_server_for_ml_training_tpu.train.steps import (
        cross_entropy_loss)

    mesh = Mesh(np.array(jax.devices()[:STAGES]).reshape(1, STAGES),
                ("data", "stage"))
    dtype = jnp.bfloat16
    prologue = ViTPrologue(patch_size=16, hidden_dim=768, dtype=dtype)
    stage = EncoderStage(num_blocks=12 // STAGES, num_heads=12, dtype=dtype)
    epilogue = ViTEpilogue(num_classes=100, dtype=dtype)

    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
    pro_p = prologue.init(rng, sample)["params"]
    tokens = prologue.apply({"params": pro_p}, sample)
    stage_ps = [stage.init(jax.random.fold_in(rng, 100 + s), tokens)["params"]
                for s in range(STAGES)]
    epi_p = epilogue.init(jax.random.fold_in(rng, 7), tokens)["params"]
    params = {"prologue": pro_p,
              "stages": stack_stage_params(stage_ps),
              "epilogue": epi_p}

    pipe = make_pipeline_apply(
        mesh, lambda p, x: stage.apply({"params": p}, x),
        num_microbatches=MICROBATCHES, data_axis=None,
        shard_io=shard_io, remat=remat)

    def loss_fn(params, images, labels):
        t = prologue.apply({"params": params["prologue"]}, images)
        t = pipe(params["stages"], t)
        logits = epilogue.apply({"params": params["epilogue"]}, t)
        return cross_entropy_loss(logits, labels)

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))
    images = jax.ShapeDtypeStruct((batch, image_size, image_size, 3),
                                  jnp.float32,
                                  sharding=NamedSharding(mesh, P()))
    labels = jax.ShapeDtypeStruct((batch,), jnp.int32,
                                  sharding=NamedSharding(mesh, P()))
    # Place stage params on the mesh so the AOT compile sees the real
    # layout (stage leaves one-per-slot, rest replicated).
    placed = {
        "prologue": jax.device_put(pro_p, NamedSharding(mesh, P())),
        "stages": jax.tree_util.tree_map(
            lambda x: jax.device_put(x, NamedSharding(mesh, P("stage"))),
            params["stages"]),
        "epilogue": jax.device_put(epi_p, NamedSharding(mesh, P())),
    }
    compiled = grad_fn.lower(placed, images, labels).compile()
    ma = compiled.memory_analysis()
    rec = {
        "shard_io": shard_io, "remat": remat,
        "temp_gb": round(ma.temp_size_in_bytes / 1e9, 3),
        "argument_gb": round(ma.argument_size_in_bytes / 1e9, 3),
        "output_gb": round(ma.output_size_in_bytes / 1e9, 3),
        "peak_estimate_gb": round(
            (ma.temp_size_in_bytes + ma.argument_size_in_bytes
             + ma.output_size_in_bytes) / 1e9, 3),
    }
    rec["fits_v5e"] = rec["peak_estimate_gb"] < V5E_HBM_GB
    print(rec, flush=True)
    return rec


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--image-size", type=int, default=224)
    args = ap.parse_args()

    rows = []
    for shard_io, remat in ((False, False), (True, False), (False, True),
                            (True, True)):
        rows.append(build_and_measure(args.batch, args.image_size,
                                      shard_io, remat))
    out = os.path.join(REPO, "experiments", "results", "pp_memory.json")
    with open(out, "w") as f:
        json.dump({
            "config": {"model": "vit_b16", "image_size": args.image_size,
                       "batch": args.batch, "stages": STAGES,
                       "microbatches": MICROBATCHES,
                       "dtype": "bfloat16",
                       "method": "AOT compile + XLA memory_analysis, "
                                 "per device, 4-stage virtual mesh"},
            "v5e_hbm_gb": V5E_HBM_GB,
            "rows": rows}, f, indent=2)
        f.write("\n")
    print(f"wrote {out}")
    print("\n| shard_io | remat | temp GB | peak est GB | fits v5e 16GB |")
    print("|---|---|---|---|---|")
    for r in rows:
        print(f"| {r['shard_io']} | {r['remat']} | {r['temp_gb']} | "
              f"{r['peak_estimate_gb']} | {r['fits_v5e']} |")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
