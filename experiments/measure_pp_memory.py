"""Per-device pipeline memory: round-3 replicating schedule vs round-4
sharded-IO + remat.

Round-4 VERDICT item 5 'done' bar: a recorded peak-HBM table showing pp
fits where the replicating scheme OOMs. Compiles the pipeline's
forward+backward (ViT-B/16 encoder stages at 224px token shapes,
batch 512, 4 stages x 8 microbatches) ahead-of-time for each
(shard_io, remat) combination and reads XLA's per-device
``memory_analysis`` — the compiler's own peak-allocation accounting,
which is what determines an OOM on a real chip (v5e: 16 GB HBM/chip).

Scope note: the measured program is the PIPELINE segment (the stage ring
+ its backward), which dominates the step's activation memory — the
replicated prologue/epilogue add one [B, T, D] boundary tensor each.
The full train step cannot be AOT-compiled on the virtual CPU mesh:
XLA:CPU's SPMD partitioner check-fails ("Invalid binary instruction
opcode copy") on the auto-sharded patch-embed conv composed with the
manually-partitioned shard_map; the TPU backend compiles the identical
composition fine (tests/test_model_parallel.py trains it), but AOT for
a 4-device TPU mesh needs 4 physical chips this host lacks.

No execution happens (batch 512 would not fit the CPU host); the SPMD
program is what a TPU stage mesh runs.

Run:  python experiments/measure_pp_memory.py [--batch 512]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                 os.path.join(REPO, ".jax_cache")))

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

V5E_HBM_GB = 16.0
STAGES = 4
MICROBATCHES = 8
TOKENS = 197          # 224px / patch 16 -> 196 patches + CLS
HIDDEN = 768


def build_and_measure(batch: int, shard_io: bool, remat: bool) -> dict:
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from distributed_parameter_server_for_ml_training_tpu.models.vit import (
        EncoderStage)
    from distributed_parameter_server_for_ml_training_tpu.parallel.pipeline import (
        make_pipeline_apply, stack_stage_params)

    mesh = Mesh(np.array(jax.devices()[:STAGES]).reshape(1, STAGES),
                ("data", "stage"))
    stage = EncoderStage(num_blocks=12 // STAGES, num_heads=12,
                         dtype=jnp.bfloat16)
    rng = jax.random.PRNGKey(0)
    tok = jnp.zeros((1, TOKENS, HIDDEN), jnp.float32)
    stage_ps = [stage.init(jax.random.fold_in(rng, 100 + s), tok)["params"]
                for s in range(STAGES)]
    stacked = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P("stage"))),
        stack_stage_params(stage_ps))

    pipe = make_pipeline_apply(
        mesh, lambda p, x: stage.apply({"params": p}, x),
        num_microbatches=MICROBATCHES, data_axis=None,
        shard_io=shard_io, remat=remat)

    def loss_fn(stages, x):
        # sum over the pipeline output: the cotangent entering the ring's
        # backward has the same [B, T, D] shape the real CE loss feeds it.
        return jnp.sum(pipe(stages, x).astype(jnp.float32) ** 2)

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))
    # fp32 boundary tensors: a bf16 pipeline input check-fails the XLA:CPU
    # compiler (same "opcode copy" bug class as the full-step composition;
    # the TPU backend runs bf16 pipelines fine — the trainers do). This
    # overstates the IO tensors 2x, identically across all four
    # combinations, so the comparison stands.
    x = jax.ShapeDtypeStruct((batch, TOKENS, HIDDEN), jnp.float32,
                             sharding=NamedSharding(mesh, P()))
    compiled = grad_fn.lower(stacked, x).compile()
    ma = compiled.memory_analysis()
    rec = {
        "shard_io": shard_io, "remat": remat,
        "temp_gb": round(ma.temp_size_in_bytes / 1e9, 3),
        "argument_gb": round(ma.argument_size_in_bytes / 1e9, 3),
        "output_gb": round(ma.output_size_in_bytes / 1e9, 3),
        "peak_estimate_gb": round(
            (ma.temp_size_in_bytes + ma.argument_size_in_bytes
             + ma.output_size_in_bytes) / 1e9, 3),
    }
    rec["fits_v5e"] = rec["peak_estimate_gb"] < V5E_HBM_GB
    print(rec, flush=True)
    return rec


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=512)
    args = ap.parse_args()

    rows = []
    for shard_io, remat in ((False, False), (True, False), (False, True),
                            (True, True)):
        rows.append(build_and_measure(args.batch, shard_io, remat))
    out = os.path.join(REPO, "experiments", "results", "pp_memory.json")
    with open(out, "w") as f:
        json.dump({
            "config": {"model": "vit_b16 encoder pipeline",
                       "tokens": TOKENS, "hidden": HIDDEN,
                       "batch": args.batch, "stages": STAGES,
                       "microbatches": MICROBATCHES,
                       "dtype": "bfloat16",
                       "method": "AOT compile + XLA memory_analysis of "
                                 "the pipeline fwd+bwd, per device, "
                                 "4-stage virtual mesh"},
            "v5e_hbm_gb": V5E_HBM_GB,
            "rows": rows}, f, indent=2)
        f.write("\n")
    print(f"wrote {out}")
    print("\n| shard_io | remat | temp GB | peak est GB | fits v5e 16GB |")
    print("|---|---|---|---|---|")
    for r in rows:
        print(f"| {r['shard_io']} | {r['remat']} | {r['temp_gb']} | "
              f"{r['peak_estimate_gb']} | {r['fits_v5e']} |")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
