"""Recorded probe for the overlapped comms pipeline + delta fetch (ISSUE 2).

Two honest A/B cells over real localhost gRPC (CPU backend), writing
``experiments/results/pipeline/overlap_probe.json`` + the raw telemetry
snapshot streams:

**A. overlap** — one `serve` (in-process gRPC server, sync store) + one
PSWorker over RemoteStore, K-step faithful loop, serial vs ``overlap=True``
with identical seeds. Records mean per-step wall time (post-compile
epochs), the accuracy-vs-step curves (must be EQUAL — the pipeline keeps
the serial RPC sequence), and the ``dps_worker_overlap_saved_seconds``
evidence from the snapshot stream.

**B. delta fetch** — sync store expecting 2 workers where one is an
artificial straggler (sleep-wrapped grad step), K=1: the fast worker's
boundary refetches mostly hit an unchanged step. Records client-side
FetchParameters wire bytes with ``delta_fetch`` off vs on; the ISSUE
acceptance bar is a >50% fetch-byte reduction in this straggler-wait
scenario, visible in the store/client not-modified counters.

Run: JAX_PLATFORMS=cpu python experiments/run_overlap_probe.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(REPO, ".jax_cache")))

import numpy as np  # noqa: E402

OUT_DIR = os.path.join(REPO, "experiments", "results", "pipeline")


def _build(filters: int):
    from distributed_parameter_server_for_ml_training_tpu.models import (
        ResNet)
    model = ResNet(stage_sizes=(1, 1), num_filters=filters, num_classes=10)
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 32, 32, 3), np.float32),
                           train=False)
    from distributed_parameter_server_for_ml_training_tpu.utils.pytree \
        import flatten_params
    return model, flatten_params(variables["params"])


def _registry_deltas(before: dict, after: dict) -> dict:
    """Counter + histogram-sum deltas between two registry snapshots
    (the registry is process-global and cumulative across cells)."""
    out = {}
    for key, v in after.get("counters", {}).items():
        d = v - before.get("counters", {}).get(key, 0.0)
        if d:
            out[key] = round(d, 3)
    for key, h in after.get("histograms", {}).items():
        prev = before.get("histograms", {}).get(key, {})
        d_sum = h.get("sum", 0.0) - prev.get("sum", 0.0)
        d_n = h.get("count", 0) - prev.get("count", 0)
        if d_n:
            out[key] = {"sum": round(d_sum, 4), "count": d_n}
    return out


def _delay_calls(client, delay_s: float) -> None:
    """Inject symmetric one-way latency into the hot RPCs — the cross-host
    DCN term a localhost loopback doesn't have. ``time.sleep`` releases
    the GIL, so (like a real network wait) the delay is hideable by the
    comms pipeline but costs the serial loop its full duration."""
    for name in ("FetchParameters", "PushGradrients"):
        inner = client._call[name]

        def delayed(request, timeout=None, _inner=inner):
            time.sleep(delay_s)
            return _inner(request, timeout=timeout)

        client._call[name] = delayed


def _run_worker_cell(model, store_params, *, overlap: bool,
                     delta_fetch: bool, mode: str, total_workers: int,
                     sync_steps: int, epochs: int, n_train: int,
                     batch: int, straggle_s: float, log_path: str,
                     role: str, strict_rounds: bool = False,
                     rpc_delay_s: float = 0.0) -> dict:
    """One serve+worker(s) cell over localhost gRPC, snapshot stream to
    ``log_path``. Returns measurements + per-cell registry deltas."""
    from distributed_parameter_server_for_ml_training_tpu.comms import (
        RemoteStore, serve)
    from distributed_parameter_server_for_ml_training_tpu.data import (
        synthetic_cifar100)
    from distributed_parameter_server_for_ml_training_tpu.ps import (
        ParameterStore, PSWorker, StoreConfig, WorkerConfig)
    from distributed_parameter_server_for_ml_training_tpu.telemetry import (
        SnapshotEmitter, get_registry)
    from distributed_parameter_server_for_ml_training_tpu.train.steps \
        import make_eval_step, make_grad_step

    ds = synthetic_cifar100(n_train=n_train, n_test=64, num_classes=10,
                            seed=1)
    store = ParameterStore(
        {k: v.copy() for k, v in store_params.items()},
        StoreConfig(mode=mode, total_workers=total_workers,
                    learning_rate=0.05, strict_rounds=strict_rounds))
    server, port = serve(store, port=0)
    grad_step = make_grad_step(model, augment=False)
    eval_step = jax.jit(make_eval_step())

    def straggler_step(*a):
        time.sleep(straggle_s)
        return grad_step(*a)

    reg_before = get_registry().snapshot()
    clients, workers = [], []
    log_f = open(log_path, "a")
    emitter = SnapshotEmitter(interval=1.0, role=role,
                              stream=log_f).start()
    t0 = time.time()
    try:
        for i in range(total_workers):
            c = RemoteStore(f"localhost:{port}")
            if rpc_delay_s:
                _delay_calls(c, rpc_delay_s)
            clients.append(c)
            workers.append(PSWorker(
                c, model, ds,
                WorkerConfig(batch_size=batch, num_epochs=epochs,
                             sync_steps=sync_steps, augment=False,
                             overlap=overlap, delta_fetch=delta_fetch,
                             # Liveness pings ride the same delta gating:
                             # a ping against an unchanged step costs a
                             # header instead of the full model (the
                             # polling half of the straggler-wait story).
                             heartbeat_interval=(0.15 if straggle_s
                                                 else 0.0)),
                grad_step=straggler_step if (straggle_s and i > 0)
                else grad_step,
                eval_step=eval_step, worker_name=f"{role}-w{i}"))
        for w in workers:
            w.start()
        for w in workers:
            w.join(timeout=1800)
        for w in workers:
            if w.result.error is not None:
                raise w.result.error
    finally:
        emitter.stop(final=True)
        log_f.close()
        server.stop(grace=None)
        for c in clients:
            c.close()
    wall = time.time() - t0
    reg_after = get_registry().snapshot()
    r0 = workers[0].result
    # Post-compile per-step wall time: epoch 0 pays jit, drop it.
    steady = r0.epoch_times[1:] or r0.epoch_times
    steps_per_epoch = r0.local_steps_completed // epochs
    return {
        "wall_seconds": round(wall, 2),
        "epoch_times_seconds": [round(t, 3) for t in r0.epoch_times],
        "mean_step_seconds_post_compile": round(
            sum(steady) / (len(steady) * steps_per_epoch), 5),
        "test_accuracies": r0.test_accuracies,
        "local_steps": r0.local_steps_completed,
        "pushes_accepted": r0.pushes_accepted,
        "wire": clients[0].wire_stats(),
        "registry_deltas": _registry_deltas(reg_before, reg_after),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="smaller model/dataset (CI smoke, not recorded)")
    ap.add_argument("--filters", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--n-train", type=int, default=768)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--sync-steps", type=int, default=4)
    ap.add_argument("--straggle", type=float, default=0.25)
    args = ap.parse_args()
    if args.quick:
        args.filters, args.epochs = 16, 2
        args.n_train, args.batch = 256, 16

    os.makedirs(OUT_DIR, exist_ok=True)
    model, params = _build(args.filters)
    n_params = sum(int(v.size) for v in params.values())
    print(f"model: {n_params} params "
          f"({n_params * 4 / 1e6:.2f} MB fp32 fetch payload)", flush=True)

    # -- A: overlap serial vs pipelined, across injected RPC latencies -----
    # This host has ONE core: CPU-bound codec/handler work cannot truly
    # run under CPU-bound XLA compute, so the 0 ms row measures pipeline
    # OVERHEAD honestly. The injected one-way delays simulate the
    # cross-host DCN latency the pipeline exists to hide (the reference's
    # deployed topology); sleeps release the GIL exactly like a socket
    # wait, so the overlap they show is real, not an artifact.
    overlap_log = os.path.join(OUT_DIR, "overlap_cells.log")
    open(overlap_log, "w").close()
    latencies = [0.0, 0.01] if args.quick else [0.0, 0.01, 0.025]
    by_latency = {}
    for delay in latencies:
        cells = {}
        for name, overlap in (("serial", False), ("overlapped", True)):
            tag = f"{name}@{int(delay * 1e3)}ms"
            print(f"[A:{tag}] running...", flush=True)
            cells[name] = _run_worker_cell(
                model, params, overlap=overlap, delta_fetch=True,
                mode="sync", total_workers=1, sync_steps=args.sync_steps,
                epochs=args.epochs, n_train=args.n_train, batch=args.batch,
                straggle_s=0.0, log_path=overlap_log,
                role=f"overlap-{tag}", rpc_delay_s=delay)
            print(f"[A:{tag}] mean step "
                  f"{cells[name]['mean_step_seconds_post_compile'] * 1e3:.2f}"
                  f" ms, accs {cells[name]['test_accuracies']}", flush=True)
        s, o = (cells["serial"]["mean_step_seconds_post_compile"],
                cells["overlapped"]["mean_step_seconds_post_compile"])
        by_latency[f"{int(delay * 1e3)}ms"] = {
            **{k: cells[k] for k in ("serial", "overlapped")},
            "accuracy_vs_step_equal": (cells["serial"]["test_accuracies"]
                                       == cells["overlapped"]
                                       ["test_accuracies"]),
            "mean_step_reduction_pct": round(100.0 * (s - o) / s, 2),
        }
    overlap_result = {"by_rpc_latency": by_latency}

    # -- B: delta fetch in a straggler-wait sync scenario -------------------
    delta_log = os.path.join(OUT_DIR, "delta_cells.log")
    open(delta_log, "w").close()
    fetch_key = ("dps_rpc_client_bytes_total"
                 "{direction=in,rpc=FetchParameters}")
    dcells = {}
    for name, on in (("delta_off", False), ("delta_on", True)):
        print(f"[B:{name}] running...", flush=True)
        # strict_rounds: a round needs BOTH workers, so the step genuinely
        # waits on the straggler (with quirk-3 counting, the fast worker's
        # own double pushes would complete rounds and advance the step,
        # which is restart pollution, not a straggler wait).
        dcells[name] = _run_worker_cell(
            model, params, overlap=False, delta_fetch=on, mode="sync",
            total_workers=2, sync_steps=1, epochs=2,
            n_train=256, batch=32, straggle_s=args.straggle,
            log_path=delta_log, role=f"delta-{name}", strict_rounds=True)
        fetched = dcells[name]["registry_deltas"].get(fetch_key, 0.0)
        print(f"[B:{name}] FetchParameters bytes in: {fetched:.0f}",
              flush=True)
    f_off = dcells["delta_off"]["registry_deltas"].get(fetch_key, 0.0)
    f_on = dcells["delta_on"]["registry_deltas"].get(fetch_key, 0.0)
    delta_result = {
        **dcells,
        "fetch_bytes_in": {"delta_off": f_off, "delta_on": f_on},
        "fetch_bytes_reduction_pct": round(
            100.0 * (f_off - f_on) / f_off, 2) if f_off else None,
    }

    # -- telemetry-stream evidence (the wins, visible in snapshots) ---------
    from distributed_parameter_server_for_ml_training_tpu.analysis.parse_logs \
        import build_telemetry_timeseries
    streams = {}
    for label, path in (("overlap", overlap_log), ("delta", delta_log)):
        with open(path) as f:
            ts = build_telemetry_timeseries(f.read())
        streams[label] = {
            proc_key: proc.get("pipeline", {})
            for proc_key, proc in ts["procs"].items()}

    record = {
        "experiment": "overlap_probe",
        "topology": "in-process gRPC serve + RemoteStore PSWorker threads, "
                    "localhost, JAX_PLATFORMS=cpu",
        "model_params": n_params,
        "config": vars(args),
        "overlap": overlap_result,
        "delta_fetch": delta_result,
        "telemetry_pipeline_sections": streams,
        "notes": [
            "mean_step_seconds_post_compile drops epoch 0 (jit compile).",
            "A-cell runs are seed-identical; accuracy_vs_step_equal is the "
            "pipeline's serial-RPC-sequence guarantee, checked not assumed.",
            "SINGLE-CORE HOST: the 0ms A-row measures pipeline overhead "
            "honestly (CPU-bound comms cannot hide under CPU-bound compute "
            "on one core); the 10/25ms rows inject symmetric one-way RPC "
            "latency simulating the cross-host DCN term — sleeps release "
            "the GIL exactly like socket waits, so the overlap they show "
            "is the mechanism's real effect on its target topology.",
            "B-cell fetch bytes are the client-side FetchParameters "
            "direction=in counter delta over both clients (fast worker + "
            "straggler); strict_rounds makes the round genuinely wait on "
            "the straggler.",
            "registry deltas are per-cell differences of the process-global "
            "registry; the raw snapshot streams are in *_cells.log.",
        ],
    }
    out_path = os.path.join(OUT_DIR, "overlap_probe.json")
    with open(out_path, "w") as f:
        json.dump(record, f, indent=2)
        f.write("\n")
    print(f"\nwrote {out_path}")
    for lat, row in by_latency.items():
        print(f"overlap@{lat}: step "
              f"{row['serial']['mean_step_seconds_post_compile'] * 1e3:.2f}"
              f" -> "
              f"{row['overlapped']['mean_step_seconds_post_compile'] * 1e3:.2f}"
              f" ms ({row['mean_step_reduction_pct']}%), "
              f"acc equal: {row['accuracy_vs_step_equal']}")
    print(f"delta fetch: {f_off / 1e6:.2f} -> {f_on / 1e6:.2f} MB in "
          f"({delta_result['fetch_bytes_reduction_pct']}% reduction)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
