"""Pod-scale config validation: ResNet-50 sync-SGD over 32 workers.

BASELINE.json configs[3] is "ResNet-50 / ImageNet-1k sync-SGD, 32 workers
(pod-scale allreduce)" — every other config has recorded evidence at its
worker count, but 32-way sync had only the 8-device dryrun. This compiles
and executes the REAL sync train step (parallel/sync_dp.py shard_map +
pmean; bf16 wire and the int8 ring) for ResNet-50 with the ImageNet stem
and 1000 classes over a 32-device virtual mesh — the driver's
`xla_force_host_platform_device_count` technique at the pod-scale worker
count (and the store bound: MAX_WORKERS is 32, ps/store.py).

Host-sized shapes (112px, global batch 32 = 1 image/worker) keep the
single-core CPU run tractable; the sharding/collective structure is
identical at 224px — the per-device program only scales.

Run:  python experiments/validate_pod_scale.py
Writes experiments/results/pod_scale_dryrun.json.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_WORKERS = 32

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={N_WORKERS}")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                 os.path.join(REPO, ".jax_cache")))

import numpy as np  # noqa: E402


def main() -> int:
    import jax.numpy as jnp

    from distributed_parameter_server_for_ml_training_tpu.models import (
        ResNet50)
    from distributed_parameter_server_for_ml_training_tpu.parallel import (
        make_mesh, make_sync_dp_step, shard_batch)
    from distributed_parameter_server_for_ml_training_tpu.train import (
        create_train_state, server_sgd)

    assert jax.device_count() == N_WORKERS, jax.devices()
    mesh = make_mesh(N_WORKERS)
    size = 112
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16,
                     axis_name="data", imagenet_stem=True, s2d_stem=True)
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (N_WORKERS, size, size, 3),
                          dtype=np.uint8)
    labels = (np.arange(N_WORKERS) % 1000).astype(np.int32)
    bi, bl = shard_batch(mesh, (images, labels))

    record = {"n_workers": N_WORKERS,
              "provenance": ("32-device virtual CPU mesh "
                             "(xla_force_host_platform_device_count) on a "
                             "single host — collective structure, not pod "
                             "timing"),
              "model": "resnet50_imagenet_stem",
              "num_classes": 1000, "image_size": size,
              "global_batch": N_WORKERS, "cells": {}}
    for comp in ("bf16", "int8"):
        state = create_train_state(model, jax.random.PRNGKey(0),
                                   server_sgd(0.1),
                                   input_shape=(1, size, size, 3))
        step = make_sync_dp_step(mesh, compression=comp, augment=False)
        t0 = time.time()
        state, m = step(state, bi, bl, jax.random.PRNGKey(1))
        jax.block_until_ready(state)
        loss0 = float(m["loss"])
        state, m2 = step(state, bi, bl, jax.random.PRNGKey(2))
        jax.block_until_ready(state)
        record["cells"][comp] = {
            "compile_plus_2_steps_seconds": round(time.time() - t0, 1),
            "loss_step1": round(loss0, 4),
            "loss_step2": round(float(m2["loss"]), 4),
            "per_worker_loss_count": int(
                np.asarray(m2["worker_loss"]).shape[0]),
        }
        print(f"{comp}: {record['cells'][comp]}", flush=True)
        assert record["cells"][comp]["per_worker_loss_count"] == N_WORKERS

    out = os.path.join(REPO, "experiments", "results",
                       "pod_scale_dryrun.json")
    with open(out, "w") as f:
        json.dump(record, f, indent=2)
        f.write("\n")
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
