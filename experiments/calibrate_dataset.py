"""Calibrate compositional_cifar100 difficulty to the reference curve.

Target (round-2 VERDICT item 1), from /root/reference/baseline/results/
baseline_summary.json and README.md:446:
  - epoch-1 test acc ~ 12%
  - 65% crossed only mid-training (>5 epochs, realistically after the
    first MultiStepLR drop at epoch 10)
  - plateau ~ 70%

Runs the exact baseline recipe (batch 128, SGD m=0.9 wd=5e-4,
MultiStepLR([10,15], 0.1), 20 epochs, device epoch loop) over a grid of
generator knobs; all configs share one compiled executable (identical
shapes), so each extra config costs dataset-gen + ~35 s of training.

Run:  python experiments/calibrate_dataset.py [--configs i,j,...]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                 os.path.join(REPO, ".jax_cache")))

GRID = [
    # name, generator kwargs. Round-1 finding: the original defaults
    # (motif_amp .22, template .035, bg .22, lbl .22) give ep1 44.9%,
    # cross65 @ 4, final 76.7% — too easy on every axis.
    ("base", dict()),
    ("hard_a", dict(template_amp=0.015, motif_amp=0.15, bg_noise=0.28,
                    label_noise=0.28)),
    ("hard_b", dict(template_amp=0.0, motif_amp=0.16, bg_noise=0.28,
                    label_noise=0.28)),
    ("hard_c", dict(template_amp=0.02, motif_amp=0.12, bg_noise=0.30,
                    label_noise=0.25, n_distractors=3)),
    ("hard_d", dict(template_amp=0.015, motif_amp=0.18, bg_noise=0.35,
                    label_noise=0.28, amp_jitter=0.7)),
    # Round 2: hard_* overshot (ep1 2.5-4.8%, never cross 65, final 41-51);
    # interpolate between base and hard_a.
    ("mid_a", dict(template_amp=0.022, motif_amp=0.18, bg_noise=0.25,
                   label_noise=0.25)),
    ("mid_b", dict(template_amp=0.020, motif_amp=0.19, bg_noise=0.25,
                   label_noise=0.22)),
    ("mid_c", dict(template_amp=0.025, motif_amp=0.17, bg_noise=0.26,
                   label_noise=0.25)),
    # Round 3: mid_b (ep1 8.8, cross65 @11, final 68.0) is nearly the
    # reference curve (ep1 11.95, ~65 @ 20); nudge ep1 up a touch.
    ("mid_d", dict(template_amp=0.024, motif_amp=0.20, bg_noise=0.25,
                   label_noise=0.22)),
]


def run_config(name: str, kw: dict, epochs: int = 20) -> dict:
    from distributed_parameter_server_for_ml_training_tpu.data import (
        compositional_cifar100)
    from distributed_parameter_server_for_ml_training_tpu.train.baseline import (
        BaselineConfig, BaselineTrainer)

    t0 = time.time()
    ds = compositional_cifar100(**kw)
    gen_s = time.time() - t0
    trainer = BaselineTrainer(ds, BaselineConfig(num_epochs=epochs,
                                                 device_loop=True))
    t0 = time.time()
    m = trainer.train()
    train_s = time.time() - t0
    rec = {"name": name, "kwargs": kw, "gen_seconds": round(gen_s, 1),
           "train_seconds": round(train_s, 1),
           "test_accuracies_pct": [round(a, 2) for a in m.test_accuracies],
           "train_accuracies_pct": [round(a, 2) for a in m.train_accuracies]}
    te = m.test_accuracies
    cross = next((i + 1 for i, a in enumerate(te) if a >= 65.0), None)
    rec["epoch1_test"] = round(te[0], 2)
    rec["cross65_epoch"] = cross
    rec["final_test"] = round(te[-1], 2)
    print(f"== {name}: ep1 {te[0]:.1f}%  cross65 @ {cross}  "
          f"final {te[-1]:.1f}%  ({train_s:.0f}s)", flush=True)
    return rec


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", default=None,
                    help="comma-separated indices into GRID (default: all)")
    ap.add_argument("--epochs", type=int, default=20)
    args = ap.parse_args()
    sel = (range(len(GRID)) if args.configs is None
           else [int(i) for i in args.configs.split(",")])
    out = []
    for i in sel:
        name, kw = GRID[i]
        out.append(run_config(name, kw, epochs=args.epochs))
        path = os.path.join(REPO, "experiments", "results",
                            "calibration_sweep.json")
        with open(path, "w") as f:
            json.dump(out, f, indent=2)
    for r in out:
        print(f"{r['name']:>16}: ep1 {r['epoch1_test']:5.1f}  "
              f"cross65 {str(r['cross65_epoch']):>4}  "
              f"final {r['final_test']:5.1f}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
