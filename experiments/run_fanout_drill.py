"""Recorded fan-out tree drill (ISSUE 17 acceptance evidence).

Four cells under ``experiments/results/fanout/``, every check
exit-code-verified (the recorded-demo format of PRs 4-16). Environment
note recorded in the artifact: this container exposes ONE cpu, so
process-parallel scale-out is not measurable here — as in the PR 8/9
recorded methodology, the QPS lever this drill pins is the PER-REQUEST
serve-cost collapse of the tree's read path (cached-bytes edge replicas
+ coalesced delta polls) against the flat-star reference path (every
consumer full-fetching the primary directly).

**Cell A — flat-star baseline.** One ``cli serve`` primary takes
``cli loadgen`` FULL fetches directly (the reference consumer path:
every fetch ships the whole model from the one hub). Records
``star_qps``.

**Cell B — depth-3 tree under a distributed poll storm.** The same
primary grows a depth-3 tree: 2 interior ``cli replica`` processes
(tier 1) + 4 edge replicas started with ``--parent <interior>``
(tier 2, two per interior). The storm is DISTRIBUTED generation —
``cli loadgen --scale-out 2 --fetch-mode delta`` against the four
edges — and the artifact keeps the merged LOADGEN_JSON (union-percentile
merge, ``scale_out``/``per_process_qps`` stamped). Checks: tree
consumer QPS >= 6x the cell-A star QPS; the primary's fetch-handler
count moved only by its DIRECT children's rate-bounded polls (2 pollers
at 20 Hz — consumer traffic never reaches it); under a second, FOCUSED
storm (all generator threads on one edge) that edge's windowed coalesce
ratio (delta ``dps_replica_coalesced_total`` / delta upstream refresh
rounds) exceeds 2x — each upstream round answers >2 parked identical
polls from the one pre-encoded payload; ``cli status`` renders the
parent->child tree rows and ``cli top`` (over a live ``cli observe``
collector) renders the same tree fleet-wide, both exit 0.

**Cell C — mid-drill interior SIGKILL.** A fresh consumer loadgen runs
against all four edges while interior A is SIGKILLed mid-window. Its
two children must re-parent to interior B (the only remaining tier-1
node — the "prefer tier-1, fall back to primary" policy's first arm)
within the drill window, the consumer loadgen must record ZERO fetch
errors (edges serve from their cached bytes throughout the move), the
primary's ``slo_burn_fast`` rule must not fire, and the announce-dedup
contract must hold live: each replica address appears exactly once in
``GET /cluster``, dead A's ``dps_replica_children`` series disappears
from the primary's /metrics, and B's child count reads 4.

**Cell D — merged percentiles vs single-process ground truth.** The
cell-B merged report's p50/p95/p99 are recomputed from its union
``latency_hist`` by an INDEPENDENT CDF walk (plain loops, no shared
helper) — the merged numbers must equal the walk exactly, and the
histogram's sample count must equal the summed per-process fetches:
union percentiles, never averaged ones.

Artifacts: ``fanout_drill.json`` (summary + PASS/FAIL checks), the
star/storm/kill LOADGEN_JSONs, cluster + /metrics captures around the
kill, ``status_tree.txt`` / ``top_tree.txt`` renders, and all process
logs.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT_DIR = os.path.join(REPO, "experiments", "results", "fanout")
PKG = "distributed_parameter_server_for_ml_training_tpu"
sys.path.insert(0, REPO)

MODEL = "vit_tiny"
INTERIORS = 2
EDGES_PER_INTERIOR = 2
POLL_INTERVAL = 0.05
HEADLINE_MIN_RATIO = 6.0
COALESCE_MIN_RATIO = 2.0


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env(**extra) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _http(url: str, timeout: float = 5.0) -> str | None:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.read().decode()
    except Exception:
        return None


def _cluster(port: int) -> dict | None:
    raw = _http(f"http://127.0.0.1:{port}/cluster")
    if raw is None:
        return None
    try:
        return json.loads(raw)
    except ValueError:
        return None


def _metric_value(metrics_text: str | None, name: str,
                  labels: str = "") -> float | None:
    import re
    if not metrics_text:
        return None
    pat = re.compile(rf"^{re.escape(name + labels)} ([0-9.e+-]+)$", re.M)
    m = pat.search(metrics_text)
    return float(m.group(1)) if m else None


def _spawn(argv: list[str], log_path: str, **env_extra) -> tuple:
    log = open(log_path, "w")
    proc = subprocess.Popen(argv, stdout=log, stderr=subprocess.STDOUT,
                            env=_env(**env_extra), cwd=REPO)
    return proc, log


def _stop(proc, log, grace: float = 15.0) -> int | None:
    if proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=grace)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=grace)
    log.close()
    return proc.returncode


def _serve_argv(*, port: int, metrics_port: int) -> list[str]:
    return [sys.executable, "-m", f"{PKG}.cli", "serve",
            "--mode", "async", "--workers", "1",
            "--port", str(port), "--model", MODEL, "--num-classes", "100",
            "--image-size", "32", "--platform", "cpu",
            "--shard-count", "1",
            "--shard-peers", f"localhost:{port}",
            "--metrics-port", str(metrics_port)]


def _replica_argv(*, primary: int, port: int, metrics_port: int,
                  parent: str | None = None) -> list[str]:
    argv = [sys.executable, "-m", f"{PKG}.cli", "replica",
            "--primary", f"localhost:{primary}", "--port", str(port),
            "--poll-interval", str(POLL_INTERVAL),
            "--reparent-after", "3", "--reparent-cooldown", "0.5",
            "--metrics-port", str(metrics_port)]
    if parent is not None:
        argv += ["--parent", parent]
    return argv


def _wait_up(metrics_port: int, proc, what: str,
             timeout: float = 180.0) -> None:
    deadline = time.time() + timeout
    while _cluster(metrics_port) is None:
        if time.time() > deadline or proc.poll() is not None:
            raise RuntimeError(f"{what} never came up "
                               f"(rc={proc.poll()})")
        time.sleep(0.25)


def _grpc_up(addr: str, timeout: float = 60.0) -> None:
    from distributed_parameter_server_for_ml_training_tpu.comms.loadgen \
        import run_loadgen
    deadline = time.time() + timeout
    while time.time() < deadline:
        r = run_loadgen([addr], duration_s=0.2, concurrency=1,
                        rpc_timeout=2.0)
        if r["fetches_ok"] > 0:
            return
        time.sleep(0.5)
    raise RuntimeError(f"no PS answering at {addr}")


def _loadgen(targets: list[str], mode: str, name: str, duration: float,
             concurrency: int = 4, scale_out: int = 0,
             background: bool = False):
    """Run ``cli loadgen`` as a subprocess; foreground returns
    ``(rc, LOADGEN_JSON)``, background returns the live Popen."""
    argv = [sys.executable, "-m", f"{PKG}.cli", "loadgen",
            "--targets", ",".join(targets),
            "--duration", str(duration),
            "--concurrency", str(concurrency), "--fetch-mode", mode]
    if scale_out:
        argv += ["--scale-out", str(scale_out)]
    if background:
        return subprocess.Popen(argv, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True,
                                env=_env(), cwd=REPO)
    p = subprocess.run(argv, capture_output=True, text=True, env=_env(),
                       cwd=REPO, timeout=max(300, duration * 20))
    result = _parse_loadgen(p.stdout)
    with open(os.path.join(OUT_DIR, f"loadgen_{name}.json"), "w") as f:
        json.dump({"rc": p.returncode, "result": result}, f, indent=2)
    return p.returncode, result


def _parse_loadgen(text: str) -> dict | None:
    from distributed_parameter_server_for_ml_training_tpu.comms.loadgen \
        import parse_loadgen_json
    return parse_loadgen_json(text)


def _edge_counters(metrics_port: int) -> dict:
    text = _http(f"http://127.0.0.1:{metrics_port}/metrics")
    return {
        "coalesced": _metric_value(text,
                                   "dps_replica_coalesced_total") or 0.0,
        "rounds": _metric_value(text, "dps_replica_polls_total") or 0.0,
        "ratio_gauge": _metric_value(text, "dps_coalesce_ratio"),
        "tier": _metric_value(text, "dps_replica_tier"),
        "reparents": _metric_value(text,
                                   "dps_replica_reparents_total") or 0.0,
    }


def _run_cli(argv: list[str], timeout: float = 60.0):
    try:
        p = subprocess.run([sys.executable, "-m", f"{PKG}.cli"] + argv,
                           capture_output=True, text=True, env=_env(),
                           cwd=REPO, timeout=timeout)
        return p.returncode, p.stdout + p.stderr
    except subprocess.TimeoutExpired:
        return None, "cli timed out"


def _cdf_walk_quantiles(hist: dict) -> dict:
    """Independent single-process ground truth: percentiles recomputed
    from the union histogram by a from-scratch CDF walk, sharing no code
    with the pinned-scheme quantile helper. Same CONTRACT: the quantile
    is the upper edge of the bucket containing the p-th observation
    (conservative, never understated), None when it lands in the
    trailing overflow slot."""
    les, counts = list(hist["le"]), list(hist["counts"])
    total = sum(counts)
    out = {"samples": int(total)}
    for pct, key in ((50, "p50"), (95, "p95"), (99, "p99")):
        if total == 0:
            out[key] = None
            continue
        rank = total * pct / 100.0
        cum = 0.0
        val = None
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank and c > 0:
                if i < len(les):
                    val = float(les[i])
                break
        out[key] = None if val is None else round(val * 1e3, 3)
    return out


class _Tree:
    """The depth-3 process tree: primary + 2 interiors + 4 edges, with
    every port and log handle in one place."""

    def __init__(self):
        self.procs: list[tuple] = []
        self.primary_port = _free_port()
        self.primary_metrics = _free_port()
        self.interior_ports = [_free_port() for _ in range(INTERIORS)]
        self.interior_metrics = [_free_port() for _ in range(INTERIORS)]
        n_edges = INTERIORS * EDGES_PER_INTERIOR
        self.edge_ports = [_free_port() for _ in range(n_edges)]
        self.edge_metrics = [_free_port() for _ in range(n_edges)]
        self.interior_procs: list = []

    @property
    def interior_addrs(self) -> list[str]:
        return [f"localhost:{p}" for p in self.interior_ports]

    @property
    def edge_addrs(self) -> list[str]:
        return [f"localhost:{p}" for p in self.edge_ports]

    def start_primary(self):
        proc, log = _spawn(
            _serve_argv(port=self.primary_port,
                        metrics_port=self.primary_metrics),
            os.path.join(OUT_DIR, "primary.log"))
        self.procs.append((proc, log))
        _wait_up(self.primary_metrics, proc, "fan-out primary")

    def start_replicas(self):
        for i in range(INTERIORS):
            proc, log = _spawn(
                _replica_argv(primary=self.primary_port,
                              port=self.interior_ports[i],
                              metrics_port=self.interior_metrics[i]),
                os.path.join(OUT_DIR, f"interior{i}.log"))
            self.procs.append((proc, log))
            self.interior_procs.append(proc)
        # Interiors must be serving before their children's first polls:
        # an edge that fails --reparent-after refreshes against a
        # still-importing interior would legitimately fall back to the
        # primary and flatten the tree under test.
        for addr in self.interior_addrs:
            _grpc_up(addr)
        for j, eport in enumerate(self.edge_ports):
            parent = self.interior_addrs[j // EDGES_PER_INTERIOR]
            proc, log = _spawn(
                _replica_argv(primary=self.primary_port, port=eport,
                              metrics_port=self.edge_metrics[j],
                              parent=parent),
                os.path.join(OUT_DIR, f"edge{j}.log"))
            self.procs.append((proc, log))
        for addr in self.edge_addrs:
            _grpc_up(addr)

    def sharding(self) -> dict:
        view = _cluster(self.primary_metrics) or {}
        return view.get("sharding") or {}

    def wait_tree_announced(self, timeout: float = 60.0) -> dict:
        """Block until all 6 replica rows reached the primary with the
        expected parent edges, then give topology two extra beats to
        flow down to the edges (it rides their next refresh replies)."""
        want = INTERIORS * (1 + EDGES_PER_INTERIOR)
        deadline = time.time() + timeout
        while time.time() < deadline:
            sh = self.sharding()
            rows = sh.get("replicas") or []
            by_parent: dict = {}
            for r in rows:
                by_parent.setdefault(r.get("parent"), []).append(r)
            edges_ok = all(
                len(by_parent.get(a, [])) == EDGES_PER_INTERIOR
                for a in self.interior_addrs)
            if len(rows) == want and edges_ok \
                    and set((sh.get("tiers") or {})) >= {"1", "2"}:
                time.sleep(20 * POLL_INTERVAL)
                return self.sharding()
            time.sleep(0.2)
        raise RuntimeError(f"tree never fully announced: "
                           f"{json.dumps(self.sharding(), indent=2)}")

    def stop_all(self):
        for proc, log in self.procs:
            _stop(proc, log)


def _primary_fetch_calls(metrics_port: int) -> float:
    return _metric_value(
        _http(f"http://127.0.0.1:{metrics_port}/metrics"),
        "dps_rpc_handler_calls_total", '{rpc="FetchParameters"}') or 0.0


def run_drill(star_secs: float, storm_secs: float,
              spread_secs: float, kill_secs: float) -> dict:
    checks: dict = {}
    record: dict = {
        "model": MODEL,
        "tree": {"interiors": INTERIORS,
                 "edges_per_interior": EDGES_PER_INTERIOR,
                 "poll_interval_s": POLL_INTERVAL},
        "environment": {"cpus": os.cpu_count()},
        "note": "single-cpu container: the >=6x lever is per-request "
                "serve cost (tree-cached delta polls vs flat-star full "
                "fetches), the PR 8/9 recorded methodology",
    }
    tree = _Tree()
    observe = None
    try:
        # ---- Cell A: flat star ----------------------------------------
        tree.start_primary()
        star_rc, star = _loadgen(
            [f"localhost:{tree.primary_port}"], "full", "star_full",
            star_secs, concurrency=4)
        star_qps = (star or {}).get("qps", 0.0)
        record["cell_a"] = {"star_qps": star_qps,
                            "duration_s": star_secs}
        print(f"cell A: flat star {star_qps:.1f} full-fetch qps",
              flush=True)

        # ---- Cell B: depth-3 tree + distributed storm -----------------
        tree.start_replicas()
        announced = tree.wait_tree_announced()
        with open(os.path.join(OUT_DIR, "cluster_tree.json"), "w") as f:
            json.dump(announced, f, indent=2)
        fleet_port = _free_port()
        observe, ob_log = _spawn(
            [sys.executable, "-m", f"{PKG}.cli", "observe",
             "--targets", f"localhost:{tree.primary_metrics}",
             "--port", str(fleet_port), "--interval", "0.5"],
            os.path.join(OUT_DIR, "observe.log"))
        tree.procs.append((observe, ob_log))
        fleet_url = f"http://127.0.0.1:{fleet_port}"
        deadline = time.time() + 30
        while time.time() < deadline \
                and _http(f"{fleet_url}/fleet", timeout=1.0) is None:
            time.sleep(0.25)
        time.sleep(1.5)   # at least one full scrape tick behind the view

        before_edges = [_edge_counters(mp) for mp in tree.edge_metrics]
        before_primary = _primary_fetch_calls(tree.primary_metrics)
        # Offered concurrency matches the star cell (2 threads x 2
        # generator processes = 4) so the headline ratio compares
        # per-request serve cost, not thread counts; the longer window
        # amortizes generator-process startup on the shared CPU.
        t_storm = time.time()
        storm_rc, storm = _loadgen(tree.edge_addrs, "delta",
                                   "tree_storm", spread_secs,
                                   concurrency=2, scale_out=2)
        t_storm = time.time() - t_storm
        after_primary = _primary_fetch_calls(tree.primary_metrics)
        after_edges = [_edge_counters(mp) for mp in tree.edge_metrics]

        # Focused poll storm: all the generator's threads hammer ONE
        # edge, so identical delta polls pile onto each upstream refresh
        # window — the coalescing gate is measured here, where poll
        # concurrency per node is storm-shaped rather than spread thin
        # over four targets by the QPS cell.
        hot_metrics = tree.edge_metrics[0]
        hot_before = _edge_counters(hot_metrics)
        hot_rc, hot = _loadgen([tree.edge_addrs[0]], "delta",
                               "coalesce_storm", storm_secs,
                               concurrency=8)
        hot_after = _edge_counters(hot_metrics)
        hot_rounds = hot_after["rounds"] - hot_before["rounds"]
        coalesce_ratio = ((hot_after["coalesced"]
                           - hot_before["coalesced"])
                          / max(1.0, hot_rounds))

        status_rc, status_out = _run_cli(
            ["status", "--metrics-port", str(tree.primary_metrics)])
        with open(os.path.join(OUT_DIR, "status_tree.txt"), "w") as f:
            f.write(f"# cli status exit code: {status_rc}\n\n{status_out}")
        top_rc, top_out = _run_cli(["top", "--url", fleet_url])
        with open(os.path.join(OUT_DIR, "top_tree.txt"), "w") as f:
            f.write(f"# cli top exit code: {top_rc}\n\n{top_out}")

        tree_qps = (storm or {}).get("qps", 0.0)
        ratios = []
        for b, a in zip(before_edges, after_edges):
            d_rounds = a["rounds"] - b["rounds"]
            ratios.append((a["coalesced"] - b["coalesced"])
                          / max(1.0, d_rounds))
        # Direct children only: the interiors poll at 1/POLL_INTERVAL Hz
        # each; consumer storm traffic must not reach the primary.
        poll_budget = INTERIORS * t_storm / POLL_INTERVAL * 1.5 + 50
        primary_delta = after_primary - before_primary
        record["cell_b"] = {
            "tree_qps": tree_qps,
            "headline_ratio": round(tree_qps / max(1e-9, star_qps), 1),
            "scale_out": (storm or {}).get("scale_out"),
            "generators_failed": (storm or {}).get("generators_failed"),
            "per_process_qps": (storm or {}).get("per_process_qps"),
            "spread_storm_coalesce_per_edge":
                [round(r, 2) for r in ratios],
            "coalesce_storm_qps": (hot or {}).get("qps"),
            "coalesce_storm_rounds": hot_rounds,
            "coalesce_ratio": round(coalesce_ratio, 2),
            "coalesce_ratio_gauge": hot_after["ratio_gauge"],
            "edge_tiers": [a["tier"] for a in after_edges],
            "primary_fetches_during_storm": primary_delta,
            "primary_poll_budget": int(poll_budget),
            "storm_window_s": round(t_storm, 1),
            "status_rc": status_rc, "top_rc": top_rc,
        }
        checks.update({
            "B_loadgen_exit_codes_zero":
                star_rc == 0 and storm_rc == 0 and hot_rc == 0,
            "B_tree_6x_flat_star":
                tree_qps >= HEADLINE_MIN_RATIO * star_qps > 0,
            "B_distributed_generation_merged":
                (storm or {}).get("scale_out") == 2
                and (storm or {}).get("generators_failed") == 0
                and len((storm or {}).get("per_process_qps") or []) == 2,
            "B_coalesce_ratio_over_2x":
                coalesce_ratio > COALESCE_MIN_RATIO,
            "B_primary_sees_only_child_polls":
                0 < primary_delta <= poll_budget,
            "B_edges_announce_tier2":
                all(a["tier"] == 2.0 for a in after_edges),
            "B_status_renders_tree":
                status_rc == 0 and "[tier 1]" in status_out
                and "[tier 2]" in status_out
                and "tiers:" in status_out,
            "B_top_renders_tree_fleetwide":
                top_rc == 0 and "[tier 2]" in top_out,
        })
        print(f"cell B: tree {tree_qps:.1f} delta qps "
              f"(x{record['cell_b']['headline_ratio']} vs star), "
              f"coalesce {coalesce_ratio:.1f} poll(s)/round under the "
              f"focused storm, primary saw {primary_delta:.0f} polls",
              flush=True)

        # ---- Cell C: interior SIGKILL mid-drill -----------------------
        victim = tree.interior_procs[0]
        victim_addr = tree.interior_addrs[0]
        survivor_addr = tree.interior_addrs[1]
        orphans = tree.edge_addrs[:EDGES_PER_INTERIOR]
        consumer = _loadgen(tree.edge_addrs, "delta", "kill_drill",
                            kill_secs, concurrency=4, background=True)
        time.sleep(kill_secs / 3.0)
        victim.send_signal(signal.SIGKILL)
        t_kill = time.time()
        # Watch the primary's view live: both orphans must re-announce
        # under the surviving interior.
        moved_at = None
        while time.time() - t_kill < max(30.0, kill_secs):
            rows = tree.sharding().get("replicas") or []
            parents = {r["address"]: r.get("parent") for r in rows}
            if all(parents.get(o) == survivor_addr for o in orphans):
                moved_at = time.time() - t_kill
                break
            time.sleep(0.1)
        out, _ = consumer.communicate(timeout=kill_secs * 4 + 60)
        kill_report = _parse_loadgen(out or "")
        with open(os.path.join(OUT_DIR, "loadgen_kill_drill.json"),
                  "w") as f:
            json.dump({"rc": consumer.returncode,
                       "result": kill_report}, f, indent=2)

        sh_after = tree.sharding()
        with open(os.path.join(OUT_DIR, "cluster_after_kill.json"),
                  "w") as f:
            json.dump(sh_after, f, indent=2)
        rows = sh_after.get("replicas") or []
        addr_counts: dict = {}
        for r in rows:
            addr_counts[r["address"]] = addr_counts.get(r["address"],
                                                        0) + 1
        pm_text = _http(f"http://127.0.0.1:{tree.primary_metrics}"
                        "/metrics")
        with open(os.path.join(OUT_DIR, "primary_metrics_after_kill.txt"),
                  "w") as f:
            f.write(pm_text or "")
        dead_children = _metric_value(
            pm_text, "dps_replica_children", f'{{node="{victim_addr}"}}')
        b_children = _metric_value(
            pm_text, "dps_replica_children",
            f'{{node="{survivor_addr}"}}')
        slo = (_cluster(tree.primary_metrics) or {}).get("slo") or {}
        fast_breaches = [b for b in slo.get("breaches", [])
                         if b.get("rule") == "slo_burn_fast"]
        reparent_counts = [
            _edge_counters(mp)["reparents"]
            for mp in tree.edge_metrics[:EDGES_PER_INTERIOR]]
        record["cell_c"] = {
            "victim": victim_addr,
            "survivor": survivor_addr,
            "reparent_latency_s": (None if moved_at is None
                                   else round(moved_at, 2)),
            "consumer_qps": (kill_report or {}).get("qps"),
            "consumer_fetch_errors":
                (kill_report or {}).get("fetches_err"),
            "orphan_reparent_counters": reparent_counts,
            "dead_parent_children_series": dead_children,
            "survivor_children": b_children,
            "slo_burn_fast_breaches": fast_breaches,
        }
        checks.update({
            "C_children_reparent_to_surviving_interior":
                moved_at is not None
                and all(c >= 1 for c in reparent_counts),
            "C_zero_consumer_fetch_errors":
                consumer.returncode == 0 and kill_report is not None
                and kill_report.get("fetches_err") == 0
                and kill_report.get("fetches_ok", 0) > 0,
            "C_slo_burn_fast_not_firing": not fast_breaches,
            "C_announce_dedup_one_row_per_replica":
                bool(addr_counts)
                and all(n == 1 for n in addr_counts.values()),
            "C_dead_parents_children_series_removed":
                dead_children is None and b_children == float(
                    INTERIORS * EDGES_PER_INTERIOR),
        })
        print(f"cell C: re-parented in "
              f"{record['cell_c']['reparent_latency_s']}s, consumer "
              f"errors {record['cell_c']['consumer_fetch_errors']}, "
              f"slo_burn_fast breaches {len(fast_breaches)}", flush=True)

        # ---- Cell D: union percentiles vs independent ground truth ----
        hist = (storm or {}).get("latency_hist") or {}
        walk = _cdf_walk_quantiles(hist) if hist else {}
        merged_ms = (storm or {}).get("latency_ms") or {}
        record["cell_d"] = {
            "merged_latency_ms": merged_ms,
            "ground_truth_cdf_walk": walk,
        }
        checks.update({
            "D_merged_percentiles_equal_union_ground_truth":
                bool(walk) and all(
                    walk.get(k) == merged_ms.get(k)
                    for k in ("samples", "p50", "p95", "p99")),
            "D_histogram_counts_cover_all_fetches":
                bool(hist) and int(hist.get("count", 0))
                == (storm or {}).get("fetches_ok"),
        })
        print(f"cell D: union p99 {merged_ms.get('p99')}ms == "
              f"cdf-walk {walk.get('p99')}ms over "
              f"{walk.get('samples')} samples", flush=True)
    finally:
        tree.stop_all()
    record["checks"] = checks
    record["all_pass"] = all(checks.values())
    return record


def main(argv=None) -> int:
    import argparse
    global OUT_DIR
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out-dir", default=OUT_DIR,
                    help="artifact directory (default: the recorded "
                         "experiments/results/fanout)")
    ap.add_argument("--quick", action="store_true",
                    help="short windows for the slow-test wrapper")
    args = ap.parse_args(argv)
    OUT_DIR = args.out_dir
    os.makedirs(OUT_DIR, exist_ok=True)
    t0 = time.time()
    if args.quick:
        record = run_drill(star_secs=2.0, storm_secs=3.0,
                           spread_secs=8.0, kill_secs=6.0)
    else:
        record = run_drill(star_secs=5.0, storm_secs=6.0,
                           spread_secs=10.0, kill_secs=9.0)
    record["quick"] = bool(args.quick)
    record["elapsed_seconds"] = round(time.time() - t0, 1)
    with open(os.path.join(OUT_DIR, "fanout_drill.json"), "w") as f:
        json.dump(record, f, indent=2)
    checks = record["checks"]
    n_pass = sum(bool(v) for v in checks.values())
    print(f"fan-out drill: {n_pass}/{len(checks)} checks PASS "
          f"({record['elapsed_seconds']}s)")
    for name, ok in checks.items():
        print(f"  [{'PASS' if ok else 'FAIL'}] {name}")
    return 0 if record["all_pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
