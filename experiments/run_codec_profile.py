"""Phase-attribution artifact for the device codec (ISSUE 14).

The perf observatory (PR 12) exists to say WHERE step wall goes; this
drill is its first hot-path consumer. The same 1-worker int8 training
epoch runs twice — NumPy codec vs device codec — under the flight
recorder, with the device cell also captured by jax.profiler. Per cell
the critical-path report attributes every ``worker.step`` into
compute / fetch_wait / push_wait / server_apply / codec phases
(coverage residual REPORTED, never hidden), and the device cell's
jax.profiler capture is joined with its trace dumps through
``cli perf profile`` — the same merged artifact `bench.py --profile-dir`
rounds produce, committed here as the recorded attribution evidence.

Wire honesty: both cells diff the per-worker precodec/wire byte
counters and must move IDENTICAL wire bytes (the device codec is
bit-identical, so the only thing allowed to change is where the encode
time is attributed). The device cell must also observe the new
``dps_worker_codec_seconds`` histogram.

The platform is recorded per cell — on CPU the "device" codec is the
same XLA backend the compute uses, so this artifact demonstrates the
ATTRIBUTION machinery and the wire invariants; the throughput claim
lives in the BENCH ledger where the chip runs the same code.

Artifacts: experiments/results/codec/codec_profile.json
           experiments/results/codec/codec_perf_profile.json (merged)
Run:       python experiments/run_codec_profile.py
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

OUT = os.path.join(REPO, "experiments", "results", "codec")
CLI = [sys.executable, "-m",
       "distributed_parameter_server_for_ml_training_tpu.cli"]


def run_cell(name: str, device_codec: bool, model, dataset,
             profile: bool) -> dict:
    import jax

    from distributed_parameter_server_for_ml_training_tpu import (
        telemetry as T)
    from distributed_parameter_server_for_ml_training_tpu.analysis.traces \
        import critical_path_report, find_trace_dumps, load_trace_dumps
    from distributed_parameter_server_for_ml_training_tpu.ps import (
        ParameterStore, StoreConfig, WorkerConfig, run_workers)
    from distributed_parameter_server_for_ml_training_tpu.telemetry import (
        get_registry)
    from distributed_parameter_server_for_ml_training_tpu.telemetry. \
        profiler import capture
    from distributed_parameter_server_for_ml_training_tpu.utils import (
        flatten_params)
    import contextlib
    import numpy as np

    prof_dir = os.path.join(OUT, f"{name}_profile")
    dump_dir = os.path.join(OUT, f"{name}_trace_dumps")
    for d in (prof_dir, dump_dir):  # stale captures would double-count
        shutil.rmtree(d, ignore_errors=True)
    os.makedirs(dump_dir, exist_ok=True)

    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 32, 32, 3), np.float32),
                           train=False)
    store = ParameterStore(
        flatten_params(variables["params"]),
        StoreConfig(mode="sync", total_workers=1, learning_rate=0.05,
                    push_codec="int8"))
    reg = get_registry()
    codec_h = reg.histogram("dps_worker_codec_seconds", worker="0")
    codec_before = (codec_h.count, codec_h.sum)
    bytes_before = {
        stage: reg.counter("dps_worker_push_bytes_total", stage=stage,
                           worker="0").value
        for stage in ("precodec", "wire")}

    rec = T.enable_tracing(buffer=8192, role=f"codecprof-{name}")
    rec.clear()
    try:
        ctx = capture(prof_dir) if profile else contextlib.nullcontext()
        with ctx:
            results = run_workers(
                store, model, dataset, n_workers=1,
                config=WorkerConfig(batch_size=32, num_epochs=1,
                                    augment=False, eval_each_epoch=False,
                                    device_codec=device_codec))
        for r in results:
            if r.error is not None:
                raise RuntimeError(f"cell {name}: worker failed: {r.error}")
        rec.dump_to_dir(dump_dir, f"codecprof-{name}")
    finally:
        T.disable_tracing()

    report = critical_path_report(
        load_trace_dumps(find_trace_dumps(dump_dir)))
    return {
        "cell": name,
        "platform": jax.devices()[0].platform,
        "device_codec": device_codec,
        "steps": report["steps"],
        "step_wall_total_s": round(report["step_wall_total_s"], 4),
        "phase_totals_s": {k: round(v, 4) for k, v in
                           report["phase_totals_s"].items()},
        "by_dominant_phase": report["by_dominant_phase"],
        "codec_seconds_observed": round(codec_h.sum - codec_before[1], 4),
        "codec_observations": codec_h.count - codec_before[0],
        "push_bytes": {
            stage: reg.counter("dps_worker_push_bytes_total", stage=stage,
                               worker="0").value - bytes_before[stage]
            for stage in ("precodec", "wire")},
        "profile_dir": prof_dir if profile else None,
        "dump_dir": dump_dir,
    }


def main() -> int:
    from distributed_parameter_server_for_ml_training_tpu.data import (
        synthetic_cifar100)
    from distributed_parameter_server_for_ml_training_tpu.models import (
        ResNet)

    dataset = synthetic_cifar100(n_train=640, n_test=128, num_classes=10,
                                 seed=1)
    model = ResNet(stage_sizes=(1, 1), num_filters=8, num_classes=10)

    os.makedirs(OUT, exist_ok=True)
    cells = [run_cell("numpy_codec", False, model, dataset, profile=False),
             run_cell("device_codec", True, model, dataset, profile=True)]
    dev = cells[1]

    merged_out = os.path.join(OUT, "codec_perf_profile.json")
    p = subprocess.run(
        CLI + ["perf", "profile", "--profile-dir", dev["profile_dir"],
               "--trace-dump-dir", dev["dump_dir"], "--out", merged_out],
        capture_output=True, text=True, cwd=REPO)
    merged = {}
    if os.path.exists(merged_out):
        with open(merged_out) as f:
            merged = json.load(f)
    # The raw jax.profiler capture is tens of MB for a full epoch; the
    # merged artifact above is the committed evidence. Prune via the
    # uniform policy (telemetry/profiler.prune_capture, ISSUE 20
    # satellite f): only after a SUCCESSFUL attribution — a basis=none
    # or parse-error join keeps the raw traces debuggable. The span
    # dumps stay — they're small.
    if merged and (merged.get("profile") or {}).get("basis") \
            not in (None, "none") and not merged.get("parse_errors"):
        from distributed_parameter_server_for_ml_training_tpu \
            .telemetry.profiler import prune_capture
        prune_capture(dev["profile_dir"])
        dev["profile_dir"] = "pruned after join (see merged_profile)"

    checks = []

    def check(name, ok, detail):
        checks.append({"check": name, "pass": bool(ok), "detail": detail})
        print(f"[{'PASS' if ok else 'FAIL'}] {name}: {detail}", flush=True)

    check("both_cells_trained_and_attributed",
          all(c["steps"] > 0 and c["step_wall_total_s"] > 0
              for c in cells),
          f"{[c['steps'] for c in cells]} steps attributed")
    check("codec_phase_attributed_in_both_cells",
          all(c["phase_totals_s"].get("codec", 0) > 0 for c in cells),
          f"codec s: numpy {cells[0]['phase_totals_s'].get('codec')}, "
          f"device {cells[1]['phase_totals_s'].get('codec')}")
    check("identical_wire_bytes_across_codecs",
          cells[0]["push_bytes"] == dev["push_bytes"]
          and dev["push_bytes"]["wire"] > 0,
          f"numpy {cells[0]['push_bytes']} == device {dev['push_bytes']}")
    check("device_cell_observed_codec_histogram",
          dev["codec_observations"] > 0
          and dev["codec_seconds_observed"] > 0,
          f"{dev['codec_observations']} observations, "
          f"{dev['codec_seconds_observed']}s")
    check("merged_profile_artifact_reconciles",
          p.returncode == 0 and merged.get("trace_files")
          and merged.get("reconciliation") is not None,
          f"cli perf profile rc={p.returncode}, "
          f"basis={((merged.get('profile') or {}).get('basis'))}, "
          f"residual reported="
          f"{'reconciliation' in merged}")

    summary = {
        "experiment": "codec_profile",
        "cells": cells,
        "merged_profile": {
            "path": os.path.relpath(merged_out, REPO),
            "basis": (merged.get("profile") or {}).get("basis"),
            "reconciliation": merged.get("reconciliation"),
        },
        "checks": checks,
        "all_pass": all(c["pass"] for c in checks),
    }
    out_path = os.path.join(OUT, "codec_profile.json")
    with open(out_path, "w") as f:
        json.dump(summary, f, indent=2)
        f.write("\n")
    print(f"\n{sum(c['pass'] for c in checks)}/{len(checks)} checks PASS "
          f"-> {out_path}", flush=True)
    return 0 if summary["all_pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
