"""Recorded elastic-serve-tier demo (ISSUE 11 acceptance evidence).

Three cells under ``experiments/results/elastic_serve/``, every check
exit-code-verified (the PR 4-9 recorded-demo format). All processes are
real ``cli`` subprocesses; the driver talks to them only over the wire.

**Cell A — live slot-range migration under client load.** Two shard
primaries (``--shard-count 2``) take a continuous ``cli loadgen`` full-
fetch stream while ``cli reshard`` moves the upper half of shard 0's
slot range to shard 1 (export -> import -> apply_ranges -> commit).
Checks: the loadgen window spanning the migration records ZERO failed
fetches; a push token applied on the donor BEFORE the handoff, replayed
byte-identical against the recipient AFTER it, answers ``duplicate``
with params and step unmoved (the journal travelled with the range —
exactly-once across the handoff); a client still on the stale map has
its push disowned by the donor and re-routed exactly once — the moved
tensor shows exactly ONE SGD application; both primaries publish the
bumped map to their clients through the delta handshake.

**Cell B — replica autoscaler closes the loop.** One primary with
``--autoscale`` (max 2, short cooldown, fast health tick). A delta-mode
loadgen ramp drives windowed fetch QPS over the high-water mark: the
fleet must grow to max, the grown ``cli replica`` children must announce
themselves into the shard map, and after the ramp ends the fleet must
shrink back to min — all read live from ``GET /cluster``'s ``autoscale``
block (grow/shrink action counts, bounded event log, live count).

**Cell C — canary-gated inference serving.** One primary + one
``--canary`` replica (50% split, 5-sample windows). The driver pushes
step 1 (candidate) and runs ``cli loadgen --fetch-mode infer``: constant
quality promotes the candidate (promotions counter, stable step gauge),
with both arms' request counts and latency percentiles visible in
LOADGEN_JSON. Then it pushes step 2 and scores it 0.0 via an in-process
``run_loadgen(quality_fn=...)``: the replica must ROLL BACK (rollback
counter), keep serving the promoted step 1, and fence step 2. A final
``cli infer`` confirms post-rollback requests all serve the stable arm.

Artifacts: ``elastic_serve.json`` (summary + PASS/FAIL checks), per-cell
loadgen/reshard/autoscale JSON, cluster captures, and process logs.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT_DIR = os.path.join(REPO, "experiments", "results", "elastic_serve")
PKG = "distributed_parameter_server_for_ml_training_tpu"
sys.path.insert(0, REPO)

MODEL = "vit_tiny"
LR = 0.1                     # serve default (StoreConfig.learning_rate)


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env(**extra) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _http(url: str, timeout: float = 5.0) -> str | None:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.read().decode()
    except Exception:
        return None


def _cluster(port: int) -> dict | None:
    raw = _http(f"http://127.0.0.1:{port}/cluster")
    if raw is None:
        return None
    try:
        return json.loads(raw)
    except ValueError:
        return None


def _metric_value(metrics_text: str | None, name: str,
                  labels: str = "") -> float | None:
    if not metrics_text:
        return None
    import re
    pat = re.compile(rf"^{re.escape(name)}{re.escape(labels)} (\S+)$",
                     re.M)
    m = pat.search(metrics_text)
    return float(m.group(1)) if m else None


def _spawn(argv: list, log_path: str, **env_extra):
    log = open(log_path, "w")
    proc = subprocess.Popen(argv, stdout=log, stderr=subprocess.STDOUT,
                            env=_env(**env_extra), cwd=REPO)
    return proc, log


def _stop(proc, log, grace: float = 15.0) -> int | None:
    if proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=grace)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=grace)
    log.close()
    return proc.returncode


def _serve_argv(*, port: int, metrics_port: int, mode: str = "async",
                extra: list[str] | None = None) -> list:
    return [sys.executable, "-m", f"{PKG}.cli", "serve",
            "--mode", mode, "--workers", "1",
            "--port", str(port), "--model", MODEL, "--num-classes", "100",
            "--image-size", "32", "--platform", "cpu",
            "--metrics-port", str(metrics_port)] + (extra or [])


def _wait_up(metrics_port: int, proc, what: str,
             timeout: float = 180.0) -> None:
    deadline = time.time() + timeout
    while _cluster(metrics_port) is None:
        if time.time() > deadline or proc.poll() is not None:
            raise RuntimeError(f"{what} never came up (rc={proc.poll()})")
        time.sleep(0.25)


def _grpc_up(addr: str, timeout: float = 60.0) -> None:
    from distributed_parameter_server_for_ml_training_tpu.comms.loadgen \
        import run_loadgen
    deadline = time.time() + timeout
    while time.time() < deadline:
        r = run_loadgen([addr], duration_s=0.2, concurrency=1,
                        rpc_timeout=2.0)
        if r["fetches_ok"] > 0:
            return
        time.sleep(0.5)
    raise RuntimeError(f"no PS answering at {addr}")


def _loadgen_proc(targets: list[str], mode: str, duration: float,
                  concurrency: int = 4) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, "-m", f"{PKG}.cli", "loadgen",
         "--targets", ",".join(targets), "--duration", str(duration),
         "--concurrency", str(concurrency), "--fetch-mode", mode],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=_env(), cwd=REPO)


def _json_line(text: str, prefix: str) -> dict | None:
    out = None
    for line in (text or "").splitlines():
        if line.startswith(prefix):
            out = json.loads(line[len(prefix):])
    return out


def _raw_stub(addr: str, method: str):
    import grpc
    from distributed_parameter_server_for_ml_training_tpu.comms.service \
        import GRPC_OPTIONS, SERVICE_NAME
    ident = lambda b: b  # noqa: E731
    channel = grpc.insecure_channel(addr, options=GRPC_OPTIONS)
    return channel, channel.unary_unary(
        f"/{SERVICE_NAME}/{method}",
        request_serializer=ident, response_deserializer=ident)


# ---------------------------------------------------------------------------
# Cell A: live migration under client load
# ---------------------------------------------------------------------------

def cell_a() -> tuple[dict, dict]:
    import numpy as np

    from distributed_parameter_server_for_ml_training_tpu.comms.client \
        import RemoteStore
    from distributed_parameter_server_for_ml_training_tpu.comms.service \
        import pack_msg, unpack_msg
    from distributed_parameter_server_for_ml_training_tpu.comms.sharded \
        import ShardedRemoteStore
    from distributed_parameter_server_for_ml_training_tpu.comms.wire \
        import encode_tensor_dict
    from distributed_parameter_server_for_ml_training_tpu.ps.sharding \
        import key_slot

    procs = []
    try:
        ports = [_free_port(), _free_port()]
        mports = [_free_port(), _free_port()]
        peers = ",".join(f"localhost:{p}" for p in ports)
        for i in range(2):
            sp, slog = _spawn(
                _serve_argv(port=ports[i], metrics_port=mports[i],
                            extra=["--shard-index", str(i),
                                   "--shard-count", "2",
                                   "--shard-peers", peers]),
                os.path.join(OUT_DIR, f"a_shard{i}_server.log"))
            procs.append((sp, slog))
        for i in range(2):
            _wait_up(mports[i], procs[i][0], f"cell A shard {i}")

        # Stale-map client: registers NOW (map v1), pushes only after the
        # migration bumped the map — its moved-key slice must be disowned
        # by the donor and re-routed exactly once.
        stale = ShardedRemoteStore(peers)
        wid, _ = stale.register_worker("elastic-stale")
        params, step0 = stale.fetch(wid)
        old_version = (stale.shard_map or {}).get("version")

        slots0 = sorted({key_slot(n) for n in params if key_slot(n) < 32})
        lo = slots0[len(slots0) // 2]
        if lo == 0:
            lo = next(s for s in slots0 if s > 0)
        moved = sorted(n for n in params if lo <= key_slot(n) < 32)
        kept = sorted(n for n in params if key_slot(n) < lo)
        k_parity, k_route = moved[0], moved[-1]

        # Pre-handoff tokened push on the donor: its journal entry must
        # survive the migration.
        rs0 = RemoteStore(f"localhost:{ports[0]}")
        rs1 = RemoteStore(f"localhost:{ports[1]}")
        widp, _ = rs0.register_worker("elastic-parity")
        rs1.register_worker("elastic-parity")
        pparams, pstep = rs0.fetch(widp)
        g_parity = np.full_like(pparams[k_parity], 0.25)
        parity_req = pack_msg(
            {"worker_id": widp, "fetched_step": pstep,
             "push_token": "elastic-parity:1"},
            encode_tensor_dict({k_parity: g_parity}))
        ch0, push0 = _raw_stub(f"localhost:{ports[0]}", "PushGradrients")
        first, _ = unpack_msg(push0(parity_req, timeout=10.0))
        v_parity_donor = rs0.fetch(widp)[0][k_parity].copy()

        # Client load spanning the whole migration window.
        lg = _loadgen_proc([f"localhost:{p}" for p in ports], "full",
                           duration=12.0, concurrency=4)
        time.sleep(1.5)
        rp = subprocess.run(
            [sys.executable, "-m", f"{PKG}.cli", "reshard",
             "--primaries", peers, "--donor", "0", "--recipient", "1",
             "--slots", f"{lo}:32", "--json"],
            capture_output=True, text=True, env=_env(), cwd=REPO,
            timeout=120)
        reshard = _json_line(rp.stdout, "RESHARD_JSON ")
        with open(os.path.join(OUT_DIR, "a_reshard.json"), "w") as f:
            json.dump({"rc": rp.returncode, "result": reshard,
                       "stderr": rp.stderr[-2000:]}, f, indent=2)

        # Journal parity: byte-identical replay against the RECIPIENT.
        r1_before, r1_step_before = rs1.fetch(None)
        ch1, push1 = _raw_stub(f"localhost:{ports[1]}", "PushGradrients")
        replay, _ = unpack_msg(push1(parity_req, timeout=10.0))
        r1_after, r1_step_after = rs1.fetch(None)
        ch0.close(), ch1.close()

        # Stale-map push: donor disowns the moved key, the sharded client
        # re-routes it once; exactly one SGD application must land.
        v_route_before = r1_after[k_route].copy()
        grads = {k_route: np.full_like(params[k_route], 0.5)}
        if kept:
            grads[kept[0]] = np.full_like(params[kept[0]], 0.5)
        push_ok = stale.push(wid, grads, step0)
        v_route_after = rs1.fetch(None)[0][k_route]
        new_version = (stale.shard_map or {}).get("version")

        lg_out, _ = lg.communicate(timeout=60)
        lg_rc = lg.returncode
        loadgen = _json_line(lg_out, "LOADGEN_JSON ")
        with open(os.path.join(OUT_DIR, "a_loadgen.json"), "w") as f:
            json.dump({"rc": lg_rc, "result": loadgen}, f, indent=2)

        # Both primaries publish the bumped map through the delta
        # handshake (have_shard_map rode the fetches above for rs1; rs0
        # needs one more fetch to learn it).
        rs0.fetch(None)
        maps = [rs0.shard_map, rs1.shard_map]
        for s in (rs0, rs1):
            s.close()
        stale.close()

        want_ranges = [[0, lo], [lo, 64]]
        record = {
            "slots_moved": [lo, 32],
            "moved_params": len(moved),
            "kept_params": len(kept),
            "reshard_rc": rp.returncode,
            "reshard": reshard,
            "loadgen": {k: (loadgen or {}).get(k)
                        for k in ("fetches_ok", "fetches_err", "qps",
                                  "latency_ms", "errors_by_target")},
            "parity_first": {k: first.get(k)
                             for k in ("accepted", "duplicate")},
            "parity_replay": {k: replay.get(k)
                              for k in ("accepted", "duplicate")},
            "recipient_step_around_replay": [r1_step_before,
                                             r1_step_after],
            "stale_push_ok": bool(push_ok),
            "map_versions_after": [(m or {}).get("version")
                                   for m in maps],
            "old_map_version": old_version,
        }
        checks = {
            "A_reshard_protocol_completed":
                rp.returncode == 0 and reshard is not None
                and reshard["exported"] >= 1
                and reshard["adopted"] == reshard["exported"]
                and reshard["journal_loaded"] >= 1
                and reshard["dropped"] >= 1
                and reshard["ranges"] == want_ranges,
            "A_zero_failed_fetches_under_migration":
                lg_rc == 0 and loadgen is not None
                and loadgen["fetches_ok"] > 0
                and loadgen["fetches_err"] == 0,
            "A_params_travelled_with_range":
                np.array_equal(r1_before[k_parity], v_parity_donor),
            "A_journal_parity_replay_deduped":
                bool(first.get("accepted"))
                and not first.get("duplicate")
                and bool(replay.get("duplicate"))
                and bool(replay.get("accepted"))
                and np.array_equal(r1_before[k_parity],
                                   r1_after[k_parity])
                and r1_step_before == r1_step_after,
            "A_stale_push_rerouted_exactly_once":
                push_ok
                and bool(np.allclose(v_route_after,
                                     v_route_before - LR * 0.5,
                                     atol=1e-6)),
            "A_bumped_map_published_to_clients":
                record["map_versions_after"]
                == [reshard["map_version"]] * 2 if reshard else False,
        }
        return record, checks
    finally:
        for proc, log in procs:
            _stop(proc, log)


# ---------------------------------------------------------------------------
# Cell B: replica autoscaler grow/shrink from measured QPS
# ---------------------------------------------------------------------------

def cell_b() -> tuple[dict, dict]:
    port, mport = _free_port(), _free_port()
    # The pool's `cli replica` children inherit the primary's env:
    # DPS_REPLICA_POLL=0.5 keeps their delta polls (2 Hz each) far below
    # the qps_low water mark, so an idle fleet can actually shrink.
    proc, log = _spawn(
        _serve_argv(port=port, metrics_port=mport,
                    extra=["--shard-count", "1",
                           "--shard-peers", f"localhost:{port}",
                           "--autoscale",
                           "--autoscale-min", "0",
                           "--autoscale-max", "2",
                           "--autoscale-qps-high", "100",
                           "--autoscale-qps-low", "10",
                           "--autoscale-cooldown", "1.5",
                           "--health-interval", "0.5"]),
        os.path.join(OUT_DIR, "b_primary.log"),
        DPS_REPLICA_POLL=0.5)
    try:
        _wait_up(mport, proc, "cell B primary")
        lg = _loadgen_proc([f"localhost:{port}"], "delta",
                           duration=14.0, concurrency=4)
        samples = []
        max_live = max_announced = 0
        while lg.poll() is None:
            view = _cluster(mport) or {}
            asc = view.get("autoscale") or {}
            live = int(asc.get("live") or 0)
            announced = len((view.get("sharding") or {})
                            .get("replicas") or [])
            max_live = max(max_live, live)
            max_announced = max(max_announced, announced)
            samples.append({"t": round(time.time(), 2), "live": live,
                            "announced": announced})
            time.sleep(0.5)
        lg_out, _ = lg.communicate(timeout=30)
        loadgen = _json_line(lg_out, "LOADGEN_JSON ")

        # Ramp over: QPS collapses to replica polls; the fleet must
        # shrink back to min. Keep sampling (announce can trail spawn).
        shrunk_to_min = False
        deadline = time.time() + 60
        while time.time() < deadline:
            view = _cluster(mport) or {}
            asc = view.get("autoscale") or {}
            live = int(asc.get("live") or 0)
            announced = len((view.get("sharding") or {})
                            .get("replicas") or [])
            max_live = max(max_live, live)
            max_announced = max(max_announced, announced)
            samples.append({"t": round(time.time(), 2), "live": live,
                            "announced": announced})
            if live == 0 and (asc.get("actions") or {}) \
                    .get("replica_shrink", 0) >= 2:
                shrunk_to_min = True
                break
            time.sleep(0.5)
        final_view = _cluster(mport) or {}
        asc = final_view.get("autoscale") or {}
        live_gauge = _metric_value(
            _http(f"http://127.0.0.1:{mport}/metrics"),
            "dps_replicas_live")
        with open(os.path.join(OUT_DIR, "b_autoscale.json"), "w") as f:
            json.dump({"final_view": asc, "samples": samples,
                       "loadgen": loadgen}, f, indent=2)

        actions = asc.get("actions") or {}
        record = {
            "ramp_qps": (loadgen or {}).get("qps"),
            "max_live_observed": max_live,
            "max_replicas_announced": max_announced,
            "final_live": asc.get("live"),
            "final_replicas_live_gauge": live_gauge,
            "actions": actions,
            "events_tail": (asc.get("events") or [])[-8:],
        }
        checks = {
            "B_ramp_loadgen_clean":
                lg.returncode == 0 and loadgen is not None
                and loadgen["fetches_err"] == 0
                and (loadgen["qps"] or 0) > 100,
            "B_grew_to_max_under_ramp": max_live == 2,
            "B_grown_replicas_announced_into_shard_map":
                max_announced >= 1,
            "B_shrank_to_min_after_ramp":
                shrunk_to_min and asc.get("live") == 0
                and live_gauge == 0,
            "B_actions_counted":
                actions.get("replica_grow", 0) >= 2
                and actions.get("replica_shrink", 0) >= 2,
        }
        return record, checks
    finally:
        _stop(proc, log)


# ---------------------------------------------------------------------------
# Cell C: canary-gated inference — promote, then forced rollback
# ---------------------------------------------------------------------------

def cell_c() -> tuple[dict, dict]:
    import numpy as np

    from distributed_parameter_server_for_ml_training_tpu.comms.client \
        import RemoteStore
    from distributed_parameter_server_for_ml_training_tpu.comms.loadgen \
        import run_loadgen

    procs = []
    try:
        port, mport = _free_port(), _free_port()
        primary, plog = _spawn(
            _serve_argv(port=port, metrics_port=mport,
                        extra=["--shard-count", "1",
                               "--shard-peers", f"localhost:{port}"]),
            os.path.join(OUT_DIR, "c_primary.log"))
        procs.append((primary, plog))
        _wait_up(mport, primary, "cell C primary")

        rp, rmport = _free_port(), _free_port()
        rep, rlog = _spawn(
            [sys.executable, "-m", f"{PKG}.cli", "replica",
             "--primary", f"localhost:{port}", "--port", str(rp),
             "--poll-interval", "0.02", "--staleness-bound", "30",
             "--canary", "--canary-fraction", "0.5",
             "--canary-min-samples", "5",
             "--metrics-port", str(rmport)],
            os.path.join(OUT_DIR, "c_replica.log"))
        procs.append((rep, rlog))
        _grpc_up(f"localhost:{rp}")

        rs = RemoteStore(f"localhost:{port}")
        wid, _ = rs.register_worker("elastic-canary")
        params, step = rs.fetch(wid)
        name = sorted(params)[0]
        g = np.full_like(params[name], 0.01)

        def advance() -> int:
            nonlocal step
            rs.push(wid, {name: g}, step)
            step = rs.fetch(wid)[1]
            return step

        def rep_metric(mname: str, labels: str = "") -> float | None:
            return _metric_value(
                _http(f"http://127.0.0.1:{rmport}/metrics"),
                mname, labels)

        def wait_replica_at(want: int, timeout: float = 20.0) -> None:
            deadline = time.time() + timeout
            while time.time() < deadline:
                if (rep_metric("dps_replica_step") or -1) >= want:
                    return
                time.sleep(0.1)
            raise RuntimeError(f"replica never reached step {want}")

        # Phase 1 — candidate step 1, constant quality => PROMOTE.
        advance()
        wait_replica_at(1)
        p1 = subprocess.run(
            [sys.executable, "-m", f"{PKG}.cli", "loadgen",
             "--targets", f"localhost:{rp}", "--duration", "4",
             "--concurrency", "2", "--fetch-mode", "infer"],
            capture_output=True, text=True, env=_env(), cwd=REPO,
            timeout=120)
        promote_lg = _json_line(p1.stdout, "LOADGEN_JSON ")
        with open(os.path.join(OUT_DIR, "c_loadgen_promote.json"),
                  "w") as f:
            json.dump({"rc": p1.returncode, "result": promote_lg},
                      f, indent=2)
        promotions = rep_metric("dps_canary_promotions_total")
        stable_after_promote = rep_metric("dps_canary_stable_step")

        # Phase 2 — candidate step 2 scored 0.0 => ROLLBACK.
        advance()
        wait_replica_at(2)
        rollback_lg = run_loadgen(
            [f"localhost:{rp}"], duration_s=4.0, concurrency=2,
            mode="infer",
            quality_fn=lambda s: 0.0 if s >= 2 else 1.0)
        with open(os.path.join(OUT_DIR, "c_loadgen_rollback.json"),
                  "w") as f:
            json.dump(rollback_lg, f, indent=2)
        rollbacks = rep_metric("dps_canary_rollbacks_total")
        stable_after_rollback = rep_metric("dps_canary_stable_step")

        # Post-rollback: `cli infer` must see only the stable arm at the
        # promoted step (step 2 is fenced).
        pi = subprocess.run(
            [sys.executable, "-m", f"{PKG}.cli", "infer",
             "--target", f"localhost:{rp}", "--count", "6", "--json"],
            capture_output=True, text=True, env=_env(), cwd=REPO,
            timeout=60)
        infer = _json_line(pi.stdout, "INFER_JSON ")
        with open(os.path.join(OUT_DIR, "c_infer.json"), "w") as f:
            json.dump({"rc": pi.returncode, "result": infer}, f,
                      indent=2)
        rs.close()

        arms1 = (promote_lg or {}).get("arms") or {}
        arms2 = rollback_lg.get("arms") or {}
        record = {
            "promotions_total": promotions,
            "rollbacks_total": rollbacks,
            "stable_step_after_promote": stable_after_promote,
            "stable_step_after_rollback": stable_after_rollback,
            "promote_arms": {a: {k: r.get(k) for k in
                                 ("ok", "quality_mean", "latency_ms",
                                  "serving_steps")}
                             for a, r in arms1.items()},
            "rollback_arms": {a: {k: r.get(k) for k in
                                  ("ok", "quality_mean", "latency_ms",
                                   "serving_steps")}
                              for a, r in arms2.items()},
            "post_rollback_served": (infer or {}).get("served"),
        }
        served = (infer or {}).get("served") or []
        checks = {
            "C_promoted_on_quality":
                p1.returncode == 0 and (promotions or 0) >= 1
                and stable_after_promote == 1,
            "C_split_visible_in_loadgen":
                arms1.get("stable", {}).get("ok", 0) > 0
                and arms1.get("canary", {}).get("ok", 0) > 0
                and arms1.get("canary", {}).get(
                    "latency_ms", {}).get("samples", 0) > 0,
            "C_rollback_on_regression":
                (rollbacks or 0) >= 1 and stable_after_rollback == 1
                and arms2.get("canary", {}).get("serving_steps") == [2]
                and arms2.get("stable", {}).get("serving_steps") == [1],
            "C_post_rollback_serves_stable_only":
                pi.returncode == 0 and len(served) == 6
                and all(r["arm"] == "stable" and r["serving_step"] == 1
                        for r in served),
        }
        return record, checks
    finally:
        for proc, log in procs:
            _stop(proc, log)


def main(argv=None) -> int:
    import argparse
    global OUT_DIR
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out-dir", default=OUT_DIR,
                    help="artifact directory (default: the recorded "
                         "experiments/results/elastic_serve)")
    args = ap.parse_args(argv)
    OUT_DIR = args.out_dir
    os.makedirs(OUT_DIR, exist_ok=True)
    t0 = time.time()
    checks: dict = {}

    a_rec, a_checks = cell_a()
    checks.update(a_checks)
    print(f"cell A: moved slots {a_rec['slots_moved']} "
          f"({a_rec['moved_params']} tensors) under "
          f"{a_rec['loadgen']['fetches_ok']} live fetches, "
          f"{a_rec['loadgen']['fetches_err']} failed", flush=True)

    b_rec, b_checks = cell_b()
    checks.update(b_checks)
    print(f"cell B: ramp {b_rec['ramp_qps']} qps -> fleet peaked at "
          f"{b_rec['max_live_observed']}, settled at "
          f"{b_rec['final_live']} ({b_rec['actions']})", flush=True)

    c_rec, c_checks = cell_c()
    checks.update(c_checks)
    print(f"cell C: promotions={c_rec['promotions_total']} "
          f"rollbacks={c_rec['rollbacks_total']}, stable step held at "
          f"{c_rec['stable_step_after_rollback']}", flush=True)

    record = {
        "demo": "elastic serve tier: live resharding, replica "
                "autoscaling, canary-gated inference (ISSUE 11)",
        "elapsed_seconds": round(time.time() - t0, 1),
        "environment": {"cpus": os.cpu_count()},
        "checks": checks,
        "all_pass": all(checks.values()),
        "cell_a": a_rec,
        "cell_b": b_rec,
        "cell_c": c_rec,
    }
    with open(os.path.join(OUT_DIR, "elastic_serve.json"), "w") as f:
        json.dump(record, f, indent=2)
    n_pass = sum(bool(v) for v in checks.values())
    print(f"elastic serve demo: {n_pass}/{len(checks)} checks PASS "
          f"({record['elapsed_seconds']}s)")
    for cname, ok in checks.items():
        print(f"  [{'PASS' if ok else 'FAIL'}] {cname}")
    return 0 if record["all_pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
