"""Fetch-codec accuracy parity: bf16-compressed fetches vs fp32 fetches.

Round-4 VERDICT weak 3 'done' bar: the dominant wire term (fp32 parameter
fetches — the reference's own hot spot, server.py:222's ~45 MB re-pickle)
halves under ``serve --fetch-codec bf16`` *with curves unchanged*. The
byte halving is recorded by the wire matrix's ``*_fetchbf16`` cells; THIS
script records the numerics half: two identical PS training runs (same
model/seed/shards/recipe, 2 workers against an in-process store) differing
ONLY in the store's fetch codec, loss/accuracy curves side by side.

bf16 keeps fp32's exponent range and drops 16 mantissa bits; workers hold
the decompressed weights only for the K-step window before refetching, so
rounding does not accumulate — the curves should track within noise.

Run:  python experiments/run_fetch_codec_parity.py [--epochs 4]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                 os.path.join(REPO, ".jax_cache")))


def run_arm(fetch_codec: str, epochs: int, n_train: int) -> dict:
    import numpy as np

    from distributed_parameter_server_for_ml_training_tpu.data.cifar import (
        compositional_cifar100)
    from distributed_parameter_server_for_ml_training_tpu.models import (
        get_model)
    from distributed_parameter_server_for_ml_training_tpu.ps import (
        ParameterStore, StoreConfig)
    from distributed_parameter_server_for_ml_training_tpu.ps.worker import (
        PSWorker, WorkerConfig)
    from distributed_parameter_server_for_ml_training_tpu.utils.pytree \
        import flatten_params

    ds = compositional_cifar100(n_train=n_train, n_test=1024)
    model = get_model("vit_tiny", num_classes=ds.num_classes,
                      image_size=32)
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 32, 32, 3), np.float32),
                           train=False)
    store = ParameterStore(
        flatten_params(variables["params"]),
        StoreConfig(mode="async", total_workers=2, learning_rate=0.1,
                    push_codec="fp16", fetch_codec=fetch_codec))
    cfg = WorkerConfig(batch_size=64, num_epochs=epochs, augment=False,
                       seed=0)
    t0 = time.time()
    workers = [PSWorker(store, model, ds, cfg, worker_name=f"w{i}")
               for i in range(2)]
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    for w in workers:
        if w.result.error is not None:
            raise w.result.error
    return {
        "fetch_codec": fetch_codec,
        "wall_seconds": round(time.time() - t0, 1),
        "per_worker_accuracy_curves": {
            w.worker_name: w.result.test_accuracies for w in workers},
        "final_accuracy_mean": round(float(np.mean(
            [w.result.test_accuracies[-1] for w in workers])), 4),
        "server_metrics": store.metrics(),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--num-train", type=int, default=4096)
    args = ap.parse_args()

    out = os.path.join(REPO, "experiments", "results", "calibrated",
                       "fetch_codec_parity.json")
    record = {
        "experiment_name": "fetch_codec_parity",
        "setup": "2 in-process PSWorkers, async store, push fp16 (the "
                 "reference default); ONLY the fetch codec differs. "
                 "Byte effect recorded separately by the wire matrix "
                 "(async_4w_fp16_*_fetchbf16 cells: params-in halves).",
    }
    for codec in ("none", "bf16"):
        record[f"fetch_{codec}"] = run_arm(codec, args.epochs,
                                           args.num_train)
        with open(out, "w") as f:
            json.dump(record, f, indent=2, default=float)
            f.write("\n")
        print(f"fetch_codec={codec}: "
              f"{record[f'fetch_{codec}']['final_accuracy_mean']} "
              f"final acc", flush=True)
    a = record["fetch_none"]["final_accuracy_mean"]
    b = record["fetch_bf16"]["final_accuracy_mean"]
    record["parity"] = {
        "final_acc_fp32_fetch": a, "final_acc_bf16_fetch": b,
        "abs_delta": round(abs(a - b), 4),
        # Async-store runs are order-dependent (thread interleaving), so
        # exact equality is not expected even at fetch_codec=none; the
        # bar is "within run-to-run noise".
        "within_noise": abs(a - b) < 0.02,
    }
    with open(out, "w") as f:
        json.dump(record, f, indent=2, default=float)
        f.write("\n")
    print("parity:", record["parity"])
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
