"""Recorded goodput-observatory demo (ISSUE 20 acceptance evidence).

Six checks, each exercising the production plumbing end to end:

**Phase A — live badput attribution.** A ``cli serve`` primary plus one
``cli worker`` with a seeded client-side ``fetch.delay`` fault, both
journaling into one durable directory. While the worker trains,
``cli goodput`` against the worker's ``/metrics.json`` must show the
injected badput attributed to ``fetch_wait`` (not smeared into the
residual) with the ledger reconciling (categories sum to wall inside
tolerance, residual reported).

**Phase B — retro from the journal alone.** Both processes are stopped;
``cli query --journal <dir> --goodput`` re-derives the same ledger from
disk by counter subtraction and must agree with the live verdict
(fetch_wait badput present, reconciled).

**Phase C — seeded host leak fires ``memory_growth``.** A
:class:`~telemetry.memory.MemoryMonitor` with a seeded leaky RSS reader
(16 MiB/s) on a fake clock feeds verdicts through the real
:class:`~telemetry.health.HealthRuleEngine`: the ``memory_growth``
warning must fire once the window gates open, and a healthy slope must
NOT fire.

**Phase D — regression auto-captures a profile exactly once.** A real
``jax.profiler`` window (matmul load running) is trigger-captured by a
benchwatch ``regression`` verdict through :class:`ProfileTrigger`; a
second verdict inside the cooldown must be SUPPRESSED (one ledger
record, ``dps_profiles_suppressed_total`` = 1), and the raw Chrome
traces must be pruned after the successful attribution join.

**Phase E — ``cli perf diff`` localizes a deliberate slowdown.** Two
more trigger captures bracket a baseline matmul workload and a
deliberately slowed one (4x matrix dimension); ``cli perf diff`` over
the two committed ledger records must name ``matmul`` as the top
mover with a positive delta.

**Phase F — overhead guard.** The measured per-step cost of one goodput
span bracket plus one wall tick must stay under 2% of one core even
against a fast 5 ms reference step.

Artifacts: ``goodput_demo.json`` (summary + PASS/FAIL checks), the live
and retro ledgers, the memory alert, the ``profiles/`` ledger records,
the rendered perf diff, the journal directory, and process logs.
"""

from __future__ import annotations

import json
import os
import shutil
import statistics
import subprocess
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT_DIR = os.path.join(REPO, "experiments", "results", "goodput")
PKG = "distributed_parameter_server_for_ml_training_tpu"
sys.path.insert(0, REPO)

MODEL = "vit_tiny"
FAULT_SPEC = "fetch.delay=0.1@p=1.0"
MiB = 1048576


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env(**extra) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONUNBUFFERED"] = "1"
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _get_json(url: str, timeout: float = 5.0) -> dict | None:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read().decode())
    except Exception:
        return None


def _spawn(argv: list, log_path: str):
    log = open(log_path, "a")
    proc = subprocess.Popen(argv, stdout=log, stderr=subprocess.STDOUT,
                            env=_env(), cwd=REPO)
    return proc, log


def _stop(proc, log, grace: float = 20.0) -> int | None:
    if proc is not None and proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=grace)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=grace)
    if log is not None:
        log.close()
    return None if proc is None else proc.returncode


def _trim_log(path: str) -> None:
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        return
    kept = [ln for ln in lines if "METRICS_JSON:" not in ln]
    dropped = len(lines) - len(kept)
    if dropped:
        kept.append(f"[demo] trimmed {dropped} METRICS_JSON line(s); "
                    f"the durable copies are in journal/\n")
        with open(path, "w") as f:
            f.writelines(kept)


def _wait(pred, what: str, timeout: float = 120.0, poll: float = 0.25):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(poll)
    raise RuntimeError(f"timed out waiting for {what}")


def _cli(argv: list, timeout: float = 120.0):
    cp = subprocess.run([sys.executable, "-m", f"{PKG}.cli"] + argv,
                        capture_output=True, text=True, env=_env(),
                        cwd=REPO, timeout=timeout)
    return cp.returncode, cp.stdout, cp.stderr


def _json_line(text: str, tag: str) -> dict | None:
    for ln in text.splitlines():
        if ln.startswith(tag):
            return json.loads(ln[len(tag):])
    return None


def _badput_top(report: dict) -> str | None:
    """Largest steady-state badput category of a goodput report.
    ``startup`` is excluded: it is a one-time cost every cold process
    pays (jax import + first compile) and would mask the *injected*
    badput over a short recorded window; ``other`` is the residual, not
    an attribution."""
    rows = [(cat, row["seconds"])
            for cat, row in (report.get("categories") or {}).items()
            if cat not in ("compute", "other", "startup")
            and row["seconds"] > 0]
    rows.sort(key=lambda kv: -kv[1])
    return rows[0][0] if rows else None


class _MatmulLoad:
    """Background jax matmul loop so a profiler window has real op
    events to attribute (dot kernels classify as ``matmul``)."""

    def __init__(self, dim: int):
        import jax
        import jax.numpy as jnp
        self._stop = threading.Event()
        a = jnp.ones((dim, dim), jnp.float32)
        f = jax.jit(lambda x: x @ x)
        f(a).block_until_ready()  # compile outside the capture

        def run():
            while not self._stop.is_set():
                f(a).block_until_ready()
        self._thread = threading.Thread(target=run, daemon=True)

    def __enter__(self):
        self._thread.start()
        time.sleep(0.1)  # make sure ops are in flight before the capture
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join(timeout=10)
        return False


def _fake_clock(start: float = 1000.0):
    state = {"t": start}

    def clock() -> float:
        return state["t"]

    def advance(dt: float) -> None:
        state["t"] += dt
    return clock, advance


def _phase_memory_growth(checks: list) -> dict:
    """Seeded host leak -> MemoryMonitor verdict -> HealthRuleEngine
    ``memory_growth`` edge (fake clock: the real 20 s window gates run
    without the wall wait)."""
    from distributed_parameter_server_for_ml_training_tpu.telemetry \
        import HealthRuleEngine, MetricsRegistry
    from distributed_parameter_server_for_ml_training_tpu.telemetry. \
        health import ClusterState
    from distributed_parameter_server_for_ml_training_tpu.telemetry. \
        memory import MemoryMonitor

    def drive(rate_bytes_per_s: float) -> list:
        clock, advance = _fake_clock()
        t0 = clock()

        def leaky_rss():
            n = int(512 * MiB + (clock() - t0) * rate_bytes_per_s)
            return {"rss_bytes": n, "peak_rss_bytes": n}
        mon = MemoryMonitor(MetricsRegistry(), interval_s=5.0,
                            window_s=120.0, clock=clock,
                            rss_fn=leaky_rss, device_fn=lambda: None)
        engine = HealthRuleEngine()
        fired = []
        for _ in range(8):
            verdict = mon.observe()
            state = ClusterState(ts=clock(), global_step=0, workers={},
                                 memory=verdict)
            fired += [ev for ev in engine.evaluate(state)
                      if ev["rule"] == "memory_growth"]
            advance(5.0)
        return fired

    leak_events = drive(16 * MiB)       # 2x the 8 MiB/s threshold
    healthy_events = drive(1 * MiB)     # well under it
    ok = (len(leak_events) == 1
          and leak_events[0]["state"] == "fired"
          and leak_events[0]["severity"] == "warning"
          and healthy_events == [])
    checks.append(
        ("C_seeded_leak_fires_memory_growth", ok,
         f"16MiB/s -> {[(e['rule'], e['state']) for e in leak_events]}, "
         f"1MiB/s -> {len(healthy_events)} event(s)"))
    return {"leak_alert": leak_events[0] if leak_events else None,
            "healthy_events": len(healthy_events)}


def _phase_profile_triggers(profiles_dir: str, window_s: float,
                            checks: list) -> dict:
    """Phase D (storm dedupe) + phase E (perf diff localization) share
    one real-profiler setup."""
    from distributed_parameter_server_for_ml_training_tpu.telemetry \
        import MetricsRegistry
    from distributed_parameter_server_for_ml_training_tpu.telemetry. \
        proftrigger import ProfileTrigger

    # -- D: one capture per cooldown window ------------------------------
    reg = MetricsRegistry()
    trig = ProfileTrigger(profiles_dir, window_s=window_s,
                          cooldown_s=600.0, role="demo", registry=reg)
    verdict = {"status": "regression", "regressions": ["steps_per_s"]}
    with _MatmulLoad(192):
        first = trig.on_bench_verdict(verdict)
        second = trig.on_bench_verdict(verdict)  # inside the cooldown
    counters = reg.snapshot()["counters"]
    rec_d = json.load(open(first)) if first else {}
    d_ok = (first is not None and second is None
            and counters.get("dps_profiles_captured_total") == 1.0
            and counters.get("dps_profiles_suppressed_total") == 1.0
            and rec_d.get("profile", {}).get("basis") not in (None, "none")
            and rec_d.get("traces_pruned") is True
            and not os.path.isdir(os.path.join(profiles_dir, "raw")))
    checks.append(
        ("D_regression_captures_once_cooldown_suppresses", d_ok,
         f"first={os.path.basename(first) if first else None} "
         f"second={second} captured="
         f"{counters.get('dps_profiles_captured_total')} suppressed="
         f"{counters.get('dps_profiles_suppressed_total')} basis="
         f"{rec_d.get('profile', {}).get('basis')} "
         f"pruned={rec_d.get('traces_pruned')}"))

    # -- E: baseline vs deliberately slowed matmul, localized by diff ----
    trig2 = ProfileTrigger(profiles_dir, window_s=window_s,
                           cooldown_s=0.0, role="demo",
                           registry=MetricsRegistry())
    with _MatmulLoad(128):
        baseline = trig2.maybe_capture({"rule": "baseline"})
    time.sleep(1.1)  # distinct UTC-second stamps -> distinct record ids
    with _MatmulLoad(512):  # 4x the dimension: ~64x the matmul flops
        candidate = trig2.maybe_capture({"rule": "candidate"})
    rc, out, err = _cli(["perf", "diff", baseline, candidate, "--json"])
    diff = json.loads(out) if rc == 0 else {}
    rows = diff.get("op_classes") or {}
    top = max(rows, key=lambda c: abs(rows[c]["delta_s"])) if rows \
        else None
    e_ok = (rc == 0 and top == "matmul"
            and rows["matmul"]["delta_s"] > 0)
    checks.append(
        ("E_perf_diff_localizes_slowed_matmul", e_ok,
         f"rc={rc} top_mover={top} "
         f"matmul_delta={rows.get('matmul', {}).get('delta_s')}s "
         f"basis={diff.get('basis')}"))
    rc_txt, out_txt, _ = _cli(["perf", "diff", baseline, candidate])
    return {"storm": {"captured": counters.get(
                          "dps_profiles_captured_total"),
                      "suppressed": counters.get(
                          "dps_profiles_suppressed_total"),
                      "record": os.path.basename(first) if first
                      else None},
            "diff": diff, "diff_rendered": out_txt,
            "records": {"baseline": os.path.basename(baseline),
                        "candidate": os.path.basename(candidate)}}


def _phase_overhead(checks: list) -> dict:
    """Per-step accounting cost: one span bracket + one wall tick,
    best-of-3 medians, against 2% of a fast 5 ms reference step."""
    from distributed_parameter_server_for_ml_training_tpu.telemetry \
        import GoodputAccount, MetricsRegistry

    acct = GoodputAccount(MetricsRegistry())
    acct.start_wall()
    n = 5000
    runs = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            with acct.span("compute"):
                pass
            acct.tick_wall()
        runs.append((time.perf_counter() - t0) / n)
    per_step = statistics.median(runs)
    frac = per_step / 0.005
    checks.append(
        ("F_accounting_overhead_under_2pct", frac < 0.02,
         f"{per_step * 1e6:.2f}us per span+tick = "
         f"{frac * 100:.3f}% of a 5ms step"))
    return {"per_step_us": round(per_step * 1e6, 3),
            "fraction_of_5ms_step": round(frac, 5)}


def main(argv=None) -> int:
    import argparse
    global OUT_DIR

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out-dir", default=OUT_DIR)
    args = ap.parse_args(argv)
    OUT_DIR = args.out_dir
    os.makedirs(OUT_DIR, exist_ok=True)
    quick = args.quick
    fetch_floor = 1.0 if quick else 2.5
    window_s = 0.5 if quick else 0.8

    journal_dir = os.path.join(OUT_DIR, "journal")
    profiles_dir = os.path.join(OUT_DIR, "profiles")
    for d in (journal_dir, profiles_dir):
        shutil.rmtree(d, ignore_errors=True)

    t0 = time.time()
    checks: list[tuple[str, bool, str]] = []
    summary: dict = {
        "demo": "goodput observatory: wall accounting, memory "
                "telemetry, trigger profiling, perf diff (ISSUE 20)",
        "quick": quick, "fault": FAULT_SPEC,
        "environment": {"cpus": os.cpu_count()},
    }
    procs: list[tuple] = []

    try:
        # -- phase A: live cluster with a seeded fetch-delay fault -----------
        port, mport, wport = _free_port(), _free_port(), _free_port()
        server, slog = _spawn(
            [sys.executable, "-m", f"{PKG}.cli", "serve",
             "--mode", "async", "--workers", "1",
             "--port", str(port), "--model", MODEL,
             "--num-classes", "100", "--image-size", "32",
             "--platform", "cpu", "--metrics-port", str(mport),
             "--telemetry", "--telemetry-interval", "0.5",
             "--journal-dir", journal_dir],
            os.path.join(OUT_DIR, "server.log"))
        procs.append((server, slog))
        _wait(lambda: _get_json(f"http://127.0.0.1:{mport}/cluster"),
              "the primary admin plane")

        worker, wlog = _spawn(
            [sys.executable, "-m", f"{PKG}.cli", "worker",
             "--server", f"localhost:{port}", "--model", MODEL,
             "--synthetic", "--num-train", "768", "--num-test", "96",
             "--epochs", "200", "--batch-size", "32",
             "--dtype", "float32", "--no-augment", "--platform", "cpu",
             "--heartbeat", "0.5", "--faults", FAULT_SPEC,
             "--metrics-port", str(wport),
             "--telemetry", "--telemetry-interval", "0.5",
             "--journal-dir", journal_dir],
            os.path.join(OUT_DIR, "worker.log"))
        procs.append((worker, wlog))
        worker_metrics = f"http://127.0.0.1:{wport}/metrics.json"

        def fetch_wait_accrued():
            m = _get_json(worker_metrics)
            fw = ((m or {}).get("counters") or {}).get(
                "dps_goodput_seconds_total{category=fetch_wait}", 0.0)
            return m if fw >= fetch_floor else None
        _wait(fetch_wait_accrued,
              f"{fetch_floor}s of injected fetch_wait badput", 300)

        rc, out, err = _cli(["goodput", "--url",
                             f"http://127.0.0.1:{wport}", "--json"])
        live = _json_line(out, "GOODPUT_JSON: ") or {}
        live_cats = live.get("categories") or {}
        fetch_live = (live_cats.get("fetch_wait") or {}).get(
            "seconds", 0.0)
        a_ok = (rc == 0 and live.get("reconciled") is True
                and fetch_live > 0
                and _badput_top(live) == "fetch_wait"
                and (live.get("goodput_fraction") or 1.0) < 0.9)
        checks.append(
            ("A_live_badput_lands_in_fetch_wait", a_ok,
             f"rc={rc} goodput={live.get('goodput_fraction')} "
             f"fetch_wait={fetch_live}s top_badput={_badput_top(live)} "
             f"residual={live.get('residual_s')}s "
             f"reconciled={live.get('reconciled')}"))
        with open(os.path.join(OUT_DIR, "goodput_live.json"), "w") as f:
            json.dump(live, f, indent=2)
        rc_h, out_h, _ = _cli(["goodput", "--url",
                               f"http://127.0.0.1:{wport}"])
        with open(os.path.join(OUT_DIR, "goodput_live.txt"), "w") as f:
            f.write(out_h)
        print(f"phase A: live goodput={live.get('goodput_fraction')} "
              f"fetch_wait={fetch_live}s", flush=True)

        # -- phase B: stop everything, re-derive from the journal alone ------
        for proc, log in reversed(procs):
            _stop(proc, log)
        procs.clear()
        rc, out, err = _cli(["query", "--journal", journal_dir,
                             "--goodput", "--json"])
        q = _json_line(out, "QUERY_JSON: ") or {}
        retro = q.get("goodput") or {}
        retro_cats = retro.get("categories") or {}
        fetch_retro = (retro_cats.get("fetch_wait") or {}).get(
            "seconds", 0.0)
        b_ok = (rc == 0 and retro.get("reconciled") is True
                and fetch_retro > 0
                and _badput_top(retro) == "fetch_wait"
                and retro.get("processes", 0) >= 1)
        checks.append(
            ("B_retro_journal_agrees_with_live", b_ok,
             f"rc={rc} goodput={retro.get('goodput_fraction')} "
             f"fetch_wait={fetch_retro}s over "
             f"{retro.get('processes')} process(es) "
             f"reconciled={retro.get('reconciled')}"))
        with open(os.path.join(OUT_DIR, "goodput_retro.json"),
                  "w") as f:
            json.dump(retro, f, indent=2)
        print(f"phase B: retro goodput={retro.get('goodput_fraction')} "
              f"fetch_wait={fetch_retro}s from the journal alone",
              flush=True)

        # -- phase C: seeded host leak -> memory_growth ----------------------
        summary["memory"] = _phase_memory_growth(checks)
        with open(os.path.join(OUT_DIR, "memory_alert.json"), "w") as f:
            json.dump(summary["memory"], f, indent=2, default=str)
        print(f"phase C: {checks[-1][2]}", flush=True)

        # -- phases D + E: trigger captures + perf diff ----------------------
        summary["profiles"] = _phase_profile_triggers(
            profiles_dir, window_s, checks)
        with open(os.path.join(OUT_DIR, "perf_diff.json"), "w") as f:
            json.dump(summary["profiles"]["diff"], f, indent=2)
        with open(os.path.join(OUT_DIR, "perf_diff.txt"), "w") as f:
            f.write(summary["profiles"]["diff_rendered"])
        print(f"phase D: {checks[-2][2]}", flush=True)
        print(f"phase E: {checks[-1][2]}", flush=True)

        # -- phase F: accounting overhead ------------------------------------
        summary["overhead"] = _phase_overhead(checks)
        print(f"phase F: {checks[-1][2]}", flush=True)

        summary["live_goodput"] = {
            k: live.get(k) for k in ("goodput_fraction", "wall_s",
                                     "badput_s", "residual_s",
                                     "reconciled")}
        summary["retro_goodput"] = {
            k: retro.get(k) for k in ("goodput_fraction", "wall_s",
                                      "badput_s", "processes",
                                      "reconciled")}
    finally:
        for proc, log in reversed(procs):
            _stop(proc, log)
        for name in ("server.log", "worker.log"):
            _trim_log(os.path.join(OUT_DIR, name))

    summary["elapsed_seconds"] = round(time.time() - t0, 1)
    summary["checks"] = [{"name": n, "ok": bool(ok), "detail": d}
                         for n, ok, d in checks]
    summary["ok"] = all(ok for _, ok, _ in checks)
    with open(os.path.join(OUT_DIR, "goodput_demo.json"), "w") as f:
        json.dump(summary, f, indent=2)
    n_pass = sum(1 for _, ok, _ in checks if ok)
    print(f"goodput demo: {n_pass}/{len(checks)} checks PASS "
          f"({summary['elapsed_seconds']}s)")
    for name, ok, detail in checks:
        print(f"  [{'PASS' if ok else 'FAIL'}] {name} — {detail}")
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
