"""Over-the-wire distributed experiment matrix: serve + worker OS processes.

Round-4 VERDICT item 4: every recorded experiment so far ran the IN-PROCESS
trainers; the reference's artifacts come from its real deployed topology —
separate processes, gradients crossing a network (worker.py:270-311). This
script runs that topology for THIS framework: one `cli serve` process and N
`cli worker` processes over localhost gRPC, for a matrix of cells:

    mode=async x workers={2,4} x push-codec={fp16,none}
                x store-backend={python,native}  (+ int8 x python)

and records, per cell, wire-level numbers no in-process run can produce:
pushes/s at the server, client wire MB (out = gradients, in = fetched
params), MB/s, the fp16-codec byte effect, and the python-vs-native server
backend — into experiments/results/wire/<cell>.json (reference schema via
the shared ETL) + wire_summary.json.

Workers run --platform cpu (the chip can't host N independent processes);
the numbers measure the WIRE + store path, complementing the on-chip
in-process records in experiments/results/calibrated/.

Run:  python experiments/run_wire_matrix.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

OUT = os.path.join(REPO, "experiments", "results", "wire")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_cell(mode: str, n_workers: int, codec: str, backend: str,
             epochs: int, n_train: int, batch: int) -> dict:
    from distributed_parameter_server_for_ml_training_tpu.analysis.parse_logs import (
        parse_experiment)

    name = f"{mode}_{n_workers}w_{codec}_{backend}"
    port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               JAX_COMPILATION_CACHE_DIR=os.path.join(REPO, ".jax_cache"))
    common = [sys.executable, "-m",
              "distributed_parameter_server_for_ml_training_tpu.cli"]
    t0 = time.time()
    server = subprocess.Popen(
        common + ["serve", "--mode", mode, "--workers", str(n_workers),
                  "--port", str(port), "--model", "vit_tiny",
                  "--num-classes", "100", "--image-size", "32",
                  "--store-backend", backend, "--push-codec", codec,
                  "--platform", "cpu", "--emit-metrics"],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT)
    workers = []
    try:
        for w in range(n_workers):
            workers.append(subprocess.Popen(
                common + ["worker", "--server", f"localhost:{port}",
                          "--worker-name", f"wire-w{w}",
                          "--model", "vit_tiny", "--synthetic",
                          "--num-train", str(n_train),
                          "--num-test", "64",
                          "--epochs", str(epochs),
                          "--batch-size", str(batch),
                          "--platform", "cpu", "--dtype", "float32",
                          "--no-augment", "--emit-metrics"],
                cwd=REPO, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT))
        texts = []
        for w in workers:
            out, _ = w.communicate(timeout=900)
            texts.append(out.decode(errors="replace"))
            assert w.returncode == 0, texts[-1][-2000:]
        s_out, _ = server.communicate(timeout=120)
        texts.append(s_out.decode(errors="replace"))
        assert server.returncode == 0, texts[-1][-2000:]
    finally:
        for p in [server] + workers:
            if p.poll() is None:
                p.kill()
    wall = time.time() - t0

    record = parse_experiment("\n".join(texts), name)
    sm = record["server_metrics"]
    wm = record["raw_worker_metrics"]
    total_out = sum(w.get("wire_bytes_out", 0) for w in wm)
    total_in = sum(w.get("wire_bytes_in", 0) for w in wm)
    train_time = max((w["total_training_time_seconds"] for w in wm),
                     default=wall)
    record["wire"] = {
        "cell_wall_seconds": round(wall, 2),
        # Over the server's whole lifetime — includes worker process
        # startup + jit compile, which dominate on this single-core host.
        "pushes_per_second": round(
            sm.get("gradients_processed", 0)
            / max(sm.get("total_training_time_seconds", wall), 1e-9), 3),
        # Over the slowest worker's ACTIVE training window (sum of its
        # epoch times) — the wire-rate number comparable across hosts.
        "pushes_per_second_active": round(
            sm.get("gradients_processed", 0) / max(train_time, 1e-9), 3),
        "client_mb_out_gradients": round(total_out / 1e6, 3),
        "client_mb_in_params": round(total_in / 1e6, 3),
        "client_mb_per_second": round(
            (total_out + total_in) / 1e6 / max(train_time, 1e-9), 3),
        "push_codec": codec,
        "store_backend": backend,
    }
    out_path = os.path.join(OUT, f"{name}.json")
    with open(out_path, "w") as f:
        json.dump(record, f, indent=2)
        f.write("\n")
    print(f"{name}: {record['wire']}", flush=True)
    return record


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="2-worker cells only")
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--num-train", type=int, default=512)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()

    os.makedirs(OUT, exist_ok=True)
    from distributed_parameter_server_for_ml_training_tpu.native import (
        bindings)

    backends = ["python"]
    if bindings.native_available():
        backends.append("native")
    worker_counts = [2] if args.quick else [2, 4]

    cells = []
    for n in worker_counts:
        for codec in ("fp16", "none"):
            for backend in backends:
                cells.append(run_cell("async", n, codec, backend,
                                      args.epochs, args.num_train,
                                      args.batch_size))
        # int8 wire codec decodes on the Python store only.
        cells.append(run_cell("async", n, "int8", "python",
                              args.epochs, args.num_train,
                              args.batch_size))

    summary = []
    for rec in cells:
        summary.append({"cell": rec["experiment_name"], **rec["wire"],
                        "final_acc": rec["worker_metrics_aggregated"].get(
                            "average_final_accuracy")})
    with open(os.path.join(OUT, "wire_summary.json"), "w") as f:
        json.dump({"cells": summary,
                   "topology": "1 serve + N worker OS processes, "
                               "localhost gRPC, --platform cpu",
                   "caveat": "single-core host: all worker processes + "
                             "serve share one CPU, so pushes/s and MB/s "
                             "carry compile/dispatch convoy overhead "
                             "(notably the 4w cells); the MB columns are "
                             "exact wire-payload byte counts from the "
                             "client-side counters"}, f,
                  indent=2)
        f.write("\n")
    print("\n| cell | pushes/s | MB out | MB in | MB/s |")
    print("|---|---|---|---|---|")
    for s in summary:
        print(f"| {s['cell']} | {s['pushes_per_second']} | "
              f"{s['client_mb_out_gradients']} | "
              f"{s['client_mb_in_params']} | "
              f"{s['client_mb_per_second']} |")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
