"""Over-the-wire distributed experiment matrix: serve + worker OS processes.

The reference's recorded artifacts come from its real deployed topology —
separate processes, gradients crossing a network (worker.py:270-311), in BOTH
modes: its flagship record is sync (experiment_results/sync_4workers.json,
server.py:264-288) and async goes to 8 workers
(experiment_results/async_8workers.json). This script runs that topology for
THIS framework: one `cli serve` process and N `cli worker` processes over
localhost gRPC, for a matrix of cells:

    mode={async,sync} x workers={2,4} x push-codec={fp16,none,int8}
                      x store-backend={python,native}
    + fetch-codec cells (async, --fetch-codec bf16: params-in halved)
    + an async 8-worker cell (the reference's largest recorded count)
    + an ELASTIC cell: kill a worker mid-run, start a replacement, record
      slot inheritance + membership staying at N (the honest counterpart
      of the reference's restart pollution, README.md:368-371)

and records, per cell, wire-level numbers no in-process run can produce:
pushes/s at the server, client wire MB (out = gradients, in = fetched
params), MB/s, codec byte effects, python-vs-native — into
experiments/results/wire/<cell>.json (reference schema via the shared ETL)
+ wire_summary.json.

Statistical hygiene (round-4 VERDICT weak 6): every core cell runs
--repeats times (default 3) against the persistent jit cache (the first
run warms it); the summary reports the MEDIAN with min-max spread, so the
python-vs-native and codec columns carry error bars instead of riding on
single-run noise.

Workers run --platform cpu (the chip can't host N independent processes);
the numbers measure the WIRE + store path, complementing the on-chip
in-process records in experiments/results/calibrated/.

Run:  python experiments/run_wire_matrix.py [--quick] [--only async_4w...]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import statistics
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

OUT = os.path.join(REPO, "experiments", "results", "wire")
CLI = [sys.executable, "-m",
       "distributed_parameter_server_for_ml_training_tpu.cli"]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env() -> dict:
    return dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1",
                JAX_COMPILATION_CACHE_DIR=os.path.join(REPO, ".jax_cache"))


def _popen(cmd: list[str], log_path: str) -> subprocess.Popen:
    """Start a process with stdout+stderr appended to a REAL file — a PIPE
    would deadlock once the 64 KB buffer fills mid-run (round-4 ADVICE),
    and a file lets the elastic cell tail progress markers live."""
    f = open(log_path, "ab")
    try:
        return subprocess.Popen(cmd, cwd=REPO, env=_env(), stdout=f,
                                stderr=subprocess.STDOUT)
    finally:
        f.close()  # the child owns its dup'd fd


def _serve_cmd(mode: str, n_workers: int, codec: str, backend: str,
               port: int, fetch_codec: str = "none",
               extra: list[str] | None = None) -> list[str]:
    cmd = CLI + ["serve", "--mode", mode, "--workers", str(n_workers),
                 "--port", str(port), "--model", "vit_tiny",
                 "--num-classes", "100", "--image-size", "32",
                 "--store-backend", backend, "--push-codec", codec,
                 "--fetch-codec", fetch_codec,
                 "--platform", "cpu", "--emit-metrics"]
    return cmd + (extra or [])


def _worker_cmd(name: str, port: int, epochs: int, n_train: int,
                batch: int) -> list[str]:
    return CLI + ["worker", "--server", f"localhost:{port}",
                  "--worker-name", name,
                  "--model", "vit_tiny", "--synthetic",
                  "--num-train", str(n_train), "--num-test", "64",
                  "--epochs", str(epochs), "--batch-size", str(batch),
                  "--platform", "cpu", "--dtype", "float32",
                  "--no-augment", "--emit-metrics"]


def _wire_stats(record: dict, wall: float) -> dict:
    sm = record["server_metrics"]
    wm = record["raw_worker_metrics"]
    total_out = sum(w.get("wire_bytes_out", 0) for w in wm)
    total_in = sum(w.get("wire_bytes_in", 0) for w in wm)
    train_time = max((w["total_training_time_seconds"] for w in wm),
                     default=wall)
    return {
        "cell_wall_seconds": round(wall, 2),
        # Over the server's whole lifetime — includes worker process
        # startup + jit compile, which dominate on this single-core host.
        "pushes_per_second": round(
            sm.get("gradients_processed", 0)
            / max(sm.get("total_training_time_seconds", wall), 1e-9), 3),
        # Over the slowest worker's ACTIVE training window (sum of its
        # epoch times) — the wire-rate number comparable across hosts.
        "pushes_per_second_active": round(
            sm.get("gradients_processed", 0) / max(train_time, 1e-9), 3),
        "client_mb_out_gradients": round(total_out / 1e6, 3),
        "client_mb_in_params": round(total_in / 1e6, 3),
        "client_mb_per_second": round(
            (total_out + total_in) / 1e6 / max(train_time, 1e-9), 3),
    }


def _run_once(name: str, mode: str, n_workers: int, codec: str,
              backend: str, epochs: int, n_train: int, batch: int,
              fetch_codec: str, timeout: int) -> tuple[dict, dict]:
    """One serve + N workers run. Returns (record, wire_stats)."""
    from distributed_parameter_server_for_ml_training_tpu.analysis.parse_logs \
        import parse_experiment

    port = _free_port()
    t0 = time.time()
    with tempfile.TemporaryDirectory(prefix=f"wire_{name}_") as td:
        logs = [os.path.join(td, "server.log")]
        server = _popen(_serve_cmd(mode, n_workers, codec, backend, port,
                                   fetch_codec), logs[0])
        procs = [server]
        try:
            for w in range(n_workers):
                lp = os.path.join(td, f"worker{w}.log")
                logs.append(lp)
                procs.append(_popen(
                    _worker_cmd(f"wire-w{w}", port, epochs, n_train, batch),
                    lp))
            for p in procs[1:]:
                p.wait(timeout=timeout)
            server.wait(timeout=120)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        texts = []
        for lp in logs:
            with open(lp, errors="replace") as f:
                texts.append(f.read())
        for p, lp, text in zip(procs, logs, texts):
            assert p.returncode == 0, (lp, text[-2000:])
        wall = time.time() - t0
        record = parse_experiment("\n".join(texts), name)
    return record, _wire_stats(record, wall)


def run_cell(mode: str, n_workers: int, codec: str, backend: str,
             epochs: int, n_train: int, batch: int, *,
             fetch_codec: str = "none", repeats: int = 3,
             timeout: int = 900) -> dict:
    name = f"{mode}_{n_workers}w_{codec}_{backend}"
    if fetch_codec != "none":
        name += f"_fetch{fetch_codec}"
    runs = []
    record = None
    for r in range(repeats):
        record, stats = _run_once(name, mode, n_workers, codec, backend,
                                  epochs, n_train, batch, fetch_codec,
                                  timeout)
        runs.append(stats)
        print(f"{name} run {r + 1}/{repeats}: {stats}", flush=True)
    # The RECORD (reference schema) is the last run; wire stats carry all
    # repeats + median/spread so conclusions don't ride on one run.
    record["wire"] = _median_spread(runs)
    record["wire"].update({"push_codec": codec, "fetch_codec": fetch_codec,
                           "store_backend": backend, "repeats": runs})
    _save(name, record)
    return record


def _median_spread(runs: list[dict]) -> dict:
    out: dict = {}
    for key in runs[0]:
        vals = [r[key] for r in runs]
        out[key] = round(statistics.median(vals), 3)
        if len(vals) > 1:
            out[f"{key}_spread"] = [round(min(vals), 3),
                                    round(max(vals), 3)]
    return out


def _save(name: str, record: dict) -> str:
    os.makedirs(OUT, exist_ok=True)
    out_path = os.path.join(OUT, f"{name}.json")
    with open(out_path, "w") as f:
        json.dump(record, f, indent=2)
        f.write("\n")
    return out_path


def _wait_for_marker(path: str, marker: str, timeout: float) -> bool:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if os.path.exists(path):
            with open(path, errors="replace") as f:
                if marker in f.read():
                    return True
        time.sleep(2.0)
    return False


def run_elastic_cell(epochs: int, n_train: int, batch: int,
                     timeout: int = 1200) -> dict:
    """Kill worker 1 after its first epoch; start a replacement; record the
    replacement inheriting the freed slot (same worker_id), membership
    staying at N, and the accuracy curve surviving. The reference's
    restarts instead inflated ids and skewed shards (num_workers: 11 in
    its sync_4workers.json; README.md:368-371)."""
    from distributed_parameter_server_for_ml_training_tpu.analysis.parse_logs \
        import parse_experiment

    name = "elastic_replace"
    port = _free_port()
    t0 = time.time()
    with tempfile.TemporaryDirectory(prefix="wire_elastic_") as td:
        s_log = os.path.join(td, "server.log")
        server = _popen(_serve_cmd(
            "async", 2, "fp16", "python", port,
            extra=["--elastic", "--worker-timeout", "30"]), s_log)
        w_logs = [os.path.join(td, f"worker{i}.log") for i in range(3)]
        procs = [server]
        killed_at = replacement_started = None
        try:
            w0 = _popen(_worker_cmd("elastic-w0", port, epochs, n_train,
                                    batch), w_logs[0])
            victim = _popen(_worker_cmd("elastic-victim", port, epochs,
                                        n_train, batch), w_logs[1])
            procs += [w0, victim]
            # Kill the victim once it has demonstrably trained (epoch 1
            # done) but before it can finish.
            assert _wait_for_marker(w_logs[1], "EPOCH_DONE", timeout), \
                "victim never finished an epoch"
            victim.kill()
            victim.wait()
            killed_at = round(time.time() - t0, 1)
            # Replacement registers AFTER the reaper expires the victim
            # (worker-timeout 30): give it a head start, then start it —
            # RemoteStore registration retries cover the gap either way.
            time.sleep(10)
            repl = _popen(_worker_cmd("elastic-replacement", port, epochs,
                                      n_train, batch), w_logs[2])
            procs.append(repl)
            replacement_started = round(time.time() - t0, 1)
            w0.wait(timeout=timeout)
            repl.wait(timeout=timeout)
            server.wait(timeout=180)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        texts = []
        for lp in [s_log] + w_logs:
            if os.path.exists(lp):
                with open(lp, errors="replace") as f:
                    texts.append(f.read())
        # Survivor + replacement must have SUCCEEDED — a crashed worker
        # here is a harness failure, not a framework finding, and must not
        # be recorded as one (the victim's kill is of course expected).
        assert server.returncode == 0, texts[0][-2000:]
        assert w0.returncode == 0, texts[1][-2000:]
        assert repl.returncode == 0, texts[-1][-2000:]
        wall = time.time() - t0
        record = parse_experiment("\n".join(texts), name)

    wm = record["raw_worker_metrics"]
    by_name = {w.get("worker_name", ""): w for w in wm}
    repl_row = by_name.get("elastic-replacement", {})
    w0_row = by_name.get("elastic-w0", {})
    victim_ids = [ln for t in texts for ln in t.splitlines()
                  if "EPOCH_DONE worker=elastic-victim" in ln]
    victim_id = (int(victim_ids[0].split("id=")[1].split()[0])
                 if victim_ids else None)
    record["elastic"] = {
        "timeline_seconds": {"victim_killed": killed_at,
                             "replacement_started": replacement_started,
                             "total_wall": round(wall, 1)},
        "victim_worker_id": victim_id,
        "replacement_worker_id": repl_row.get("worker_id"),
        "slot_inherited": repl_row.get("worker_id") == victim_id,
        "survivor_final_accuracy": w0_row.get("final_test_accuracy"),
        "replacement_final_accuracy": repl_row.get("final_test_accuracy"),
        "server_expired_victim": any("expired silent workers" in t
                                     for t in texts),
        # Membership stayed at N iff NO worker was ever assigned an id
        # beyond the original N slots — the reference's restarts instead
        # grew ids monotonically (num_workers: 11, README.md:368-371).
        "membership_stayed_at_n": (
            victim_id is not None
            and max([victim_id] + [int(w.get("worker_id", 0))
                                   for w in wm]) < 2),
    }
    record["wire"] = _wire_stats(record, wall)
    _save(name, record)
    print(f"{name}: {record['elastic']}", flush=True)
    return record


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="2-worker async cells only, 1 repeat")
    ap.add_argument("--only", default=None,
                    help="substring filter on cell names")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--num-train", type=int, default=512)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--skip-8w", action="store_true")
    ap.add_argument("--skip-elastic", action="store_true")
    args = ap.parse_args()

    os.makedirs(OUT, exist_ok=True)
    from distributed_parameter_server_for_ml_training_tpu.native import (
        bindings)

    backends = ["python"]
    if bindings.native_available():
        backends.append("native")
    worker_counts = [2] if args.quick else [2, 4]
    repeats = 1 if args.quick else args.repeats
    modes = ["async"] if args.quick else ["async", "sync"]

    # (mode, n, codec, backend, fetch_codec, repeats, timeout)
    plan: list[tuple] = []
    for n in worker_counts:
        for mode in modes:
            codecs = (("fp16", "none", "int8") if mode == "async"
                      else ("fp16", "none"))
            for codec in codecs:
                for backend in backends:
                    plan.append((mode, n, codec, backend, "none", repeats,
                                 900))
    if not args.quick:
        # Fetch-side compression: params-in (the dominant term) halves.
        for backend in backends:
            plan.append(("async", 4, "fp16", backend, "bf16", repeats, 900))
        # The reference's largest recorded worker count. One run (9
        # processes convoying on one core — spread would measure the
        # convoy, not the wire).
        if not args.skip_8w:
            plan.append(("async", 8, "fp16",
                         backends[-1], "none", 1, 2400))
    def cell_name(p):
        name = f"{p[0]}_{p[1]}w_{p[2]}_{p[3]}"
        return name + (f"_fetch{p[4]}" if p[4] != "none" else "")

    if args.only:
        plan = [p for p in plan if args.only in cell_name(p)]

    for (mode, n, codec, backend, fetch, reps, timeout) in plan:
        run_cell(mode, n, codec, backend, args.epochs,
                 args.num_train, args.batch_size,
                 fetch_codec=fetch, repeats=reps, timeout=timeout)
        _write_summary()  # incremental: a crash keeps finished cells

    if not args.quick and not args.skip_elastic and not args.only:
        try:
            run_elastic_cell(max(4, args.epochs * 2),
                             args.num_train, args.batch_size)
        except AssertionError as e:
            print(f"elastic cell failed: {e}", file=sys.stderr)
        _write_summary()
    return 0


def _write_summary() -> None:
    """Summarize EVERY recorded cell on disk (not just this invocation's),
    so partial re-runs via --only/--quick refresh rather than destroy the
    other rows."""
    summary = []
    for fn in sorted(os.listdir(OUT)):
        if not fn.endswith(".json") or fn == "wire_summary.json":
            continue
        with open(os.path.join(OUT, fn)) as f:
            rec = json.load(f)
        if "wire" not in rec:
            continue
        summary.append({"cell": rec["experiment_name"], **{
            k: v for k, v in rec["wire"].items() if k != "repeats"},
            "final_acc": rec.get("worker_metrics_aggregated", {}).get(
                "average_final_accuracy")})
    # Preserve non-cell keys written by other tools (e.g. the measured
    # 16-worker host_limits record from experiments/probe_wire_scale.py) —
    # a matrix re-run must refresh cells, not erase evidence.
    extra = {}
    summary_path = os.path.join(OUT, "wire_summary.json")
    if os.path.exists(summary_path):
        try:
            with open(summary_path) as f:
                extra = {k: v for k, v in json.load(f).items()
                         if k not in ("cells", "topology", "methodology",
                                      "caveat")}
        except (OSError, json.JSONDecodeError) as e:
            # A corrupt summary must not kill a finished matrix run — the
            # rewrite below repairs it (only foreign keys are lost).
            print(f"warning: unreadable {summary_path} ({e}); rewriting")
    with open(summary_path, "w") as f:
        json.dump({**extra, "cells": summary,
                   "topology": "1 serve + N worker OS processes, "
                               "localhost gRPC, --platform cpu",
                   "methodology": "each core cell repeated; columns are "
                                  "the MEDIAN across repeats with "
                                  "[min,max] *_spread fields; the first "
                                  "repeat warms the persistent jit cache "
                                  "shared by all later runs",
                   "caveat": "single-core host: all worker processes + "
                             "serve share one CPU, so pushes/s and MB/s "
                             "carry compile/dispatch convoy overhead "
                             "(notably the 4w/8w cells); the MB columns "
                             "are exact wire-payload byte counts from "
                             "the client-side counters"}, f, indent=2)
        f.write("\n")
    print("\n| cell | pushes/s (active) | MB out | MB in | MB/s |")
    print("|---|---|---|---|---|")
    for s in summary:
        print(f"| {s['cell']} | {s.get('pushes_per_second_active')} | "
              f"{s['client_mb_out_gradients']} | "
              f"{s['client_mb_in_params']} | "
              f"{s['client_mb_per_second']} |")


if __name__ == "__main__":
    raise SystemExit(main())
