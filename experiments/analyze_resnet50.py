"""Per-stage time/FLOPs breakdown of ResNet-50 @224 on the attached chip.

Round-4 VERDICT item 6: the 224px ResNet-50 sits near ~27% MFU while the
other families reach 44-47%. This measures WHERE the step goes: fwd+bwd
wall time and XLA-counted FLOPs of model PREFIXES (stem, +stage0, ...,
full), so per-stage deltas give each stage's achieved TF/s — the
trace-backed ceiling analysis PERF.md records.

Run:  python experiments/analyze_resnet50.py [--batch 256]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                 os.path.join(REPO, ".jax_cache")))

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

V5E_BF16_PEAK_TFLOPS = 197.0
REPS = 10  # chained iterations per dispatch (amortizes the axon tunnel)
TRIALS_MIN = 5  # median-of-5 minimum: best-of-N lets tunnel excursions
                # corrupt prefix deltas (same fix as measure_mfu's bench)


def measure_prefix(n_stages: int, batch: int, trials: int) -> dict:
    # The REAL registry architecture truncated in place (max_stages) —
    # not a re-implementation that could drift from models/resnet.py.
    from distributed_parameter_server_for_ml_training_tpu.models.resnet import (
        Bottleneck, ResNet)

    model = ResNet(stage_sizes=(3, 4, 6, 3), block_cls=Bottleneck,
                   num_classes=1000, dtype=jnp.bfloat16,
                   imagenet_stem=True, s2d_stem=True,
                   max_stages=n_stages)
    x = jnp.asarray(np.random.default_rng(0).normal(
        size=(batch, 224, 224, 3)), jnp.float32)
    vs = model.init(jax.random.PRNGKey(0), x[:1], train=False)

    def loss(params, x):
        y, _ = model.apply({"params": params,
                            "batch_stats": vs["batch_stats"]}, x,
                           train=True, mutable=["batch_stats"])
        return jnp.sum(y.astype(jnp.float32) ** 2) * 1e-6

    grad = jax.grad(loss)

    def chain(params, x):
        def body(p, _):
            g = grad(p, x)
            return jax.tree_util.tree_map(
                lambda a, b: a - 1e-6 * b.astype(a.dtype), p, g), ()
        out, _ = jax.lax.scan(body, params, None, length=REPS)
        return jax.tree_util.tree_reduce(
            lambda a, b: a + jnp.sum(jnp.abs(b).astype(jnp.float32)), out,
            0.0)

    jitted = jax.jit(chain)
    single = jax.jit(grad).lower(vs["params"], x).compile()
    flops = float(single.cost_analysis().get("flops", 0.0))
    _ = float(jitted(vs["params"], x))          # compile + warm
    times = []
    for _t in range(max(trials, TRIALS_MIN)):
        t0 = time.perf_counter()
        _ = float(jitted(vs["params"], x))
        times.append(time.perf_counter() - t0)
    med = float(np.median(times))
    ms = med / REPS * 1e3
    return {"prefix_stages": n_stages, "ms_fwd_bwd": round(ms, 2),
            "gflops": round(flops / 1e9, 1),
            "tf_per_s": round(flops / (med / REPS) / 1e12, 1),
            "mfu_pct": round(100 * flops / (med / REPS) / 1e12
                             / V5E_BF16_PEAK_TFLOPS, 1)}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--trials", type=int, default=3)
    args = ap.parse_args()

    rows = []
    for n in range(5):
        rows.append(measure_prefix(n, args.batch, args.trials))
        print(rows[-1], flush=True)
    # per-stage deltas
    deltas = []
    for i in range(1, len(rows)):
        dms = rows[i]["ms_fwd_bwd"] - rows[i - 1]["ms_fwd_bwd"]
        dfl = rows[i]["gflops"] - rows[i - 1]["gflops"]
        deltas.append({
            "stage": i - 1,
            "ms": round(dms, 2),
            "gflops": round(dfl, 1),
            "tf_per_s": round(dfl / max(dms, 1e-9), 1),  # GF/ms == TF/s
            "mfu_pct": round(100 * (dfl / max(dms, 1e-9))
                             / V5E_BF16_PEAK_TFLOPS, 1),
        })
        print(deltas[-1], flush=True)
    out = os.path.join(REPO, "experiments", "results",
                       "resnet50_stage_breakdown.json")
    with open(out, "w") as f:
        json.dump({"batch": args.batch, "reps_per_dispatch": REPS,
                   "prefixes": rows, "stage_deltas": deltas}, f, indent=2)
        f.write("\n")
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
