// Native parameter-store core.
//
// The host-side hot path of the async parameter server: the reference spent
// it in Python pickle + numpy temporaries (server.py:222 re-pickles ~45 MB
// per fetch; server.py:232-237 allocates a full fp32 copy per push before a
// second pass applies SGD). Here:
//
//   - parameters live in ONE contiguous float arena (single allocation; the
//     Python side keeps {name -> (offset, shape)} and exposes zero-copy
//     numpy views for reads),
//   - push applies fused fp16-decode + staleness-weighted SGD in a single
//     multithreaded pass over the arena (no intermediate fp32 gradient
//     buffer at all),
//   - the staleness rule is the reference's exactly: reject if
//     global_step - fetched_step > bound, else weight
//     max(0.1, 1/(1+0.1*s)) (server.py:171-186),
//   - a seqlock-style version counter lets fetches copy the arena without
//     blocking pushes (readers retry if a push raced them), replacing the
//     reference's exclusive param_lock on the fetch path (server.py:221).
//
// Built as a plain shared library; Python binds via ctypes (no pybind11 in
// this environment).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

namespace {

// scalar fp16 <-> fp32 (IEEE 754 half), portable bit manipulation
static inline float half_to_float(uint16_t h) {
  uint32_t sign = (uint32_t)(h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1Fu;
  uint32_t mant = h & 0x3FFu;
  uint32_t bits;
  if (exp == 0) {
    if (mant == 0) {
      bits = sign;  // +-0
    } else {  // subnormal: value = mant * 2^-24; normalize so the implicit
              // bit lands in place — exponent is 2^(-15-shift), biased 127.
      int shift = 0;
      while (!(mant & 0x400u)) {
        mant <<= 1;
        ++shift;
      }
      mant &= 0x3FFu;
      bits = sign | ((uint32_t)(127 - 15 + 1 - shift) << 23) | (mant << 13);
    }
  } else if (exp == 0x1F) {
    bits = sign | 0x7F800000u | (mant << 13);  // inf / nan
  } else {
    bits = sign | ((exp - 15 + 127) << 23) | (mant << 13);
  }
  float out;
  std::memcpy(&out, &bits, 4);
  return out;
}

static inline uint16_t float_to_half(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  uint32_t sign = (bits >> 16) & 0x8000u;
  int32_t exp = (int32_t)((bits >> 23) & 0xFFu) - 127 + 15;
  uint32_t mant = bits & 0x7FFFFFu;
  if (exp <= 0) {  // underflow -> subnormal or zero (round-to-nearest-even)
    if (exp < -10) return (uint16_t)sign;
    mant |= 0x800000u;
    int shift = 14 - exp;
    uint16_t sub = (uint16_t)(mant >> shift);
    uint32_t rem = mant & ((1u << shift) - 1);
    uint32_t half_point = 1u << (shift - 1);
    if (rem > half_point || (rem == half_point && (sub & 1))) ++sub;
    return (uint16_t)(sign | sub);
  }
  if (exp >= 0x1F) {  // overflow -> inf; nan keeps payload bit
    if (((bits >> 23) & 0xFFu) == 0xFFu && mant)
      return (uint16_t)(sign | 0x7E00u);  // nan
    return (uint16_t)(sign | 0x7C00u);
  }
  uint16_t out = (uint16_t)(sign | (exp << 10) | (mant >> 13));
  uint32_t rem = mant & 0x1FFFu;
  if (rem > 0x1000u || (rem == 0x1000u && (out & 1))) ++out;
  return out;
}

static void parallel_for(int64_t n, int64_t grain,
                         const std::function<void(int64_t, int64_t)>& body) {
  unsigned hw = std::thread::hardware_concurrency();
  int64_t nthreads = std::max<int64_t>(
      1, std::min<int64_t>(hw ? hw : 1, n / grain));
  if (nthreads <= 1) {
    body(0, n);
    return;
  }
  std::vector<std::thread> ts;
  int64_t chunk = (n + nthreads - 1) / nthreads;
  ts.reserve(nthreads);
  for (int64_t t = 0; t < nthreads; ++t) {
    int64_t lo = t * chunk, hi = std::min(n, lo + chunk);
    if (lo >= hi) break;
    ts.emplace_back([&body, lo, hi] { body(lo, hi); });
  }
  for (auto& t : ts) t.join();
}

struct Store {
  std::vector<float> params;
  std::mutex write_lock;               // serializes pushes (param_lock role)
  std::atomic<int64_t> version{0};     // seqlock: odd = write in progress
  std::atomic<int64_t> global_step{0};
  std::atomic<int64_t> rejected{0};
  float lr;
  // Sync-round stash: one arena-sized buffer per worker slot (allocated on
  // first stash). unique_ptr keeps each buffer's address stable across
  // outer-vector resizes (a reference obtained before a concurrent resize
  // must stay valid). Round orchestration (locks, counting, elastic
  // targets) stays on the Python side; C++ only does the bulk passes.
  std::vector<std::unique_ptr<std::vector<float>>> slots;
  std::mutex slots_lock;
};

}  // namespace

extern "C" {

// ---- fp16 codec (multithreaded) -------------------------------------------

void dps_fp32_to_fp16(const float* src, uint16_t* dst, int64_t n) {
  parallel_for(n, 1 << 16, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) dst[i] = float_to_half(src[i]);
  });
}

void dps_fp16_to_fp32(const uint16_t* src, float* dst, int64_t n) {
  parallel_for(n, 1 << 16, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) dst[i] = half_to_float(src[i]);
  });
}

// bfloat16 = top 16 bits of fp32, round-to-nearest-even on the dropped
// half. The FETCH-side codec (serve --fetch-codec bf16): full fp32
// exponent range at half the wire bytes, matching ml_dtypes' cast
// bit-for-bit (tested) so python- and native-backend fetches are
// indistinguishable on the wire.
void dps_fp32_to_bf16(const float* src, uint16_t* dst, int64_t n) {
  parallel_for(n, 1 << 16, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      uint32_t bits;
      std::memcpy(&bits, &src[i], 4);
      if ((bits & 0x7F800000u) == 0x7F800000u && (bits & 0x007FFFFFu)) {
        // NaN: truncating could zero the kept mantissa bits and decay to
        // inf — force a quiet bit instead.
        dst[i] = (uint16_t)((bits >> 16) | 0x0040u);
      } else {
        // RNE: add 0x7FFF + lsb-of-result; inf (mantissa 0) is unchanged
        // because the add cannot carry past bit 16.
        dst[i] = (uint16_t)((bits + (0x7FFFu + ((bits >> 16) & 1u))) >> 16);
      }
    }
  });
}

void dps_bf16_to_fp32(const uint16_t* src, float* dst, int64_t n) {
  parallel_for(n, 1 << 16, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      uint32_t bits = (uint32_t)src[i] << 16;
      std::memcpy(&dst[i], &bits, 4);
    }
  });
}

// ---- store lifecycle -------------------------------------------------------

void* dps_store_create(int64_t n, const float* init, float lr) {
  auto* s = new Store();
  s->params.assign(init, init + n);
  s->lr = lr;
  return s;
}

void dps_store_destroy(void* h) { delete static_cast<Store*>(h); }

int64_t dps_store_step(void* h) {
  return static_cast<Store*>(h)->global_step.load();
}

int64_t dps_store_rejected(void* h) {
  return static_cast<Store*>(h)->rejected.load();
}

// Seqlock fetch: copy the arena + step without blocking writers. Retries
// until it observes a stable version. Returns the global step of the copy.
int64_t dps_store_fetch(void* h, float* out) {
  auto* s = static_cast<Store*>(h);
  const int64_t n = (int64_t)s->params.size();
  while (true) {
    int64_t v0 = s->version.load(std::memory_order_acquire);
    if (v0 & 1) continue;  // write in progress
    int64_t step = s->global_step.load(std::memory_order_acquire);
    std::memcpy(out, s->params.data(), n * sizeof(float));
    std::atomic_thread_fence(std::memory_order_acquire);
    if (s->version.load(std::memory_order_acquire) == v0) return step;
  }
}

// Checkpoint restore: overwrite the arena + step under the write lock with
// the seqlock odd/even bracket, so concurrent fetches never observe a
// half-restored parameter set (the write-side dual of dps_store_fetch).
void dps_store_load(void* h, const float* src, int64_t step) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->write_lock);
  const int64_t n = (int64_t)s->params.size();
  s->version.fetch_add(1, std::memory_order_acq_rel);  // odd: writing
  std::memcpy(s->params.data(), src, n * sizeof(float));
  s->global_step.store(step);  // before even bump, like the push paths
  s->version.fetch_add(1, std::memory_order_acq_rel);  // even: stable
}

// Fused fp16-decode + staleness-weighted SGD apply (async push).
// Returns the new global step, or -1 if rejected by the staleness bound.
int64_t dps_store_push_fp16(void* h, const uint16_t* grads,
                            int64_t fetched_step, int64_t bound) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->write_lock);
  int64_t staleness = s->global_step.load() - fetched_step;
  if (bound >= 0 && staleness > bound) {
    s->rejected.fetch_add(1);
    return -1;
  }
  double w = 1.0 / (1.0 + 0.1 * (double)staleness);  // server.py:178
  if (w < 0.1) w = 0.1;
  const float scale = (float)(s->lr * w);
  float* p = s->params.data();
  const int64_t n = (int64_t)s->params.size();
  s->version.fetch_add(1, std::memory_order_acq_rel);  // odd: writing
  parallel_for(n, 1 << 15, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i)
      p[i] -= scale * half_to_float(grads[i]);
  });
  // Step must advance BEFORE the version returns to even: a fetch validated
  // against the post-write version would otherwise pair new params with the
  // pre-push step, inflating every later staleness computation by 1.
  int64_t new_step = s->global_step.fetch_add(1) + 1;
  s->version.fetch_add(1, std::memory_order_acq_rel);  // even: stable
  return new_step;
}

// fp32 variant (push_codec='none'), same semantics.
int64_t dps_store_push_fp32(void* h, const float* grads,
                            int64_t fetched_step, int64_t bound) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->write_lock);
  int64_t staleness = s->global_step.load() - fetched_step;
  if (bound >= 0 && staleness > bound) {
    s->rejected.fetch_add(1);
    return -1;
  }
  double w = 1.0 / (1.0 + 0.1 * (double)staleness);
  if (w < 0.1) w = 0.1;
  const float scale = (float)(s->lr * w);
  float* p = s->params.data();
  const int64_t n = (int64_t)s->params.size();
  s->version.fetch_add(1, std::memory_order_acq_rel);
  parallel_for(n, 1 << 15, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) p[i] -= scale * grads[i];
  });
  int64_t new_step = s->global_step.fetch_add(1) + 1;  // before even bump
  s->version.fetch_add(1, std::memory_order_acq_rel);
  return new_step;
}

// ---- int8 codec: fused dequant + apply --------------------------------------
//
// The int8 wire codec (ops/compression.py int8_wire_compress) ships each
// tensor as int8 values + ONE fp32 symmetric scale. The arena is a
// concatenation of tensors, so the kernel walks per-tensor segments:
// `offsets` has n_tensors+1 boundaries (offsets[0]=0,
// offsets[n_tensors]=arena size, same order the Python index packs),
// `scales` one fp32 per tensor. Restores x = scale * q fused into the
// same single pass the fp16 kernels use — the fastest backend now speaks
// the smallest codec instead of rejecting it (round-4 VERDICT weak 2).

static inline int64_t segment_of(const int64_t* offsets, int64_t n_tensors,
                                 int64_t i) {
  return (int64_t)(std::upper_bound(offsets, offsets + n_tensors + 1, i) -
                   offsets) - 1;
}

// Fused int8-dequant + staleness-weighted SGD apply (async push).
// Returns the new global step, or -1 if rejected by the staleness bound.
int64_t dps_store_push_int8(void* h, const int8_t* grads,
                            const float* scales, const int64_t* offsets,
                            int64_t n_tensors, int64_t fetched_step,
                            int64_t bound) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->write_lock);
  int64_t staleness = s->global_step.load() - fetched_step;
  if (bound >= 0 && staleness > bound) {
    s->rejected.fetch_add(1);
    return -1;
  }
  double w = 1.0 / (1.0 + 0.1 * (double)staleness);  // server.py:178
  if (w < 0.1) w = 0.1;
  const float lrw = (float)(s->lr * w);
  float* p = s->params.data();
  const int64_t n = (int64_t)s->params.size();
  s->version.fetch_add(1, std::memory_order_acq_rel);  // odd: writing
  parallel_for(n, 1 << 15, [&](int64_t lo, int64_t hi) {
    int64_t t = segment_of(offsets, n_tensors, lo);
    float scale = lrw * scales[t];
    int64_t seg_end = offsets[t + 1];
    for (int64_t i = lo; i < hi; ++i) {
      while (i >= seg_end) {  // also skips empty segments
        ++t;
        scale = lrw * scales[t];
        seg_end = offsets[t + 1];
      }
      p[i] -= scale * (float)grads[i];
    }
  });
  int64_t new_step = s->global_step.fetch_add(1) + 1;  // before even bump
  s->version.fetch_add(1, std::memory_order_acq_rel);  // even: stable
  return new_step;
}

// ---- sync rounds: per-slot stash + fused mean-apply -------------------------
//
// The reference's sync mode stashes one gradient set per worker and, when
// the round is full, averages per-parameter and applies SGD
// (server.py:264-288 + 145-169 + 126-143). Here the stash decode and the
// mean+apply are single multithreaded passes over the contiguous arena.

static std::vector<float>& slot_buffer(Store* s, int64_t slot) {
  std::lock_guard<std::mutex> g(s->slots_lock);
  if ((int64_t)s->slots.size() <= slot) s->slots.resize(slot + 1);
  if (!s->slots[slot])
    s->slots[slot] = std::make_unique<std::vector<float>>(
        s->params.size(), 0.0f);
  return *s->slots[slot];
}

void dps_store_stash_fp16(void* h, int64_t slot, const uint16_t* grads) {
  auto* s = static_cast<Store*>(h);
  std::vector<float>& buf = slot_buffer(s, slot);
  parallel_for((int64_t)buf.size(), 1 << 15, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) buf[i] = half_to_float(grads[i]);
  });
}

void dps_store_stash_fp32(void* h, int64_t slot, const float* grads) {
  auto* s = static_cast<Store*>(h);
  std::vector<float>& buf = slot_buffer(s, slot);
  std::memcpy(buf.data(), grads, buf.size() * sizeof(float));
}

// int8 stash for sync rounds: dequantize into the worker's slot buffer
// (the fused mean+apply then consumes fp32 slots uniformly). Same
// per-tensor segment layout as dps_store_push_int8.
void dps_store_stash_int8(void* h, int64_t slot, const int8_t* grads,
                          const float* scales, const int64_t* offsets,
                          int64_t n_tensors) {
  auto* s = static_cast<Store*>(h);
  std::vector<float>& buf = slot_buffer(s, slot);
  parallel_for((int64_t)buf.size(), 1 << 15, [&](int64_t lo, int64_t hi) {
    int64_t t = segment_of(offsets, n_tensors, lo);
    float scale = scales[t];
    int64_t seg_end = offsets[t + 1];
    for (int64_t i = lo; i < hi; ++i) {
      while (i >= seg_end) {
        ++t;
        scale = scales[t];
        seg_end = offsets[t + 1];
      }
      buf[i] = scale * (float)grads[i];
    }
  });
}

// Release a departed/expired worker's slot buffer (caller must guarantee no
// concurrent stash/apply for this slot — the Python sync lock does).
void dps_store_free_slot(void* h, int64_t slot) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->slots_lock);
  if (slot >= 0 && slot < (int64_t)s->slots.size()) s->slots[slot].reset();
}

// Fused p -= lr * mean(slots): one pass, all threads. Returns the new
// global step. Caller guarantees the listed slots are fully stashed and
// holds its own round lock (matching the Python store's sync_lock).
int64_t dps_store_apply_mean(void* h, const int64_t* slot_ids, int64_t n) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->write_lock);
  const float scale = s->lr / (float)n;
  float* p = s->params.data();
  const int64_t size = (int64_t)s->params.size();
  // Collect raw pointers outside the hot loop.
  std::vector<const float*> bufs;
  bufs.reserve(n);
  {
    std::lock_guard<std::mutex> sg(s->slots_lock);
    for (int64_t j = 0; j < n; ++j)
      bufs.push_back(s->slots[slot_ids[j]]->data());
  }
  s->version.fetch_add(1, std::memory_order_acq_rel);  // odd: writing
  parallel_for(size, 1 << 15, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      float acc = 0.0f;
      for (int64_t j = 0; j < n; ++j) acc += bufs[j][i];
      p[i] -= scale * acc;
    }
  });
  int64_t new_step = s->global_step.fetch_add(1) + 1;  // before even bump
  s->version.fetch_add(1, std::memory_order_acq_rel);
  return new_step;
}

}  // extern "C"
